"""Self-drafting speculation: the host-side drafting tier (ISSUE 13).

PERF.md's round-5/6 record pins bs1 KV-cached decode at the per-step
dispatch floor; megastep (PR 7) fused K steps into one dispatch but
still emits ONE token per verified step. Speculative decoding
(Leviathan et al., "Fast Inference from Transformers via Speculative
Decoding") breaks that floor from the other side: a cheap drafter
proposes γ tokens per slot, the full model scores all γ+1 positions in
ONE dispatch (``models/transformer_infer._spec_logits_paged`` through
the paged block-table gather), and the engine accepts the longest
prefix of drafts matching what the model would have emitted anyway —
so every dispatch lands 1..γ+1 VERIFIED tokens and correctness never
depends on the drafter being right.

This module is the drafting half, pure host-side Python (device-free,
unit-testable like ``kvpool``):

  * ``NgramDrafter`` — tier A (the default): prompt/n-gram lookup in
    the spirit of "Prompt Lookup Decoding" / self-drafting. The
    request's own token chain (prompt + generated tokens) is searched
    for an earlier occurrence of its current n-token suffix (longest
    n first); the tokens that followed that occurrence become the
    draft. The radix prefix cache's published chains
    (``kvpool.RadixCache.token_chains``) are consulted too, so a
    request can draft from text OTHER requests already committed —
    shared-prefix traffic drafts across requests, not just within one.
    Free-running decode loops (the dominant greedy failure mode AND
    the dominant acceptance win: repeated boilerplate, cycles, copied
    spans) are proposed at full γ.
  * tier B (flag ``serving_spec_drafter=truncated``) lives in
    ``serving/engine.py``: a truncated-layer pass over the SAME
    weights and paged pool, scanned γ steps into one dispatch — no
    separate draft model, no extra KV state (draft writes land only
    at positions the verify dispatch immediately overwrites).

The drafter proposes; it never decides. Acceptance runs inside the
compiled scoring step against the model's own (greedy or counter-keyed
sampled) tokens, which is what keeps temperature-0 output bitwise the
non-speculative engine's and seeded sampling replay-identical.
"""

__all__ = ["NgramDrafter"]


class NgramDrafter:
    """Prompt/n-gram lookup drafting over token chains.

    ``max_n``: longest suffix n-gram tried first (flag
    ``serving_spec_ngram``); shorter suffixes are fallbacks down to
    ``min_n`` (flag ``serving_spec_ngram_min``). The default floor of
    2 skips single-token matches: measured on the CPU container, weak
    1-gram evidence proposes mostly-rejected drafts whose scoring
    dispatches cost more than they return — requiring a 2..3-gram
    match roughly doubles the acceptance rate at a small loss of
    draft opportunity (drafting less is free; drafting wrong is not).
    ``window``: how many trailing chain tokens are searched (bounds
    the per-slot host cost on long contexts).
    """

    def __init__(self, max_n=3, min_n=2, window=256):
        self.max_n = max(1, int(max_n))
        self.min_n = max(1, min(int(min_n), self.max_n))
        self.window = max(self.max_n + 1, int(window))

    @staticmethod
    def _continuation(hay, suffix, gamma, self_match):
        """Tokens following the best occurrence of ``suffix`` in
        ``hay``: the RIGHTMOST match with a full γ-token continuation,
        else the match with the longest one (recency is the
        tie-breaker — recent text predicts the immediate future best).
        ``self_match`` excludes the chain's own trailing suffix from
        matching itself (it has no continuation). Returns [] when
        ``suffix`` never occurs with at least one following token."""
        n = len(suffix)
        last = len(hay) - n - 1 if self_match else len(hay) - n
        best = []
        for i in range(last, -1, -1):
            if hay[i:i + n] != suffix:
                continue
            cont = hay[i + n:i + n + gamma]
            if len(cont) >= gamma:
                return cont
            if len(cont) > len(best):
                best = cont
        return best

    def propose(self, chain, gamma, extra_chains=()):
        """Up to ``gamma`` draft tokens continuing ``chain`` (the
        request's committed prompt + generated tokens). The request's
        own chain is searched first (longest n-gram first — the most
        specific evidence), then each published chain in
        ``extra_chains`` order. Returns a (possibly empty) int list;
        an empty draft costs the engine nothing — it falls back to the
        plain dispatch for that iteration."""
        gamma = int(gamma)
        if gamma <= 0 or not chain:
            return []
        hay = [int(t) for t in chain[-self.window:]]
        others = [[int(t) for t in o] for o in extra_chains]
        best = []
        for n in range(min(self.max_n, len(hay) - 1), self.min_n - 1,
                       -1):
            # a FULL-length continuation returns immediately at the
            # strongest n that offers one; a partial match never
            # blocks the ladder — a weaker suffix lower down may
            # still complete the full draft (period-2 cycles do
            # exactly this), and a full draft amortizes the scoring
            # dispatch best
            suffix = hay[-n:]
            cont = self._continuation(hay, suffix, gamma,
                                      self_match=True)
            if len(cont) >= gamma:
                return [int(t) for t in cont]
            if len(cont) > len(best):
                best = cont
            for other in others:
                oc = self._continuation(other, suffix, gamma,
                                        self_match=False)
                if len(oc) >= gamma:
                    return [int(t) for t in oc]
                if len(oc) > len(best):
                    best = oc
        return [int(t) for t in best]
