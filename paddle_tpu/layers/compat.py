"""Reference-parity layer wrappers over already-registered op lowerings.

Every function here mirrors a `fluid.layers.*` entry of the reference
whose OP already has a TPU lowering but which previously lacked the thin
Python wrapper (reference layers/nn.py, tensor.py, detection.py,
metric.py, ops.py). No new compute — just the user-facing API.
"""

from .layer_helper import LayerHelper
from ..param_attr import ParamAttr

__all__ = [
    "Print", "mul", "sums", "sum", "pad", "multiplex", "smooth_l1",
    "lrn", "im2sequence", "uniform_random", "gaussian_random",
    "uniform_random_batch_size_like", "gaussian_random_batch_size_like",
    "nce", "warpctc", "ctc_greedy_decoder", "edit_distance", "chunk_eval",
    "beam_search", "beam_search_decode", "bipartite_match",
    "target_assign", "prior_box", "box_coder", "multiclass_nms",
    "detection_output", "detection_map", "create_parameter",
    "autoincreased_step_counter", "shrink_memory",
    "reorder_lod_tensor_by_rank", "batch", "shuffle", "double_buffer",
    "open_recordio_file", "open_files", "ConditionalBlock",
    "multi_box_head", "ssd_loss",
]


def _simple(op_type, inputs, attrs, out_slots=("Out",), dtype="float32",
            name=None):
    helper = LayerHelper(op_type, name=name)
    outs = {s: [helper.create_variable_for_type_inference(dtype)]
            for s in out_slots}
    helper.append_op(type=op_type, inputs=inputs, outputs=outs,
                     attrs=attrs or {})
    vals = tuple(outs[s][0] for s in out_slots)
    return vals if len(vals) > 1 else vals[0]


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    return _simple("print", {"In": [input]},
                   {"message": message or "", "first_n": first_n,
                    "summarize": summarize}, dtype=input.dtype)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    shape = None
    if x.shape is not None and y.shape is not None:
        shape = (tuple(x.shape[:x_num_col_dims])
                 + tuple(y.shape[y_num_col_dims:]))
    out = helper.create_variable_for_type_inference(x.dtype, shape=shape)
    helper.append_op(type="mul", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"x_num_col_dims": x_num_col_dims,
                            "y_num_col_dims": y_num_col_dims})
    return out


from .tensor import sums          # noqa: F401  (single implementation)

sum = sums   # reference ops.py exported `sum` for the same op


def pad(x, paddings, pad_value=0.0, name=None):
    return _simple("pad", {"X": [x]},
                   {"paddings": list(paddings), "pad_value": pad_value},
                   dtype=x.dtype, name=name)


def multiplex(inputs, index):
    return _simple("multiplex", {"X": list(inputs), "Ids": [index]}, {},
                   dtype=inputs[0].dtype)


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    out, _ = _simple("smooth_l1_loss", inputs,
                     {"sigma": 1.0 if sigma is None else float(sigma)},
                     out_slots=("Out", "Diff"), dtype=x.dtype)
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    return _simple("lrn", {"X": [input]},
                   {"n": n, "k": k, "alpha": alpha, "beta": beta},
                   dtype=input.dtype, name=name)


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    pad4 = _pair(padding)
    if len(pad4) == 2:
        pad4 = pad4 + pad4
    return _simple("im2sequence", {"X": [input]},
                   {"kernels": _pair(filter_size),
                    "strides": _pair(stride), "paddings": pad4},
                   dtype=input.dtype, name=name)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    return _simple("uniform_random", {},
                   {"shape": list(shape), "min": float(min),
                    "max": float(max), "seed": seed, "dtype": dtype},
                   dtype=dtype)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    return _simple("gaussian_random", {},
                   {"shape": list(shape), "mean": float(mean),
                    "std": float(std), "seed": seed, "dtype": dtype},
                   dtype=dtype)


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    return _simple("uniform_random_batch_size_like", {"Input": [input]},
                   {"shape": list(shape), "input_dim_idx": input_dim_idx,
                    "output_dim_idx": output_dim_idx, "min": float(min),
                    "max": float(max), "seed": seed, "dtype": dtype},
                   dtype=dtype)


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    return _simple("gaussian_random_batch_size_like", {"Input": [input]},
                   {"shape": list(shape), "input_dim_idx": input_dim_idx,
                    "output_dim_idx": output_dim_idx, "mean": float(mean),
                    "std": float(std), "seed": seed, "dtype": dtype},
                   dtype=dtype)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None):
    helper = LayerHelper("nce", param_attr=param_attr, bias_attr=bias_attr)
    if input.shape is None or len(input.shape) < 2:
        raise ValueError(
            "nce: `input` must carry a known [batch, dim] shape; got %r"
            % (input.shape,))
    dim = int(input.shape[1])
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[num_total_classes, dim],
                                dtype=input.dtype)
    b = helper.create_parameter(attr=helper.bias_attr,
                                shape=[num_total_classes, 1],
                                dtype=input.dtype, is_bias=True)
    cost = helper.create_variable_for_type_inference(input.dtype)
    sl = helper.create_variable_for_type_inference(input.dtype)
    sll = helper.create_variable_for_type_inference("int64")
    inputs = {"Input": [input], "Label": [label], "Weight": [w],
              "Bias": [b]}
    if sample_weight is not None:
        # per-example cost weight (nce_op.cc:97 sample_weight input)
        inputs["SampleWeight"] = [sample_weight]
    helper.append_op(
        type="nce",
        inputs=inputs,
        outputs={"Cost": [cost], "SampleLogits": [sl],
                 "SampleLabels": [sll]},
        attrs={"num_total_classes": num_total_classes,
               "num_neg_samples": num_neg_samples or 10})
    return cost


def warpctc(input, label, blank=0, norm_by_times=False):
    loss, _ = _simple("warpctc", {"Logits": [input], "Label": [label]},
                      {"blank": blank, "norm_by_times": norm_by_times},
                      out_slots=("Loss", "WarpCTCGrad"),
                      dtype=input.dtype)
    return loss


def ctc_greedy_decoder(input, blank, name=None):
    """argmax over classes then CTC collapse (ctc_align), matching the
    reference's topk+ctc_align composition."""
    from .tensor import argmax
    ids = argmax(input, axis=1)
    return _simple("ctc_align", {"Input": [ids]},
                   {"blank": blank, "merge_repeated": True},
                   out_slots=("Output",), dtype="int64", name=name)


def edit_distance(input, label, normalized=True, ignored_tokens=None):
    if ignored_tokens:
        # reference composition (layers/metric wrappers): erase the
        # ignored tokens from both sequences BEFORE the distance
        from .sequence_layers import sequence_erase
        input = sequence_erase(input, list(ignored_tokens))
        label = sequence_erase(label, list(ignored_tokens))
    out, seq_num = _simple(
        "edit_distance", {"Hyps": [input], "Refs": [label]},
        {"normalized": normalized},
        out_slots=("Out", "SequenceNum"))
    return out, seq_num


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None):
    outs = _simple(
        "chunk_eval", {"Inference": [input], "Label": [label]},
        {"chunk_scheme": chunk_scheme, "num_chunk_types": num_chunk_types,
         "excluded_chunk_types": list(excluded_chunk_types or [])},
        out_slots=("Precision", "Recall", "F1-Score", "NumInferChunks",
                   "NumLabelChunks", "NumCorrectChunks"))
    return outs


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None):
    """One beam-search step (beam_search_op.cc). `ids` is accepted for
    API parity; selection uses `scores` ([rows, vocab] accumulated
    log-probs when is_accumulated)."""
    helper = LayerHelper("beam_search", name=name)
    sel = helper.create_variable_for_type_inference("int64")
    ssc = helper.create_variable_for_type_inference(scores.dtype)
    par = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="beam_search",
        inputs={"pre_ids": [pre_ids], "pre_scores": [pre_scores],
                "scores": [scores]},
        outputs={"selected_ids": [sel], "selected_scores": [ssc],
                 "parent_idx": [par]},
        attrs={"beam_size": beam_size, "end_id": end_id,
               "is_accumulated": is_accumulated})
    return sel, ssc, par


def beam_search_decode(ids, scores, beam_size, end_id, name=None):
    helper = LayerHelper("beam_search_decode", name=name)
    out_ids = helper.create_variable_for_type_inference("int64")
    out_scores = helper.create_variable_for_type_inference(scores.dtype)
    helper.append_op(
        type="beam_search_decode",
        inputs={"Ids": [ids], "Scores": [scores]},
        outputs={"SentenceIds": [out_ids],
                 "SentenceScores": [out_scores]},
        attrs={"beam_size": beam_size, "end_id": end_id})
    return out_ids, out_scores


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    idx, dist = _simple(
        "bipartite_match", {"DistMat": [dist_matrix]},
        {"match_type": match_type or "bipartite",
         "dist_threshold": dist_threshold or 0.5},
        out_slots=("ColToRowMatchIndices", "ColToRowMatchDist"))
    return idx, dist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0.0, name=None):
    inputs = {"X": [input], "MatchIndices": [matched_indices]}
    if negative_indices is not None:
        inputs["NegIndices"] = [negative_indices]
    out, w = _simple("target_assign", inputs,
                     {"mismatch_value": float(mismatch_value)},
                     out_slots=("Out", "OutWeight"), name=name)
    return out, w


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=None,
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=None, offset=0.5, name=None):
    boxes, vars_ = _simple(
        "prior_box", {"Input": [input], "Image": [image]},
        {"min_sizes": list(min_sizes), "max_sizes": list(max_sizes or []),
         "aspect_ratios": list(aspect_ratios or [1.0]),
         "variances": list(variance), "flip": flip, "clip": clip,
         "step_w": (steps or [0.0, 0.0])[0],
         "step_h": (steps or [0.0, 0.0])[1], "offset": offset},
        out_slots=("Boxes", "Variances"), name=name)
    return boxes, vars_


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None):
    return _simple("box_coder",
                   {"PriorBox": [prior_box], "PriorBoxVar": [prior_box_var],
                    "TargetBox": [target_box]},
                   {"code_type": code_type,
                    "box_normalized": box_normalized},
                   out_slots=("OutputBox",), name=name)


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=400,
                   keep_top_k=200, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    return _simple("multiclass_nms",
                   {"BBoxes": [bboxes], "Scores": [scores]},
                   {"score_threshold": score_threshold,
                    "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                    "nms_threshold": nms_threshold,
                    "normalized": normalized, "nms_eta": float(nms_eta),
                    "background_label": background_label}, name=name)


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01,
                     nms_eta=1.0):
    """Reference composition (layers/detection.py detection_output):
    softmax the raw class scores [N, M, C], decode predicted offsets
    against priors, transpose scores to [N, C, M], then multiclass
    NMS (nms_eta < 1 = adaptive threshold decay, detection.py:54)."""
    from .nn import softmax
    from .tensor import transpose
    probs = transpose(softmax(scores), perm=[0, 2, 1])
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    return multiclass_nms(decoded, probs,
                          score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold, nms_eta=nms_eta,
                          background_label=background_label)


def detection_map(detect_res, label, class_num=None, background_label=0,
                  overlap_threshold=0.5, evaluate_difficult=True,
                  has_state=None, input_states=None, out_states=None,
                  ap_version="11point", difficult=None):
    """VOC mAP over NMS-format detections (detection_map_op.cc).
    evaluate_difficult=False needs the per-GT `difficult` column input;
    ap_version: "11point" | "integral"."""
    if ap_version not in ("11point", "integral"):
        raise ValueError("detection_map: ap_version must be '11point' "
                         "or 'integral', got %r" % (ap_version,))
    if not evaluate_difficult and difficult is None:
        raise ValueError(
            "detection_map: evaluate_difficult=False needs the "
            "`difficult` ground-truth flag input")
    inputs = {"DetectRes": [detect_res], "Label": [label]}
    if difficult is not None:
        inputs["Difficult"] = [difficult]
    m, _ = _simple("detection_map", inputs,
                   {"overlap_threshold": overlap_threshold,
                    "ap_version": ap_version,
                    "evaluate_difficult": bool(evaluate_difficult),
                    "class_num": int(class_num or 0),
                    "background_label": background_label},
                   out_slots=("MAP", "AccumPosCount"))
    return m


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    helper = LayerHelper("create_parameter")
    attr = attr or ParamAttr(name=name)
    return helper.create_parameter(attr, list(shape), dtype,
                                   is_bias=is_bias,
                                   default_initializer=default_initializer)


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    from .learning_rate_scheduler import _decay_step_counter
    assert step == 1, "only step=1 counters are emitted"
    return _decay_step_counter(begin=begin)


def shrink_memory(x, i, table):
    return _simple("shrink_rnn_memory", {"X": [x]}, {}, dtype=x.dtype)


def reorder_lod_tensor_by_rank(x, rank_table):
    return _simple("reorder_lod_tensor_by_rank",
                   {"X": [x], "RankTable": [rank_table]}, {},
                   dtype=x.dtype)


# -- reader-layer API (layers/io.py) ---------------------------------------
# The reference's graph-reader ops (READER variables consumed by a `read`
# op) are HOST readers in this design (SURVEY §7: the data plane stays on
# the host; DeviceLoader overlaps the transfer). These aliases keep
# reference scripts working: each takes/returns a host reader callable.

def batch(reader, batch_size, drop_last=False):
    from ..reader import batch as _batch
    return _batch(reader, batch_size, drop_last=drop_last)


def shuffle(reader, buffer_size):
    from ..reader import shuffle as _shuffle
    return _shuffle(reader, buffer_size)


def double_buffer(reader, place=None, name=None):
    """Host-side prefetch decorator (create_double_buffer_reader_op
    capability; device-side overlap is reader.DeviceLoader)."""
    from ..reader import buffered
    return buffered(reader, 2)


def open_recordio_file(filename, shapes=None, lod_levels=None,
                       dtypes=None):
    """Host reader over the native chunked record format
    (create_recordio_file_reader_op capability)."""
    from .. import recordio
    return recordio.reader(filename)


def open_files(filenames, shapes=None, lod_levels=None, dtypes=None,
               thread_num=1, buffer_size=64, pass_num=1, **kwargs):
    """Multi-file threaded recordio ingestion (layers/io.py:360 +
    operators/reader/open_files_op.cc capability): returns a host
    reader-creator scanning the files with `thread_num` prefetch
    threads; shapes/lod_levels/dtypes are accepted for signature parity
    (samples carry their own shapes in the record codec). File-shard
    kwargs (shard_id/num_shards) pass through — the multi-host input
    path where each host reads its file subset."""
    from ..reader import open_files as _open_files
    return _open_files(filenames, thread_num=thread_num,
                       buffer_size=buffer_size, pass_num=pass_num,
                       **kwargs)


class ConditionalBlock:
    """`with ConditionalBlock([cond]).block(): ...` — ops built inside
    run only when cond holds (conditional_block_op.cc). Vars written in
    the block must have a pre-set default (the false branch keeps it)."""

    def __init__(self, inputs, is_scalar_condition=True, name=None):
        self.cond = inputs[0] if isinstance(inputs, (list, tuple)) \
            else inputs

    def block(self):
        from .control_flow import BlockGuard
        from ..core.program import default_main_program
        outer = self

        class _Guard(BlockGuard):
            def __init__(self):
                super().__init__(default_main_program())

            def __exit__(self, *exc):
                program = self.program
                sub_block = program.current_block()
                super().__exit__(*exc)
                if exc[0] is None:
                    # Only vars of OUTER blocks are conditional outputs;
                    # names created inside the sub-block are its private
                    # temps and die with it (conditional_block_op.cc: the
                    # op's Out is the parent-scope vars the block assigns).
                    written = sorted({n for o in sub_block.ops
                                      for ns in o.outputs.values()
                                      for n in ns} - set(sub_block.vars))
                    program.current_block().append_op(
                        type="conditional_block",
                        inputs={"Condition": [outer.cond]},
                        outputs={"Out": written},
                        attrs={"sub_block": sub_block,
                               "written_names": written})
                return False

        return _Guard()


def _num_priors_per_loc(min_sizes, max_sizes, aspect_ratios, flip):
    """Priors per feature-map cell — mirrors the prior_box lowering's
    aspect-ratio expansion (ops/detection_ops.py)."""
    ars = [1.0]
    for r in aspect_ratios or [1.0]:
        if all(abs(r - a) > 1e-6 for a in ars):
            ars.append(r)
            if flip:
                ars.append(1.0 / r)
    cnt = 0
    for k, ms in enumerate(min_sizes):
        for a in ars:
            cnt += 1
            if a == 1.0 and k < len(max_sizes or []):
                cnt += 1
    return cnt


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None):
    """SSD detection head over multiple feature maps (reference
    layers/detection.py multi_box_head): per input, generate priors and
    predict per-prior location offsets + class confidences with convs;
    concat across maps. Returns (mbox_locs [N, Np, 4],
    mbox_confs [N, Np, C], boxes [Np, 4], variances [Np, 4])."""
    import math
    from .conv_layers import conv2d
    from .tensor import concat, reshape, transpose

    if not isinstance(inputs, (list, tuple)):
        raise ValueError("inputs should be a list or tuple")
    num_layer = len(inputs)
    if num_layer <= 2:
        assert min_sizes is not None and max_sizes is not None
    elif min_sizes is None and max_sizes is None:
        min_sizes, max_sizes = [], []
        step = int(math.floor((max_ratio - min_ratio) / (num_layer - 2)))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.10] + min_sizes
        max_sizes = [base_size * 0.20] + max_sizes
    if steps:
        step_w = step_h = steps

    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, feat in enumerate(inputs):
        ms = min_sizes[i]
        ms = ms if isinstance(ms, (list, tuple)) else [ms]
        mx = (max_sizes or [None] * num_layer)[i]
        mx = [] if mx is None else \
            (mx if isinstance(mx, (list, tuple)) else [mx])
        ar = (aspect_ratios or [None] * num_layer)[i]
        ar = [1.0] if ar is None else \
            (list(ar) if isinstance(ar, (list, tuple)) else [ar])
        box, var = prior_box(
            feat, image, ms, mx, ar, variance=variance, flip=flip,
            clip=clip,
            steps=[(step_w[i] if step_w else 0.0),
                   (step_h[i] if step_h else 0.0)], offset=offset)
        boxes_all.append(reshape(box, [-1, 4]))
        vars_all.append(reshape(var, [-1, 4]))
        p = _num_priors_per_loc(ms, mx, ar, flip)

        loc = conv2d(feat, num_filters=p * 4,
                     filter_size=kernel_size, padding=pad,
                     stride=stride)
        loc = transpose(loc, perm=[0, 2, 3, 1])
        locs.append(reshape(loc, [0, -1, 4]))
        cf = conv2d(feat, num_filters=p * num_classes,
                    filter_size=kernel_size, padding=pad,
                    stride=stride)
        cf = transpose(cf, perm=[0, 2, 3, 1])
        confs.append(reshape(cf, [0, -1, num_classes]))

    mbox_locs = locs[0] if len(locs) == 1 else concat(locs, axis=1)
    mbox_confs = confs[0] if len(confs) == 1 else concat(confs, axis=1)
    boxes = boxes_all[0] if len(boxes_all) == 1 else \
        concat(boxes_all, axis=0)
    variances = vars_all[0] if len(vars_all) == 1 else \
        concat(vars_all, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0,
             overlap_threshold=0.5, neg_pos_ratio=3.0, neg_overlap=0.5,
             loc_loss_weight=1.0, conf_loss_weight=1.0,
             match_type="per_prediction", mining_type="max_negative",
             normalize=True, sample_size=None, name=None):
    """SSD multibox loss (reference layers/detection.py ssd_loss): IoU
    matching + hard negative mining + softmax CE + smooth-l1, fused into
    one batch-aware op (ops/detection_ops.py `ssd_loss`). location
    [N, Np, 4], confidence [N, Np, C], gt_box/gt_label flat LoD
    ([Ng, 4]/[Ng, 1]). Returns the per-image weighted loss [N, 1]."""
    if mining_type != "max_negative":
        raise ValueError("Only mining_type == 'max_negative' is "
                         "supported (reference parity)")
    helper = LayerHelper("ssd_loss", name=name)
    out = helper.create_variable_for_type_inference(
        "float32", shape=(location.shape[0], 1))
    inputs = {"Loc": [location], "Conf": [confidence],
              "GTBox": [gt_box], "GTLabel": [gt_label],
              "PriorBox": [prior_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(
        type="ssd_loss", inputs=inputs, outputs={"Loss": [out]},
        attrs={"background_label": int(background_label),
               "overlap_threshold": float(overlap_threshold),
               "neg_pos_ratio": float(neg_pos_ratio),
               "neg_overlap": float(neg_overlap),
               "loc_loss_weight": float(loc_loss_weight),
               "conf_loss_weight": float(conf_loss_weight),
               "match_type": match_type, "normalize": bool(normalize)})
    return out
