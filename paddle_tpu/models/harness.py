"""Shared analyzer entry-point harness for the model zoo.

``program_entry(build_fn, feed_fn)`` stages a model exactly the way the
Executor would run it — build the Program, run startup init, extract
state, and return the pure ``step(state, feeds, key)`` the jit would
compile — so paddle_tpu.analysis lints the real training/inference
graph, not a simplified stand-in. Each models/* module wraps this in a
small ``analysis_entry()`` so the zoo registry (models/__init__.ZOO)
can enumerate every workload.
"""

import numpy as np


def program_entry(build_fn, feed_fn, seed=0):
    """(fn, example_args) for the analyzer.

    build_fn() -> fetch Variables (called under fresh program guards);
    feed_fn(rng) -> feed dict (arrays or LoDTensors).
    """
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.core import executor as core_exec

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        fetch_vars = build_fn()
        if not isinstance(fetch_vars, (tuple, list)):
            fetch_vars = (fetch_vars,)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
    state = {n: np.asarray(scope.find_var(n))
             for n in scope.local_var_names()
             if scope.find_var(n) is not None}
    feeds = feed_fn(np.random.RandomState(seed))
    feed_arrays, static_info = core_exec._normalize_feeds(feeds)
    fn = exe._build(main, tuple(sorted(feed_arrays)),
                    tuple(v.name for v in fetch_vars),
                    tuple(sorted(state)), static_info=static_info)
    return fn, (state, feed_arrays, jax.random.key(seed))
