"""SPMD parallel execution over a TPU device mesh.

Replaces the reference's multi-device stack (SURVEY.md §2.7):
  * ParallelExecutor + NCCL allreduce op-handles
    (parallel_executor.cc:54-203, nccl_all_reduce_op_handle.cc) →
    ``ParallelExecutor`` here: the SAME traced step function jitted with
    sharded inputs over a ``jax.sharding.Mesh``; XLA inserts the gradient
    all-reduces (and overlaps them with compute, which the reference's
    per-grad NCCL insertion approximated by hand).
  * NCCLContextMap / ncclCommInitAll → the Mesh itself (ICI topology).
  * BCastParamsToGPUs → replicated device_put of the initial state.
  * parallel_do / MultiGradientMachine → the dp axis of the mesh.
Beyond the reference (required for TPU scale): tensor/pipeline/sequence/
expert parallelism via sharding hints + shard_map collectives (see ring.py,
pipeline.py, moe.py).
"""

from .mesh import (  # noqa: F401
    make_mesh, default_mesh, set_default_mesh, shard, sharding_hint,
    DistributedStrategy,
)
from .executor import ParallelExecutor  # noqa: F401
from . import collective  # noqa: F401
from .ring import ring_attention, ulysses_attention  # noqa: F401
from .pipeline import gpipe, gpipe_interleaved  # noqa: F401
from .moe import moe_ffn, top1_gating, topk_gating  # noqa: F401
