"""R001 dtype-promotion audit.

Verifies the bf16 serving invariants statically (the contract the bf16
KV-cache work established at runtime: weights/caches bf16, softmax
normalizers + LN statistics f32) and rejects fp16, which the serving
path hand-rejects per model (TransformerInfer._cast_params) — here the
rejection happens before any model-specific code runs.
"""

import numpy as np
import jax.numpy as jnp

from ..diagnostics import Diagnostic, ERROR, WARNING
from ..engine import Rule, register_rule

_F16 = np.dtype(np.float16)
_BF16 = jnp.bfloat16
_F32 = np.dtype(np.float32)

# eqns after which an upcast result plausibly needs f32 (accumulation /
# contraction); upcasts feeding ONLY these stay un-flagged
_ACCUMULATING = {
    "dot_general", "conv_general_dilated", "reduce_sum", "reduce_max",
    "reduce_min", "reduce_prod", "cumsum", "scan", "while", "cond",
    "pjit", "custom_vjp_call", "custom_jvp_call", "shard_map", "sort",
    "reduce_precision", "argmax", "argmin",
}


def _is_dtype(aval, dt):
    try:
        return np.dtype(aval.dtype) == np.dtype(dt)
    except TypeError:
        return False   # extended dtypes (PRNG keys)


@register_rule
class DtypePromotionRule(Rule):
    name = "dtype-promotion"
    id = "R001"
    doc = ("fp16 creep (error), bf16 softmax/reduction accumulators "
           "(error/warning), and bf16->f32 upcasts that feed no "
           "accumulation (warning)")

    def __init__(self, upcast_min_elems=4096):
        self.upcast_min_elems = upcast_min_elems

    def check(self, a):
        for var in a.closed_jaxpr.jaxpr.invars:
            if hasattr(var, "aval") and _is_dtype(var.aval, _F16):
                yield Diagnostic(
                    self.name, ERROR,
                    "float16 input %s: fp16 is rejected on the serving "
                    "path (5-bit exponent degrades LN/softmax stats)"
                    % a.label(var),
                    hint="cast parameters to bfloat16 or float32")
        for view, eqn in a.iter_eqns():
            prim = eqn.primitive.name
            out_avals = [v.aval for v in eqn.outvars
                         if hasattr(v, "aval")]
            if any(_is_dtype(av, _F16) for av in out_avals):
                yield Diagnostic(
                    self.name, ERROR,
                    "float16 value produced by %r" % prim,
                    path=view.eqn_path(eqn),
                    hint="use bfloat16 (same exponent range as f32) "
                         "for reduced-precision compute on TPU")
                continue
            in_avals = [v.aval for v in eqn.invars if hasattr(v, "aval")]
            if prim == "exp" and in_avals \
                    and _is_dtype(in_avals[0], _BF16):
                # a bf16 exp is (in every graph we ship) a softmax /
                # logsumexp numerator about to be sum-reduced: its
                # normalizer then accumulates in bf16 (8-bit mantissa)
                yield Diagnostic(
                    self.name, ERROR,
                    "exp over bfloat16 — softmax/logsumexp normalizer "
                    "accumulates in bf16",
                    path=view.eqn_path(eqn),
                    hint="cast scores to float32 before exp (the bf16 "
                         "KV-cache serving contract keeps softmax "
                         "stats f32)")
                continue
            if prim == "reduce_sum" and in_avals and out_avals \
                    and _is_dtype(in_avals[0], _BF16) \
                    and _is_dtype(out_avals[0], _BF16):
                yield Diagnostic(
                    self.name, WARNING,
                    "bf16 reduce_sum accumulates in bf16 over %s "
                    "elements" % int(np.prod(in_avals[0].shape)),
                    path=view.eqn_path(eqn),
                    hint="upcast to f32 before the reduction (LN/"
                         "softmax statistics must be f32 in bf16 "
                         "serving mode)")
                continue
            if prim == "convert_element_type" and in_avals:
                src, dst = in_avals[0], eqn.outvars[0].aval
                if _is_dtype(src, _BF16) and _is_dtype(dst, _F32) \
                        and np.prod(src.shape) >= self.upcast_min_elems:
                    users = view.consumers.get(eqn.outvars[0], [])
                    if users and all(
                            u.primitive.name not in _ACCUMULATING
                            for u in users):
                        yield Diagnostic(
                            self.name, WARNING,
                            "bf16->f32 upcast of %s elements feeds "
                            "only non-accumulating ops (%s) — compute "
                            "could stay bf16"
                            % (int(np.prod(src.shape)),
                               ",".join(sorted({u.primitive.name
                                                for u in users}))),
                            path=view.eqn_path(eqn),
                            hint="drop the upcast or move it after "
                                 "the elementwise chain")
