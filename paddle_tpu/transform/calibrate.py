"""Autoparallel constant calibration (ISSUE 15 satellite, ROADMAP
direction-4 remainder): measure the two constants the planner's cost
model has carried as documented placeholders — per-chip matmul FLOP/s
and ring-collective bandwidth — and write a platform-stamped
``calib.json`` that ``plan_cost()`` loads through the
``autoparallel_calib`` flag. With the flag unset (or the record
unreadable) the placeholders stay in force, exactly as before: rankings
were always ordinal; a measured record makes the modeled seconds
absolute for THIS platform.

CLI: ``python -m paddle_tpu.transform --calibrate [--out calib.json]``.
A CPU-container record is committed as ``CALIB_r01.json`` (rankings
unchanged — same constants for every candidate); the owed chip round
re-runs it so plan costs become real seconds.
"""

import json
import os
import time

__all__ = ["run_calibration", "write_calibration", "load_calibration"]

SCHEMA = 1


def _time_best(fn, repeats):
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def run_calibration(matmul_n=1024, ring_elems=1 << 20, repeats=5):
    """Measure matmul FLOP/s and (multi-device only) ring all-reduce
    bandwidth on the current backend. Returns the calib record dict."""
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    n = int(matmul_n)
    a = jnp.ones((n, n), jnp.float32)
    b = jnp.ones((n, n), jnp.float32)
    mm = jax.jit(lambda x, y: x @ y)
    mm(a, b).block_until_ready()          # compile outside the clock
    best = _time_best(lambda: mm(a, b).block_until_ready(), repeats)
    measured_flops = 2.0 * n ** 3 / best

    devices = jax.device_count()
    ici_bps = None
    ring_note = "single device: ring collective not measurable"
    if devices >= 2:
        elems = int(ring_elems)
        xs = jnp.ones((devices, elems), jnp.float32)
        ar = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")
        ar(xs).block_until_ready()
        t = _time_best(lambda: ar(xs).block_until_ready(), repeats)
        # ring all-reduce moves 2(d-1)/d of the buffer per link
        vol = 2.0 * (devices - 1) / devices * elems * 4
        ici_bps = vol / t
        ring_note = ("ring all-reduce over %d %s device(s)"
                     % (devices, dev.platform))

    return {
        "schema": SCHEMA,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", ""),
        "devices": devices,
        "matmul_n": n,
        "matmul_best_s": best,
        "peak_flops": measured_flops,
        "ici_bps": ici_bps,
        "ring_note": ring_note,
        "generated_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                      time.gmtime()),
    }


def write_calibration(path, record=None):
    """Run (if needed) and atomically persist a calib record."""
    from ..io import write_json_atomic
    record = record if record is not None else run_calibration()
    write_json_atomic(path, record)
    return record


def load_calibration(path):
    """Read + validate one calib record; raises ValueError on a file
    that is not a calibration record (the planner falls back to
    placeholders on any failure, loudly)."""
    with open(path) as f:
        rec = json.load(f)
    if not isinstance(rec, dict) or "peak_flops" not in rec:
        raise ValueError("%s is not a calibration record "
                         "(no peak_flops stamp)" % (path,))
    if not (isinstance(rec["peak_flops"], (int, float))
            and rec["peak_flops"] > 0):
        raise ValueError("%s: peak_flops must be a positive number"
                         % (path,))
    ici = rec.get("ici_bps")
    if ici is not None and not (isinstance(ici, (int, float))
                                and ici > 0):
        raise ValueError("%s: ici_bps must be positive or null"
                         % (path,))
    return rec


def describe(record, path="?"):
    ici = record.get("ici_bps")
    return ("calibration %s [%s/%s, %d dev]: peak %.3e FLOP/s, ici %s"
            % (os.path.basename(str(path)), record.get("platform"),
               record.get("device_kind") or "-",
               record.get("devices", 0), record["peak_flops"],
               ("%.3e B/s" % ici) if ici else "placeholder"))
