"""RT02 verb-conformance: dispatch loops vs fault/retry tables + trace.

The wire protocol's request-verb universe is extracted structurally:
a DISPATCHER is any function comparing a variable named ``op`` against
>=3 distinct verb literals (``op == "SEND"`` / ``op in ("SEND",
"PUT")``), or a ``handle`` method with >=1 verb comparison that also
calls the rpc framing receive helpers (the pre-dispatch CHNK/EXIT
fast paths live there). Pure reply verbs (OK/VAL/ERR/MISS/NONE/TASK/
STLE/BADR) never reach server dispatch comparisons and are excluded,
so client-side reply checks don't pollute the universe.

Every dispatch verb must then be:

  * covered by ``resilience/faults._DEFAULT_OPS`` (the fault-injection
    verb table) unless its retry class is ``admin`` — ERROR names the
    missing table, so a new verb that forgets the chaos tier fails CI;
  * classified in ``resilience/retry.VERB_CLASSES`` as one of
    idempotent / round_tag / nonretryable / admin — the machine-
    readable form of the retry-idempotence contract the clients rely
    on — ERROR otherwise;
  * served by a trace-header-aware loop: the dispatcher's enclosing
    handler must consume the propagated span context
    (``want_ctx=True`` / ``_recv_frame_head`` / ``server_span``) —
    WARNING otherwise.

Stale table entries (a verb in either table that no dispatch loop
serves) are WARNINGs anchored at the table, so deleting a verb cleans
the tables too. Both tables are read by literal AST extraction — the
lint never imports the runtime.
"""

import ast
import re

from ..astscan import dotted_name, literal_str
from ..engine import (Finding, RuntimeRule, register_runtime_rule,
                      ERROR, WARNING)

__all__ = ["VerbConformanceRule"]

_VERB_RE = re.compile(r"^[A-Z]{2,5}$")

# reply-channel verbs: sent with _send_msg, never compared in a server
# dispatch loop ("FAIL" is BOTH a master request verb and a KV reply,
# so it stays in the universe when seen in a qualifying dispatcher)
REPLY_VERBS = frozenset({"OK", "VAL", "ERR", "MISS", "NONE", "TASK",
                         "STLE", "BADR"})

VALID_CLASSES = ("idempotent", "round_tag", "nonretryable", "admin")

_RECV_HELPERS = {"_recv_msg", "_recv_frame_head"}


def _own_nodes(fn):
    """ast.walk over ``fn`` excluding nested function/class bodies —
    comparisons belong to their innermost scope (the handler classes
    are nested inside server constructors)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _verb_comparisons(fn):
    """[(verb, line)] for ``op == "X"`` / ``op in ("X", "Y")`` in the
    function's own scope."""
    out = []
    for node in _own_nodes(fn):
        if not isinstance(node, ast.Compare) or len(node.ops) != 1:
            continue
        if not (isinstance(node.left, ast.Name)
                and node.left.id == "op"):
            continue
        cmp_node = node.comparators[0]
        if isinstance(node.ops[0], ast.Eq):
            v = literal_str(cmp_node)
            if v is not None and _VERB_RE.match(v):
                out.append((v, node.lineno))
        elif isinstance(node.ops[0], ast.In) and \
                isinstance(cmp_node, (ast.Tuple, ast.List, ast.Set)):
            for elt in cmp_node.elts:
                v = literal_str(elt)
                if v is not None and _VERB_RE.match(v):
                    out.append((v, node.lineno))
    return out


def _calls_recv_helper(fn):
    for node in _own_nodes(fn):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name and name.split(".")[-1] in _RECV_HELPERS:
                return True
    return False


def _call_tails(fn):
    """Bare tails of every call in the function's own scope."""
    out = set()
    for node in _own_nodes(fn):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name:
                out.add(name.split(".")[-1])
    return out


def _consumes_trace_ctx(fn):
    """The handler threads the propagated span context: passes
    ``want_ctx=True`` to _recv_msg, calls _recv_frame_head (which
    always yields ctx), or opens a server_span itself."""
    for node in _own_nodes(fn):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        tail = name.split(".")[-1] if name else None
        if tail == "_recv_frame_head" or tail == "server_span":
            return True
        if tail == "_recv_msg":
            for kw in node.keywords:
                if kw.arg == "want_ctx" and \
                        isinstance(kw.value, ast.Constant) and \
                        kw.value.value is True:
                    return True
    return False


def _extract_frozenset(sf, var):
    """Literal frozenset({...}) assigned to ``var`` at module level."""
    if sf is None:
        return None
    for stmt in sf.tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == var
                   for t in stmt.targets):
            continue
        val = stmt.value
        if isinstance(val, ast.Call) and \
                dotted_name(val.func) == "frozenset" and val.args:
            val = val.args[0]
        try:
            lit = ast.literal_eval(val)
        except ValueError:
            return None
        return frozenset(lit), stmt.lineno
    return None


def _extract_dict(sf, var):
    if sf is None:
        return None
    for stmt in sf.tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == var
                   for t in stmt.targets):
            continue
        try:
            lit = ast.literal_eval(stmt.value)
        except ValueError:
            return None
        if isinstance(lit, dict):
            return lit, stmt.lineno
    return None


class _Dispatcher:
    def __init__(self, sf, qualname, fn, verbs):
        self.sf = sf
        self.qualname = qualname
        self.fn = fn
        self.verbs = verbs   # {verb: first line}


def _all_scopes(sf):
    """Every function def in the file, any nesting depth, with its
    dotted qualname (e.g. ``VariableServer.serve.Handler.handle``)."""
    out = []

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                qual = prefix + child.name
                out.append((qual, child))
                visit(child, qual + ".")
            elif isinstance(child, ast.ClassDef):
                visit(child, prefix + child.name + ".")

    visit(sf.tree, "")
    return out


def _find_dispatchers(index):
    out = []
    for sf in index.iter_files():
        for qualname, fn in _all_scopes(sf):
            comps = _verb_comparisons(fn)
            if not comps:
                continue
            request_verbs = {v for v, _ in comps
                             if v not in REPLY_VERBS}
            qualifies = len(request_verbs) >= 3 or (
                fn.name == "handle" and request_verbs
                and _calls_recv_helper(fn))
            if not qualifies:
                continue
            verbs = {}
            for v, ln in comps:
                if v in REPLY_VERBS:
                    continue
                verbs.setdefault(v, ln)
            out.append(_Dispatcher(sf, qualname, fn, verbs))
    return out


@register_runtime_rule
class VerbConformanceRule(RuntimeRule):
    name = "verb-conformance"
    id = "RT02"
    doc = ("every RPC dispatch verb covered by faults._DEFAULT_OPS, "
           "classified in retry.VERB_CLASSES, and served by a "
           "trace-aware handler; stale table entries flagged")
    max_reports = 60

    def check(self, index):
        faults_sf = index.find("resilience/faults.py")
        retry_sf = index.find("resilience/retry.py")
        ops = _extract_frozenset(faults_sf, "_DEFAULT_OPS")
        classes = _extract_dict(retry_sf, "VERB_CLASSES")
        dispatchers = _find_dispatchers(index)
        if ops is None:
            anchor = faults_sf or (dispatchers[0].sf if dispatchers
                                   else None)
            if anchor is not None:
                yield Finding(
                    self.name, ERROR, anchor.path, 1,
                    "fault-injection verb table resilience/faults."
                    "_DEFAULT_OPS not found (literal frozenset "
                    "expected)")
            ops = (frozenset(), 1)
        if classes is None:
            anchor = retry_sf or (dispatchers[0].sf if dispatchers
                                  else None)
            if anchor is not None:
                yield Finding(
                    self.name, ERROR, anchor.path, 1,
                    "retry idempotence table resilience/retry."
                    "VERB_CLASSES not found (literal dict expected)")
            classes = ({}, 1)
        default_ops, ops_line = ops
        verb_classes, classes_line = classes

        served = {}
        for d in dispatchers:
            for v, ln in sorted(d.verbs.items()):
                served.setdefault(v, (d, ln))
                cls_val = verb_classes.get(v)
                if cls_val is None:
                    yield Finding(
                        self.name, ERROR, d.sf.path, ln,
                        "dispatch verb '%s' has no retry idempotence "
                        "class in resilience/retry.VERB_CLASSES" % v,
                        where=d.qualname,
                        hint="classify it: idempotent | round_tag | "
                             "nonretryable | admin")
                elif cls_val not in VALID_CLASSES:
                    yield Finding(
                        self.name, ERROR, d.sf.path, ln,
                        "dispatch verb '%s' has invalid retry class "
                        "%r (expected one of %s)"
                        % (v, cls_val, "/".join(VALID_CLASSES)),
                        where=d.qualname)
                if v not in default_ops and cls_val != "admin":
                    yield Finding(
                        self.name, ERROR, d.sf.path, ln,
                        "dispatch verb '%s' missing from resilience/"
                        "faults._DEFAULT_OPS — the chaos tier cannot "
                        "fault it" % v, where=d.qualname,
                        hint="add it to the _DEFAULT_OPS frozenset "
                             "(or classify it 'admin')")
            # trace-header reachability: the dispatcher consumes the
            # span context itself, or a ctx-aware ``handle`` in the
            # same file calls into it (the nested Handler classes)
            aware = _consumes_trace_ctx(d.fn)
            if not aware:
                for _q, fn in _all_scopes(d.sf):
                    if fn.name == "handle" and \
                            _consumes_trace_ctx(fn) and \
                            d.fn.name in _call_tails(fn):
                        aware = True
                        break
            if not aware:
                yield Finding(
                    self.name, WARNING, d.sf.path, d.fn.lineno,
                    "dispatch loop is not reachable by the trace "
                    "header path (no want_ctx=True / _recv_frame_head "
                    "/ server_span in the handler)",
                    where=d.qualname,
                    hint="thread the propagated span context through "
                         "the receive path")
        # stale table entries
        if faults_sf is not None:
            for v in sorted(default_ops - set(served)):
                yield Finding(
                    self.name, WARNING, faults_sf.path, ops_line,
                    "faults._DEFAULT_OPS covers verb '%s' that no "
                    "dispatch loop serves" % v,
                    hint="stale entry — delete it or wire the verb")
        if retry_sf is not None:
            for v in sorted(set(verb_classes) - set(served)):
                yield Finding(
                    self.name, WARNING, retry_sf.path, classes_line,
                    "retry.VERB_CLASSES classifies verb '%s' that no "
                    "dispatch loop serves" % v,
                    hint="stale entry — delete it or wire the verb")
