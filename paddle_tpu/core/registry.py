"""Op lowering registry.

This replaces the reference's kernel registry (paddle/fluid/framework/
op_registry.h:52-129 + per-device kernels): instead of CPU/CUDA kernel
functions selected at interpreter time, each op type registers a *lowering
rule* — a pure function from jax values to jax values — that the Executor's
tracer calls while staging the whole Program into one XLA computation.

An op therefore needs no per-device variants: XLA compiles the same lowering
for TPU and CPU. Pallas kernels slot in as lowering bodies for ops where XLA
fusion is insufficient (attention etc.).
"""

import jax.numpy as jnp


class OpInfo:
    def __init__(self, type, lower, infer_shape=None, stateful_rng=False,
                 host=False):
        self.type = type
        self.lower = lower            # fn(ctx, op) -> None (writes ctx env)
        self.infer_shape = infer_shape
        self.stateful_rng = stateful_rng  # consumes a PRNG key at trace time
        self.host = host  # does IO → program runs in eager-interpreter mode


_REGISTRY = {}


def register(type, lower=None, infer_shape=None, stateful_rng=False,
             host=False):
    """Register an op lowering. Usable as decorator or direct call."""
    def deco(fn):
        _REGISTRY[type] = OpInfo(type, fn, infer_shape, stateful_rng, host)
        return fn
    if lower is not None:
        return deco(lower)
    return deco


def is_host_op(type):
    info = _REGISTRY.get(type)
    return bool(info and info.host)


def lookup(type):
    return _REGISTRY.get(type)


def registered_ops():
    return sorted(_REGISTRY)


class LowerContext:
    """Environment handed to lowering rules during tracing.

    env maps var name -> jax value. Replaces the reference's ExecutionContext
    (scope lookup + device context); there is no device context because
    placement is XLA's job.
    """

    def __init__(self, env, rng_fn, is_test=False, executor=None, block=None,
                 mesh=None, static_info=None, fetch_names=()):
        self.env = env
        self._rng_fn = rng_fn      # () -> fresh jax PRNG key
        self.is_test = is_test
        self.executor = executor
        self.block = block
        self.mesh = mesh
        # trace-time constants derived from the feed (e.g. "<name>@MAXLEN"
        # bucketed max sequence length); part of the compile-cache key
        self.static_info = static_info or {}
        # what the caller will fetch — rematerialization regions consult
        # this so a fetched region output is exported instead of dropped
        self.fetch_names = tuple(fetch_names or ())

    # -- value access --------------------------------------------------------
    def get(self, name):
        if name not in self.env:
            raise KeyError("var %r not materialized at lowering time" % name)
        return self.env[name]

    def maybe_get(self, name, default=None):
        return self.env.get(name, default)

    def set(self, name, value):
        self.env[name] = value

    def in1(self, op, slot, default=None):
        names = op.input(slot)
        if not names:
            return default
        return self.get(names[0])

    def in_list(self, op, slot):
        return [self.get(n) for n in op.input(slot)]

    def out_name(self, op, slot):
        names = op.output(slot)
        return names[0] if names else None

    def set_out(self, op, slot, value):
        name = self.out_name(op, slot)
        if name is not None:
            self.env[name] = value

    def rng(self):
        return self._rng_fn()

    # -- helpers -------------------------------------------------------------
    @staticmethod
    def cast_like(x, ref):
        return jnp.asarray(x, dtype=ref.dtype)
