"""Transformer (encoder-decoder MT + decoder-only LM).

Reference parity: tests/unittests/transformer_model.py:41 (multi_head_
attention, positionwise FFN, pre/post-process wrappers, encoder/decoder,
sinusoid position encoding) and nets.py:168 scaled_dot_product_attention.

TPU-first: dense padded [B, T] batches with in-graph masks (no LoD), all
attention math as batched matmuls on the MXU; bf16-friendly. This is the
flagship perf model (BASELINE.json north star: Transformer tokens/sec/chip).
"""

import contextlib

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def position_encoding_init(n_position, d_model):
    """Sinusoid position encoding table [n_position, d_model]."""
    pos = np.arange(n_position)[:, None].astype(np.float64)
    dim = np.arange(d_model)[None, :].astype(np.float64)
    angle = pos / np.power(10000, 2 * (dim // 2) / d_model)
    enc = np.zeros((n_position, d_model), np.float32)
    enc[:, 0::2] = np.sin(angle[:, 0::2])
    enc[:, 1::2] = np.cos(angle[:, 1::2])
    return enc


def multi_head_attention(queries, keys, values, attn_bias, d_key, d_value,
                         d_model, n_head=1, dropout_rate=0.0,
                         causal=False):
    """queries/keys/values: [B, T, D]; attn_bias: [B, n_head, Tq, Tk] addend
    (−inf at masked positions) or None.

    `causal=True` with no bias and no attention dropout takes the FUSED
    path: the sp_attention op, whose local lowering is the Pallas flash
    kernel on TPU (ops/flash_attention.py) — no [T, T] score tensor in
    HBM. Arbitrary biases keep the composed matmul+softmax form."""
    q = layers.fc(queries, d_key * n_head, num_flatten_dims=2,
                  bias_attr=False)
    k = layers.fc(keys, d_key * n_head, num_flatten_dims=2, bias_attr=False)
    v = layers.fc(values, d_value * n_head, num_flatten_dims=2,
                  bias_attr=False)

    def split_heads(x, d):
        b, t = x.shape[0], x.shape[1]
        x = layers.reshape(x, [b, t, n_head, d])
        return layers.transpose(x, perm=[0, 2, 1, 3])     # [B, H, T, d]

    q = split_heads(q, d_key)
    k = split_heads(k, d_key)
    v = split_heads(v, d_value)

    if causal and attn_bias is None and not dropout_rate:
        ctx = layers.sequence_parallel_attention(q, k, v, causal=True)
    else:
        if causal:
            # fused-path preconditions not met (dropout/bias): the
            # composed form must still mask the future. The T^2 constant
            # is created once per (block, T) and shared by every layer
            # instead of materializing a fresh triu per call.
            t = q.shape[2]
            blk = q.block
            cname = "causal_bias_%d" % t
            if blk.has_var(cname):
                tri_var = blk.var(cname)
            else:
                tri = np.triu(np.ones((t, t), np.float32), k=1) * -1e9
                tri_var = blk.create_var(name=cname, shape=(1, 1, t, t),
                                         dtype="float32")
                blk.append_op(
                    "assign_value", {}, {"Out": [cname]},
                    {"shape": [1, 1, t, t], "dtype": "float32",
                     # ndarray attr (serialized natively) — a .tolist()
                     # would box T^2 python floats
                     "values": tri.reshape(1, 1, t, t)})
            attn_bias = tri_var if attn_bias is None else \
                layers.elementwise_add(attn_bias, tri_var)
        product = layers.matmul(layers.scale(q, d_key ** -0.5), k,
                                transpose_y=True)         # [B, H, Tq, Tk]
        if attn_bias is not None:
            product = layers.elementwise_add(product, attn_bias)
        weights = layers.softmax(product)
        if dropout_rate:
            weights = layers.dropout(weights, dropout_prob=dropout_rate)
        ctx = layers.matmul(weights, v)                   # [B, H, Tq, dv]
    ctx = layers.transpose(ctx, perm=[0, 2, 1, 3])
    b, t = ctx.shape[0], ctx.shape[1]
    ctx = layers.reshape(ctx, [b, t, n_head * d_value])
    return layers.fc(ctx, d_model, num_flatten_dims=2, bias_attr=False)


def positionwise_feed_forward(x, d_inner, d_model):
    hidden = layers.fc(x, d_inner, num_flatten_dims=2, act="relu")
    return layers.fc(hidden, d_model, num_flatten_dims=2)


def pre_post_process_layer(prev, out, process_cmd, dropout_rate=0.0):
    """'a' residual-add, 'n' layernorm, 'd' dropout (transformer_model.py
    pre_post_process_layer parity)."""
    for cmd in process_cmd:
        if cmd == "a":
            out = layers.elementwise_add(out, prev) if prev is not None \
                else out
        elif cmd == "n":
            out = layers.layer_norm(out, begin_norm_axis=len(out.shape) - 1)
        elif cmd == "d":
            if dropout_rate:
                out = layers.dropout(out, dropout_prob=dropout_rate)
    return out


def encoder_layer(x, attn_bias, n_head, d_key, d_value, d_model, d_inner,
                  dropout_rate=0.0):
    attn = multi_head_attention(x, x, x, attn_bias, d_key, d_value, d_model,
                                n_head, dropout_rate)
    attn_out = pre_post_process_layer(x, attn, "dan", dropout_rate)
    ffn = positionwise_feed_forward(attn_out, d_inner, d_model)
    return pre_post_process_layer(attn_out, ffn, "dan", dropout_rate)


def decoder_layer(x, enc_output, slf_attn_bias, dec_enc_attn_bias, n_head,
                  d_key, d_value, d_model, d_inner, dropout_rate=0.0,
                  causal=False):
    slf = multi_head_attention(x, x, x, slf_attn_bias, d_key, d_value,
                               d_model, n_head, dropout_rate,
                               causal=causal)
    slf_out = pre_post_process_layer(x, slf, "dan", dropout_rate)
    if enc_output is not None:
        cross = multi_head_attention(slf_out, enc_output, enc_output,
                                     dec_enc_attn_bias, d_key, d_value,
                                     d_model, n_head, dropout_rate)
        cross_out = pre_post_process_layer(slf_out, cross, "dan",
                                           dropout_rate)
    else:
        cross_out = slf_out
    ffn = positionwise_feed_forward(cross_out, d_inner, d_model)
    return pre_post_process_layer(cross_out, ffn, "dan", dropout_rate)


def _embed(tokens, vocab_size, d_model, max_len, pos_input, name):
    word = layers.embedding(
        tokens, size=[vocab_size, d_model],
        param_attr=fluid.ParamAttr(
            name=name + "_word_emb",
            initializer=fluid.initializer.Normal(0., d_model ** -0.5)))
    word = layers.scale(word, d_model ** 0.5)
    pos = layers.embedding(
        pos_input, size=[max_len, d_model],
        param_attr=fluid.ParamAttr(
            name=name + "_pos_emb", trainable=False,
            initializer=fluid.initializer.NumpyArrayInitializer(
                position_encoding_init(max_len, d_model))))
    return layers.elementwise_add(word, pos)


def make_attn_bias(mask_2d, n_head, causal=False, seq_len=None):
    """mask_2d: [B, T] 1/0 validity → additive bias [B, H, T, T]."""
    b, t = mask_2d.shape[0], mask_2d.shape[1]
    key_mask = layers.reshape(mask_2d, [b, 1, 1, t])
    # (mask-1)*1e9 : 0 where valid, -1e9 where padding.
    # scale(bias_after_scale=False) computes scale*(x+bias) → bias=-1.0
    bias = layers.scale(key_mask, 1e9, bias=-1.0, bias_after_scale=False)
    bias = layers.expand(bias, expand_times=[1, n_head, t, 1])
    if causal:
        tri = np.triu(np.ones((t, t), np.float32), k=1) * -1e9
        tri_var = layers.assign(tri.reshape(1, 1, t, t))
        bias = layers.elementwise_add(bias, tri_var)
    return bias


def transformer_lm(vocab_size=4096, max_len=256, n_layer=4, n_head=8,
                   d_model=512, d_inner=2048, dropout_rate=0.0,
                   label_smooth_eps=0.0, packed=False, recompute=False):
    """Decoder-only LM (flagship bench model). Feeds: src [B,T] int64,
    pos [B,T] int64, mask [B,T] float32, label [B,T] int64.
    Returns (avg_cost, logits).

    packed=True assumes full-length (packed) sequences — the standard LM
    pretraining layout — and drops the padding half of the attention bias
    so self-attention runs through the fused flash path; `mask` still
    weights the loss. recompute=True wraps each decoder layer in a
    layers.recompute() region (jax.checkpoint): layer activations are
    recomputed in the backward pass, trading ~1/3 extra forward FLOPs
    for activation memory — the long-context lever."""
    d_key = d_value = d_model // n_head
    src = layers.data("src", [max_len], dtype="int64")
    pos = layers.data("pos", [max_len], dtype="int64")
    mask = layers.data("mask", [max_len], dtype="float32")
    label = layers.data("label", [max_len], dtype="int64")

    x = _embed(src, vocab_size, d_model, max_len, pos, "lm")
    if dropout_rate:
        x = layers.dropout(x, dropout_prob=dropout_rate)
    bias = None if packed else make_attn_bias(mask, n_head, causal=True)
    for _ in range(n_layer):
        with layers.recompute() if recompute else contextlib.nullcontext():
            x = decoder_layer(x, None, bias, None, n_head, d_key, d_value,
                              d_model, d_inner, dropout_rate,
                              causal=packed)
    logits = layers.fc(x, vocab_size, num_flatten_dims=2, bias_attr=False)

    b, t = logits.shape[0], logits.shape[1]
    flat_logits = layers.reshape(logits, [-1, vocab_size])
    flat_label = layers.reshape(label, [-1, 1])
    if label_smooth_eps:
        smooth = layers.label_smooth(
            layers.one_hot(flat_label, vocab_size), epsilon=label_smooth_eps)
        cost = layers.softmax_with_cross_entropy(flat_logits, smooth,
                                                 soft_label=True)
    else:
        cost = layers.softmax_with_cross_entropy(flat_logits, flat_label)
    flat_mask = layers.reshape(mask, [-1, 1])
    masked = layers.elementwise_mul(cost, flat_mask)
    avg_cost = layers.reduce_sum(masked) / layers.reduce_sum(flat_mask)
    return avg_cost, logits


def transformer(src_vocab_size=4096, trg_vocab_size=4096, max_len=64,
                n_layer=2, n_head=8, d_model=256, d_inner=1024,
                dropout_rate=0.0, label_smooth_eps=0.0, packed=False):
    """Encoder-decoder MT model (machine_translation benchmark parity).
    Feeds: src_word, src_pos, src_mask, trg_word, trg_pos, trg_mask,
    lbl_word — all [B, T]. Returns (avg_cost, predictions).

    packed=True assumes full-length (packed) sequences: padding biases are
    dropped and decoder self-attention takes the fused flash path
    (causal in-kernel); `trg_mask` still weights the loss."""
    d_key = d_value = d_model // n_head
    src_word = layers.data("src_word", [max_len], dtype="int64")
    src_pos = layers.data("src_pos", [max_len], dtype="int64")
    src_mask = layers.data("src_mask", [max_len], dtype="float32")
    trg_word = layers.data("trg_word", [max_len], dtype="int64")
    trg_pos = layers.data("trg_pos", [max_len], dtype="int64")
    trg_mask = layers.data("trg_mask", [max_len], dtype="float32")
    lbl_word = layers.data("lbl_word", [max_len], dtype="int64")

    enc_in = _embed(src_word, src_vocab_size, d_model, max_len, src_pos,
                    "src")
    enc_bias = None if packed else make_attn_bias(src_mask, n_head)
    enc = enc_in
    for _ in range(n_layer):
        enc = encoder_layer(enc, enc_bias, n_head, d_key, d_value, d_model,
                            d_inner, dropout_rate)

    dec_in = _embed(trg_word, trg_vocab_size, d_model, max_len, trg_pos,
                    "trg")
    slf_bias = None if packed else make_attn_bias(trg_mask, n_head,
                                                  causal=True)
    # cross bias: queries = trg positions, keys = src positions (Tq == Tk
    # == max_len, so the plain key-padding bias applies verbatim)
    cross_bias = None if packed else make_attn_bias(src_mask, n_head)
    dec = dec_in
    for _ in range(n_layer):
        dec = decoder_layer(dec, enc, slf_bias, cross_bias, n_head, d_key,
                            d_value, d_model, d_inner, dropout_rate,
                            causal=packed)

    logits = layers.fc(dec, trg_vocab_size, num_flatten_dims=2,
                       bias_attr=False)
    flat_logits = layers.reshape(logits, [-1, trg_vocab_size])
    flat_label = layers.reshape(lbl_word, [-1, 1])
    if label_smooth_eps:
        smooth = layers.label_smooth(
            layers.one_hot(flat_label, trg_vocab_size),
            epsilon=label_smooth_eps)
        cost = layers.softmax_with_cross_entropy(flat_logits, smooth,
                                                 soft_label=True)
    else:
        cost = layers.softmax_with_cross_entropy(flat_logits, flat_label)
    flat_mask = layers.reshape(trg_mask, [-1, 1])
    masked = layers.elementwise_mul(cost, flat_mask)
    avg_cost = layers.reduce_sum(masked) / layers.reduce_sum(flat_mask)
    return avg_cost, logits


def transformer_lm_parallel(vocab_size=4096, max_len=256, n_layer=4,
                            n_head=8, d_model=512, d_inner=2048,
                            strategy=None, num_experts=0,
                            moe_aux_weight=0.01):
    """Flagship decoder-only LM wired to the parallel subsystem.

    strategy: parallel.DistributedStrategy (or None). The build adapts:
      * pp > 1  → layers.pipelined_decoder_stack (GPipe or interleaved
                  virtual stages per strategy.pp_schedule); composes
                  with tp (Megatron shards + psum inside the stage) and
                  sp (ring attention inside the stage)
      * sp > 1  → attention via layers.sequence_parallel_attention
                  (ring attention over the sp axis)
      * num_experts > 0 → FFN via layers.sparse_moe (ep axis); with
                  pp > 1 the MoE rides inside the pipeline stage body
                  (expert shards per stage, all-to-all over ep)
      * tp > 1  → Megatron-style sharding hints on attention/FFN weights
                  (col-shard in-proj, row-shard out-proj; GSPMD inserts
                  the allreduce)
    All paths are dense-math-identical off-mesh, so single-device loss
    equals the sharded loss (tested in test_parallel_integration.py).
    Feeds: src/pos/mask/label [B, max_len]. Returns (avg_cost, logits)."""
    from .. import parallel

    st = strategy or parallel.DistributedStrategy()
    d_key = d_value = d_model // n_head
    src = layers.data("src", [max_len], dtype="int64")
    pos = layers.data("pos", [max_len], dtype="int64")
    mask = layers.data("mask", [max_len], dtype="float32")
    label = layers.data("label", [max_len], dtype="int64")

    x = _embed(src, vocab_size, d_model, max_len, pos, "lmp")
    aux_losses = []

    if st.pp > 1:
        # pp x tp: Megatron col/row shards inside the stage body with one
        # psum per sublayer; pp x sp: ring attention over sp inside the
        # stage (ops/parallel_ops._decoder_layer_apply_tp); pp x ep: MoE
        # FFN with the expert all-to-all nested in the stage body
        # (per-stage expert placement — parallel/moe.moe_ffn_pp_sharded).
        # dp shards microbatches throughout. MoE routing is
        # per-microbatch per dp*ep token group, so M and the group count
        # are pinned STATICALLY from the strategy (the dense fallback
        # reproduces the exact routing — the dryrun parity contract).
        schedule = getattr(st, "pp_schedule", "gpipe") or "gpipe"
        kwargs = {}
        if num_experts > 0:
            # M = pp (not gpipe's 2*pp default): each microbatch must
            # still split into dp*ep token groups, and the smaller M
            # keeps that feasible at parity-test batch sizes. dp
            # resolves through the mesh/device count (effective_dp) so
            # a dp=None strategy bakes the SAME dp*ep granularity the
            # mesh will have, instead of tripping _pipeline_stack's
            # gate_groups validation with a misleading mismatch error.
            kwargs.update(
                num_experts=num_experts,
                moe_gate_groups=st.effective_dp() * st.ep,
                num_microbatches=st.pp)
        x = layers.pipelined_decoder_stack(
            x, n_layer, n_head, d_inner,
            schedule=schedule,
            virtual_stages=getattr(st, "pp_virtual_stages", 0),
            tp_shard=st.tp > 1, **kwargs)
        if num_experts > 0:
            x, pp_aux = x
            aux_losses.append(pp_aux)
    else:
        for _ in range(n_layer):
            x = _parallel_decoder_layer(x, n_head, d_key, d_value, d_model,
                                        d_inner, st, num_experts,
                                        aux_losses)
    logits = layers.fc(x, vocab_size, num_flatten_dims=2, bias_attr=False)

    flat_logits = layers.reshape(logits, [-1, vocab_size])
    flat_label = layers.reshape(label, [-1, 1])
    cost = layers.softmax_with_cross_entropy(flat_logits, flat_label)
    flat_mask = layers.reshape(mask, [-1, 1])
    masked = layers.elementwise_mul(cost, flat_mask)
    avg_cost = layers.reduce_sum(masked) / layers.reduce_sum(flat_mask)
    for aux in aux_losses:
        avg_cost = layers.elementwise_add(
            avg_cost, layers.scale(aux, moe_aux_weight))
    return avg_cost, logits


def _parallel_decoder_layer(x, n_head, d_key, d_value, d_model, d_inner,
                            st, num_experts, aux_losses):
    """One causal decoder layer routed through sp_attention + (optionally)
    MoE, with Megatron-style tp hints on explicitly-named weights:
    in-projections col-sharded, out-projections row-sharded — GSPMD derives
    the single allreduce per sublayer."""
    from ..core import unique_name
    from ..parallel import shard

    lid = unique_name.generate("pdl")

    def named_fc(inp, size, suffix, col_spec, act=None):
        name = "%s_%s.w_0" % (lid, suffix)
        out = layers.fc(inp, size, num_flatten_dims=2, bias_attr=False,
                        act=act,
                        param_attr=fluid.ParamAttr(name=name))
        if st.tp > 1:
            shard(name, *col_spec)
        return out

    b, t = x.shape[0], x.shape[1]
    q = named_fc(x, d_key * n_head, "q", (None, "tp"))
    k = named_fc(x, d_key * n_head, "k", (None, "tp"))
    v = named_fc(x, d_value * n_head, "v", (None, "tp"))

    def heads(z, d):
        z = layers.reshape(z, [b, t, n_head, d])
        return layers.transpose(z, perm=[0, 2, 1, 3])

    attn = layers.sequence_parallel_attention(
        heads(q, d_key), heads(k, d_key), heads(v, d_value), causal=True)
    attn = layers.transpose(attn, perm=[0, 2, 1, 3])
    attn = layers.reshape(attn, [b, t, n_head * d_value])
    o = named_fc(attn, d_model, "o", ("tp", None))
    x = layers.layer_norm(layers.elementwise_add(x, o),
                          begin_norm_axis=len(x.shape) - 1)

    if num_experts > 0:
        f, aux = layers.sparse_moe(x, num_experts, d_inner)
        aux_losses.append(aux)
    else:
        h = named_fc(x, d_inner, "ffn1", (None, "tp"), act="relu")
        f = named_fc(h, d_model, "ffn2", ("tp", None))
    return layers.layer_norm(layers.elementwise_add(x, f),
                             begin_norm_axis=len(x.shape) - 1)


def zoo_spec():
    """(build_fn, feed_fn): flagship decoder-only LM, SGD train step
    (the same tiny config the driver's entry() compiles)."""
    vocab, max_len = 256, 32

    def build():
        avg_cost, _ = transformer_lm(vocab_size=vocab, max_len=max_len,
                                     n_layer=2, n_head=4, d_model=64,
                                     d_inner=128)
        fluid.optimizer.SGD(learning_rate=1e-3).minimize(avg_cost)
        return (avg_cost,)

    def feeds(rng):
        return make_lm_batch(rng, 4, max_len, vocab)

    return build, feeds


def zoo_spec_moe():
    """(build_fn, feed_fn): MoE LM (sparse_moe FFN, dense fallback
    routing on one device)."""
    vocab, max_len = 256, 32

    def build():
        avg_cost, _ = transformer_lm_parallel(
            vocab_size=vocab, max_len=max_len, n_layer=2, n_head=4,
            d_model=64, d_inner=128, num_experts=2)
        return (avg_cost,)

    def feeds(rng):
        return make_lm_batch(rng, 4, max_len, vocab)

    return build, feeds


def zoo_spec_mt():
    """(build_fn, feed_fn): encoder-decoder MT model
    (machine_translation benchmark parity), SGD train step. The build
    derives BOTH the encoder self-attention bias and the decoder
    cross-attention bias from ``src_mask`` through identical
    make_attn_bias chains — the redundancy the transform tier's CSE
    pass is measured against (tests pin that this program shrinks)."""
    vocab, max_len = 64, 16

    def build():
        avg_cost, _ = transformer(
            src_vocab_size=vocab, trg_vocab_size=vocab,
            max_len=max_len, n_layer=1, n_head=2, d_model=32,
            d_inner=64)
        fluid.optimizer.SGD(learning_rate=1e-3).minimize(avg_cost)
        return (avg_cost,)

    def feeds(rng):
        src = make_lm_batch(rng, 2, max_len, vocab)
        trg = make_lm_batch(rng, 2, max_len, vocab)
        return {"src_word": src["src"], "src_pos": src["pos"],
                "src_mask": src["mask"], "trg_word": trg["src"],
                "trg_pos": trg["pos"], "trg_mask": trg["mask"],
                "lbl_word": trg["label"]}

    return build, feeds


def analysis_entry():
    """Static-analyzer entry: flagship decoder-only LM, SGD train step."""
    from .harness import program_entry
    return program_entry(*zoo_spec())


def analysis_entry_moe():
    """Static-analyzer entry: MoE LM — keeps the expert path
    lint-covered."""
    from .harness import program_entry
    return program_entry(*zoo_spec_moe())



def plan_entry():
    """Automatic-parallelism planner surface (transform/autoparallel):
    the tiny flagship LM with a STRATEGY-AWARE builder plus the
    structural facts the comm/bubble cost model sizes its terms from.
    ``build(strategy)`` routes through transformer_lm_parallel, so the
    planner's apply() instantiates the exact pp/tp/sp/ep composition
    the parity tests already pin against single-device math; build()
    with no strategy is the single-device pricing baseline."""
    vocab, max_len, n_layer, n_head = 256, 32, 2, 4
    d_model, d_inner, batch = 64, 128, 8

    def build(strategy=None):
        avg_cost, _ = transformer_lm_parallel(
            vocab_size=vocab, max_len=max_len, n_layer=n_layer,
            n_head=n_head, d_model=d_model, d_inner=d_inner,
            strategy=strategy)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(avg_cost)
        return (avg_cost,)

    def feeds(rng):
        return make_lm_batch(rng, batch, max_len, vocab)

    return {"build": build, "feeds": feeds, "batch": batch,
            "seq": max_len, "d_model": d_model, "n_layer": n_layer,
            "n_head": n_head, "d_inner": d_inner, "vocab": vocab,
            "num_experts": 0}


def make_lm_batch(rng, batch, max_len, vocab_size):
    """Synthetic LM batch (shifted-token next-token task)."""
    lens = rng.randint(max_len // 2, max_len + 1, size=batch)
    src = rng.randint(3, vocab_size, size=(batch, max_len))
    mask = (np.arange(max_len)[None, :] < lens[:, None]).astype(np.float32)
    src = (src * mask).astype(np.int64)
    label = np.roll(src, -1, axis=1)
    label[:, -1] = 0
    pos = np.tile(np.arange(max_len, dtype=np.int64), (batch, 1))
    return {"src": src, "pos": pos, "mask": mask, "label": label}
