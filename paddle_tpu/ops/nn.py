"""NN ops: softmax, dropout, normalization.

Reference parity: operators/{softmax,dropout,batch_norm,layer_norm,lrn,
maxout}_op.cc. batch_norm keeps running stats as persistable state threaded
through the step function (the reference mutates scope vars in-place;
functional state threading is the XLA equivalent).
"""

import jax
import jax.numpy as jnp

from ..core.registry import register


@register("softmax")
def _softmax(ctx, op):
    x = ctx.in1(op, "X")
    # AMP: exponentials/normalization in fp32, result back to input dtype
    xf = x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x
    ctx.set_out(op, "Out", jax.nn.softmax(xf, axis=-1).astype(x.dtype))


@register("log_softmax")
def _log_softmax(ctx, op):
    ctx.set_out(op, "Out", jax.nn.log_softmax(ctx.in1(op, "X"), axis=-1))


@register("sequence_softmax")
def _sequence_softmax(ctx, op):
    # softmax over each sequence segment; lengths come in via <X>@LOD
    x = ctx.in1(op, "X")
    lod_name = op.input("X")[0] + "@LOD"
    lengths = ctx.maybe_get(lod_name)
    if lengths is None:
        ctx.set_out(op, "Out", jax.nn.softmax(x.reshape(-1), axis=0).reshape(x.shape))
        return
    # segment softmax on flattened [T] data
    seg = _lengths_to_segments(lengths, x.shape[0])
    flat = x.reshape(x.shape[0])
    m = jax.ops.segment_max(flat, seg, num_segments=lengths.shape[0])
    e = jnp.exp(flat - m[seg])
    s = jax.ops.segment_sum(e, seg, num_segments=lengths.shape[0])
    ctx.set_out(op, "Out", (e / s[seg]).reshape(x.shape))


def _lengths_to_segments(lengths, total):
    ends = jnp.cumsum(lengths)
    return jnp.searchsorted(ends, jnp.arange(total), side="right")


@register("dropout", stateful_rng=True)
def _dropout(ctx, op):
    x = ctx.in1(op, "X")
    p = op.attr("dropout_prob", 0.5)
    is_test = op.attr("is_test", False) or ctx.is_test
    impl = op.attr("dropout_implementation", "downgrade_in_infer")
    if is_test or p == 0.0:
        # downgrade_in_infer scales at inference time (reference default)
        out = x * (1.0 - p) if (impl == "downgrade_in_infer" and p > 0.0) \
            else x
        ctx.set_out(op, "Out", out)
        ctx.set_out(op, "Mask", jnp.ones_like(x))
        return
    keep = 1.0 - p
    mask = jax.random.bernoulli(ctx.rng(), keep, x.shape).astype(x.dtype)
    ctx.set_out(op, "Mask", mask)
    if impl == "upscale_in_train":
        ctx.set_out(op, "Out", x * mask / keep)
    else:
        ctx.set_out(op, "Out", x * mask)


@register("batch_norm")
def _batch_norm(ctx, op):
    x = ctx.in1(op, "X")
    scale = ctx.in1(op, "Scale")
    bias = ctx.in1(op, "Bias")
    mean_in = ctx.in1(op, "Mean")
    var_in = ctx.in1(op, "Variance")
    eps = op.attr("epsilon", 1e-5)
    momentum = op.attr("momentum", 0.9)
    layout = op.attr("data_layout", "NCHW")
    is_test = op.attr("is_test", False) or ctx.is_test

    ch_axis = 1 if layout == "NCHW" and x.ndim > 1 else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    bshape = [1] * x.ndim
    bshape[ch_axis] = x.shape[ch_axis]

    # stats in fp32 regardless of activation dtype (bf16 under AMP): a
    # bf16 accumulation over B*H*W elements loses the mean entirely.
    # ONE fused pass computes E[x] and E[x^2] together (vs mean-then-var's
    # second centered pass) — BN is the HBM-bandwidth tax of ResNet
    # training (~1/3 of step time at bs256), so activation reads are
    # minimized: stats read x once, normalization reads it once more with
    # the per-channel affine pre-folded in x's own dtype.
    if is_test:
        mean, var = mean_in, var_in
    else:
        n = 1
        for a in reduce_axes:
            n *= x.shape[a]
        # shifted one-pass stats: center on the RUNNING mean so the
        # E[x^2]-E[x]^2 form never cancels catastrophically (with c near
        # the true mean, s2/n ~ var instead of var + mean^2). Exact for
        # any c: var = E[(x-c)^2] - (E[x-c])^2, mean = c + E[x-c].
        # A producing 1x1 conv may have ALREADY accumulated these sums
        # in its matmul epilogue (conv.py _maybe_conv1x1_bn_fused /
        # matmul_stats.py) — consume the stash and skip the extra read
        # of x entirely (the ResNet BN bandwidth tax).
        stash = ctx.env.pop(op.input("X")[0] + "@BNSTATS", None)
        if stash is not None:
            s1, s2 = stash
        else:
            # (Round-4 note: a raw-sum variant with the shift applied on
            # the [C] results measured NO faster on the real model — the
            # stat pass is structural XLA behavior with residual-block
            # consumers, not a artifact of this x - c form; see PERF.md
            # "ResNet conv+BN fusion probe".)
            xf = x.astype(jnp.float32)
            c = jax.lax.stop_gradient(mean_in.reshape(bshape)
                                      .astype(jnp.float32))
            xc = xf - c
            s1 = jnp.sum(xc, axis=reduce_axes)
            s2 = jnp.sum(jnp.square(xc), axis=reduce_axes)
        d1 = s1 / n
        mean = mean_in + d1
        var = jnp.maximum(s2 / n - jnp.square(d1), 0.0)
        new_mean = momentum * mean_in + (1 - momentum) * mean
        new_var = momentum * var_in + (1 - momentum) * var
        ctx.set_out(op, "MeanOut", new_mean)
        ctx.set_out(op, "VarianceOut", new_var)
        ctx.set_out(op, "SavedMean", mean)
        ctx.set_out(op, "SavedVariance", 1.0 / jnp.sqrt(var + eps))
        # MeanOut/VarianceOut alias Mean/Variance in the reference; keep the
        # state var updated under its own name too.
        min_names = op.input("Mean")
        vin_names = op.input("Variance")
        if min_names:
            ctx.env[min_names[0]] = jax.lax.stop_gradient(new_mean)
        if vin_names:
            ctx.env[vin_names[0]] = jax.lax.stop_gradient(new_var)

    # fold (mean, var, scale, bias) into one per-channel FMA applied in the
    # activation's own dtype: y = x * a + b — bf16 activations never make
    # an fp32 round-trip through HBM
    inv = jax.lax.rsqrt(var + eps)
    a = (scale * inv).astype(x.dtype)
    b = (bias - mean * scale * inv).astype(x.dtype)
    out = x * a.reshape(bshape) + b.reshape(bshape)
    ctx.set_out(op, "Y", out)


@register("layer_norm")
def _layer_norm(ctx, op):
    x = ctx.in1(op, "X")
    scale = ctx.in1(op, "Scale")
    bias = ctx.in1(op, "Bias")
    eps = op.attr("epsilon", 1e-5)
    begin = op.attr("begin_norm_axis", 1)
    axes = tuple(range(begin, x.ndim))
    xf = x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + eps)
    if scale is not None:
        out = out * scale.reshape((1,) * begin + x.shape[begin:])
    if bias is not None:
        out = out + bias.reshape((1,) * begin + x.shape[begin:])
    ctx.set_out(op, "Y", out.astype(x.dtype))
    ctx.set_out(op, "Mean", mean.reshape(x.shape[:begin]))
    ctx.set_out(op, "Variance", var.reshape(x.shape[:begin]))


@register("lrn")
def _lrn(ctx, op):
    x = ctx.in1(op, "X")                 # NCHW
    n = op.attr("n", 5)
    k = op.attr("k", 2.0)
    alpha = op.attr("alpha", 1e-4)
    beta = op.attr("beta", 0.75)
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(pad[:, i:i + x.shape[1]] for i in range(n))
    mid = k + alpha * acc
    ctx.set_out(op, "MidOut", mid)
    ctx.set_out(op, "Out", x / jnp.power(mid, beta))


@register("maxout")
def _maxout(ctx, op):
    x = ctx.in1(op, "X")                 # [N, C, H, W]
    groups = op.attr("groups")
    n, c, h, w = x.shape
    ctx.set_out(op, "Out",
                x.reshape(n, c // groups, groups, h, w).max(axis=2))


@register("im2sequence")
def _im2sequence(ctx, op):
    """Image → sequence of flattened patches (operators/im2sequence_op.cc)."""
    x = ctx.in1(op, "X")                 # [N, C, H, W]
    kh, kw = op.attr("kernels", [1, 1])
    sh, sw = op.attr("strides", [1, 1])
    pads = op.attr("paddings", [0, 0, 0, 0])
    x = jnp.pad(x, ((0, 0), (0, 0), (pads[0], pads[2]), (pads[1], pads[3])))
    n, c, h, w = x.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    # HIGHEST precision: pure data movement (a one-hot conv) — the TPU
    # default bf16 MXU pass would quantize the copied pixel values
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        precision=jax.lax.Precision.HIGHEST)          # [N, C*kh*kw, oh, ow]
    seq = patches.transpose(0, 2, 3, 1).reshape(n * oh * ow, c * kh * kw)
    ctx.set_out(op, "Out", seq)
