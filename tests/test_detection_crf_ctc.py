"""Detection, CRF, and CTC op tests vs numpy/torch references."""

import numpy as np
import pytest
import torch

import paddle_tpu as fluid


def _one_op(op_type, inputs, outputs, attrs, feeds, fetch, lods=None):
    # isolate each op in its own program so tests can chain _one_op calls
    main = fluid.Program()
    fluid.switch_main_program(main)
    blk = fluid.default_main_program().current_block()
    in_map = {}
    for slot, (name, shape, dtype) in inputs.items():
        v = fluid.layers.data(name, list(shape), dtype=dtype,
                              append_batch_size=False,
                              lod_level=1 if (lods and name in lods) else 0)
        in_map[slot] = [v]
    out_map = {}
    for slot, name in outputs.items():
        out_map[slot] = [blk.create_var(name=name, dtype="float32")]
    blk.append_op(type=op_type, inputs=in_map, outputs=out_map, attrs=attrs)
    exe = fluid.Executor(fluid.CPUPlace())
    return exe.run(feed=feeds, fetch_list=fetch)


def test_iou_similarity():
    x = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], np.float32)
    y = np.array([[0, 0, 2, 2], [2, 2, 4, 4]], np.float32)
    got, = _one_op("iou_similarity",
                   {"X": ("bx", (2, 4), "float32"),
                    "Y": ("by", (2, 4), "float32")},
                   {"Out": "iou_out"}, {},
                   {"bx": x, "by": y}, ["iou_out"])
    np.testing.assert_allclose(got[0, 0], 1.0, rtol=1e-6)
    np.testing.assert_allclose(got[0, 1], 0.0, atol=1e-6)
    np.testing.assert_allclose(got[1, 0], 1 / 7, rtol=1e-5)


def test_box_coder_encode_decode_roundtrip():
    rng = np.random.RandomState(0)
    prior = np.sort(rng.rand(5, 4).astype(np.float32), axis=1)
    var = np.full((5, 4), 0.1, np.float32)
    target = np.sort(rng.rand(3, 4).astype(np.float32), axis=1)
    enc, = _one_op("box_coder",
                   {"PriorBox": ("pb", (5, 4), "float32"),
                    "PriorBoxVar": ("pbv", (5, 4), "float32"),
                    "TargetBox": ("tb", (3, 4), "float32")},
                   {"OutputBox": "enc_out"},
                   {"code_type": "encode_center_size"},
                   {"pb": prior, "pbv": var, "tb": target}, ["enc_out"])
    assert enc.shape == (3, 5, 4)
    # decode back
    dec, = _one_op("box_coder",
                   {"PriorBox": ("pb2", (5, 4), "float32"),
                    "PriorBoxVar": ("pbv2", (5, 4), "float32"),
                    "TargetBox": ("tb2", (3, 5, 4), "float32")},
                   {"OutputBox": "dec_out"},
                   {"code_type": "decode_center_size"},
                   {"pb2": prior, "pbv2": var, "tb2": enc}, ["dec_out"])
    want = np.broadcast_to(target[:, None, :], (3, 5, 4))
    np.testing.assert_allclose(dec, want, rtol=1e-3, atol=1e-4)


def test_bipartite_match_greedy():
    dist = np.array([[0.9, 0.1, 0.3],
                     [0.8, 0.7, 0.2]], np.float32)
    idx, d = _one_op("bipartite_match",
                     {"DistMat": ("dm", (2, 3), "float32")},
                     {"ColToRowMatchIndices": "bm_idx",
                      "ColToRowMatchDist": "bm_dist"}, {},
                     {"dm": dist}, ["bm_idx", "bm_dist"])
    # greedy: (0,0)=0.9 first, then (1,1)=0.7
    np.testing.assert_array_equal(idx[0], [0, 1, -1])
    np.testing.assert_allclose(d[0], [0.9, 0.7, 0.0], rtol=1e-6)


def test_multiclass_nms_suppresses_overlaps():
    boxes = np.array([[[0, 0, 1, 1], [0, 0, 1.05, 1.05],
                       [2, 2, 3, 3]]], np.float32)        # [1, 3, 4]
    scores = np.zeros((1, 2, 3), np.float32)
    scores[0, 1] = [0.9, 0.8, 0.7]   # class 1
    out, = _one_op("multiclass_nms",
                   {"BBoxes": ("nb", (1, 3, 4), "float32"),
                    "Scores": ("ns", (1, 2, 3), "float32")},
                   {"Out": "nms_out"},
                   {"score_threshold": 0.1, "nms_threshold": 0.5,
                    "keep_top_k": 3, "background_label": 0},
                   {"nb": boxes, "ns": scores}, ["nms_out"])
    kept = out[0][out[0][:, 1] > 0]
    assert kept.shape[0] == 2          # overlap suppressed
    np.testing.assert_allclose(sorted(kept[:, 1]), [0.7, 0.9], rtol=1e-6)


def test_prior_box_counts():
    blk = fluid.default_main_program().current_block()
    feat = fluid.layers.data("feat", [8, 4, 4])
    img = fluid.layers.data("img", [3, 64, 64])
    boxes = blk.create_var(name="pb_boxes", dtype="float32")
    var = blk.create_var(name="pb_var", dtype="float32")
    blk.append_op(type="prior_box",
                  inputs={"Input": [feat], "Image": [img]},
                  outputs={"Boxes": [boxes], "Variances": [var]},
                  attrs={"min_sizes": [10.0], "max_sizes": [20.0],
                         "aspect_ratios": [2.0], "flip": True,
                         "clip": True})
    exe = fluid.Executor(fluid.CPUPlace())
    b, v = exe.run(feed={"feat": np.zeros((1, 8, 4, 4), np.float32),
                         "img": np.zeros((1, 3, 64, 64), np.float32)},
                   fetch_list=[boxes, var])
    # priors per cell: 1 (ar=1,min) + 1 (ar=1,max) + 2 (ar=2 flip) = 4
    assert b.shape == (4, 4, 4, 4)
    assert (b >= 0).all() and (b <= 1).all()


def test_linear_chain_crf_matches_brute_force():
    d, t = 3, 4
    rng = np.random.RandomState(0)
    emission = rng.randn(t, d).astype(np.float32)
    trans = rng.randn(d + 2, d).astype(np.float32) * 0.5
    label = rng.randint(0, d, (t, 1)).astype(np.int64)

    em = fluid.layers.data("em", [d], lod_level=1)
    lb = fluid.layers.data("lb", [1], dtype="int64", lod_level=1)
    tr = fluid.layers.data("tr", [d + 2, d], append_batch_size=False)
    blk = fluid.default_main_program().current_block()
    ll = blk.create_var(name="crf_ll", dtype="float32")
    alpha = blk.create_var(name="crf_alpha", dtype="float32")
    eexp = blk.create_var(name="crf_eexp", dtype="float32")
    texp = blk.create_var(name="crf_texp", dtype="float32")
    blk.append_op(type="linear_chain_crf",
                  inputs={"Emission": [em], "Label": [lb],
                          "Transition": [tr]},
                  outputs={"LogLikelihood": [ll], "Alpha": [alpha],
                           "EmissionExps": [eexp],
                           "TransitionExps": [texp]})
    exe = fluid.Executor(fluid.CPUPlace())
    em_t = fluid.create_lod_tensor(emission, [[t]])
    lb_t = fluid.create_lod_tensor(label, [[t]])
    got, = exe.run(feed={"em": em_t, "lb": lb_t, "tr": trans},
                   fetch_list=[ll])

    # brute force over all d^t paths
    import itertools
    w_start, w_end, w = trans[0], trans[1], trans[2:]

    def score(path):
        s = w_start[path[0]] + w_end[path[-1]]
        s += sum(emission[i, p] for i, p in enumerate(path))
        s += sum(w[path[i], path[i + 1]] for i in range(t - 1))
        return s

    scores = [score(p) for p in itertools.product(range(d), repeat=t)]
    log_z = np.log(np.sum(np.exp(scores)))
    gold = score(tuple(label.reshape(-1)))
    want = log_z - gold
    np.testing.assert_allclose(float(got.reshape(-1)[0]), want, rtol=1e-4)


def test_crf_decoding_matches_brute_force():
    d, t = 3, 4
    rng = np.random.RandomState(1)
    emission = rng.randn(t, d).astype(np.float32)
    trans = rng.randn(d + 2, d).astype(np.float32) * 0.5
    em = fluid.layers.data("em", [d], lod_level=1)
    tr = fluid.layers.data("tr", [d + 2, d], append_batch_size=False)
    blk = fluid.default_main_program().current_block()
    path = blk.create_var(name="vit_path", dtype="int64")
    blk.append_op(type="crf_decoding",
                  inputs={"Emission": [em], "Transition": [tr]},
                  outputs={"ViterbiPath": [path]})
    exe = fluid.Executor(fluid.CPUPlace())
    em_t = fluid.create_lod_tensor(emission, [[t]])
    got, = exe.run(feed={"em": em_t, "tr": trans}, fetch_list=[path])

    import itertools
    w_start, w_end, w = trans[0], trans[1], trans[2:]

    def score(p):
        s = w_start[p[0]] + w_end[p[-1]]
        s += sum(emission[i, q] for i, q in enumerate(p))
        s += sum(w[p[i], p[i + 1]] for i in range(t - 1))
        return s

    best = max(itertools.product(range(d), repeat=t), key=score)
    np.testing.assert_array_equal(got.reshape(-1)[:t], list(best))


def test_warpctc_matches_torch():
    b, t, c, l = 2, 6, 5, 2
    rng = np.random.RandomState(2)
    logits = rng.randn(b * t, c).astype(np.float32)
    labels = rng.randint(1, c, (b * l, 1)).astype(np.int64)
    lg = fluid.layers.data("lg", [c], lod_level=1)
    lb = fluid.layers.data("lb", [1], dtype="int64", lod_level=1)
    blk = fluid.default_main_program().current_block()
    loss = blk.create_var(name="ctc_loss", dtype="float32")
    grad = blk.create_var(name="ctc_grad", dtype="float32")
    blk.append_op(type="warpctc",
                  inputs={"Logits": [lg], "Label": [lb]},
                  outputs={"Loss": [loss], "WarpCTCGrad": [grad]},
                  attrs={"blank": 0})
    exe = fluid.Executor(fluid.CPUPlace())
    lg_t = fluid.create_lod_tensor(logits, [[t, t]])
    lb_t = fluid.create_lod_tensor(labels, [[l, l]])
    got, = exe.run(feed={"lg": lg_t, "lb": lb_t}, fetch_list=[loss])

    tl = torch.from_numpy(logits.reshape(b, t, c).transpose(1, 0, 2))
    tl = torch.log_softmax(tl, dim=-1)
    want = torch.nn.functional.ctc_loss(
        tl, torch.from_numpy(labels.reshape(b, l)),
        torch.full((b,), t, dtype=torch.long),
        torch.full((b,), l, dtype=torch.long),
        blank=0, reduction="none").numpy()
    np.testing.assert_allclose(got.reshape(-1), want, rtol=1e-4)


def test_ctc_align():
    x = np.array([[0], [1], [1], [0], [2], [2], [0]], np.int32)
    xv = fluid.layers.data("x", [1], dtype="int32", lod_level=1)
    blk = fluid.default_main_program().current_block()
    out = blk.create_var(name="align_out", dtype="int64")
    blk.append_op(type="ctc_align", inputs={"Input": [xv]},
                  outputs={"Output": [out]},
                  attrs={"blank": 0, "merge_repeated": True})
    exe = fluid.Executor(fluid.CPUPlace())
    t = fluid.create_lod_tensor(x, [[7]])
    got, = exe.run(feed={"x": t}, fetch_list=[out])
    np.testing.assert_array_equal(got.reshape(-1)[:2], [1, 2])


def test_chunk_eval_iob():
    # IOB, 1 type: B=0, I=1, O=2
    # label:  B I O B I   → chunks (0-1), (3-4)
    # infer:  B I O B O   → chunks (0-1), (3-3)
    lab = np.array([[0], [1], [2], [0], [1]], np.int64)
    inf = np.array([[0], [1], [2], [0], [2]], np.int64)
    iv = fluid.layers.data("iv", [1], dtype="int64", lod_level=1)
    lv = fluid.layers.data("lv", [1], dtype="int64", lod_level=1)
    blk = fluid.default_main_program().current_block()
    outs = {k: blk.create_var(name="ce_%s" % k, dtype="float32")
            for k in ["p", "r", "f", "ni", "nl", "nc"]}
    blk.append_op(type="chunk_eval",
                  inputs={"Inference": [iv], "Label": [lv]},
                  outputs={"Precision": [outs["p"]], "Recall": [outs["r"]],
                           "F1-Score": [outs["f"]],
                           "NumInferChunks": [outs["ni"]],
                           "NumLabelChunks": [outs["nl"]],
                           "NumCorrectChunks": [outs["nc"]]},
                  attrs={"num_chunk_types": 1, "chunk_scheme": "IOB"})
    exe = fluid.Executor(fluid.CPUPlace())
    got = exe.run(feed={"iv": fluid.create_lod_tensor(inf, [[5]]),
                        "lv": fluid.create_lod_tensor(lab, [[5]])},
                  fetch_list=[outs["ni"], outs["nl"], outs["nc"]])
    ni, nl, nc = [int(np.asarray(g).reshape(-1)[0]) for g in got]
    assert ni == 2 and nl == 2 and nc == 1


def test_multiclass_nms_adaptive_eta_tightens_threshold():
    """nms_eta < 1 (detection.py:54 / multiclass_nms_op.cc NMSFast):
    the overlap threshold decays after each kept box, so a box that
    SURVIVES plain NMS is suppressed under adaptive NMS."""
    # three boxes: A (top score), B overlaps A with IoU ~0.55, C far
    boxes = np.array([[[0, 0, 1, 1], [0, 0.3, 1, 1.42],
                       [2, 2, 3, 3]]], np.float32)
    scores = np.zeros((1, 2, 3), np.float32)
    scores[0, 1] = [0.9, 0.8, 0.7]

    def run(eta, tag):
        out, = _one_op("multiclass_nms",
                       {"BBoxes": (tag + "b", (1, 3, 4), "float32"),
                        "Scores": (tag + "s", (1, 2, 3), "float32")},
                       {"Out": tag + "o"},
                       {"score_threshold": 0.1, "nms_threshold": 0.6,
                        "keep_top_k": 3, "background_label": 0,
                        "nms_eta": eta},
                       {tag + "b": boxes, tag + "s": scores},
                       [tag + "o"])
        return out[0][out[0][:, 1] > 0]

    # IoU(A,B) ~ 0.52 < 0.6: plain NMS keeps all three
    assert run(1.0, "p").shape[0] == 3
    # eta=0.8: after keeping A the threshold drops to 0.48 < 0.52 -> B
    # is suppressed; C (far away) still kept
    kept = run(0.8, "a")
    assert kept.shape[0] == 2
    np.testing.assert_allclose(sorted(kept[:, 1]), [0.7, 0.9], rtol=1e-6)
