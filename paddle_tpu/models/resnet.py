"""ResNet for CIFAR-10 and ImageNet (reference benchmark/fluid/resnet.py
capabilities, re-built with the TPU-first layers).

The north-star perf model (SURVEY.md §6): ResNet-50 images/sec/chip.
"""

import paddle_tpu as fluid


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu"):
    conv = fluid.layers.conv2d(input, num_filters=ch_out,
                               filter_size=filter_size, stride=stride,
                               padding=padding, act=None, bias_attr=False)
    return fluid.layers.batch_norm(conv, act=act)


def shortcut(input, ch_out, stride):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, 0, act=None)
    return input


def basicblock(input, ch_out, stride):
    short = shortcut(input, ch_out, stride)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None)
    return fluid.layers.elementwise_add(short, conv2, act="relu")


def bottleneck(input, ch_out, stride):
    short = shortcut(input, ch_out * 4, stride)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None)
    return fluid.layers.elementwise_add(short, conv3, act="relu")


def layer_warp(block_func, input, ch_out, count, stride):
    res = block_func(input, ch_out, stride)
    for _ in range(1, count):
        res = block_func(res, ch_out, 1)
    return res


def resnet_cifar10(input, depth=32, num_classes=10):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input, ch_out=16, filter_size=3, stride=1,
                          padding=1)
    res1 = layer_warp(basicblock, conv1, 16, n, 1)
    res2 = layer_warp(basicblock, res1, 32, n, 2)
    res3 = layer_warp(basicblock, res2, 64, n, 2)
    pool = fluid.layers.pool2d(res3, pool_type="avg", global_pooling=True)
    return fluid.layers.fc(pool, num_classes, act="softmax")


def resnet_imagenet(input, depth=50, num_classes=1000):
    cfg = {18: ([2, 2, 2, 1], basicblock),
           34: ([3, 4, 6, 3], basicblock),
           50: ([3, 4, 6, 3], bottleneck),
           101: ([3, 4, 23, 3], bottleneck),
           152: ([3, 8, 36, 3], bottleneck)}
    stages, block_func = cfg[depth]
    conv1 = conv_bn_layer(input, ch_out=64, filter_size=7, stride=2,
                          padding=3)
    pool1 = fluid.layers.pool2d(conv1, pool_size=3, pool_stride=2,
                                pool_padding=1)
    res1 = layer_warp(block_func, pool1, 64, stages[0], 1)
    res2 = layer_warp(block_func, res1, 128, stages[1], 2)
    res3 = layer_warp(block_func, res2, 256, stages[2], 2)
    res4 = layer_warp(block_func, res3, 512, stages[3], 2)
    pool2 = fluid.layers.pool2d(res4, pool_type="avg", global_pooling=True)
    return fluid.layers.fc(pool2, num_classes, act="softmax")


def build_train_net(model="resnet_cifar10", depth=None, image_shape=(3, 32, 32),
                    num_classes=10, learning_rate=0.01, image=None,
                    label=None, optimize=True):
    """Returns (image, label, avg_cost, accuracy). Pass pre-built image/
    label vars (e.g. in-graph synthetic data) to skip the feed layers;
    optimize=False builds fwd (+bwd via a later append_backward) without
    the optimizer — the perf-probe ablation knob."""
    if image is None:
        image = fluid.layers.data("data", list(image_shape))
    if label is None:
        label = fluid.layers.data("label", [1], dtype="int64")
    if model == "resnet_cifar10":
        predict = resnet_cifar10(image, depth or 32, num_classes)
    else:
        predict = resnet_imagenet(image, depth or 50, num_classes)
    cost = fluid.layers.cross_entropy(predict, label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(predict, label)
    if optimize:
        fluid.optimizer.Momentum(learning_rate=learning_rate,
                                 momentum=0.9).minimize(avg_cost)
    else:
        fluid.backward.append_backward(avg_cost)
    return image, label, avg_cost, acc


def zoo_spec():
    """(build_fn, feed_fn): ResNet-CIFAR10 Momentum train step."""
    def build():
        _, _, avg_cost, acc = build_train_net(
            model="resnet_cifar10", depth=8, image_shape=(3, 16, 16))
        return avg_cost, acc

    def feeds(rng):
        return {"data": rng.rand(4, 3, 16, 16).astype("float32"),
                "label": rng.randint(0, 10, (4, 1)).astype("int64")}

    return build, feeds


def analysis_entry():
    """Static-analyzer entry: ResNet-CIFAR10 Momentum train step."""
    from .harness import program_entry
    return program_entry(*zoo_spec())

