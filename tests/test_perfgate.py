"""Perf regression gate (ISSUE 11): probe comparison semantics and
the 0/1/2 CLI exit-code contract over checked-in bench fixtures."""

import json
import os
import subprocess
import sys

import pytest

from paddle_tpu import perfgate

FIX = os.path.join(os.path.dirname(__file__), "fixtures")
BASE = os.path.join(FIX, "bench_base.json")
REGRESSED = os.path.join(FIX, "bench_regressed.json")
NOISY_OK = os.path.join(FIX, "bench_noisy_ok.json")
CHIP = os.path.join(FIX, "bench_chip.json")
BAD = os.path.join(FIX, "bench_bad.json")


def _cli(*argv):
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.perfgate"] + list(argv),
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    return out.returncode, out.stdout, out.stderr


def test_self_compare_passes_exit_0():
    rc, out, _ = _cli(BASE, BASE)
    assert rc == 0
    assert "PASS" in out and "REGRESSION" not in out.splitlines()[0]


def test_doctored_regression_flagged_exit_1():
    rc, out, _ = _cli(REGRESSED, BASE, "--json")
    assert rc == 1
    v = json.loads(out)
    assert not v["pass"]
    assert "resnet_imgs_per_sec" in v["regressions"]
    assert "megastep_k8_tok_s" in v["regressions"]
    # unrelated probes stay green
    assert "serving_tok_s" not in v["regressions"]


def test_noise_band_absorbs_same_round_jitter():
    # -2% resnet / +4% megastep: inside every band -> pass
    rc, out, _ = _cli(NOISY_OK, BASE)
    assert rc == 0, out


def test_bad_input_exit_2():
    assert _cli(BAD, BASE)[0] == 2
    assert _cli("/nonexistent.json", BASE)[0] == 2
    rc, _, err = _cli(BASE, "--baseline-dir", FIX + "/nowhere")
    assert rc == 2 and "no BENCH_r" in err


def test_platform_mismatch_skips_not_screams():
    v = perfgate.compare(BASE, CHIP)
    assert v["pass"] and v["compared"] == 0
    assert all(p["status"] == "skipped" for p in v["probes"])
    assert "platform mismatch" in v["probes"][0]["reason"]


def test_measured_spread_widens_the_band():
    base = perfgate.load_result(BASE)
    cur = json.loads(json.dumps(base))
    # megastep k1 carries a measured 12% spread; a 15% drop would
    # breach the default 20%? no — band is max(20, 12) = 20 -> pass;
    # a 25% drop breaches it
    cur["megastep"]["k1_tok_s"] = base["megastep"]["k1_tok_s"] * 0.85
    v = perfgate.compare(cur, base)
    assert "megastep_k1_tok_s" not in v["regressions"]
    cur["megastep"]["k1_tok_s"] = base["megastep"]["k1_tok_s"] * 0.70
    v = perfgate.compare(cur, base)
    assert "megastep_k1_tok_s" in v["regressions"]


def test_lower_is_better_probe_direction():
    base = perfgate.load_result(BASE)
    cur = json.loads(json.dumps(base))
    cur["lstm_ms_per_batch"] = base["lstm_ms_per_batch"] * 1.5  # +50%
    v = perfgate.compare(cur, base)
    assert "lstm_ms_per_batch" in v["regressions"]
    cur["lstm_ms_per_batch"] = base["lstm_ms_per_batch"] * 0.5
    v = perfgate.compare(cur, base)
    assert "lstm_ms_per_batch" in v["improvements"]


def test_absolute_band_probe_router_overhead():
    base = perfgate.load_result(BASE)
    cur = json.loads(json.dumps(base))
    cur["fleet"]["router_overhead_pct"] = 5.0     # within ±10 points
    assert perfgate.compare(cur, base)["pass"]
    cur["fleet"]["router_overhead_pct"] = 15.0    # 16.7 points worse
    v = perfgate.compare(cur, base)
    assert "fleet_router_overhead_pct" in v["regressions"]


def test_missing_probe_skipped_with_reason():
    base = perfgate.load_result(BASE)
    cur = json.loads(json.dumps(base))
    del cur["megastep"]
    v = perfgate.compare(cur, base)
    ent = {p["name"]: p for p in v["probes"]}["megastep_k8_tok_s"]
    assert ent["status"] == "skipped" and "missing" in ent["reason"]
    assert v["pass"]                  # a failed config != a regression


def test_latest_baseline_picks_newest_loadable(tmp_path):
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(
        {"result": {"metric": "m", "value": 1}}))
    (tmp_path / "BENCH_r07.json").write_text(json.dumps(
        {"rc": 1, "result": None}))       # aborted round: skipped
    (tmp_path / "BENCH_r05.json").write_text(json.dumps(
        {"result": {"metric": "m", "value": 2}}))
    best = perfgate.latest_baseline(str(tmp_path))
    assert best.endswith("BENCH_r05.json")
    assert perfgate.latest_baseline(
        str(tmp_path), exclude=best).endswith("BENCH_r03.json")


def test_load_result_historic_round_shapes():
    # r06+ "result" wrapper
    assert perfgate.load_result(
        {"result": {"metric": "m", "value": 1}})["value"] == 1
    # r04 "parsed"
    assert perfgate.load_result(
        {"parsed": {"metric": "m", "value": 2}})["value"] == 2
    # r01-r03: result only as the tail's last JSON line
    rec = {"tail": "noise\n{\"metric\": \"m\", \"value\": 3}"}
    assert perfgate.load_result(rec)["value"] == 3
    with pytest.raises(ValueError, match="metric"):
        perfgate.load_result({"nope": 1})


# -- platform-stamp ambiguity guard (ISSUE 14 satellite) -------------------

def _unstamped(tmp_path, name="BENCH_r02.json"):
    """A pre-r06-shaped round with NO platform stamp — the one
    platform-AMBIGUOUS pairing (the mismatch guard cannot fire)."""
    base = perfgate.load_result(BASE)
    rec = json.loads(json.dumps(base))
    rec.pop("platform", None)
    path = tmp_path / name
    path.write_text(json.dumps({"result": rec}))
    return str(path)


def test_unstamped_baseline_warns_loudly(tmp_path):
    old = _unstamped(tmp_path)
    rc, out, err = _cli(BASE, old)
    assert rc == 0                        # advisory by default: the
    assert "platform-AMBIGUOUS" in err    # warning is loud, the CPU
    assert "baseline" in err              # rehearsal keeps passing
    # stamped-vs-stamped comparisons stay silent
    rc2, _, err2 = _cli(BASE, BASE)
    assert rc2 == 0 and "AMBIGUOUS" not in err2


def test_require_platform_stamp_gates_chip_ci(tmp_path):
    old = _unstamped(tmp_path)
    rc, _, err = _cli(BASE, old, "--require-platform-stamp")
    assert rc == 1
    assert "--require-platform-stamp" in err
    # both sides stamped: the flag is satisfied (CPU self-compare)
    rc2, _, _ = _cli(BASE, BASE, "--require-platform-stamp")
    assert rc2 == 0
