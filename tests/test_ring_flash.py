"""Ring attention ⊗ Pallas flash kernel fusion (parallel/ring.py).

Round-1 verdict noted the in-mesh ring path used its own einsum blockwise
update while only the local path had the fused kernel. The ring body now
computes each K/V-shard block with flash_attention_lse and merges partial
(out, lse) pairs by stable log-sum-exp weighting. These tests check:
 - the lse output itself (vs dense logsumexp) including its gradient
   cotangent, which the merge makes load-bearing;
 - ring parity vs dense attention with the kernel forced on (interpret
   mode — CPU simulation of the TPU kernel) under a real sp mesh;
 - gradient parity through the ring with the kernel on.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from paddle_tpu.ops import flash_attention as FA
from paddle_tpu.parallel.ring import ring_attention


def _qkv(b=1, h=2, t=256, d=32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, h, t, d), jnp.float32) * 0.3
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_lse_matches_dense(causal):
    q, k, v = _qkv()
    ref_out, ref_lse = FA._dense_lse(q, k, v, causal, 32 ** -0.5)
    out, lse = FA.flash_attention_lse(q, k, v, causal=causal,
                                      force="interpret",
                                      block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               atol=2e-3, rtol=2e-2)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               atol=2e-3, rtol=2e-2)


def test_lse_cotangent_matches_dense():
    # loss uses BOTH outputs so the dlse→ds backward fold is exercised
    q, k, v = _qkv(t=128, seed=1)

    def loss_fn(att):
        def f(q, k, v):
            out, lse = att(q, k, v)
            return (out ** 2).sum() + (jnp.sin(lse) ** 2).sum()
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    g_ref = loss_fn(lambda q, k, v: FA._dense_lse(q, k, v, True, 32 ** -0.5))
    g_fa = loss_fn(lambda q, k, v: FA.flash_attention_lse(
        q, k, v, causal=True, force="interpret", block_q=128, block_k=128))
    for name, a, b in zip("qkv", g_ref, g_fa):
        scale = float(jnp.max(jnp.abs(a))) + 1e-9
        err = float(jnp.max(jnp.abs(a - b))) / scale
        assert err < 5e-3, (name, err)


def _sp_mesh():
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >=2 devices")
    return Mesh(np.array(devs[:2]), ("sp",))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_parity_dense_fallback(causal):
    # default dispatch (CPU → dense per-block math, same merge code path)
    q, k, v = _qkv(t=256)
    mesh = _sp_mesh()
    ref = FA._dense(q, k, v, causal, 32 ** -0.5)
    got = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-3, rtol=2e-2)


def test_ring_with_kernel_forced_matches_dense(monkeypatch):
    # force every per-shard block through the Pallas kernel (interpret):
    # T=256 over sp=2 → T_local=128 = one kernel block per shard
    q, k, v = _qkv(t=256)
    mesh = _sp_mesh()

    orig = FA.flash_attention_lse

    def forced(q, k, v, causal=False, scale=None, **kw):
        return orig(q, k, v, causal=causal, scale=scale,
                    force="interpret", block_q=128, block_k=128)

    monkeypatch.setattr(FA, "flash_attention_lse", forced)
    ref = FA._dense(q, k, v, True, 32 ** -0.5)
    got = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=3e-3, rtol=3e-2)


def test_ring_grads_with_kernel_forced(monkeypatch):
    q, k, v = _qkv(t=256, seed=2)
    mesh = _sp_mesh()

    orig = FA.flash_attention_lse

    def forced(q, k, v, causal=False, scale=None, **kw):
        return orig(q, k, v, causal=causal, scale=scale,
                    force="interpret", block_q=128, block_k=128)

    def ring_loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(FA._dense(q, k, v, True, 32 ** -0.5) ** 2)

    g_ref = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setattr(FA, "flash_attention_lse", forced)
    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ref, g_ring):
        scale = float(jnp.max(jnp.abs(a))) + 1e-9
        err = float(jnp.max(jnp.abs(a - b))) / scale
        assert err < 1e-2, (name, err)
