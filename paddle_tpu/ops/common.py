"""Shared dtype helpers for op lowerings.

The reference emits int64 indices/counters (framework.proto INT64 defaults).
On TPU with JAX x64 off those become int32; ``I64()`` picks the effective
dtype at lowering time so lowerings state the intent without tripping JAX's
per-call truncation UserWarning — and stay consistent with runtime_dtype
(which fill_constant etc. consult per call) even if ``jax_enable_x64`` is
toggled after import.
"""

import jax.numpy as jnp

from ..core.program import runtime_dtype


def I64():  # noqa: N802 — reads as the dtype constant it stands for
    return jnp.dtype(runtime_dtype("int64"))


def lod_valid_mask(ctx, op, slot="X"):
    """Row-validity mask for a LoD-carrying input under flat-total
    bucketing (core/executor._normalize_feeds): rows past sum(lengths) are
    zero padding and must not contribute to reductions. Returns
    (valid_bool[t], n_valid) or (None, None) when the input carries no LoD
    or is scalar."""
    names = op.input(slot)
    if not names:
        return None, None
    lens = ctx.maybe_get(names[0] + "@LOD")
    if lens is None:
        return None, None
    x = ctx.env.get(names[0])
    if x is None or getattr(x, "ndim", 0) < 1:
        return None, None
    n_valid = jnp.sum(lens)
    valid = jnp.arange(x.shape[0]) < n_valid
    return valid, n_valid
