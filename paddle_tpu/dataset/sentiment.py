"""Movie-review sentiment (NLTK-based in the reference) — parity:
python/paddle/dataset/sentiment.py. Readers yield (word_id list, label)."""

from . import imdb

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000


def get_word_dict():
    return sorted(imdb.word_dict().items(), key=lambda kv: kv[1])


def train(n=NUM_TRAINING_INSTANCES):
    return imdb._make_reader(n, seed=10)


def test(n=NUM_TOTAL_INSTANCES - NUM_TRAINING_INSTANCES):
    return imdb._make_reader(n, seed=11)


def fetch():
    pass
