"""Perf probe: time the ResNet-50 train step at several batch sizes on the
real chip and report MFU. Not part of the bench entry — a tuning tool."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models import resnet


PEAK_BF16 = 197e12  # TPU v5e per-chip peak bf16 FLOP/s
FLOPS_PER_IMG_TRAIN = 3 * 4.1e9  # fwd + ~2x bwd, ResNet-50 @224


def run(bs, iters=8, warm=2):
    fluid.amp.enable_amp()
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        sys.path.insert(0, "benchmarks")
        from common import synthetic_feeds
        synth = synthetic_feeds({
            "data": ((bs, 3, 224, 224), "float32", 1.0),
            "label": ((bs, 1), "int64", 1000)})
        image, label, avg_cost, acc = resnet.build_train_net(
            model="resnet_imagenet", depth=50, image_shape=(3, 224, 224),
            num_classes=1000, learning_rate=0.01,
            image=synth["data"], label=synth["label"])
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        for _ in range(warm):
            loss, = exe.run(feed={}, fetch_list=[avg_cost])
            float(np.asarray(loss))
        t0 = time.perf_counter()
        for _ in range(iters):
            loss, = exe.run(feed={}, fetch_list=[avg_cost])
        float(np.asarray(loss))
        dt = (time.perf_counter() - t0) / iters
    ips = bs / dt
    mfu = ips * FLOPS_PER_IMG_TRAIN / PEAK_BF16
    print("bs=%4d  %7.2f ms/step  %8.1f img/s  MFU=%5.1f%%"
          % (bs, dt * 1e3, ips, mfu * 100), flush=True)
    return ips


if __name__ == "__main__":
    for bs in [int(a) for a in sys.argv[1:]] or [64, 128, 256]:
        run(bs)
