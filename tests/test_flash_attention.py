"""Pallas flash-attention kernel parity (ops/flash_attention.py).

The kernel runs here in interpret mode (the CPU simulation of the TPU
kernel — it emulates MXU bf16 matmul precision, hence the loose
tolerances); real-chip parity is exercised by the TPU benchmarks. The
dense jnp formulation is the reference (it equals the composed
matmul+softmax ops the models otherwise emit)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops import flash_attention as FA


def _qkv(b=2, h=3, t=256, d=64, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, h, t, d), jnp.float32) * 0.3
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_dense(causal):
    q, k, v = _qkv()
    ref = FA._dense(q, k, v, causal, 64 ** -0.5)
    got = FA.flash_attention(q, k, v, causal=causal, force="interpret",
                             block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-3, rtol=2e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_dense(causal):
    q, k, v = _qkv(b=1, h=2, t=128, d=64, seed=1)

    def loss(att):
        def f(q, k, v):
            return (att(q, k, v) ** 2).sum()
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    g_ref = loss(lambda q, k, v: FA._dense(q, k, v, causal, 64 ** -0.5))
    g_fa = loss(lambda q, k, v: FA.flash_attention(
        q, k, v, causal=causal, force="interpret",
        block_q=128, block_k=128))
    for name, a, b in zip("qkv", g_ref, g_fa):
        scale = float(jnp.max(jnp.abs(a))) + 1e-9
        err = float(jnp.max(jnp.abs(a - b))) / scale
        assert err < 5e-3, (name, err)


def test_uneven_blocks_fall_back_to_dense():
    # T=96 not divisible by the kernel blocks -> auto path must pick dense
    q, k, v = _qkv(t=96)
    out = FA.flash_attention(q, k, v, causal=True)
    ref = FA._dense(q, k, v, True, 64 ** -0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_cpu_auto_path_is_dense():
    # on the CPU test platform the auto path must not trace the kernel
    q, k, v = _qkv(t=256)
    out = FA.flash_attention(q, k, v, causal=False)
    ref = FA._dense(q, k, v, False, 64 ** -0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_sp_attention_op_routes_through_dispatcher():
    # the registered sp_attention op (off-mesh) must equal the dense math
    import paddle_tpu as fluid
    rng = np.random.RandomState(0)
    q = rng.randn(1, 2, 64, 16).astype(np.float32)
    qv = fluid.layers.data("q", [2, 64, 16])
    kv = fluid.layers.data("k", [2, 64, 16])
    vv = fluid.layers.data("v", [2, 64, 16])
    out = fluid.layers.sequence_parallel_attention(qv, kv, vv, causal=True)
    exe = fluid.Executor(fluid.CPUPlace())
    got, = exe.run(feed={"q": q, "k": q, "v": q}, fetch_list=[out])
    ref = FA._dense(jnp.asarray(q), jnp.asarray(q), jnp.asarray(q), True,
                    16 ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_packed_lm_uses_fused_attention():
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as T
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        T.transformer_lm(vocab_size=64, max_len=32, n_layer=1, n_head=2,
                         d_model=32, d_inner=64, packed=True)
    ops = [op.type for op in prog.global_block().ops]
    assert "sp_attention" in ops
    prog2 = fluid.Program()
    with fluid.program_guard(prog2, fluid.Program()):
        T.transformer_lm(vocab_size=64, max_len=32, n_layer=1, n_head=2,
                         d_model=32, d_inner=64, packed=False)
    assert "sp_attention" not in [op.type
                                  for op in prog2.global_block().ops]


def test_composed_fallback_keeps_causal_mask():
    # causal + dropout forces the composed branch, which must STILL mask
    # the future (review regression: silently dropped causal)
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as T
    rng = np.random.RandomState(0)
    b, t, dm, h = 2, 16, 32, 2
    x = rng.randn(b, t, dm).astype(np.float32) * 0.3
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        xv = fluid.layers.data("x", [t, dm])
        # a (zero) bias forces the composed branch while keeping the op
        # deterministic; causality must still hold: changing FUTURE inputs
        # must not affect earlier outputs
        zero_bias = fluid.layers.assign(
            np.zeros((1, h, t, t), np.float32))
        out = T.multi_head_attention(xv, xv, xv, zero_bias, dm // h,
                                     dm // h, dm, n_head=h, causal=True)
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            o1, = exe.run(prog, feed={"x": x}, fetch_list=[out])
            x2 = x.copy()
            x2[:, -1, :] += 100.0
            o2, = exe.run(prog, feed={"x": x2}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(o1)[:, :-1], np.asarray(o2)[:, :-1],
                               atol=1e-4)


def test_packed_encdec_transformer_matches_masked():
    # packed=True (fused causal self-attn, no bias constants) must equal
    # packed=False under all-ones masks — same math, different route
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as T

    def build(packed, seed=11):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            prog.random_seed = seed
            cost, _ = T.transformer(
                src_vocab_size=32, trg_vocab_size=32, max_len=8,
                n_layer=1, n_head=2, d_model=16, d_inner=32,
                packed=packed)
            scope = fluid.Scope()
            exe = fluid.Executor(fluid.CPUPlace())
            with fluid.scope_guard(scope):
                exe.run(startup)
            return prog, cost, scope, exe

    p1, c1, s1, e1 = build(False)
    p2, c2, s2, e2 = build(True)
    # identical params
    for v in p1.global_block().all_parameters():
        s2.set(v.name, np.array(np.asarray(s1.find_var(v.name))))

    rng = np.random.RandomState(0)
    b, t = 2, 8
    pos = np.tile(np.arange(t, dtype=np.int64), (b, 1))
    ones = np.ones((b, t), np.float32)
    feeds = {"src_word": rng.randint(3, 32, (b, t)).astype(np.int64),
             "src_pos": pos, "src_mask": ones,
             "trg_word": rng.randint(3, 32, (b, t)).astype(np.int64),
             "trg_pos": pos, "trg_mask": ones,
             "lbl_word": rng.randint(3, 32, (b, t)).astype(np.int64)}
    with fluid.scope_guard(s1):
        l1, = e1.run(p1, feed=feeds, fetch_list=[c1])
    with fluid.scope_guard(s2):
        l2, = e2.run(p2, feed=feeds, fetch_list=[c2])
    np.testing.assert_allclose(float(np.asarray(l1)),
                               float(np.asarray(l2)), rtol=1e-5)
    # and sp_attention really is in the packed program
    assert "sp_attention" in [op.type for op in p2.global_block().ops]
    assert "sp_attention" not in [op.type
                                  for op in p1.global_block().ops]


def test_auto_blocks_divide_non_pow2_t():
    """Auto block sizing must pick a DIVISOR of T (largest <= 1024), so
    T=1536 keeps the fused kernel instead of demoting to dense."""
    path, _, bq, bk = FA._resolve_path(
        jnp.zeros((1, 1, 1536, 128)), None, None, None, "interpret")
    assert bq == 768 and bk == 768
    assert 1536 % bq == 0
    # and the kernel at those blocks matches dense
    q, k, v = _qkv(b=1, h=1, t=1536, d=32, seed=3)
    got = FA.flash_attention(q, k, v, causal=True, force="interpret")
    ref = FA._dense(q, k, v, True, 32 ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-3, rtol=2e-2)


def test_bwd_vmem_clamp_keeps_divisibility():
    """The d>128 backward block clamp must shrink to a DIVISOR of T: at
    T=768, d=192 the clamp (512 -> 384) still covers every query row —
    gradients match dense (a non-divisor 512 would silently drop rows
    512-767 from dq/dk/dv)."""
    q, k, v = _qkv(b=1, h=1, t=768, d=192, seed=4)

    def grads(att):
        def f(q, k, v):
            return (att(q, k, v) ** 2).sum()
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    g_ref = grads(lambda q, k, v: FA._dense(q, k, v, True, 192 ** -0.5))
    g_fa = grads(lambda q, k, v: FA.flash_attention(
        q, k, v, causal=True, force="interpret"))
    for name, a, b in zip("qkv", g_ref, g_fa):
        scale = float(jnp.max(jnp.abs(a))) + 1e-9
        err = float(jnp.max(jnp.abs(a - b))) / scale
        assert err < 5e-3, (name, err)


def test_auto_block_degenerate_t_demotes_to_dense(monkeypatch):
    """T with no divisor >= 128 under the auto cap (prime 4099, 2*1031)
    must NOT build a near-T^2 grid of tiny blocks — auto sizing demotes
    to the dense path; explicit block sizes still honor the caller."""
    monkeypatch.setattr(FA, "_on_tpu", lambda x: True)

    def path_for(t, block=None):
        q = jnp.zeros((1, 1, t, 64), jnp.float32)
        return FA._resolve_path(q, None, block, block, None)[0]

    assert path_for(2048) == "pallas"        # sanity: clean T stays fused
    assert path_for(4099) == "dense"         # prime
    assert path_for(2 * 1031) == "dense"     # largest divisor 2
    assert path_for(17 * 127) == "dense"     # largest divisor 127 < 128
    assert path_for(2062, block=1031) == "pallas"  # explicit block wins
