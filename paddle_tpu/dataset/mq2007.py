"""MQ2007 learning-to-rank — reference parity:
python/paddle/dataset/mq2007.py. Supports pointwise/pairwise/listwise
reader formats over 46-dim query-document features."""

import numpy as np

from . import common

FEATURE_DIM = 46


def _gen_query(rng):
    n_docs = int(rng.randint(5, 20))
    feats = rng.randn(n_docs, FEATURE_DIM).astype(np.float32)
    w = common.synthetic_rng("mq2007_w", 0).randn(FEATURE_DIM)
    scores = feats @ w
    rels = np.digitize(scores, np.percentile(scores, [50, 80]))
    return feats, rels.astype(np.int64)


def _make_reader(n, seed, format):
    def pointwise():
        rng = common.synthetic_rng("mq2007", seed)
        for _ in range(n):
            feats, rels = _gen_query(rng)
            for i in range(len(rels)):
                yield feats[i], int(rels[i])

    def pairwise():
        rng = common.synthetic_rng("mq2007", seed)
        for _ in range(n):
            feats, rels = _gen_query(rng)
            for i in range(len(rels)):
                for j in range(len(rels)):
                    if rels[i] > rels[j]:
                        yield np.array([1.0], np.float32), feats[i], feats[j]

    def listwise():
        rng = common.synthetic_rng("mq2007", seed)
        for _ in range(n):
            feats, rels = _gen_query(rng)
            yield feats, rels

    return {"pointwise": pointwise, "pairwise": pairwise,
            "listwise": listwise}[format]


def train(format="pairwise", n=256):
    return _make_reader(n, seed=0, format=format)


def test(format="pairwise", n=64):
    return _make_reader(n, seed=1, format=format)


def fetch():
    pass
