"""Pipeline parallelism over the ``pp`` mesh axis (GPipe schedule).

Beyond the 2018 reference (SURVEY.md §2.7: PP absent; the closest legacy
analog is ParallelNeuralNetwork's static layer placement). TPU-native
design: stage parameters are STACKED on a leading [S, ...] axis sharded on
``pp`` — every device runs the same stage function on its own parameter
shard, and activations ride the ICI ring via ``ppermute``. One jitted
computation, S + M - 1 ticks for M microbatches (the classic GPipe bubble),
differentiable end-to-end (grads flow through ppermute).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import PartitionSpec as P


def _gpipe_sharded(params, xs, stage_fn, axis_name):
    """Inside shard_map. params: stage-local pytree (leading [1,...] leaves);
    xs [M, mb, ...] microbatches (replicated). Returns [M, mb, ...] final-
    stage outputs (valid on every shard; the last stage's results are
    broadcast back through the ring)."""
    s_idx = lax.axis_index(axis_name)
    n_stage = lax.psum(1, axis_name)
    m = xs.shape[0]
    local_params = jax.tree_util.tree_map(lambda p: p[0], params)

    def tick(t, carry):
        state_in, outputs = carry
        # stage 0 ingests microbatch t (zeros once drained)
        mb_idx = jnp.clip(t, 0, m - 1)
        inject = jnp.where(t < m, xs[mb_idx], jnp.zeros_like(xs[0]))
        inp = jnp.where(s_idx == 0, inject, state_in)
        out = stage_fn(local_params, inp)
        # last stage completed microbatch t-(S-1)
        out_mb = t - (n_stage - 1)
        write = jnp.logical_and(s_idx == n_stage - 1, out_mb >= 0)
        upd = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, out, outputs[jnp.clip(out_mb, 0, m - 1)]),
            jnp.clip(out_mb, 0, m - 1), 0)
        outputs = jnp.where(write, upd, outputs)
        state_next = lax.ppermute(
            out, axis_name,
            [(j, (j + 1) % n_stage) for j in range(n_stage)])
        return state_next, outputs

    state0 = jnp.zeros_like(xs[0])
    outputs0 = jnp.zeros_like(xs)
    _, outputs = lax.fori_loop(0, n_stage + m - 1, tick, (state0, outputs0))
    # broadcast final-stage outputs to every shard so out_specs can be
    # replicated: non-final stages hold zeros, so a psum is an exact
    # broadcast (and stays differentiable)
    return lax.psum(outputs, axis_name)


def gpipe(stage_fn, stacked_params, microbatches, mesh, axis_name="pp",
          batch_axis=None):
    """Run ``stage_fn(params_i, x)`` as an S-stage pipeline.

    stacked_params: pytree whose leaves have leading dim S (= mesh[axis]);
    microbatches:   [M, mb, ...] array of M microbatches.
    batch_axis:     mesh axis the mb dim is data-sharded on (e.g. "dp"),
                    None if replicated.
    Returns [M, mb, ...] outputs of the final stage.
    """
    s = mesh.shape[axis_name]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != s:
            raise ValueError(
                "stacked_params leading dim %d != %d pipeline stages"
                % (leaf.shape[0], s))

    pspec = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    xspec = P(None, batch_axis)
    fn = shard_map(
        functools.partial(_gpipe_sharded, stage_fn=stage_fn,
                          axis_name=axis_name),
        mesh=mesh, in_specs=(pspec, xspec), out_specs=xspec,
        check_vma=False)
    return fn(stacked_params, microbatches)
