"""Loss ops.

Reference parity: operators/{cross_entropy,softmax_with_cross_entropy,
sigmoid_cross_entropy_with_logits,hinge_loss,huber_loss,log_loss,
smooth_l1_loss,rank_loss,margin_rank_loss,modified_huber_loss,mean_iou,
nce}_op.cc. All lower to numerically-stable jnp expressions (logsumexp-based
softmax losses) that XLA fuses with the producing matmul.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register


def _gather_label_prob(x, label):
    """x: [..., C] probabilities or logits; label: [..., 1] or [...] int."""
    if label.ndim == x.ndim and label.shape[-1] == 1:
        label = label.reshape(label.shape[:-1])
    return jnp.take_along_axis(
        x, label.astype(jnp.int32)[..., None], axis=-1), label


@register("cross_entropy")
def _cross_entropy(ctx, op):
    x = ctx.in1(op, "X")          # probabilities [N, C]
    if x.dtype == jnp.bfloat16:   # AMP: loss math in fp32 (loss-scale-free)
        x = x.astype(jnp.float32)
    label = ctx.in1(op, "Label")
    if x.shape[0] != label.shape[0]:
        raise ValueError(
            "cross_entropy batch mismatch: X has %d rows, Label has %d "
            "(a silent broadcast here would train the class prior)"
            % (x.shape[0], label.shape[0]))
    if op.attr("soft_label", False):
        if label.ndim == x.ndim - 1:
            label = label[..., None]
        loss = -jnp.sum(label * jnp.log(jnp.clip(x, 1e-20)), axis=-1,
                        keepdims=True)
    else:
        ignore_index = op.attr("ignore_index", -100)
        if label.ndim == x.ndim and label.shape[-1] == 1:
            flat_label = label.reshape(label.shape[:-1])
        else:
            flat_label = label
        valid = flat_label != ignore_index
        safe_label = jnp.where(valid, flat_label, 0)
        p = jnp.take_along_axis(
            x, safe_label.astype(jnp.int32)[..., None], axis=-1)
        loss = -jnp.log(jnp.clip(p, 1e-20)) * valid[..., None].astype(x.dtype)
    ctx.set_out(op, "Y", loss)


@register("softmax_with_cross_entropy")
def _softmax_xent(ctx, op):
    logits = ctx.in1(op, "Logits")
    if logits.dtype == jnp.bfloat16:   # AMP: loss math in fp32
        logits = logits.astype(jnp.float32)
    label = ctx.in1(op, "Label")
    log_sm = jax.nn.log_softmax(logits, axis=-1)
    if op.attr("soft_label", False):
        loss = -jnp.sum(label * log_sm, axis=-1, keepdims=True)
    else:
        lp, _ = _gather_label_prob(log_sm, label)
        loss = -lp
    ctx.set_out(op, "Softmax", jnp.exp(log_sm))
    ctx.set_out(op, "Loss", loss)


@register("sigmoid_cross_entropy_with_logits")
def _sigmoid_xent(ctx, op):
    x = ctx.in1(op, "X")
    label = ctx.in1(op, "Label")
    # stable: max(x,0) - x*z + log(1+exp(-|x|))
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ctx.set_out(op, "Out", loss)


@register("hinge_loss")
def _hinge_loss(ctx, op):
    logits = ctx.in1(op, "Logits")
    labels = ctx.in1(op, "Labels")
    ctx.set_out(op, "Loss",
                jax.nn.relu(1.0 - (2.0 * labels - 1.0) * logits))


@register("huber_loss")
def _huber_loss(ctx, op):
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    delta = op.attr("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= delta, 0.5 * r * r,
                     delta * (ar - 0.5 * delta))
    ctx.set_out(op, "Residual", r)
    ctx.set_out(op, "Out", loss)


@register("log_loss")
def _log_loss(ctx, op):
    p = ctx.in1(op, "Predicted")
    label = ctx.in1(op, "Labels")
    eps = op.attr("epsilon", 1e-4)
    loss = -label * jnp.log(p + eps) - (1 - label) * jnp.log(1 - p + eps)
    ctx.set_out(op, "Loss", loss)


@register("smooth_l1_loss")
def _smooth_l1(ctx, op):
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    sigma = op.attr("sigma", 1.0)
    in_w = ctx.in1(op, "InsideWeight")
    out_w = ctx.in1(op, "OutsideWeight")
    d = x - y
    if in_w is not None:
        d = d * in_w
    s2 = sigma * sigma
    ad = jnp.abs(d)
    elem = jnp.where(ad < 1.0 / s2, 0.5 * s2 * d * d, ad - 0.5 / s2)
    if out_w is not None:
        elem = elem * out_w
    ctx.set_out(op, "Diff", d)
    ctx.set_out(op, "Out", jnp.sum(elem, axis=tuple(range(1, elem.ndim)),
                                   keepdims=True).reshape(x.shape[0], 1))


@register("rank_loss")
def _rank_loss(ctx, op):
    label = ctx.in1(op, "Label")
    left = ctx.in1(op, "Left")
    right = ctx.in1(op, "Right")
    d = left - right
    loss = jnp.maximum(d, 0) - d * label + jnp.log1p(jnp.exp(-jnp.abs(d)))
    ctx.set_out(op, "Out", loss)


@register("margin_rank_loss")
def _margin_rank_loss(ctx, op):
    label = ctx.in1(op, "Label")
    x1 = ctx.in1(op, "X1")
    x2 = ctx.in1(op, "X2")
    margin = op.attr("margin", 0.0)
    out = jax.nn.relu(-label * (x1 - x2) + margin)
    ctx.set_out(op, "Out", out)
    ctx.set_out(op, "Activated", (out > 0).astype(x1.dtype))


@register("modified_huber_loss")
def _modified_huber_loss(ctx, op):
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    z = (2.0 * y - 1.0) * x
    loss = jnp.where(z < -1.0, -4.0 * z,
                     jnp.square(jnp.maximum(0.0, 1.0 - z)))
    ctx.set_out(op, "IntermediateVal", z)
    ctx.set_out(op, "Out", loss)


@register("nce", stateful_rng=True)   # samples negatives from the stream
def _nce(ctx, op):
    """Noise-contrastive estimation (operators/nce_op.cc) — full-softmax-free
    training of big output layers. Samples negatives uniformly."""
    x = ctx.in1(op, "Input")            # [B, D]
    label = ctx.in1(op, "Label")        # [B, T]
    w = ctx.in1(op, "Weight")           # [C, D]
    b = ctx.in1(op, "Bias")             # [C]
    num_neg = op.attr("num_neg_samples", 10)
    num_classes = op.attr("num_total_classes", w.shape[0])
    batch = x.shape[0]
    if label.ndim == 1:
        label = label[:, None]
    num_true = label.shape[1]

    neg = jax.random.randint(ctx.rng(), (batch, num_neg), 0, num_classes)
    samples = jnp.concatenate([label.astype(jnp.int32), neg], axis=1)
    sw = jnp.take(w, samples, axis=0)                # [B, T+K, D]
    logits = jnp.einsum("bd,bkd->bk", x, sw)
    if b is not None:
        logits = logits + jnp.take(b, samples)
    labels01 = jnp.concatenate(
        [jnp.ones((batch, num_true)), jnp.zeros((batch, num_neg))], axis=1)
    # noise prob = uniform
    logits = logits - jnp.log(jnp.asarray(num_classes, jnp.float32))
    per = jnp.maximum(logits, 0) - logits * labels01 + jnp.log1p(
        jnp.exp(-jnp.abs(logits)))
    cost = jnp.sum(per, axis=1, keepdims=True)
    if op.input("SampleWeight"):
        # per-example weight scales the example's whole cost
        # (nce_op.cc:97 sample_weight)
        swt = ctx.in1(op, "SampleWeight").reshape(batch, 1)
        cost = cost * swt.astype(cost.dtype)
    ctx.set_out(op, "Cost", cost)
    ctx.set_out(op, "SampleLogits", logits)
    ctx.set_out(op, "SampleLabels", samples)
