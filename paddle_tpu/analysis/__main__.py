"""CLI: python -m paddle_tpu.analysis [models...] [--all] [--json] ...

Runs the static analyzer over zoo models and exits non-zero when any
diagnostic reaches --fail-on severity (default: error) — the CI gate
that keeps the model zoo honest without TPU time. Run under
JAX_PLATFORMS=cpu; tracing never touches a device.
"""

import argparse
import sys

# Runtime-only packages the jaxpr analyzer cannot see into: a broken
# import here (a bad refactor, a missing stub) would sail straight past
# the zoo lint, so the CLI gate import-checks them too. Keep in sync
# with the package layout.
IMPORT_CHECK_PACKAGES = (
    "paddle_tpu.resilience",
    "paddle_tpu.resilience.faults",
    "paddle_tpu.resilience.retry",
    "paddle_tpu.resilience.driver",
    "paddle_tpu.monitor",
    "paddle_tpu.monitor.watch",
    "paddle_tpu.monitor.collector",
    "paddle_tpu.monitor.goodput",
    "paddle_tpu.monitor.signals",
    "paddle_tpu.perfgate",
    "paddle_tpu.serving",
    "paddle_tpu.serving.engine",
    "paddle_tpu.serving.fleet",
    "paddle_tpu.serving.kvpool",
    "paddle_tpu.serving.sampling",
    "paddle_tpu.serving.spec",
    "paddle_tpu.serving.sparse",
    "paddle_tpu.serving.sparse.cache",
    "paddle_tpu.serving.sparse.scoring",
    "paddle_tpu.serving.sparse.online",
    "paddle_tpu.reader",
    "paddle_tpu.reader.device_loader",
    "paddle_tpu.slo",
    "paddle_tpu.transform",
    "paddle_tpu.transform.passes",
    "paddle_tpu.transform.fusion",
    "paddle_tpu.transform.infer",
    "paddle_tpu.transform.memory",
    "paddle_tpu.transform.calibrate",
    "paddle_tpu.transform.autoparallel",
    "paddle_tpu.serving.artifact",
    "paddle_tpu.trace",
    "paddle_tpu.trace.runtime",
    "paddle_tpu.trace.clock",
    "paddle_tpu.trace.merge",
    "paddle_tpu.distributed",
    "paddle_tpu.distributed.master",
    "paddle_tpu.distributed.membership",
)


def import_check(packages=IMPORT_CHECK_PACKAGES):
    """Import every runtime-only package; returns [(name, error), ...]
    (empty = all clean). Part of the --all CI gate."""
    import importlib
    failures = []
    for name in packages:
        try:
            importlib.import_module(name)
        except Exception as e:        # any failure mode is a gate fail
            failures.append((name, repr(e)))
    return failures


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="jaxpr static analyzer over the paddle_tpu model "
                    "zoo")
    p.add_argument("models", nargs="*",
                   help="zoo model names (see --list-models)")
    p.add_argument("--all", action="store_true",
                   help="analyze every model in the zoo")
    p.add_argument("--json", action="store_true",
                   help="emit a JSON report instead of text")
    p.add_argument("--rules",
                   help="comma-separated rule names to run "
                        "(default: all)")
    p.add_argument("--fail-on", default="error",
                   choices=["error", "warning", "info"],
                   help="exit 1 if any diagnostic reaches this "
                        "severity (default: error)")
    p.add_argument("--verbose", "-v", action="store_true",
                   help="include info-level diagnostics in text "
                        "output")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--list-models", action="store_true")
    args = p.parse_args(argv)

    from . import registered_rules, zoo_names
    from .zoo import analyze_zoo

    if args.list_rules:
        for name, cls in sorted(registered_rules().items(),
                                key=lambda kv: kv[1].id):
            print("%-6s %-18s %s" % (cls.id, name, cls.doc))
        return 0
    if args.list_models:
        for name in zoo_names():
            print(name)
        return 0

    failures = import_check()
    for name, err in failures:
        print("import-check FAILED: %s (%s)" % (name, err),
              file=sys.stderr)
    if failures:
        return 1

    names = zoo_names() if args.all or not args.models else args.models
    unknown = set(names) - set(zoo_names())
    if unknown:
        p.error("unknown model(s) %s; --list-models for the zoo"
                % ", ".join(sorted(unknown)))
    rules = args.rules.split(",") if args.rules else None
    if rules:
        bad = set(rules) - set(registered_rules())
        if bad:
            p.error("unknown rule(s) %s; --list-rules for the catalog"
                    % ", ".join(sorted(bad)))

    def progress(name, report, dt):
        if not args.json:
            c = report.counts()
            print("analyzed %-18s %5.1fs  %d error(s) %d warning(s)"
                  % (name, dt, c["error"], c["warning"]),
                  file=sys.stderr)

    report = analyze_zoo(names, rules=rules, progress=progress)
    if args.json:
        print(report.to_json())
    else:
        print(report.render_text(verbose=args.verbose))
    return 1 if report.at_least(args.fail_on) else 0


if __name__ == "__main__":
    sys.exit(main())
