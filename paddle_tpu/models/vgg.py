"""VGG-16 (reference benchmark/fluid/vgg.py capabilities, TPU-first)."""

import paddle_tpu as fluid
from paddle_tpu.nets import img_conv_group


def vgg16_bn_drop(input, num_classes=10):
    def group(x, num, filters):
        return img_conv_group(x, conv_num_filter=[filters] * num,
                              pool_size=2, pool_stride=2, conv_act="relu",
                              conv_with_batchnorm=True,
                              conv_batchnorm_drop_rate=[0.3] * (num - 1) + [0.0])

    conv1 = group(input, 2, 64)
    conv2 = group(conv1, 2, 128)
    conv3 = group(conv2, 3, 256)
    conv4 = group(conv3, 3, 512)
    conv5 = group(conv4, 3, 512)
    drop = fluid.layers.dropout(conv5, dropout_prob=0.5)
    fc1 = fluid.layers.fc(drop, 512, act=None)
    bn = fluid.layers.batch_norm(fc1, act="relu")
    drop2 = fluid.layers.dropout(bn, dropout_prob=0.5)
    fc2 = fluid.layers.fc(drop2, 512, act=None)
    return fluid.layers.fc(fc2, num_classes, act="softmax")


def build_train_net(image_shape=(3, 32, 32), num_classes=10,
                    learning_rate=1e-3, image=None, label=None):
    if image is None:
        image = fluid.layers.data("data", list(image_shape))
    if label is None:
        label = fluid.layers.data("label", [1], dtype="int64")
    predict = vgg16_bn_drop(image, num_classes)
    cost = fluid.layers.cross_entropy(predict, label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(predict, label)
    fluid.optimizer.Adam(learning_rate=learning_rate).minimize(avg_cost)
    return image, label, avg_cost, acc


def zoo_spec():
    """(build_fn, feed_fn): VGG-16 Adam train step (with dropout, so
    the step exercises the RNG path — transform passes must pin the
    dropout ops in place to keep the stream bitwise-stable)."""
    def build():
        _, _, avg_cost, acc = build_train_net(image_shape=(3, 32, 32))
        return avg_cost, acc

    def feeds(rng):
        return {"data": rng.rand(2, 3, 32, 32).astype("float32"),
                "label": rng.randint(0, 10, (2, 1)).astype("int64")}

    return build, feeds


def analysis_entry():
    """Static-analyzer entry: VGG-16 Adam train step."""
    from .harness import program_entry
    return program_entry(*zoo_spec())

