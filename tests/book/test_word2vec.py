"""Book test: word2vec (reference
python/paddle/fluid/tests/book/test_word2vec.py) — N-gram LM with a SHARED
embedding table across the 4 context words, trained until the loss drops
well under the uniform-prediction entropy."""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu as fluid


def test_word2vec_ngram_trains():
    dict_size = paddle.dataset.imikolov.VOCAB_SIZE
    emb_size, hidden = 16, 64

    words = [fluid.layers.data("w%d" % i, [1], dtype="int64")
             for i in range(4)]
    target = fluid.layers.data("target", [1], dtype="int64")
    embeds = [fluid.layers.embedding(
        w, size=[dict_size, emb_size],
        param_attr=fluid.ParamAttr(name="shared_w")) for w in words]
    concat = fluid.layers.concat(embeds, axis=1)
    hidden1 = fluid.layers.fc(concat, hidden, act="sigmoid")
    predict = fluid.layers.fc(hidden1, dict_size, act="softmax")
    cost = fluid.layers.cross_entropy(predict, target)
    avg_cost = fluid.layers.mean(cost)
    fluid.optimizer.Adam(learning_rate=1e-2).minimize(avg_cost)

    # the table really is shared: one parameter, used 4 times
    params = [p.name for p in
              fluid.default_main_program().global_block().all_parameters()]
    assert params.count("shared_w") == 1

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    reader = paddle.batch(paddle.dataset.imikolov.train(None, 5),
                          batch_size=64)
    feeder = fluid.DataFeeder(words + [target], fluid.CPUPlace())

    first = last = None
    for epoch in range(10):
        for batch in reader():
            feed = feeder.feed(batch)
            feed = {k: np.asarray(v).reshape(-1, 1) for k, v in feed.items()}
            lv, = exe.run(feed=feed, fetch_list=[avg_cost])
            if first is None:
                first = float(lv)
            last = float(lv)
    # reference stops at avg_cost < 35 (huge dict); here: require a real
    # drop below the uniform entropy (~ln V), which bias-only fitting
    # cannot produce
    assert last < first * 0.7, (first, last)
