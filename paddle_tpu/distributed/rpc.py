"""Variable RPC: length-prefixed pickle over TCP.

Reference parity: operators/detail/ gRPC service {SendVariable, GetVariable,
PrefetchVariable} (send_recv.proto:17-25) with VariableMessage carrying
LoDTensor or SelectedRows payloads, plus the reference's port-discovery file
(listen_and_serv_op.cc:51-57 SavePort → /tmp/paddle.selected_port) so
multi-process tests can rendezvous on an ephemeral port.

The wire format is numpy-native (header + raw buffers), not pickle-of-
arbitrary-objects, so a malicious peer can't execute code via the
deserializer.

Verb map over this one frame protocol (every tier rides the same
``_send_msg``/``_recv_msg``, so fault injection, trace-context
propagation and the retry policy apply to all of them for free):

    pserver   SEND PUT GET PRFT BARR CHNK EXIT
    master    GETT DONE FAIL PING        (distributed/master.py)
    kv store  PUT GET CAS DEL CAD LIST LEAS   (membership.py)
    serving   SUBM POLL CANC STAT        (serving/fleet.py replicas)
    all       CLKS                       (trace clock probes)
    all       METR HLTH                  (fleet telemetry scrape:
                                          registry snapshot + recorder
                                          delta / liveness — served by
                                          every dispatch loop plus
                                          monitor.collector's
                                          TelemetryServer)
    all       DUMP                       (forensics black-box capture:
                                          span ring + recorder tail +
                                          metrics + flags + role state
                                          in one reply — see
                                          monitor/forensics.py)
"""

import itertools
import json
import os
import socket
import socketserver
import struct
import threading
import time
import uuid

import numpy as np

from ..core.selected_rows import SelectedRows
from ..monitor import metrics as _metrics
from ..monitor import runtime as _mon
from ..resilience import faults as _faults
from ..resilience.retry import RETRYABLE
from ..trace import clock as _clock
from ..trace import runtime as _trace

__all__ = ["VariableServer", "RPCClient", "serialize_var",
           "deserialize_var"]

_MAGIC = b"PTV1"

# Optional trace-context block: an armed tracer prefixes a frame with
#   <4sII>(op=@TRC, nlen=len(ctx), plen=0) + ctx
# before the real header. '@' can never start a verb (ops are ljust'd
# uppercase names), so receivers detect and consume the block
# UNCONDITIONALLY — a disarmed process still interoperates with an
# armed peer — while headerless (old) frames parse exactly as before.
# Absent when tracing is disarmed or the ambient span is sampled out,
# so a disarmed fleet exchanges byte-identical old frames.
_TRC_OP = b"@TRC"
_TRC_MAX = 256

# distributed-runtime telemetry (paddle_tpu.monitor registry; a counter
# bump is sub-microsecond next to a socket round-trip, so these record
# unconditionally — the watchdog/flight-recorder read them on stalls)
_REG = _metrics.registry()
_RPC_REQS = _REG.counter("ptpu_rpc_requests_total",
                         "pserver requests handled", ("op",))
_RPC_BYTES = _REG.counter("ptpu_rpc_payload_bytes_total",
                          "pserver payload bytes received")
_PS_ROUNDS = _REG.counter("ptpu_ps_rounds_total",
                          "sync-SGD rounds applied by this pserver")
_PS_EVICTIONS = _REG.counter(
    "ptpu_ps_incarnation_evictions_total",
    "pending grads/barrier slots evicted from dead trainer incarnations")
_PS_STALE = _REG.counter(
    "ptpu_ps_stale_rejections_total",
    "messages rejected (STLE) as stale-incarnation stragglers")
_RPC_CHUNK_PUSHES = _REG.counter(
    "ptpu_rpc_chunk_pushes_total",
    "chunk-parallel large-value pushes (client side)")


def _serialize_parts(value):
    """numpy array / SelectedRows → list of buffers (VariableMessage
    parity). Scatter-gather: the value's own memory is one of the parts,
    so a 100MB send never copies the tensor into an intermediate blob —
    the wire-efficiency property the reference built zero-copy bytebuffer
    streams for (operators/detail/variable_response.cc)."""
    if isinstance(value, SelectedRows):
        head = {"kind": "selected_rows", "height": value.height,
                "rows_n": int(value.rows.shape[0]),
                "dtype": str(value.value.dtype),
                "shape": list(value.value.shape)}
        hb = json.dumps(head).encode()
        return [struct.pack("<I", len(hb)), hb,
                _array_buffer(value.rows.astype("<i8")),
                _array_buffer(value.value)]
    arr = np.ascontiguousarray(np.asarray(value))
    head = {"kind": "lod_tensor", "dtype": str(arr.dtype),
            "shape": list(arr.shape)}
    hb = json.dumps(head).encode()
    return [struct.pack("<I", len(hb)), hb, _array_buffer(arr)]


def _array_buffer(arr):
    """Zero-copy byte view of an array; memoryview.cast rejects shapes
    containing 0, so empty arrays fall back to b''."""
    arr = np.ascontiguousarray(arr)
    if arr.size == 0:
        return b""
    return memoryview(arr).cast("B")


def serialize_var(value):
    """numpy array / SelectedRows → one bytes blob (kept for tests and
    checkpoint paths; the wire uses _serialize_parts without the join)."""
    return b"".join(_serialize_parts(value))


def deserialize_var(buf):
    (hlen,) = struct.unpack("<I", bytes(buf[:4]))
    head = json.loads(bytes(buf[4:4 + hlen]).decode())
    body = memoryview(buf)[4 + hlen:]
    # np.frombuffer over the (private, per-message) receive buffer: when
    # it is writable (bytearray from _recv_exact) the array shares it —
    # no third copy of a large tensor
    own = isinstance(buf, (bytearray, memoryview)) and not \
        (isinstance(buf, memoryview) and buf.readonly)
    if head["kind"] == "selected_rows":
        n = head["rows_n"]
        rows = np.frombuffer(body[:8 * n], "<i8")
        value = np.frombuffer(body[8 * n:],
                              head["dtype"]).reshape(head["shape"])
        if not own:
            rows, value = rows.copy(), value.copy()
        return SelectedRows(rows, value, head["height"])
    arr = np.frombuffer(body, head["dtype"]).reshape(head["shape"])
    return arr if own else arr.copy()


def _slice_parts(parts, start, stop):
    """Byte range [start, stop) of the logical concatenation of a
    buffer list, as a list of zero-copy views."""
    out, pos = [], 0
    for p in parts:
        mv = p if isinstance(p, memoryview) else memoryview(p)
        if mv.format != "B" or mv.ndim != 1:
            mv = mv.cast("B")
        ln = len(mv)
        lo, hi = max(start - pos, 0), min(stop - pos, ln)
        if lo < hi:
            out.append(mv[lo:hi])
        pos += ln
        if pos >= stop:
            break
    return out


# chunk-parallel push: values above the threshold split into ranges
# pushed CONCURRENTLY over side connections, each received directly
# into the shared transfer buffer (the reference's zero-copy
# bytebuffer-stream intent, variable_response.cc, scaled out). Streams
# only pay when cores can actually run them: measured on a 1-core box
# the scaling INVERTS (1 stream 53 ms, 4 streams 131 ms for 52 MB — the
# "syscall-bound" single stream was really core-bound), so the stream
# count is capped by cpu_count and a single-core host keeps the plain
# path (PERF.md round-4 "DCN chunk-parallel probe").
_CHUNK_THRESHOLD = 8 << 20
_CHUNK_STREAMS = min(4, os.cpu_count() or 1)
_CHUNK_MARKER = b"@PTCHUNKED:"


def _sendall_parts(sock, parts):
    """sendall over a buffer list: scatter-gather sendmsg with
    short-send handling (sendmsg is one syscall and may send less than
    the total for large messages)."""
    bufs = []
    for p in parts:
        mv = p if isinstance(p, memoryview) else memoryview(p)
        if mv.ndim != 1 or mv.format != "B":
            mv = mv.cast("B")
        if len(mv):
            bufs.append(mv)
    while bufs:
        try:
            sent = sock.sendmsg(bufs)
        except AttributeError:          # platform without sendmsg
            for mv in bufs:
                sock.sendall(mv)
            return
        while sent:
            if sent >= len(bufs[0]):
                sent -= len(bufs[0])
                bufs.pop(0)
            else:
                bufs[0] = bufs[0][sent:]
                sent = 0


def _send_msg(sock, op, name="", payload=b""):
    """payload: bytes or a list of buffers (scatter-gather, no join).

    An armed resilience fault plan hooks the frame here (drop / delay /
    close-mid-frame / duplicate); an armed tracer prefixes the ambient
    span's context block (same scatter-gather write — zero extra
    syscalls). Disarmed, each hook is one None check."""
    parts = payload if isinstance(payload, list) else [payload]
    total = sum(len(p) for p in parts)
    nb = name.encode()
    head = struct.pack("<4sII", op.encode().ljust(4), len(nb), total) + nb
    trc = _trace._TRACER
    if trc is not None:
        wire = trc.wire_context()
        if wire is not None:
            head = struct.pack("<4sII", _TRC_OP, len(wire), 0) \
                + wire + head
    frame = [head] + parts
    plan = _faults._ACTIVE
    if plan is not None:
        plan.on_send(sock, op, frame)   # may sleep or break the conn
    _sendall_parts(sock, frame)


def _recv_exact(sock, n):
    """Read exactly n bytes into ONE buffer via recv_into (no
    chunk-append-join reassembly copies)."""
    buf = bytearray(n)
    _recv_into(sock, memoryview(buf))
    return buf


def _recv_into(sock, view):
    """Fill a writable memoryview exactly (recv_into loop)."""
    got, n = 0, len(view)
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if not r:
            raise ConnectionError("peer closed")
        got += r


def _recv_frame_head(sock):
    """Read the 12-byte frame head, transparently consuming an optional
    leading trace-context block — at most ONE, bounded BEFORE
    allocating: a garbage peer must not drive an unbounded read or pin
    the handler thread streaming repeated blocks. Returns raw
    (op_bytes, nlen, plen, ctx_bytes_or_None)."""
    head = _recv_exact(sock, 12)
    op, nlen, plen = struct.unpack("<4sII", head)
    ctx = None
    if op == _TRC_OP:
        if not 0 < nlen <= _TRC_MAX or plen:
            raise ConnectionError(
                "bad trace-context block (nlen %d plen %d)"
                % (nlen, plen))
        ctx = bytes(_recv_exact(sock, nlen))
        head = _recv_exact(sock, 12)
        op, nlen, plen = struct.unpack("<4sII", head)
        if op == _TRC_OP:
            raise ConnectionError("repeated trace-context block")
    return op, nlen, plen, ctx


def _recv_msg(sock, want_ctx=False):
    plan = _faults._ACTIVE
    if plan is not None:
        plan.on_recv(sock)              # may sleep or break the conn
    op, nlen, plen, ctx = _recv_frame_head(sock)
    name = _recv_exact(sock, nlen).decode() if nlen else ""
    payload = _recv_exact(sock, plen) if plen else b""
    if want_ctx:
        # server dispatch loops ask for the propagated span context to
        # open a child span; replies / old frames carry none
        return op.strip().decode(), name, payload, ctx
    return op.strip().decode(), name, payload


def _clock_exchange(sock):
    """One CLKS round trip on an IDLE client connection → the server's
    epoch seconds (None on a non-OK reply). The three timestamps around
    this call feed trace.clock's midpoint offset estimator."""
    _send_msg(sock, "CLKS")
    op, _, payload = _recv_msg(sock)
    if op != "OK" or not payload:
        return None
    return float(json.loads(bytes(payload).decode())["t"])


def _clock_reply(sock):
    """Serve one CLKS probe (shared by the pserver / master / KV
    dispatchers): reply with this process's epoch clock, stamped as
    late as possible so the sample sits at the handling midpoint."""
    _send_msg(sock, "OK", "", json.dumps({"t": time.time()}).encode())


def _metr_reply(sock, payload, role="proc", registry=None):
    """Serve one METR scrape (fleet telemetry): the full metrics
    registry snapshot (incarnation + uptime stamped by the registry
    itself) plus the flight-recorder event delta since the caller's
    cursor (empty when no recorder is armed — counters alone still
    make the process observable). Shared by every dispatch loop; the
    reply is one JSON frame, so faults/trace/retry ride along exactly
    like any other verb."""
    body = {}
    if payload:
        try:
            body = json.loads(bytes(payload).decode())
        except (ValueError, UnicodeDecodeError):
            body = {}
    reg = registry if registry is not None else _metrics.registry()
    out = {"role": role, "pid": os.getpid(),
           "incarnation": reg.incarnation, "uptime_s": reg.uptime_s(),
           "snapshot": reg.snapshot(),
           "events": [], "cursor": body.get("cursor"), "lost": 0}
    # a collector scraping several endpoints of the SAME process asks
    # only its designated primary for the event delta ("events": false
    # on the others) — the ring cursor advances once per process. The
    # ring belongs to the process-GLOBAL identity: a server pinning a
    # private registry override reports a different incarnation, and
    # serving it the global ring would double-deliver every event
    # (two "processes", each a primary of the one ring).
    rec = _mon.recorder() if registry is None else None
    if rec is not None and body.get("events", True):
        try:
            # cursors are only meaningful within ONE ring's sequence
            # space: monitor.enable() mid-process replaces the
            # recorder, and the caller's old-ring cursor would
            # silently filter every new row — reply with the ring id
            # and restart the delta when the caller's doesn't match
            cursor = body.get("cursor")
            if body.get("ring") is not None \
                    and body.get("ring") != rec.ring_id:
                cursor = None
            cur, rows, lost = rec.events_since(cursor)
            out["events"] = rows
            out["cursor"] = cur
            out["lost"] = lost
            out["ring"] = rec.ring_id
        except Exception:
            pass            # telemetry must never fail the server loop
    _send_msg(sock, "VAL", "", json.dumps(out).encode())


def _hlth_reply(sock, role="proc", registry=None):
    """Serve one HLTH liveness probe: who am I (role / pid /
    incarnation) and how long have I been up — the cheap half of the
    scrape a collector uses to paint fleet membership without pulling
    a whole registry snapshot."""
    reg = registry if registry is not None else _metrics.registry()
    _send_msg(sock, "VAL", "", json.dumps(
        {"role": role, "pid": os.getpid(), "alive": True,
         "incarnation": reg.incarnation,
         "uptime_s": reg.uptime_s()}).encode())


def _dump_reply(sock, payload, role="proc", registry=None, state=None):
    """Serve one DUMP black-box capture (incident forensics): this
    process's tail span ring (sampled-out spans included), flight-
    recorder ring tail, metrics snapshot, non-default flags, and the
    dispatcher's role-specific ``state`` summary — everything a
    coordinator needs to explain an incident after the fact, in one
    JSON frame. Every section is salvage-guarded: a capture must
    degrade to a partial snapshot, never fail (or stall) the serving
    loop. Shared by every dispatch loop, like _metr_reply."""
    body = {}
    if payload:
        try:
            body = json.loads(bytes(payload).decode())
        except (ValueError, UnicodeDecodeError):
            body = {}
    reg = registry if registry is not None else _metrics.registry()
    out = {"role": role, "pid": os.getpid(), "t": time.time(),
           "incarnation": reg.incarnation, "uptime_s": reg.uptime_s()}
    if state is not None:
        out["state"] = state
    try:
        out["snapshot"] = reg.snapshot()
    except Exception:
        pass
    try:
        from .. import flags as _flags_mod
        out["flags"] = _flags_mod.overrides()
    except Exception:
        pass
    try:
        out["spans"] = _trace.tail_dump(
            max_spans=int(body.get("spans_max", 4096)))
    except Exception:
        pass
    rec = _mon.recorder() if registry is None else None
    if rec is not None and body.get("events", True):
        try:
            _cur, rows, lost = rec.events_since(None)
            limit = int(body.get("events_max", 1024))
            out["events"] = rows[-limit:] if limit else rows
            out["events_lost"] = lost
            out["ring"] = rec.ring_id
        except Exception:
            pass
    _send_msg(sock, "VAL", "", json.dumps(out).encode())


def _parse_tag(tag):
    """'t<id>:i<inc>:s<seq>' → ('t<id>:i<inc>', seq); else (None, None)."""
    if not tag:
        return None, None
    parts = tag.split(":")
    if len(parts) == 3 and parts[2][:1] == "s":
        try:
            return parts[0] + ":" + parts[1], int(parts[2][1:])
        except ValueError:
            pass
    return None, None


class StaleIncarnationError(RuntimeError):
    """A server has seen a NEWER incarnation of this trainer id, so it
    rejected our message. Normally that means we are a dead
    incarnation's delayed retry — but after an elastic reschedule onto
    a host whose clock is behind, a LIVE replacement can look stale
    too. The error carries the server's max epoch so the sender can
    re-incarnate past it and retry instead of deadlocking the round."""

    def __init__(self, max_epoch):
        super().__init__(
            "server knows a newer incarnation (epoch %d) of this "
            "trainer — re-incarnate past it and retry" % max_epoch)
        self.max_epoch = max_epoch


def _inc_epoch(pref):
    """Incarnation ordering: 't<id>:i<16-hex-epoch><nonce>' → epoch int,
    or None for legacy/handmade incarnation ids (no ordering known)."""
    inc = pref.split(":i", 1)
    if len(inc) != 2 or len(inc[1]) < 16:
        return None
    try:
        return int(inc[1][:16], 16)
    except ValueError:
        return None


class VariableServer:
    """Parameter-server process half (listen_and_serv_op.cc semantics):
    holds a scope of variables; SEND accumulates gradients, GET serves
    values, PRFT serves embedding rows by id, BARR implements the fan_in
    round barrier, after which `optimize_fn` is invoked once per round."""

    def __init__(self, host="127.0.0.1", port=0, fan_in=1,
                 optimize_fn=None, port_file=None, sync=True,
                 sparse_tables=None):
        self.store = {}              # name -> np.ndarray
        # per-process-lifetime identity: a REPLACEMENT server recovered
        # from checkpoint restores the same round counter, so readers
        # that cache rows (serving.sparse hot-ID cache) key their
        # invalidation on this token — an incarnation bump means every
        # cached row from the dead server is suspect, round number
        # notwithstanding
        self.incarnation = uuid.uuid4().hex[:12]
        self.grads = {}              # name -> list of pending grads
        self.fan_in = fan_in
        self.optimize_fn = optimize_fn
        # name -> {"shard": i, "num_shards": n, "height": global_rows}:
        # this server holds rows {g : g % n == i} of the GLOBAL table,
        # stored compactly at local index g // n (mod-sharding, the
        # split_ids placement — distribute_transpiler.py:201-255 parity)
        self.sparse_tables = dict(sparse_tables or {})
        self.sync = sync             # False → async SGD: apply on arrival
        self._lock = threading.Lock()
        self._round_cv = threading.Condition(self._lock)
        self._barrier_count = 0
        self._barr_seen = set()      # tags counted toward THIS round
        self._applied = {}           # "t<id>:i<inc>" -> last applied seq
        self._untagged_seq = itertools.count()
        self._max_epoch = {}         # "t<id>" -> newest incarnation epoch
        self._pending_chunks = {}    # tid -> chunk-parallel push parts
        self._round = 0
        self._shutdown = threading.Event()
        # accepted connections, tracked so stop() can SEVER them: a
        # dying process resets every socket it holds, but an in-process
        # stop() would otherwise leave handler threads parked in recv
        # serving the dead store — exactly the zombie a client-side
        # resolver could never notice (serving.sparse's stale-forever
        # hazard). Closing them makes stop() look like process death
        # from every peer's side.
        self._conns = set()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                with outer._lock:
                    outer._conns.add(self.request)
                try:
                    while True:
                        op, nlen, plen, tctx = _recv_frame_head(
                            self.request)
                        op = op.strip().decode()
                        name = _recv_exact(self.request, nlen).decode() \
                            if nlen else ""
                        if op == "CHNK":
                            # receive straight into the shared transfer
                            # buffer — no per-message temp copy (and no
                            # span: the commit SEND carries the trace)
                            outer._recv_chunk(self.request, name, plen)
                            continue
                        payload = _recv_exact(self.request, plen) \
                            if plen else b""
                        trc = _trace._TRACER
                        if trc is not None and tctx is not None \
                                and op != "CLKS":
                            with trc.server_span("pserver." + op, tctx,
                                                 op=op, var=name):
                                outer._dispatch(self.request, op, name,
                                                payload)
                        else:
                            outer._dispatch(self.request, op, name,
                                            payload)
                        if op == "EXIT":
                            break
                except (ConnectionError, OSError):
                    pass
                finally:
                    with outer._lock:
                        outer._conns.discard(self.request)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        if port_file:
            with open(port_file, "w") as f:
                f.write(str(self.port))
        trc = _trace._TRACER
        if trc is not None:
            # merge maps clients' clock-sample peer endpoints to this
            # process through the registered endpoint/port
            trc.record_server_port(self.port,
                                   "%s:%d" % (host, self.port))
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._shutdown.set()
        with self._round_cv:
            self._round_cv.notify_all()
        # shutdown() handshakes with serve_forever; if the serve thread was
        # never started that handshake would block forever — just close.
        if self._thread.is_alive():
            self._server.shutdown()
        self._server.server_close()
        # sever accepted connections (see _conns above): peers see the
        # same connection reset a real process death gives them, so
        # their retry/resolver recovery path engages instead of a
        # zombie handler thread serving the dead store forever
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    # -- dispatch ------------------------------------------------------------
    def _prune_chunks_locked(self, now):
        for t in [t for t, e in self._pending_chunks.items()
                  if now - e["t0"] > 120.0]:
            del self._pending_chunks[t]

    def _recv_chunk(self, sock, name, plen):
        """One range of a chunk-parallel push, received DIRECTLY into
        the shared transfer buffer at its offset — zero reassembly
        copies (the scaled-out analog of variable_response.cc's
        zero-copy stream). name: "tid:i:n:off:total". Header fields are
        client-supplied: bound them BEFORE allocating or receiving, so a
        garbage peer cannot trigger an unbounded allocation or desync
        the stream with an out-of-range slice."""
        tid, _i, n, off, total = name.rsplit(":", 4)
        n, off, total = int(n), int(off), int(total)
        if not (0 < total <= (1 << 32) and 0 < n <= 64
                and 0 <= off and off + plen <= total):
            raise ConnectionError(
                "bad chunk header %r (plen %d)" % (name, plen))
        now = time.time()
        with self._lock:
            # prune transfers whose commit never came (dead client)
            self._prune_chunks_locked(now)
            entry = self._pending_chunks.setdefault(
                tid, {"buf": bytearray(total), "got": 0,
                      "n": n, "t0": now})
            if len(entry["buf"]) != total or entry["n"] != n:
                raise ConnectionError(
                    "chunk header %r disagrees with transfer" % name)
        _recv_into(sock, memoryview(entry["buf"])[off:off + plen])
        with self._lock:
            entry["got"] += 1
        _send_msg(sock, "OK")

    def _resolve_chunked(self, payload):
        """A SEND/PUT whose payload is the chunk-commit marker: hand
        back the already-assembled transfer buffer (every CHNK was acked
        before the client committed)."""
        if bytes(payload[:len(_CHUNK_MARKER)]) != _CHUNK_MARKER:
            return payload
        tid = bytes(payload[len(_CHUNK_MARKER):]).decode()
        with self._lock:
            self._prune_chunks_locked(time.time())
            entry = self._pending_chunks.pop(tid, None)
        if entry is None:
            raise KeyError("chunked transfer %s has no parts" % tid)
        if entry["got"] != entry["n"]:
            raise ConnectionError(
                "chunked transfer %s committed with %d/%d parts"
                % (tid, entry["got"], entry["n"]))
        return entry["buf"]

    def _dispatch(self, sock, op, name, payload):
        plan = _faults._ACTIVE
        if plan is not None and \
                plan.should_kill("pserver", self._round):
            # hard crash: no reply for the in-flight request, no
            # checkpoint — exactly what a SIGKILL'd pserver looks like.
            # stop() must run off-thread (shutdown() handshakes with
            # serve_forever and would deadlock from a handler thread).
            threading.Thread(target=self.stop, daemon=True).start()
            raise ConnectionError("injected fault: pserver killed")
        _RPC_REQS.inc(op=op)
        _RPC_BYTES.inc(len(payload))
        if op in ("SEND", "PUT"):
            payload = self._resolve_chunked(payload)
        if op == "SEND":
            value = deserialize_var(payload)
            # optional idempotency tag after "||": a retried send for the
            # same (name, tag) REPLACES the pending grad instead of
            # accumulating; a send whose round was ALREADY applied is
            # dropped; pending grads from a dead incarnation of the same
            # trainer are evicted — at-least-once trainer retries (elastic
            # recovery) then yield exactly-once round semantics
            tag = None
            if "||" in name:
                name, tag = name.split("||", 1)
            pref, seq = _parse_tag(tag)
            if self.sync:
                # decide under the lock, reply after releasing it:
                # _send_msg blocks on the socket and a slow reader must
                # not stall every other handler thread on self._lock
                # (enforced by analysis --runtime, lock-discipline)
                with self._lock:
                    stale = (self._stale_epoch(pref)
                             if pref is not None else None)
                    applied = (stale is None and pref is not None
                               and seq <= self._applied.get(pref, -1))
                    if stale is None and not applied:
                        if pref is not None:
                            self._evict_stale_incarnation(pref)
                        slot = self.grads.setdefault(name, {})
                        # untagged sends get a monotonic key, never
                        # reused: len(slot) could collide with a live
                        # key after an eviction shrank the dict,
                        # silently replacing a pending grad that
                        # should accumulate
                        slot[tag if tag is not None
                             else "#%d" % next(self._untagged_seq)] \
                            = value
                if stale is not None:
                    _PS_STALE.inc()
                    _send_msg(sock, "STLE", name, json.dumps(
                        {"max_epoch": stale}).encode())
                    return
                if applied:
                    _send_msg(sock, "OK")   # round already applied
                    return
            else:
                # Async SGD (ParameterServer2.h async paths /
                # async_update.md): apply this gradient immediately under
                # the lock — no round barrier, trainers never wait on each
                # other, updates may be stale.
                with self._lock:
                    if self.optimize_fn is not None:
                        self.optimize_fn(self.store, {name: value})
            _send_msg(sock, "OK")
        elif op == "GET":
            with self._lock:
                val = self.store.get(name)
            if val is None:
                _send_msg(sock, "MISS", name)
            else:
                _send_msg(sock, "VAL", name, _serialize_parts(val))
        elif op == "PRFT":
            ids = deserialize_var(payload).astype(np.int64).reshape(-1)
            with self._lock:
                table = self.store.get(name)
                meta = self.sparse_tables.get(name)
                rnd = self._round
            # the reply NAME carries the rows' version coordinates
            # ("<table>|v<round>|<incarnation>") so a caching reader
            # (serving.sparse) can bound staleness: the round bumps
            # once per applied optimize round, the incarnation changes
            # when a replacement server recovers from checkpoint. Old
            # clients ignore the reply name entirely — the payload is
            # byte-identical to the unversioned reply.
            ver = "%s|v%d|%s" % (name, rnd, self.incarnation)
            if table is None:
                _send_msg(sock, "MISS", name)
            elif meta is not None:
                # sharded table: global ids (all ≡ shard mod num_shards)
                # index the compact local store at g // n
                local = ids // int(meta["num_shards"])
                rows = np.asarray(table)[np.clip(local, 0,
                                                 len(table) - 1)]
                _send_msg(sock, "VAL", ver,
                          _serialize_parts(SelectedRows(
                              ids, rows, int(meta["height"]))))
            else:
                rows = np.asarray(table)[np.clip(ids, 0,
                                                 len(table) - 1)]
                _send_msg(sock, "VAL", ver,
                          _serialize_parts(SelectedRows(ids, rows,
                                                        len(table))))
        elif op == "PUT":
            with self._lock:
                self.store[name] = np.asarray(deserialize_var(payload))
            _send_msg(sock, "OK")
        elif op == "BARR":
            if self.sync:
                self._barrier(sock, name or None)
            else:
                _send_msg(sock, "OK")   # async mode: barrier is a no-op
        elif op == "CLKS":
            _clock_reply(sock)
        elif op == "METR":
            _metr_reply(sock, payload, role="pserver")
        elif op == "HLTH":
            _hlth_reply(sock, role="pserver")
        elif op == "DUMP":
            with self._lock:
                state = {"round": self._round,
                         "vars": len(self.store),
                         "pending_grads": {k: len(v) for k, v
                                           in self.grads.items()},
                         "fan_in": self.fan_in, "sync": self.sync,
                         "incarnation": self.incarnation}
            _dump_reply(sock, payload, role="pserver", state=state)
        elif op == "EXIT":
            _send_msg(sock, "OK")
            self.stop()
        else:
            _send_msg(sock, "ERR", "unknown op %s" % op)

    def _stale_epoch(self, pref):
        """Under the lock. Non-None → REJECT this message with STLE: its
        incarnation is OLDER than one already seen for the trainer id,
        i.e. it is (almost always) a dead incarnation's straggler.
        Without this gate a delayed retry from the dead incarnation
        would pass the _applied check (its entry may be pruned) and then
        evict the LIVE replacement's pending grads via
        _evict_stale_incarnation. The returned max epoch travels back in
        the STLE reply so that the rare LIVE sender judged stale (clock
        skew after an elastic reschedule) can re-incarnate past it and
        retry — a silent drop would deadlock the whole round. Legacy
        unordered incarnation ids return None and keep the old eviction
        rules."""
        epoch = _inc_epoch(pref)
        if epoch is None:
            return None
        tid = pref.split(":", 1)[0]
        cur = self._max_epoch.get(tid)
        if cur is not None and epoch < cur:
            return cur
        self._max_epoch[tid] = epoch
        return None

    def _evict_stale_incarnation(self, pref):
        """Drop EVERYTHING a dead incarnation of this trainer left
        behind: pending grads under every name, and its counted barrier
        slots. Called (under the lock) whenever a tagged SEND or BARR
        arrives — the replacement incarnation's first message cleans up
        after the crash, across all names, so a half-step from the dead
        process can never be merged into a round."""
        tid = pref.split(":", 1)[0]

        def stale(k):
            return (isinstance(k, str) and k.startswith(tid + ":")
                    and not k.startswith(pref + ":"))

        for slot in self.grads.values():
            for k in [k for k in slot if stale(k)]:
                del slot[k]
                _PS_EVICTIONS.inc()
        dead_barrs = {t for t in self._barr_seen if stale(t)}
        if dead_barrs:
            self._barr_seen -= dead_barrs
            self._barrier_count = max(
                0, self._barrier_count - len(dead_barrs))
        # drop the dead incarnations' applied-round history too, or a
        # long-lived pserver under elastic churn grows _applied forever.
        # Only prune entries PROVABLY older by epoch: for those, the
        # epoch gate already rejects any late retry, so the history is
        # dead weight. A legacy (unordered) entry must survive — it is
        # the only thing standing between a delayed applied-round retry
        # and this eviction path.
        caller_epoch = _inc_epoch(pref)
        if caller_epoch is not None:
            for k in [k for k in self._applied if stale(k + ":")]:
                ke = _inc_epoch(k)
                if ke is not None and ke < caller_epoch:
                    del self._applied[k]

    def _barrier(self, sock, tag=None):
        """Round barrier: after fan_in SENDs+BARRs, run the optimize step
        over accumulated grads, then release all waiters
        (listen_and_serv_op.cc:100-168 RunSyncLoop).

        Idempotency: a tagged barrier whose round was already applied
        returns immediately; a RETRY of a tag already counted toward the
        current round waits for the round without double-counting —
        together with tagged SENDs this makes at-least-once trainer
        retries exactly-once per round."""
        pref, seq = _parse_tag(tag)
        # the early replies (stale / already-applied) are decided under
        # the condition's lock but SENT after releasing it — socket
        # writes must never hold up the round for every other handler
        # (enforced by analysis --runtime, lock-discipline)
        with self._round_cv:
            stale = self._stale_epoch(pref) if pref is not None else None
            applied = (stale is None and pref is not None
                       and seq <= self._applied.get(pref, -1))
            if stale is None and not applied:
                if pref is not None:
                    self._evict_stale_incarnation(pref)
                my_round = self._round
                counted = not (tag and tag in self._barr_seen)
                if counted:
                    if tag:
                        self._barr_seen.add(tag)
                    self._barrier_count += 1
                if self._barrier_count >= self.fan_in:
                    grads, self.grads = self.grads, {}
                    merged = {}
                    for name, slot in grads.items():
                        glist = list(slot.values())
                        if not glist:  # fully evicted (stale incarnation)
                            continue
                        acc = glist[0]
                        for g in glist[1:]:
                            if isinstance(acc, SelectedRows):
                                acc = acc.merge(g)
                            else:
                                acc = acc + g
                        merged[name] = acc
                    if self.optimize_fn is not None:
                        self.optimize_fn(self.store, merged)
                    for t in self._barr_seen:
                        p, s = _parse_tag(t)
                        if p is not None:
                            self._applied[p] = max(
                                self._applied.get(p, -1), s)
                    self._barrier_count = 0
                    self._barr_seen = set()
                    self._round += 1
                    _PS_ROUNDS.inc()
                    self._round_cv.notify_all()
                else:
                    while (self._round == my_round
                           and not self._shutdown.is_set()):
                        self._round_cv.wait(timeout=0.1)
        if stale is not None:
            _PS_STALE.inc()
            _send_msg(sock, "STLE", tag or "", json.dumps(
                {"max_epoch": stale}).encode())
            return
        _send_msg(sock, "OK")


    # -- checkpoint / recover (go/pserver/service.go:156-205,346) ------------
    def checkpoint(self, path, keep_last=2):
        """Durably persist the parameter store. The blob goes to a
        VERSIONED file (path.<round>, CRC computed incrementally while
        writing — io.write_atomic_blob, shared with the trainer
        checkpoint path) and the meta JSON — which names the blob — is
        atomically renamed into place LAST, so a crash at any point
        leaves the previous (meta, blob) pair fully recoverable. The
        newest ``keep_last`` (meta, blob) pairs are RETAINED (versioned
        ``path.meta.<round>`` files + the ``path.meta`` newest-pointer),
        so recover() can fall back past a blob corrupted ON DISK after
        a clean write; anything older is pruned only after the new meta
        is durable."""
        import io as _io
        import json

        from ..io import write_atomic_blob, write_json_atomic

        with self._lock:
            arrays = {k: np.asarray(v) for k, v in self.store.items()}
            round_no = self._round
        d = os.path.dirname(os.path.abspath(path)) or "."
        base = os.path.basename(path)
        os.makedirs(d, exist_ok=True)
        buf = _io.BytesIO()
        np.savez(buf, **arrays)
        blob_name = "%s.%d" % (base, round_no)
        crc = write_atomic_blob(d, blob_name, buf.getbuffer())
        meta = {"round": round_no, "crc32": crc,
                "blob": blob_name, "names": sorted(arrays)}
        write_json_atomic("%s.meta.%d" % (path, round_no), meta)
        write_json_atomic(path + ".meta", meta)
        keep = {round_no}
        for n in os.listdir(d):
            if n.startswith(base + ".meta."):
                try:
                    keep.add(int(n[len(base) + 6:]))
                except ValueError:
                    pass
        keep = set(sorted(keep)[-max(1, keep_last):])
        for n in os.listdir(d):
            if not n.startswith(base + ".") or n.endswith(".tmp") \
                    or n == base + ".meta":
                continue
            tail = n[len(base) + 1:]
            ver = tail[5:] if tail.startswith("meta.") else tail
            try:
                if int(ver) in keep:
                    continue
            except ValueError:
                continue
            try:
                os.remove(os.path.join(d, n))
            except OSError:
                pass
        return meta

    def recover(self, path):
        """Reload the NEWEST VALID checkpoint written by checkpoint();
        returns its round number, or None when nothing valid exists
        (service.go recover path — a corrupt file is skipped, not
        trusted). Candidates: the versioned metas newest-first (the
        ``path.meta`` pointer is just the newest one's copy); a
        truncated or bit-flipped blob fails its CRC — checked on the
        exact bytes that get loaded, no re-read TOCTOU — and recovery
        FALLS BACK to the previous retained pair."""
        import io as _io
        import json
        import zlib

        d = os.path.dirname(os.path.abspath(path)) or "."
        base = os.path.basename(path)
        metas = []
        try:
            for n in os.listdir(d):
                if n.startswith(base + ".meta."):
                    try:
                        metas.append((int(n[len(base) + 6:]),
                                      os.path.join(d, n)))
                    except ValueError:
                        pass
        except OSError:
            return None
        metas.sort(reverse=True)
        if not metas and os.path.exists(path + ".meta"):
            metas = [(-1, path + ".meta")]    # pre-versioning layout
        for _, meta_path in metas:
            try:
                with open(meta_path) as f:
                    meta = json.load(f)
                blob = os.path.join(d, meta.get("blob", base))
                with open(blob, "rb") as f:
                    data = f.read()
                if zlib.crc32(data) != meta["crc32"]:
                    continue
                with np.load(_io.BytesIO(data)) as loaded:
                    with self._lock:
                        for name in loaded.files:
                            self.store[name] = loaded[name]
                        self._round = int(meta.get("round", 0))
                return meta["round"]
            except (OSError, ValueError, KeyError,
                    json.JSONDecodeError):
                continue
        return None


class RPCClient:
    """Trainer-side client (grpc_client.h:160-194 RPCClient parity, sync).

    retry:    optional resilience.retry.Policy — idempotent verbs (GET /
              PRFT / PUT, and SEND / BARR with a ROUND tag, which the
              server dedups across rounds) transparently reconnect and
              re-issue on socket errors. Untagged or free-form-tagged
              SEND / BARR never retry: a blind re-send would
              double-accumulate (see send_var / barrier).
    resolver: optional callable returning the CURRENT endpoint, checked
              on every reconnect — a membership-backed resolver (e.g.
              ``lambda: kv.get(PS_PREFIX + "0")``) makes the client
              follow a replacement pserver that recovered from its
              checkpoint on a new port after a lease expiry.
    """

    def __init__(self, endpoint, timeout=60.0, retry=None, resolver=None):
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = timeout
        self._retry = retry
        self._resolver = resolver
        self._sock = None
        self._side = []            # lazy chunk-parallel push streams
        self._connect()

    def _connect(self):
        if self._resolver is not None:
            try:
                ep = self._resolver()
            except Exception:
                ep = None
            if ep:
                host, port = ep.rsplit(":", 1)
                self._addr = (host, int(port))
        s = socket.create_connection(self._addr, timeout=self._timeout)
        # Steady-state recv timeout: a dead/hung server raises
        # socket.timeout instead of deadlocking the whole test suite
        # (grpc deadline parity). barrier() lifts it — a sync-mode barrier
        # legitimately blocks until the slowest trainer arrives.
        s.settimeout(self._timeout)
        self._sock = s
        if _trace._TRACER is not None:
            # the span (verb or retry attempt) learns which endpoint
            # actually served it — a resolver-followed REPLACEMENT
            # pserver shows up as a changed endpoint on the attempt
            _trace.annotate(endpoint="%s:%d" % self._addr)

    def _drop_conn(self):
        """Close the main socket AND every side stream (a reconnect must
        never reuse a half-used stream's stale bytes) — the connection
        set rebuilds lazily from scratch."""
        for s in [self._sock] + self._side:
            if s is None:
                continue
            try:
                s.close()
            except OSError:
                pass
        self._sock = None
        self._side = []

    def _retrying(self, what, idempotent, body):
        """Run a verb body under the retry policy (when configured and
        the verb is idempotent). The body must re-read self._sock — a
        retry reconnects, possibly to a REPLACEMENT endpoint via the
        resolver. With tracing armed, the verb is ONE logical client
        span; Policy.run opens an attempt child per try, so a retried
        GET reads as one span with N attempt children in the merged
        timeline."""
        trc = _trace._TRACER
        if trc is None:
            return self._retrying_inner(what, idempotent, body)
        with trc.span(what, endpoint="%s:%d" % self._addr):
            out = self._retrying_inner(what, idempotent, body)
        self._maybe_clock_probe(trc)
        return out

    def _retrying_inner(self, what, idempotent, body):
        if self._retry is None or not idempotent:
            if self._sock is None:
                self._connect()
            return body()

        def attempt():
            if self._sock is None:
                self._connect()
                _mon.on_reconnect("rpc")
                _trace.annotate(reconnected=True)
            return body()

        return self._retry.run(
            attempt, what=what, retry_on=RETRYABLE,
            on_retry=lambda a, e: self._drop_conn())

    def _maybe_clock_probe(self, trc):
        """Periodic NTP-style offset sample against this peer on the
        idle main connection (call-response protocol: nothing is in
        flight between verbs). A torn probe leaves the stream desynced
        — drop the connection and let it rebuild lazily."""
        if self._sock is None:
            return
        try:
            _clock.probe(trc, "%s:%d" % self._addr,
                         lambda: _clock_exchange(self._sock))
        except (ConnectionError, OSError, ValueError, KeyError):
            self._drop_conn()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def _streams(self, n):
        while len(self._side) < n:
            s = socket.create_connection(self._addr,
                                         timeout=self._timeout)
            s.settimeout(self._timeout)
            self._side.append(s)
        return self._side[:n]

    def _push_value(self, op, wire, value, idempotent=True):
        """SEND/PUT with chunk-parallel streaming for large values: the
        serialized bytes split into _CHUNK_STREAMS ranges pushed
        concurrently over side connections (a single TCP stream is
        syscall-bound ~0.8 GB/s — PERF.md DCN tier), then committed on
        the main socket so ordering/idempotency semantics are untouched."""
        return self._retrying(
            "rpc." + op.lower(), idempotent,
            lambda: self._push_value_once(op, wire, value))

    def _push_value_once(self, op, wire, value):
        parts = _serialize_parts(value)
        total = sum(len(p) for p in parts)
        if total < _CHUNK_THRESHOLD or _CHUNK_STREAMS < 2:
            _send_msg(self._sock, op, wire, parts)
            return self._expect_ok()
        n = _CHUNK_STREAMS
        _RPC_CHUNK_PUSHES.inc()
        tid = uuid.uuid4().hex[:12]
        bounds = [total * i // n for i in range(n + 1)]
        socks = self._streams(n)
        errs = []

        def push_part(i):
            try:
                _send_msg(socks[i], "CHNK",
                          "%s:%d:%d:%d:%d" % (tid, i, n, bounds[i],
                                              total),
                          _slice_parts(parts, bounds[i], bounds[i + 1]))
                o, _, _ = _recv_msg(socks[i])
                if o != "OK":
                    raise ConnectionError("CHNK reply %s" % o)
            except BaseException as e:
                errs.append(e)

        threads = [threading.Thread(target=push_part, args=(i,),
                                    daemon=True) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            # a half-used side socket may hold stale bytes/replies:
            # never reuse it — a retry must reconnect fresh streams
            for s in self._side:
                try:
                    s.close()
                except OSError:
                    pass
            self._side = []
            raise errs[0]
        _send_msg(self._sock, op, wire, _CHUNK_MARKER + tid.encode())
        return self._expect_ok()

    def send_var(self, name, value, tag=None):
        """tag: optional idempotency token — a retried send with the
        same tag replaces the pending grad server-side (see SEND).

        Only a ROUND-format tag ('t<id>:i<inc>:s<seq>') licenses the
        retry policy to re-issue: the server's cross-round dedup
        (_applied) is keyed by the parsed prefix, so a free-form tag is
        deduped only within the current round — a replay after the
        round closed would be accumulated into the NEXT round."""
        wire = name if tag is None else "%s||%s" % (name, tag)
        self._push_value("SEND", wire, value,
                         idempotent=_parse_tag(tag)[0] is not None)

    def _expect_ok(self):
        op, _, payload = _recv_msg(self._sock)
        if op == "STLE":
            raise StaleIncarnationError(
                json.loads(payload.decode())["max_epoch"])
        assert op == "OK", op

    def get_var(self, name):
        def body():
            _send_msg(self._sock, "GET", name)
            op, _, payload = _recv_msg(self._sock)
            if op == "MISS":
                raise KeyError("server has no var %r" % name)
            return deserialize_var(payload)
        return self._retrying("rpc.get", True, body)

    def put_var(self, name, value):
        self._push_value("PUT", name, value)

    def prefetch(self, table_name, ids, want_version=False):
        """Fetch rows by id. ``want_version=True`` additionally returns
        the server's version coordinates parsed from the reply name —
        ``{"round": <optimize rounds applied>, "inc": <server
        incarnation>}``, or None against a pre-versioning server — the
        token serving.sparse's hot-ID cache keys bounded staleness and
        respawn invalidation on."""
        def body():
            _send_msg(self._sock, "PRFT", table_name,
                      serialize_var(np.asarray(ids, np.int64)))
            op, name, payload = _recv_msg(self._sock)
            if op == "MISS":
                raise KeyError("server has no table %r" % table_name)
            sr = deserialize_var(payload)
            if not want_version:
                return sr
            ver = None
            parts = name.split("|")
            if len(parts) == 3 and parts[1][:1] == "v":
                try:
                    ver = {"round": int(parts[1][1:]), "inc": parts[2]}
                except ValueError:
                    pass
            return sr, ver
        return self._retrying("rpc.prefetch", True, body)

    def barrier(self, tag=None):
        # ROUND-tagged barriers are exactly-once server-side across
        # rounds (_applied, keyed by the parsed tag prefix), so the
        # retry policy may re-issue them; an untagged or free-form tag
        # is only deduped within the current round (_barr_seen resets
        # when it closes) — a replay would count toward the NEXT round,
        # so those never retry
        def body():
            _send_msg(self._sock, "BARR", tag or "")
            # no deadline: the server replies only after all fan_in
            # trainers arrive, which can take arbitrarily long (slow
            # peers, compiles)
            self._sock.settimeout(None)
            try:
                self._expect_ok()
            finally:
                sock = self._sock
                if sock is not None:
                    try:
                        sock.settimeout(self._timeout)
                    except OSError:
                        pass
        return self._retrying("rpc.barrier",
                              _parse_tag(tag)[0] is not None, body)

    def shutdown_server(self):
        try:
            if self._sock is None:
                self._connect()
            _send_msg(self._sock, "EXIT", "")
            _recv_msg(self._sock)
        except (ConnectionError, OSError):
            pass

    def close(self):
        self._drop_conn()
