"""paddle_tpu.serving.rollout: the canary analysis plane, chaos-gated
(ISSUE 19).

Tiers:

  * Mirror sampler + delta-spec units (no fleet): deterministic
    rid-hash sampling, loud delta-spec validation, the pure
    ``slo.evaluate_delta`` verdict arithmetic, and the DeltaRule's
    exactly-once decision (pending until the pair/request gates,
    one FIRING on FAIL, silence on PASS, forced override).
  * The accounting seam (satellite 4): shadow rows are EXCLUDED
    wholesale from the incumbent SLO surface — serving samples,
    error counters, queue/occupancy gauges, ``scale_hint()`` — while
    errored shadow rows still reach the offender ring.
  * THE CHAOS GATE (tier-1 smoke + ``-m slow`` soak, seeded like
    test_autoscale.py): a full artifact -> shadow -> canary ->
    promote pipeline under seeded frame faults with a candidate
    KILLED mid-shadow and mid-canary — the verdicts land
    exactly-once from >= min_pairs joined pairs, every accepted
    request completes exactly once, token-identical to the
    fault-free sequential baseline, zero shed; and a DEGRADED
    candidate (different weights -> token disagreement) FAILs,
    auto-rolls-back before serving a single candidate-only token,
    and opens an exactly-once incident whose forensics bundle names
    the candidate version.
"""

import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor, serving, slo
from paddle_tpu.models import transformer
from paddle_tpu.models.transformer_infer import TransformerLMInfer
from paddle_tpu.monitor import runtime as monrt
from paddle_tpu.monitor import signals as msignals
from paddle_tpu.monitor.watch import (WatchState, render_frame,
                                      rollout_line)
from paddle_tpu.distributed.membership import KVServer, KVClient
from paddle_tpu.resilience import faults
from paddle_tpu.serving import fleet
from paddle_tpu.serving.autoscale import Autoscaler
from paddle_tpu.serving.fleet import Router
from paddle_tpu.serving.rollout import (RolloutController,
                                        fetch_verdicts)

N_LAYER, N_HEAD, D_MODEL, MAX_LEN, VOCAB = 1, 2, 32, 48, 40


@pytest.fixture(scope="module")
def arts(tmp_path_factory):
    """One tiny LM saved as v1/v2 (same weights: PASS + token identity
    across the promotion is the contract) plus v_bad — same interface,
    DIFFERENT weights (d_inner halved, fresh init), whose greedy
    decode disagrees with the incumbent: the token-agreement delta
    objective must FAIL it."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        _, logits = transformer.transformer_lm(
            vocab_size=VOCAB, max_len=MAX_LEN, n_layer=N_LAYER,
            n_head=N_HEAD, d_model=D_MODEL, d_inner=64)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        lm = TransformerLMInfer(main, scope, N_LAYER, N_HEAD,
                                D_MODEL, MAX_LEN)
    base = tmp_path_factory.mktemp("canary")
    v1, v2 = str(base / "v1"), str(base / "v2")
    for d in (v1, v2):
        serving.save_lm_artifact(d, main, scope, [logits], N_LAYER,
                                 N_HEAD, D_MODEL, MAX_LEN)
    main_b, startup_b = fluid.Program(), fluid.Program()
    scope_b = fluid.Scope()
    with fluid.program_guard(main_b, startup_b), \
            fluid.scope_guard(scope_b):
        _, logits_b = transformer.transformer_lm(
            vocab_size=VOCAB, max_len=MAX_LEN, n_layer=N_LAYER,
            n_head=N_HEAD, d_model=D_MODEL, d_inner=32)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup_b)
    v_bad = str(base / "v_bad")
    serving.save_lm_artifact(v_bad, main_b, scope_b, [logits_b],
                             N_LAYER, N_HEAD, D_MODEL, MAX_LEN)
    return {"lm": lm, "v1": v1, "v2": v2, "v_bad": v_bad}


def _requests(rng, n, max_prompt=8, min_new=4, max_new=10):
    reqs = []
    for _ in range(n):
        plen = int(rng.randint(1, max_prompt + 1))
        prompt = [1] + rng.randint(3, VOCAB, plen - 1).tolist()
        reqs.append((prompt, int(rng.randint(min_new, max_new + 1))))
    return reqs


DELTA = {
    "window_s": 300.0, "min_pairs": 6, "min_requests": 6,
    "objectives": [
        # thresholds are deliberately loose: a loaded CI host must
        # not flake the latency ratio, and injected chaos legitimately
        # fails a few in-flight candidate copies (a kill right before
        # the gates fill concentrates error pairs in a tiny sample) —
        # the degradation signal under test is token agreement
        {"metric": "delta_ttft", "percentile": 0.95,
         "max_inflation": 50.0, "min_floor_s": 0.5},
        {"metric": "delta_error_rate", "max_delta": 0.75},
        {"metric": "token_agreement", "min_ratio": 0.9},
    ],
}


# -- sampler + spec units ---------------------------------------------------

def test_mirror_sampler_deterministic():
    """The shadow/canary sampler is a pure rid hash: the same rid
    always lands on the same side of the fraction (replica-count and
    call-order independent), 0.0 selects nothing, 1.0 everything, and
    the selected fraction tracks the configured one."""
    rids = ["r%04d" % i for i in range(2000)]
    for frac in (0.1, 0.25, 0.5):
        picked = [r for r in rids if Router._sampled(r, frac)]
        assert picked == [r for r in rids if Router._sampled(r, frac)]
        assert abs(len(picked) / len(rids) - frac) < 0.06
    assert not [r for r in rids if Router._sampled(r, 0.0)]
    assert len([r for r in rids if Router._sampled(r, 1.0)]) == 2000


def test_validate_delta_spec_loud():
    assert slo.validate_delta_spec(DELTA)["min_pairs"] == 6
    with pytest.raises(ValueError, match="objectives"):
        slo.validate_delta_spec({"objectives": []})
    with pytest.raises(ValueError, match="max_inflation"):
        slo.validate_delta_spec({"objectives": [
            {"metric": "delta_ttft", "percentile": 0.95}]})
    with pytest.raises(ValueError, match="percentile"):
        slo.validate_delta_spec({"objectives": [
            {"metric": "delta_tpot", "percentile": 1.5,
             "max_inflation": 2.0}]})
    with pytest.raises(ValueError, match="max_delta"):
        slo.validate_delta_spec({"objectives": [
            {"metric": "delta_error_rate"}]})
    with pytest.raises(ValueError, match="min_ratio"):
        slo.validate_delta_spec({"objectives": [
            {"metric": "token_agreement"}]})
    with pytest.raises(ValueError, match="unknown metric"):
        slo.validate_delta_spec({"objectives": [
            {"metric": "delta_goodput", "max_inflation": 2.0,
             "percentile": 0.5}]})
    # load_spec validates an embedded delta block the same way
    with pytest.raises(ValueError, match="unknown metric"):
        slo.load_spec({"objectives": [
            {"metric": "error_rate", "target": 0.99,
             "windows": [{"short_s": 60, "long_s": 300,
                          "burn_rate": 2.0}]}],
            "delta": {"objectives": [{"metric": "nope"}]}})


def test_evaluate_delta_arithmetic():
    now = 1000.0

    def req(side_shadow, ttft, err=None, version="v2"):
        e = {"ev": "serving_request", "ts": now, "ttft": ttft,
             "tpot": 0.001, "queue_wait": 0.0}
        if side_shadow:
            e["shadow"], e["version"] = True, version
        if err:
            e["error"] = err
        return e

    events = [req(False, 0.010) for _ in range(8)] \
        + [req(True, 0.012) for _ in range(8)] \
        + [{"ev": "mirror_pair", "ts": now, "version": "v2",
            "rid": "r%d" % i, "agree": i != 0, "match": 1.0}
           for i in range(8)]
    ds = slo.delta_samples_from_events(events, "v2")
    assert ds["pairs"] == 8 and ds["agree"] == 7
    assert ds["cand"]["requests"] == ds["inc"]["requests"] == 8
    rep = slo.evaluate_delta(
        {"objectives": [
            {"metric": "delta_ttft", "percentile": 0.95,
             "max_inflation": 1.5},
            {"metric": "delta_error_rate", "max_delta": 0.01},
            {"metric": "token_agreement", "min_ratio": 0.8}]}, ds)
    assert rep["pass"], rep
    by = {o["metric"]: o for o in rep["objectives"]}
    assert abs(by["delta_ttft"]["measured"] - 1.2) < 1e-6
    assert by["delta_error_rate"]["measured"] == 0.0
    assert by["token_agreement"]["measured"] == 7 / 8
    # inflation above threshold flips the verdict
    rep = slo.evaluate_delta(
        {"objectives": [{"metric": "delta_ttft", "percentile": 0.95,
                         "max_inflation": 1.1}]}, ds)
    assert not rep["pass"]
    # ... unless the candidate percentile sits under the absolute
    # floor: ratio inflation over a near-zero baseline is not a
    # regression (cand p95 = 12 ms here)
    rep = slo.evaluate_delta(
        {"objectives": [{"metric": "delta_ttft", "percentile": 0.95,
                         "max_inflation": 1.1,
                         "min_floor_s": 0.05}]}, ds)
    assert rep["pass"], rep
    assert "floor" in rep["objectives"][0]["reason"]
    with pytest.raises(ValueError, match="min_floor_s"):
        slo.validate_delta_spec(
            {"objectives": [{"metric": "delta_ttft",
                             "max_inflation": 1.1,
                             "min_floor_s": -1}]})
    # a side with no samples is a FAIL with a reason, never a crash
    rep = slo.evaluate_delta(
        {"objectives": [{"metric": "delta_tpot", "percentile": 0.5,
                         "max_inflation": 2.0}]},
        slo.delta_samples_from_events([], "v2"))
    assert not rep["pass"]
    assert "no" in rep["objectives"][0]["reason"]
    # errored candidate requests are excluded from latency per side
    # (PR-6), but counted in the error-rate delta
    events2 = [req(False, 0.010) for _ in range(4)] \
        + [req(True, 5.0, err="boom"), req(True, 0.011)]
    ds2 = slo.delta_samples_from_events(events2, "v2")
    assert ds2["cand"]["errors"] == 1
    assert ds2["cand"]["ttft"] == [0.011]


def test_delta_rule_exactly_once(tmp_path):
    """PENDING until the gates; decides once; PASS never fires; FAIL
    fires exactly one page-severity edge; the verdict recorder row
    lands exactly once either way."""
    mlog = str(tmp_path / "verdicts.jsonl")
    with monitor.session(log_path=mlog):
        now = time.time()
        inc = [{"ev": "serving_request", "ts": now, "ttft": 0.01,
                "tpot": 0.001, "queue_wait": 0.0} for _ in range(6)]
        sh = [{"ev": "serving_request", "ts": now, "ttft": 0.01,
               "tpot": 0.001, "queue_wait": 0.0, "shadow": True,
               "version": "v2"} for _ in range(6)]
        pairs = [{"ev": "mirror_pair", "ts": now, "version": "v2",
                  "rid": "r%d" % i, "agree": True, "match": 1.0}
                 for i in range(6)]
        rule = msignals.DeltaRule(DELTA, "v2", phase="shadow")
        sig = msignals.Signals(rules=[rule])
        sig.feed_events(inc + sh, now=now)     # no pairs yet: pending
        assert sig.evaluate(now=now) == []
        assert rule.verdict is None
        sig.feed_events(pairs, now=now)
        assert sig.evaluate(now=now) == []     # PASS: no edge
        assert rule.verdict == "PASS"
        assert sig.evaluate(now=now + 1) == []

        # a pair set that disagrees -> FAIL fires EXACTLY once
        bad = [dict(p, agree=False, match=0.4) for p in pairs]
        rule2 = msignals.DeltaRule(DELTA, "v3", phase="shadow")
        sig2 = msignals.Signals(rules=[rule2])
        sig2.feed_events(
            inc + [dict(e, version="v3") for e in sh]
            + [dict(p, version="v3") for p in bad], now=now)
        edges = sig2.evaluate(now=now)
        assert [e["state"] for e in edges] == ["FIRING"]
        assert edges[0]["severity"] == "page"
        assert rule2.verdict == "FAIL"
        assert sig2.evaluate(now=now + 1) == []
        assert sig2.evaluate(now=now + 100) == []
    rows = monitor.read_jsonl(mlog)
    verd = [r for r in rows if r["ev"] == "verdict"]
    assert [(v["version"], v["verdict"]) for v in verd] == \
        [("v2", "PASS"), ("v3", "FAIL")]


# -- the accounting seam (satellite 4) --------------------------------------

def test_shadow_rows_excluded_from_slo_surface():
    """Armed shadow must leave the incumbent surface untouched:
    samples_from_events drops shadow rows, Signals neither samples
    nor counts them (errored ones still reach the offender ring),
    and shadow serving_step rows never vote in the queue/occupancy
    gauges scale_hint() reads."""
    now = time.time()
    shadow_req = {"ev": "serving_request", "ts": now, "ttft": 9.0,
                  "tpot": 9.0, "queue_wait": 9.0, "shadow": True,
                  "version": "v2"}
    shadow_err = dict(shadow_req, error="candidate exploded",
                      trace="t-shadow")
    shadow_step = {"ev": "serving_step", "ts": now, "dt": 9.0,
                   "engine": "cand", "queue_depth": 50, "slots": 2,
                   "active": 2, "shadow": True, "version": "v2"}
    samples = slo.samples_from_events(
        [shadow_req, shadow_err, shadow_step], compute_goodput=False)
    assert samples["requests"] == 0 and samples["errors"] == 0
    assert samples["ttft"] == []

    sig = msignals.Signals(spec=None)
    sig.feed_events([shadow_req, shadow_err, shadow_step], now=now)
    assert sig._row_totals["requests"] == 0
    assert sig._row_totals["errors"] == 0
    assert not sig._samples.get("ttft")
    assert not sig._samples.get("step_latency")
    assert "queue_depth" not in sig._series
    assert "occupancy" not in sig._series
    assert sig.scale_hint().direction == "hold"
    offs = list(sig._offenders)
    assert len(offs) == 1 and offs[0]["trace"] == "t-shadow"

    # the identical rows WITHOUT the shadow mark do land (the seam is
    # the flag, not the shape)
    sig2 = msignals.Signals(spec=None)
    live = [{k: v for k, v in e.items() if k != "shadow"}
            for e in (shadow_req, shadow_step)]
    sig2.feed_events(live, now=now)
    assert sig2._row_totals["requests"] == 1
    assert "queue_depth" in sig2._series


def test_shadow_engine_rows_skip_serving_metrics(tmp_path):
    """runtime.on_serving_step/on_serving_request with shadow=True
    tick ONLY the mirror surface: serving tokens/latency histograms
    and engine gauges keep their incumbent-only meaning."""
    with monitor.session(log_path=str(tmp_path / "m.jsonl")):
        tok0 = sum(monrt.SERVING_TOKENS.snapshot().values())
        mir0 = sum(monrt.MIRROR_TOKENS.snapshot().values())
        t0 = {k: v["count"] for k, v
              in monrt.SERVING_TTFT.snapshot().items()}
        monrt.on_serving_step(active=2, slots=2, queue_depth=7,
                              emitted=3, engine="cand-eng", dt=0.01,
                              shadow=True, version="v2")
        monrt.on_serving_request("cand-eng", ttft=0.5, tpot=0.1,
                                 queue_wait=0.2, shadow=True,
                                 version="v2")
        assert sum(monrt.SERVING_TOKENS.snapshot().values()) == tok0
        assert sum(monrt.MIRROR_TOKENS.snapshot().values()) \
            == mir0 + 3
        t1 = {k: v["count"] for k, v
              in monrt.SERVING_TTFT.snapshot().items()}
        assert t1 == t0
        occ = monrt.SERVING_SLOT_OCCUPANCY.snapshot()
        assert ("cand-eng",) not in occ


def test_late_candidate_result_never_completes_serving_entry():
    """A candidate's LATE shadow result — its mirror job already
    dropped by disarm/sweep while the grace-window poller kept
    draining — must be acked-and-dropped, never fall through to the
    journal and complete the still-pending serving entry with
    candidate-generated tokens (the 'rollback serves zero
    candidate-only tokens' invariant). Canary-marked entries are the
    one legitimate candidate-completion path and must stay open."""
    kvs = KVServer(sweep_interval=0.05).start()
    kv = KVClient(kvs.endpoint)
    router = None
    try:
        router = Router(kvs.endpoint, refresh_interval=5.0,
                        name="lateshadow")
        cand = fleet._CAND_BASE + 0

        # shadow entry whose mirror job was dropped (disarm): the
        # late candidate result must not touch the journal entry
        router.arm_shadow("v2", fraction=1.0)
        h = router.submit([1, 2, 3], 4)
        rid = h.rid
        assert rid in router._mirror_jobs
        router.disarm_mirror()
        dropped0 = router.stats["mirror_dropped"]
        assert router._complete(
            cand, {"id": rid, "tokens": [9, 9, 9], "score": 0.0})
        with router._lock:
            entry = router._journal[rid]
            assert entry["state"] == "queued"
            assert not h._event.is_set()
        assert router.stats["completed"] == 0
        assert router.stats["canary_served"] == 0
        assert router.stats["mirror_dropped"] == dropped0 + 1

        # canary-marked entry: a candidate slot MAY complete it
        router.arm_canary("v2", weight=1.0)
        h2 = router.submit([1, 2, 3], 4)
        with router._lock:
            assert router._journal[h2.rid].get("canary")
        assert router._complete(
            cand, {"id": h2.rid, "tokens": [7, 8], "score": 0.5})
        assert h2.result(timeout=5) == ([7, 8], 0.5)
    finally:
        if router is not None:
            router.close()
        try:
            kv.shutdown_server()
            kv.close()
        except OSError:
            pass


def test_stall_evicted_candidate_tombstone_sticks():
    """Evicting a candidate must tombstone its MARKED lease value
    ('version:<ver>:<ep>' — Replica stamps it at boot): a
    bare-endpoint CAS never matches a marked lease, so the wedged
    holder's expect-guarded keepalive would keep winning and stall
    recovery would degrade into evict/re-add churn instead of the
    rollout controller's bounded respawn."""
    from paddle_tpu.distributed import membership as _mem
    kvs = KVServer(sweep_interval=0.05).start()
    kv = KVClient(kvs.endpoint)
    router = None
    try:
        router = Router(kvs.endpoint, refresh_interval=5.0,
                        name="tomb")
        ep = "127.0.0.1:59999"
        key = _mem.role_prefix(fleet.CANDIDATE_ROLE) + "0"
        kv.put(key, fleet.VERSION_PREFIX + "v2:" + ep, ttl=30.0)
        slot = fleet._CAND_BASE + 0

        class _Client:
            def close(self):
                pass

        with router._cv:
            router._replicas[slot] = {"endpoint": ep,
                                      "client": _Client()}
            router._inflight.setdefault(slot, set())
            router._cand_versions[slot] = "v2"
        assert router._replica_down(slot, ep, "stall")
        assert kv.get(key) == fleet.EVICTED_PREFIX + ep
    finally:
        if router is not None:
            router.close()
        try:
            kv.shutdown_server()
            kv.close()
        except OSError:
            pass


# -- the chaos gate ---------------------------------------------------------

CHAOS_SPEC = {
    "rpc": {"drop": 0.03, "duplicate": 0.03, "close_mid_frame": 0.02,
            "delay": 0.05, "delay_s": 0.003, "max": 6},
    "kill": [{"target": "shadow", "after": 2},
             {"target": "canary", "after": 1}],
}


def _run_rollout_chaos(arts, reqs, seq, seed, tmp_path, tag):
    """KV + autoscaler (2 incumbents from v1) + router; armed seeded
    plan (frame faults on the incumbents' ports, candidate kills
    mid-shadow and mid-canary); traffic flows while the controller
    drives artifact v2 -> shadow -> canary -> promote. Asserts the
    ISSUE-19 acceptance invariants."""
    kvs = KVServer(sweep_interval=0.05).start()
    kv = KVClient(kvs.endpoint)
    auto = router = ctl = plan = None
    try:
        auto = Autoscaler(kvs.endpoint, arts["v1"], desired=2,
                          min_replicas=1, max_replicas=5, slots=2,
                          ttl=0.4, interval=0.05, cooldown=0.0,
                          drain_timeout=15.0, health_timeout=15.0,
                          prefill_chunk=4).start()
        auto.wait_steady(timeout=30)
        spec = dict(CHAOS_SPEC)
        rpc_spec = dict(spec["rpc"])
        rpc_spec["ports"] = [c.server.port for c in auto.cells]
        spec["rpc"] = rpc_spec
        plan = faults.arm(spec, seed=seed)
        router = Router(kvs.endpoint, window=3, max_queue=64,
                        stall_timeout=1.0, refresh_interval=0.05,
                        client_timeout=0.8, name="canary-" + tag)
        router.wait_for_replicas(2, timeout=15)
        desired0 = auto.status()["desired"]

        ctl = RolloutController(
            kvs.endpoint, router, auto, arts["v2"],
            {"delta": DELTA}, candidates=2, shadow_fraction=1.0,
            canary_weight=0.4, verdict_timeout=60.0, max_respawns=4,
            slots=2, ttl=0.4, prefill_chunk=4)
        done = {}
        th = threading.Thread(
            target=lambda: done.update(st=ctl.run()), daemon=True)
        th.start()

        out, i = [], 0
        deadline = time.monotonic() + 180
        while th.is_alive():
            batch = [reqs[j % len(reqs)]
                     for j in range(i, i + 4)]
            hs = [router.submit(p, m) for p, m in batch]
            got = [h.result(timeout=120) for h in hs]
            for j, (bt, bs) in enumerate(got):
                assert bt == seq[(i + j) % len(reqs)][0], \
                    "request %d diverged" % (i + j)
            out += got
            i += 4
            if time.monotonic() > deadline:
                raise AssertionError(
                    "rollout did not terminate: %r" % ctl.status())
        th.join(timeout=120)
        st = done.get("st") or ctl.status()

        # PASS promoted the artifact, verdicts landed per phase
        assert st["phase"] == "promoted", st
        assert st["verdicts"]["shadow"]["verdict"] == "PASS"
        assert st["verdicts"]["canary"]["verdict"] == "PASS"
        assert st["verdicts"]["shadow"]["pairs"] \
            >= DELTA["min_pairs"]
        assert st["convergence_s"] and st["convergence_s"] > 0

        # chaos actually fired: frame faults + both mid-phase kills
        kinds = {k for k, _ in plan.trips}
        assert kinds & {"drop", "duplicate", "close_mid_frame",
                        "delay"}, plan.trips
        assert ("kill", "shadow") in plan.trips, plan.trips
        assert ("kill", "canary") in plan.trips, plan.trips
        assert ctl.respawns >= 1

        # exactly-once, zero shed, zero failures on the serving path
        rst = router.stats
        assert rst["failed"] == 0
        assert rst["shed"] == 0
        assert rst["completed"] == rst["requests"] == len(out)
        assert rst["mirror_pairs"] >= DELTA["min_pairs"]
        assert rst["canary_served"] >= 1

        # the fleet converged to v2-only; elasticity was untouched
        fst = auto.wait_steady(timeout=30)
        assert fst["version_mix"].get("v2") == 2
        assert not fst["version_mix"].get("v1")
        assert auto.status()["desired"] == desired0

        # verdicts are served on the wire (VERD, idempotent)
        verd = fetch_verdicts(ctl.control.endpoint)
        assert verd["phase"] == "promoted"
        assert verd["verdicts"]["shadow"]["verdict"] == "PASS"
        return ctl
    finally:
        faults.disarm()
        if ctl is not None:
            ctl.close()
        if router is not None:
            router.close()
        if auto is not None:
            auto.close()
        try:
            kv.shutdown_server()
            kv.close()
        except OSError:
            pass


def test_rollout_chaos_pass_promotes(rng, arts, tmp_path):
    """Tier-1 gate: the full pipeline under seeded frame faults +
    mid-shadow and mid-canary candidate kills — PASS verdicts from
    joined pairs, token-identical exactly-once completion, zero shed,
    fleet promoted to v2."""
    reqs = _requests(rng, 12, min_new=4, max_new=8)
    seq = serving.sequential_generate(arts["lm"], reqs)
    mlog = str(tmp_path / "rollout-mon.jsonl")
    with monitor.session(log_path=mlog):
        _run_rollout_chaos(arts, reqs, seq, seed=1907,
                           tmp_path=tmp_path, tag="smoke")
    rows = monitor.read_jsonl(mlog)
    # exactly one verdict row per phase (the exactly-once contract on
    # the evidence surface itself)
    verd = [r for r in rows if r["ev"] == "verdict"]
    assert [(v["phase"], v["verdict"]) for v in verd] == \
        [("shadow", "PASS"), ("canary", "PASS")]
    pairs = [r for r in rows if r["ev"] == "mirror_pair"]
    assert len(pairs) >= DELTA["min_pairs"]
    assert all(r["version"] == "v2" and r["rid"] for r in pairs)
    # same weights -> every CLEAN pair agrees; a copy cut down by the
    # chaos kill joins as a disagreeing pair carrying the error (the
    # error-rate delta's evidence), never as silent agreement
    clean = [r for r in pairs if not r.get("candidate_error")]
    assert clean and all(r["agree"] for r in clean)
    phases = [r["phase"] for r in rows if r["ev"] == "rollout"]
    assert phases[0] == "boot" and phases[-1] == "promoted"
    assert "shadow" in phases and "canary" in phases \
        and "rolling" in phases
    # mirrored rows are marked; canary-served rows carry the version
    sreq = [r for r in rows if r["ev"] == "serving_request"]
    assert any(r.get("shadow") for r in sreq)
    assert any(r.get("version") == "v2" and not r.get("shadow")
               for r in sreq)
    # the watch dashboard renders the status line from the same rows
    st = WatchState()
    for r in rows:
        st.feed_event(r)
    line = rollout_line(st)
    assert "phase promoted" in line and "v2" in line
    assert "shadow:PASS" in line and "canary:PASS" in line
    assert "convergence" in line
    frame = render_frame(st, mlog, now=time.time())
    assert "rollout" in frame


def test_rollout_degraded_candidate_rolls_back(rng, arts, tmp_path):
    """The FAIL path end-to-end: a candidate with DIFFERENT weights
    fails token agreement in shadow, the rollout auto-rolls-back
    WITHOUT serving a single candidate-only token, and the
    exactly-once incident carries a forensics bundle naming the
    candidate version."""
    reqs = _requests(rng, 10, min_new=4, max_new=8)
    kvs = KVServer(sweep_interval=0.05).start()
    kv = KVClient(kvs.endpoint)
    auto = router = ctl = None
    mlog = str(tmp_path / "fail-mon.jsonl")
    try:
        with monitor.session(log_path=mlog):
            auto = Autoscaler(kvs.endpoint, arts["v1"], desired=2,
                              min_replicas=1, max_replicas=4,
                              slots=2, ttl=0.4, interval=0.05,
                              cooldown=0.0,
                              prefill_chunk=4).start()
            auto.wait_steady(timeout=30)
            router = Router(kvs.endpoint, window=3, max_queue=64,
                            stall_timeout=1.0,
                            refresh_interval=0.05,
                            client_timeout=0.8, name="canary-fail")
            router.wait_for_replicas(2, timeout=15)
            ctl = RolloutController(
                kvs.endpoint, router, auto, arts["v_bad"],
                {"delta": DELTA}, candidates=1,
                shadow_fraction=1.0, verdict_timeout=60.0,
                slots=2, ttl=0.4, prefill_chunk=4, capture=True,
                capture_dir=str(tmp_path / "bundles"))
            done = {}
            th = threading.Thread(
                target=lambda: done.update(st=ctl.run()),
                daemon=True)
            th.start()
            i = 0
            deadline = time.monotonic() + 180
            while th.is_alive():
                hs = [router.submit(p, m)
                      for p, m in reqs[i % len(reqs):
                                       i % len(reqs) + 3]]
                for h in hs:
                    h.result(timeout=120)
                i += 3
                if time.monotonic() > deadline:
                    raise AssertionError(
                        "no verdict: %r" % ctl.status())
            th.join(timeout=120)
            st = done.get("st") or ctl.status()

            assert st["phase"] == "rolled-back", st
            rep = st["verdicts"]["shadow"]
            assert rep["verdict"] == "FAIL"
            agree = [o for o in rep["objectives"]
                     if o["metric"] == "token_agreement"]
            assert agree and agree[0]["pass"] is False
            # ZERO candidate-only tokens were served: canary never
            # armed, no canary completion ever counted
            assert router.stats["canary_served"] == 0
            assert router.stats["canary"] == 0
            # the incumbent fleet is intact, single-version
            fst = auto.wait_steady(timeout=30)
            assert fst["version_mix"] == {"v1": 2}
            assert router.mirror_status()["mirror"] is None
            # ...and still serves, token-identically
            seq = serving.sequential_generate(arts["lm"], reqs[:3])
            hs = [router.submit(p, m) for p, m in reqs[:3]]
            for (bt, _), h in zip(seq, hs):
                assert h.result(timeout=120)[0] == bt
    finally:
        if ctl is not None:
            ctl.close()
        if router is not None:
            router.close()
        if auto is not None:
            auto.close()
        try:
            kv.shutdown_server()
            kv.close()
        except OSError:
            pass
    rows = monitor.read_jsonl(mlog)
    verd = [r for r in rows if r["ev"] == "verdict"]
    assert len(verd) == 1 and verd[0]["verdict"] == "FAIL"
    assert verd[0]["version"] == "v_bad"
    # exactly-once incident: one FIRING alert row for the delta rule
    alerts = [r for r in rows if r["ev"] == "alert"
              and r["rule"].startswith("delta:")]
    assert len(alerts) == 1
    assert alerts[0]["state"] == "FIRING"
    assert alerts[0]["severity"] == "page"
    assert "v_bad" in alerts[0]["rule"]
    phases = [r["phase"] for r in rows if r["ev"] == "rollout"]
    assert phases[-1] == "rolled-back"
    assert "canary" not in phases and "rolling" not in phases
    # the forensics bundle landed and its incident names the version
    from paddle_tpu.monitor import forensics
    bundles = sorted((tmp_path / "bundles").glob("bundle-*"))
    assert bundles, "no forensics bundle captured"
    man = forensics.load_manifest(str(bundles[-1]))
    assert "v_bad" in (man.get("rule") or "")
    assert man.get("incident_file") == "incident.json"
    with open(bundles[-1] / "incident.json") as f:
        inc = json.load(f)
    assert "v_bad" in inc.get("rule", "")


def test_rollout_forced_fail_serves_nothing(rng, arts, tmp_path):
    """force_fail (the operator override / drill path) rolls back
    from shadow without waiting for the gates — and provably without
    a single candidate-served token."""
    kvs = KVServer(sweep_interval=0.05).start()
    kv = KVClient(kvs.endpoint)
    auto = router = ctl = None
    try:
        with monitor.session(log_path=str(tmp_path / "m.jsonl")):
            auto = Autoscaler(kvs.endpoint, arts["v1"], desired=1,
                              min_replicas=1, max_replicas=3,
                              slots=2, ttl=0.4, interval=0.05,
                              prefill_chunk=4).start()
            auto.wait_steady(timeout=30)
            router = Router(kvs.endpoint, window=3,
                            refresh_interval=0.05,
                            client_timeout=0.8,
                            name="canary-forced")
            router.wait_for_replicas(1, timeout=15)
            ctl = RolloutController(
                kvs.endpoint, router, auto, arts["v2"],
                {"delta": DELTA}, candidates=1,
                shadow_fraction=1.0, verdict_timeout=60.0,
                slots=2, ttl=0.4, prefill_chunk=4)
            ctl.force_fail("chaos drill")
            st = ctl.run()
            assert st["phase"] == "rolled-back"
            rep = st["verdicts"]["shadow"]
            assert rep["verdict"] == "FAIL" and rep.get("forced")
            assert rep["reason"] == "chaos drill"
            assert router.stats["canary_served"] == 0
            assert router.stats["canary"] == 0
            assert auto.wait_steady(timeout=30)["version_mix"] == \
                {"v1": 1}
    finally:
        if ctl is not None:
            ctl.close()
        if router is not None:
            router.close()
        if auto is not None:
            auto.close()
        try:
            kv.shutdown_server()
            kv.close()
        except OSError:
            pass


@pytest.mark.slow
def test_rollout_chaos_soak_three_runs(rng, arts, tmp_path):
    """The acceptance soak: the seeded rollout-chaos scenario passes
    3 consecutive times (fresh fleet each time)."""
    reqs = _requests(rng, 12, min_new=4, max_new=8)
    seq = serving.sequential_generate(arts["lm"], reqs)
    for attempt in range(3):
        with monitor.session(
                log_path=str(tmp_path / ("soak%d.jsonl" % attempt))):
            _run_rollout_chaos(arts, reqs, seq, seed=4242,
                               tmp_path=tmp_path,
                               tag="soak%d" % attempt)
