"""Device mesh + sharding annotations.

The mesh axes follow the scaling-book convention: ``dp`` (data), ``tp``
(tensor/model), ``pp`` (pipeline), ``sp`` (sequence/context), ``ep``
(expert). Any subset may be present; axis size 1 is free.
"""

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_DEFAULT_MESH = None


def make_mesh(axis_sizes=None, devices=None):
    """Build a Mesh. axis_sizes: dict like {"dp": 4, "tp": 2} (ordered).
    Defaults to all local devices on one dp axis."""
    devices = list(devices if devices is not None else jax.devices())
    if not axis_sizes:
        axis_sizes = {"dp": len(devices)}
    names = tuple(axis_sizes)
    sizes = tuple(int(axis_sizes[n]) for n in names)
    need = int(np.prod(sizes))
    if need > len(devices):
        raise ValueError(
            "mesh needs %d devices but only %d available" % (need,
                                                             len(devices)))
    arr = np.array(devices[:need]).reshape(sizes)
    return Mesh(arr, names)


def set_default_mesh(mesh):
    global _DEFAULT_MESH
    _DEFAULT_MESH = mesh
    return mesh


def default_mesh():
    return _DEFAULT_MESH


def shard(var, *spec):
    """Annotate a Program variable (or name) with a PartitionSpec-like
    tuple, e.g. shard(w, None, "tp") → rows replicated, cols on tp.
    The ParallelExecutor places matching state arrays with this sharding;
    XLA GSPMD propagates through the computation (tensor parallelism)."""
    from ..core.program import Variable, default_main_program
    name = var.name if isinstance(var, Variable) else str(var)
    prog = (var.block.program if isinstance(var, Variable)
            else default_main_program())
    prog._sharding_hints[name] = tuple(spec)
    return var


def sharding_hint(program, name):
    return program._sharding_hints.get(name)


def spec_to_named_sharding(mesh, spec):
    if spec is None:
        return NamedSharding(mesh, PartitionSpec())
    cleaned = []
    for s in spec:
        if s is None or s in mesh.axis_names:
            cleaned.append(s)
        else:
            cleaned.append(None)   # axis not in this mesh → replicate dim
    return NamedSharding(mesh, PartitionSpec(*cleaned))


class DistributedStrategy:
    """Knob container (reference BuildStrategy/ExecutionStrategy parity +
    the TPU axes)."""

    def __init__(self, dp=None, tp=1, pp=1, sp=1, ep=1,
                 use_bf16_compute=False, gradient_accumulation_steps=1,
                 gradient_accumulation_loss_norm=None,
                 pp_schedule="gpipe", pp_virtual_stages=0):
        self.dp = dp
        self.tp = tp
        self.pp = pp
        self.sp = sp
        self.ep = ep
        self.use_bf16_compute = use_bf16_compute
        self.gradient_accumulation_steps = gradient_accumulation_steps
        # loss-normalization contract for ragged (LoD) accumulation:
        # None | "sequence" | "token" | "token:<feed_name>" — see
        # ParallelExecutor._check_accum_weights
        self.gradient_accumulation_loss_norm = gradient_accumulation_loss_norm
        # pipeline schedule: "gpipe" (M >= S) or "interleaved" (Megatron
        # virtual stages, bubble / pp_virtual_stages; M <= S regime)
        self.pp_schedule = pp_schedule
        self.pp_virtual_stages = pp_virtual_stages

    def effective_dp(self, devices=None):
        """The dp size build_mesh will actually use: explicit dp wins;
        dp=None divides the device pool by the fixed axes. Model
        builders that bake dp-derived STATIC attrs (e.g. the MoE
        moe_gate_groups = dp*ep routing granularity) must resolve dp
        through this, not ``strategy.dp or 1`` — otherwise a dp=None
        strategy bakes groups for dp=1 while the mesh resolves dp>1 and
        the pipeline_stack validation rejects the mismatch."""
        if self.dp:
            return self.dp
        total = len(devices) if devices is not None else \
            jax.device_count()
        fixed = self.tp * self.pp * self.sp * self.ep
        return max(1, total // fixed)

    def build_mesh(self, devices=None):
        devices = list(devices if devices is not None else jax.devices())
        dp = self.effective_dp(devices)
        sizes = {}
        for name, size in (("dp", dp), ("pp", self.pp), ("sp", self.sp),
                           ("ep", self.ep), ("tp", self.tp)):
            if size and size > 1 or name == "dp":
                sizes[name] = size
        return make_mesh(sizes, devices)
