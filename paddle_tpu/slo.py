"""Declarative serving-SLO evaluation: spec in, verdict out.

The gate primitive of ROADMAP direction 2 ("latency SLOs measured from
merged trace timelines"): a JSON spec declares objectives over the
request-level figures the serving engine attributes per request
(queue_wait, TTFT, TPOT — serving/engine.py lifecycle stamps), plus
engine step latency and an error budget, and this module evaluates the
spec against any of the three observability surfaces the runtime
already produces:

  * monitor flight-recorder JSONL(s) (``serving_request`` /
    ``serving_step`` rows — EXACT per-request samples; pass one log
    per replica and the verdict covers the fleet-wide union),
  * trace span logs (``serving.request`` spans whose close-time attrs
    carry the same figures — the merged-fleet-timeline source: pass
    every process's span log and the verdict covers the fleet),
  * a metrics snapshot (``monitor.dump_metrics(...json)`` registry
    dump — ``ptpu_serving_*_seconds`` histogram buckets, APPROXIMATE
    bucket-interpolated percentiles).

Spec schema (JSON)::

    {
      "name": "chat-serving",
      "objectives": [
        {"metric": "ttft",        "percentile": 0.95, "max_seconds": 0.5},
        {"metric": "tpot",        "percentile": 0.99, "max_seconds": 0.05},
        {"metric": "queue_wait",  "percentile": 0.95, "max_seconds": 0.25},
        {"metric": "step_latency","percentile": 0.95, "max_seconds": 0.1},
        {"metric": "kv_used_blocks", "max_value": 56},
        {"metric": "staleness_s", "percentile": 0.95, "max_seconds": 6},
        {"metric": "goodput_fraction", "min_ratio": 0.7},
        {"metric": "error_rate",  "max_ratio": 0.001}
      ]
    }

``staleness_s`` (ISSUE 12) gates the sparse serving tier's measured
read-your-writes staleness (online update landed -> first serve
reflecting it): exact samples from ``sparse_staleness`` recorder rows
on the --log surface, bucket-interpolated from the
``ptpu_sparse_staleness_seconds`` histogram on --metrics.

``kv_used_blocks`` (ISSUE 10) gates paged-KV pool pressure from the
``serving_step`` rows' per-iteration occupancy (threshold is a plain
block count via ``max_value``; percentile defaults to 1.0 = the
window's max). Only the row surfaces carry it (--log / watch); a
metrics snapshot has no per-step series to gate.

``version_convergence_s`` / ``roll_shed`` (ISSUE 18) gate the elastic
fleet's rolling weight updates from ``roll`` recorder rows
(serving.autoscale): time from roll start to 100% of replicas serving
the new artifact version (completed rolls only — metrics surface reads
the ``ptpu_fleet_version_convergence_seconds`` histogram), and
requests shed while a roll was in flight (``max_value: 0`` declares
"a roll must not shed"; row surfaces only, like kv_used_blocks).

``goodput_fraction`` (ISSUE 11) gates the monitor.goodput wall-time
attribution — productive seconds over measured wall — computed from
the same recorder rows (a HIGHER-is-better objective: ``min_ratio``
is the floor). Row surfaces only, like kv_used_blocks.

Error-budget form (ISSUE 14): any objective over ``error_rate`` or a
latency metric may instead declare a target fraction plus multi-window
burn-rate pairs (the SRE-Workbook ch. 5 shape the streaming alerting
tier in ``monitor/signals.py`` evaluates live)::

    {"metric": "error_rate", "target": 0.999, "windows": [
        {"short_s": 300,  "long_s": 3600,  "burn_rate": 14.4,
         "severity": "page"},
        {"short_s": 1800, "long_s": 21600, "burn_rate": 6.0,
         "severity": "ticket"}]}

Latency metrics add ``max_seconds`` (what counts as a good event).
The burn rate over a window is ``bad_fraction / (1 - target)``; the
objective FAILS when any pair exceeds its ``burn_rate`` in BOTH
windows at the newest recorded timestamp. Window pairs are validated
at spec load (short_s < long_s, positive rates — exit 2 on
violation). Row surfaces only: the batch verdict needs timestamped
request rows, which --spans/--metrics do not carry.

An objective with NO samples fails (a run that measured nothing cannot
claim an SLO was met) and says so in its reason. CLI::

    python -m paddle_tpu.slo spec.json --log run.jsonl [--json]
    python -m paddle_tpu.slo spec.json --log rep0.jsonl rep1.jsonl ...
                                  # fleet: union across replica logs
    python -m paddle_tpu.slo spec.json --spans *.jsonl
    python -m paddle_tpu.slo spec.json --metrics metrics.json

Exit code 0 = every objective passed, 1 = any failed (the CI/chaos
gate contract), 2 = usage or spec error.
"""

import argparse
import json
import os
import sys

from .monitor.metrics import bucket_percentile as _hist_percentile
from .monitor.recorder import percentile_sorted as _pct
from .monitor.recorder import read_jsonl_tolerant

__all__ = [
    "load_spec", "evaluate", "samples_from_events",
    "samples_from_monitor_log", "samples_from_span_logs",
    "samples_from_metrics", "render", "main", "LATENCY_METRICS",
    "GAUGE_METRICS", "DELTA_LATENCY_METRICS", "validate_delta_spec",
    "delta_samples_from_events", "evaluate_delta",
]

# objective metric -> metrics-snapshot histogram. step_latency is the
# ENGINE iteration time on every surface (serving_step dt rows,
# engine.step span durations, ptpu_serving_step_seconds buckets) — the
# training executor's ptpu_step_seconds is a different quantity and is
# deliberately not consulted.
LATENCY_METRICS = {
    "ttft": "ptpu_serving_ttft_seconds",
    "tpot": "ptpu_serving_tpot_seconds",
    "queue_wait": "ptpu_serving_queue_wait_seconds",
    "step_latency": "ptpu_serving_step_seconds",
    # read-your-writes staleness of the sparse serving tier (ISSUE
    # 12): an online update landing on the pservers -> the first
    # serve reflecting it, measured end-to-end by
    # serving.sparse.measure_staleness (sparse_staleness recorder
    # rows / the ptpu_sparse_staleness_seconds histogram)
    "staleness_s": "ptpu_sparse_staleness_seconds",
    # rolling-weight-update convergence (ISSUE 18): start of a roll ->
    # 100% of the fleet serving the new artifact version, stamped by
    # serving.autoscale into `roll` recorder rows and the
    # ptpu_fleet_version_convergence_seconds histogram (aborted rolls
    # contribute NO sample — they never converged)
    "version_convergence_s": "ptpu_fleet_version_convergence_seconds",
}

# gauge-valued objectives (thresholds are plain values, not seconds):
# kv_used_blocks gates paged-KV pool pressure from the serving_step
# rows' kv_used_blocks field (ISSUE 10) — an operator bounds "how full
# may the pool run" the same way they bound a latency percentile
GAUGE_METRICS = ("kv_used_blocks", "roll_shed")


def _signals():
    # lazy: the burn math lives with the streaming alerting tier
    # (monitor/signals.py) so the batch verdict here and the live
    # evaluator can never drift
    from .monitor import signals
    return signals


def load_spec(source):
    """Parse + validate a spec (path, JSON string, or dict). Raises
    ValueError on schema violations — a malformed gate spec must fail
    LOUDLY (exit 2), never evaluate to a hollow pass."""
    if isinstance(source, dict):
        spec = source
    else:
        text = source
        if not str(source).lstrip().startswith("{"):
            with open(source) as f:
                text = f.read()
        spec = json.loads(text)
    objectives = spec.get("objectives")
    if not isinstance(objectives, list) or not objectives:
        raise ValueError("SLO spec needs a non-empty 'objectives' list")
    if spec.get("rules") is not None:
        # the signals rule overrides validate HERE, the one spec
        # choke point — every consumer (watch's alerts line, the
        # alerts CLI, a supervisor embedding Signals) gets the same
        # loud load-time failure instead of a traceback out of its
        # own loop
        _signals().build_rules({"rules": spec["rules"],
                                "objectives": []})
    if spec.get("delta") is not None:
        # canary delta objectives (ISSUE 19) live in their own block:
        # they gate candidate-vs-incumbent figures no single-version
        # sample set carries, so they never mix into the objectives
        # ladder above
        validate_delta_spec(spec["delta"])
    for i, obj in enumerate(objectives):
        metric = obj.get("metric")
        if _signals().is_budget_objective(obj):
            # error-budget form (ISSUE 14): target fraction + burn
            # window pairs, validated loudly at load — including
            # short_s < long_s on every pair
            _signals().validate_budget_objective(
                obj, i, known_metrics=("error_rate",)
                + tuple(LATENCY_METRICS))
        elif metric == "error_rate":
            if not isinstance(obj.get("max_ratio"), (int, float)):
                raise ValueError(
                    "objective %d (error_rate) needs numeric "
                    "'max_ratio'" % i)
        elif metric in LATENCY_METRICS:
            if not isinstance(obj.get("max_seconds"), (int, float)):
                raise ValueError(
                    "objective %d (%s) needs numeric 'max_seconds'"
                    % (i, metric))
            q = obj.get("percentile", 0.95)
            if not (0.0 < float(q) <= 1.0):
                raise ValueError(
                    "objective %d percentile %r outside (0, 1]"
                    % (i, q))
        elif metric == "goodput_fraction":
            if not isinstance(obj.get("min_ratio"), (int, float)):
                raise ValueError(
                    "objective %d (goodput_fraction) needs numeric "
                    "'min_ratio'" % i)
        elif metric in GAUGE_METRICS:
            if not isinstance(obj.get("max_value"), (int, float)):
                raise ValueError(
                    "objective %d (%s) needs numeric 'max_value'"
                    % (i, metric))
            q = obj.get("percentile", 1.0)
            if not (0.0 < float(q) <= 1.0):
                raise ValueError(
                    "objective %d percentile %r outside (0, 1]"
                    % (i, q))
        else:
            raise ValueError(
                "objective %d names unknown metric %r (known: %s, "
                "error_rate)"
                % (i, metric,
                   ", ".join(sorted(list(LATENCY_METRICS)
                                    + list(GAUGE_METRICS)
                                    + ["goodput_fraction"]))))
    return spec


# -- sample extraction (one function per observability surface) ------------

def _empty_samples(source):
    return {"source": source, "requests": 0, "errors": 0,
            "ttft": [], "tpot": [], "queue_wait": [],
            "step_latency": [], "kv_used_blocks": [],
            "staleness_s": [], "version_convergence_s": [],
            "roll_shed": [], "request_rows": [],
            "timed_samples": {},
            "goodput": None, "histograms": {}, "skipped": 0}


def samples_from_events(events, source="events",
                        compute_goodput=True):
    """Exact per-request samples from an iterable of flight-recorder
    event dicts (``serving_request`` rows + ``serving_step`` dt) — the
    ONE rows->samples extraction, shared by the monitor-log surface
    below and the watch dashboard's rolling-window verdict.

    ``compute_goodput=False`` skips the wall-time ledger: callers
    whose event stream is NOT one process's full timeline (the watch
    rolling window, a multi-log union) must supply their own
    per-process rollup instead — a union-timeline ledger would
    collapse concurrent replicas' intervals."""
    out = _empty_samples(source)
    if compute_goodput:
        # the goodput ledger reads the SAME rows (durations +
        # recovery markers); its wall-time attribution backs the
        # goodput_fraction objective on the row surfaces. Only this
        # double-iteration needs the events materialized — the
        # single-pass callers (watch's per-refresh window) keep
        # streaming.
        from .monitor import goodput as _goodput
        events = list(events)
        out["goodput"] = _goodput.ledger_from_events(events)
    def _timed(metric, ts, v):
        # timestamped per-metric samples back the error-budget burn
        # math for LATENCY metrics (error_rate burns over
        # request_rows) — every metric a budget spec may name gets a
        # window-countable series, matching the live evaluator
        out["timed_samples"].setdefault(metric, []).append(
            (float(ts), float(v)))

    for e in events:
        ev = e.get("ev")
        if e.get("shadow"):
            # mirrored traffic (canary analysis plane, ISSUE 19):
            # scored, never served — excluded from the incumbent
            # verdict wholesale, the same way failed requests are
            # excluded from latency. The DELTA evaluator below reads
            # these rows instead.
            continue
        if ev == "serving_request":
            out["requests"] += 1
            if e.get("ts") is not None:
                # timestamped row triple for the error-budget burn
                # math (monitor/signals.burn_pairs — the ONE window
                # arithmetic the live evaluator shares)
                out["request_rows"].append(
                    (float(e["ts"]), bool(e.get("error")),
                     {k: e.get(k) for k in ("ttft", "tpot",
                                            "queue_wait")}))
            if e.get("error"):
                # error-budget business only: a failed request's retire
                # stamp is the failure time (kill/wedge gap), and its
                # latencies would fail percentile objectives with
                # shutdown artifacts the error_rate already counts
                out["errors"] += 1
                continue
            for k in ("ttft", "tpot", "queue_wait"):
                if e.get(k) is not None:
                    out[k].append(float(e[k]))
                    if e.get("ts") is not None:
                        _timed(k, e["ts"], e[k])
        elif ev == "serving_step":
            if e.get("dt") is not None:
                out["step_latency"].append(float(e["dt"]))
                if e.get("ts") is not None:
                    _timed("step_latency", e["ts"], e["dt"])
            if e.get("kv_used_blocks") is not None:
                out["kv_used_blocks"].append(
                    float(e["kv_used_blocks"]))
        elif ev == "sparse_staleness":
            if e.get("value") is not None:
                out["staleness_s"].append(float(e["value"]))
                if e.get("ts") is not None:
                    _timed("staleness_s", e["ts"], e["value"])
        elif ev == "roll":
            # serving.autoscale rolling-update rows (ISSUE 18):
            # convergence only from COMPLETED rolls (an aborted roll
            # never reached 100% new-version), shed-during from every
            # roll — aborted or not, shed requests burned real budget
            if not e.get("aborted") \
                    and e.get("convergence_s") is not None:
                out["version_convergence_s"].append(
                    float(e["convergence_s"]))
                if e.get("ts") is not None:
                    _timed("version_convergence_s", e["ts"],
                           e["convergence_s"])
            if e.get("shed_during") is not None:
                out["roll_shed"].append(float(e["shed_during"]))
    return out


def samples_from_monitor_log(paths):
    """Exact per-request samples from ``serving_request`` rows (+
    ``serving_step`` dt for step_latency) of one flight-recorder log —
    or the UNION of several (one log per replica of a serving fleet:
    fleet-wide percentiles come from every process's rows, not a
    single replica's view). ``paths``: one path or a sequence."""
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    per_file, events, skipped = [], [], 0
    for path in paths:
        evs, sk = read_jsonl_tolerant(path)
        per_file.append(evs)
        events.extend(evs)
        skipped += sk
    out = samples_from_events(
        events, "monitor log%s %s" % ("s" if len(paths) > 1 else "",
                                      ", ".join(map(str, paths))),
        compute_goodput=len(per_file) == 1)
    if len(per_file) > 1:
        # goodput must attribute each PROCESS's own wall clock: over
        # the union timeline, two replicas' concurrent productive
        # intervals would collapse into one (undercounting the fleet)
        # — roll up per-file ledgers instead (Σ productive / Σ wall)
        from .monitor import goodput as _goodput
        out["goodput"] = _goodput.rollup(
            _goodput.ledger_from_events(evs) for evs in per_file)
    out["skipped"] = skipped
    return out


def samples_from_span_logs(paths):
    """Per-request samples from ``serving.request`` spans (their
    close-time attrs carry queue_wait/ttft/tpot) + ``engine.step`` span
    durations, across every span log of a fleet — the merged-timeline
    evaluation surface."""
    out = _empty_samples("span logs %s" % ", ".join(paths))
    for path in paths:
        events, skipped = read_jsonl_tolerant(path)
        out["skipped"] += skipped
        for e in events:
            if e.get("ev") != "span":
                continue
            attrs = e.get("attrs") or {}
            if e.get("name") == "serving.request":
                out["requests"] += 1
                if attrs.get("error"):
                    out["errors"] += 1   # latencies excluded, as above
                    continue
                for k in ("ttft", "tpot", "queue_wait"):
                    if attrs.get(k) is not None:
                        out[k].append(float(attrs[k]))
            elif e.get("name") == "engine.step":
                # the dt attr is the post-admission step time (same
                # quantity as the serving_step row / histogram); the
                # span DURATION also contains the wait-for-batch idle
                # window and is only the fallback for older logs
                out["step_latency"].append(
                    float(attrs.get("dt", e["dur"])))
    return out


def samples_from_metrics(source):
    """Approximate evaluation surface from a registry snapshot —
    ``monitor.dump_metrics('m.json')`` output (or the dict
    ``registry().snapshot()`` returns live). Histogram series merge
    across labels; percentiles interpolate inside buckets."""
    if isinstance(source, dict):
        snap, label = source, "metrics snapshot"
    else:
        with open(source) as f:
            snap = json.load(f)
        label = "metrics snapshot %s" % source
    out = _empty_samples(label)
    for key, hist_name in LATENCY_METRICS.items():
        ent = snap.get(hist_name)
        if not ent or ent.get("kind") != "histogram" \
                or "buckets" not in ent:
            continue
        buckets = [float(b) for b in ent["buckets"]]
        counts = [0] * (len(buckets) + 1)
        for series in ent["series"].values():
            for i, c in enumerate(series.get("counts", ())):
                if i < len(counts):
                    counts[i] += int(c)
        if sum(counts):
            out["histograms"][key] = (buckets, counts)

    def _counter_total(name):
        ent = snap.get(name) or {}
        series = ent.get("series") or {}
        return sum(int(v) for v in series.values()) \
            if ent.get("kind") == "counter" else 0

    failures = _counter_total("ptpu_serving_request_failures_total")
    out["errors"] = failures
    out["requests"] = \
        _counter_total("ptpu_serving_retirements_total") + failures
    return out


# -- delta objectives (canary analysis plane, ISSUE 19) --------------------
#
# A candidate model is gated against the INCUMBENT, not against fixed
# thresholds: the spec's optional "delta" block declares
# candidate-vs-incumbent objectives evaluated over a mirrored window —
#
#     "delta": {
#       "window_s": 120, "min_pairs": 8, "min_requests": 8,
#       "objectives": [
#         {"metric": "delta_ttft",       "percentile": 0.95,
#          "max_inflation": 1.5},
#         {"metric": "delta_tpot",       "percentile": 0.95,
#          "max_inflation": 1.5},
#         {"metric": "delta_queue_wait", "percentile": 0.95,
#          "max_inflation": 2.0},
#         {"metric": "delta_error_rate", "max_delta": 0.02},
#         {"metric": "token_agreement",  "min_ratio": 0.98}
#       ]
#     }
#
# delta_* latency metrics measure percentile INFLATION (candidate pN /
# incumbent pN, same nearest-rank _pct); delta_error_rate the error-
# fraction difference; token_agreement the exact-agreement fraction
# over joined mirror_pair rows. Like every objective: no samples on
# either side = FAIL with a reason.

DELTA_LATENCY_METRICS = ("delta_ttft", "delta_tpot",
                         "delta_queue_wait")


def validate_delta_spec(delta):
    """Validate one delta block (raises ValueError — same loud-at-load
    contract as load_spec)."""
    if not isinstance(delta, dict):
        raise ValueError("'delta' must be an object")
    objectives = delta.get("objectives")
    if not isinstance(objectives, list) or not objectives:
        raise ValueError("delta block needs a non-empty 'objectives' "
                         "list")
    for k in ("window_s", "min_pairs", "min_requests"):
        if delta.get(k) is not None \
                and not isinstance(delta[k], (int, float)):
            raise ValueError("delta %r must be numeric" % k)
    for i, obj in enumerate(objectives):
        metric = obj.get("metric")
        if metric in DELTA_LATENCY_METRICS:
            if not isinstance(obj.get("max_inflation"), (int, float)):
                raise ValueError(
                    "delta objective %d (%s) needs numeric "
                    "'max_inflation'" % (i, metric))
            q = obj.get("percentile", 0.95)
            if not (0.0 < float(q) <= 1.0):
                raise ValueError(
                    "delta objective %d percentile %r outside (0, 1]"
                    % (i, q))
            floor = obj.get("min_floor_s")
            if floor is not None and (
                    not isinstance(floor, (int, float))
                    or float(floor) <= 0.0):
                raise ValueError(
                    "delta objective %d (%s) 'min_floor_s' must be "
                    "a positive number" % (i, metric))
        elif metric == "delta_error_rate":
            if not isinstance(obj.get("max_delta"), (int, float)):
                raise ValueError(
                    "delta objective %d (delta_error_rate) needs "
                    "numeric 'max_delta'" % i)
        elif metric == "token_agreement":
            r = obj.get("min_ratio")
            if not isinstance(r, (int, float)) \
                    or not (0.0 < float(r) <= 1.0):
                raise ValueError(
                    "delta objective %d (token_agreement) needs "
                    "'min_ratio' in (0, 1]" % i)
        else:
            raise ValueError(
                "delta objective %d names unknown metric %r (known: "
                "%s, delta_error_rate, token_agreement)"
                % (i, metric, ", ".join(DELTA_LATENCY_METRICS)))
    return delta


def delta_samples_from_events(events, version, window_s=None,
                              now=None):
    """Candidate-vs-incumbent sample split from flight-recorder rows.

    ``serving_request`` rows are classified CANDIDATE when stamped
    with the candidate ``version`` (mirrored or canary-served for
    real), INCUMBENT otherwise — except ``shadow`` rows from a foreign
    version (another rollout, warm-up priming), which count on neither
    side. The same rows samples_from_events reads, split instead of
    filtered. ``mirror_pair`` rows for the
    version feed the token-agreement score. ``window_s``/``now`` bound
    the mirrored window by row timestamp."""
    version = str(version)

    def _bucket():
        return {"requests": 0, "errors": 0, "ttft": [], "tpot": [],
                "queue_wait": []}

    out = {"version": version, "pairs": 0, "agree": 0, "match": [],
           "cand": _bucket(), "inc": _bucket()}
    for e in events:
        ev = e.get("ev")
        if window_s is not None and now is not None \
                and e.get("ts") is not None \
                and now - float(e["ts"]) > float(window_s):
            continue
        if ev == "serving_request":
            cand = str(e.get("version")) == version
            if bool(e.get("shadow")) and not cand:
                # mirrored row from a FOREIGN version (another
                # rollout's shadow, or a candidate's warm-up priming
                # request stamped "__prime__"): PR-6 already keeps it
                # off the incumbent surface, and it is not evidence
                # about THIS candidate either — neither side
                continue
            b = out["cand" if cand else "inc"]
            b["requests"] += 1
            if e.get("error"):
                b["errors"] += 1
                continue               # PR-6 exclusion, per side
            for k in ("ttft", "tpot", "queue_wait"):
                if e.get(k) is not None:
                    b[k].append(float(e[k]))
        elif ev == "mirror_pair" \
                and str(e.get("version")) == version:
            out["pairs"] += 1
            if e.get("agree"):
                out["agree"] += 1
            if e.get("match") is not None:
                out["match"].append(float(e["match"]))
    return out


def evaluate_delta(delta, dsamples):
    """-> delta verdict dict: {"pass", "version", "pairs",
    "cand_requests", "inc_requests", "objectives": [{metric, threshold,
    measured, pass, reason?}]}. Pure function of (validated delta
    block, delta_samples_from_events output) — the batch CLI gate and
    the live DeltaRule (monitor/signals.py) share it."""
    delta = validate_delta_spec(delta)
    cand, inc = dsamples["cand"], dsamples["inc"]
    results = []
    for obj in delta["objectives"]:
        metric = obj["metric"]
        if metric in DELTA_LATENCY_METRICS:
            base = metric[len("delta_"):]
            q = float(obj.get("percentile", 0.95))
            ent = {"metric": metric, "percentile": q,
                   "threshold": float(obj["max_inflation"]),
                   "cand_n": len(cand[base]), "inc_n": len(inc[base]),
                   "measured": None}
            if not cand[base] or not inc[base]:
                ent.update({"pass": False,
                            "reason": "no %s samples"
                            % ("candidate" if not cand[base]
                               else "incumbent")})
            else:
                cp = _pct(sorted(cand[base]), q)
                ip = _pct(sorted(inc[base]), q)
                ent["measured"] = cp / max(ip, 1e-9)
                ent["pass"] = ent["measured"] <= ent["threshold"]
                floor = obj.get("min_floor_s")
                if floor is not None:
                    # ratio inflation over a near-zero incumbent
                    # baseline reads single-digit-ms queueing as a
                    # huge regression: an absolute floor says
                    # "candidate latency this small is not a
                    # regression, whatever the ratio"
                    ent["min_floor_s"] = float(floor)
                    if not ent["pass"] and cp <= float(floor):
                        ent["pass"] = True
                        ent["reason"] = ("inflation %.1fx over "
                                         "threshold but candidate "
                                         "p%d %.4fs under the "
                                         "%.4fs floor"
                                         % (ent["measured"],
                                            round(q * 100), cp,
                                            float(floor)))
        elif metric == "delta_error_rate":
            ent = {"metric": metric,
                   "threshold": float(obj["max_delta"]),
                   "cand_n": cand["requests"],
                   "inc_n": inc["requests"], "measured": None}
            if not cand["requests"] or not inc["requests"]:
                ent.update({"pass": False,
                            "reason": "no %s requests"
                            % ("candidate" if not cand["requests"]
                               else "incumbent")})
            else:
                ent["measured"] = (
                    cand["errors"] / cand["requests"]
                    - inc["errors"] / inc["requests"])
                ent["pass"] = ent["measured"] <= ent["threshold"]
        else:                            # token_agreement
            ent = {"metric": metric,
                   "threshold": float(obj["min_ratio"]),
                   "pairs": dsamples["pairs"], "measured": None}
            if not dsamples["pairs"]:
                ent.update({"pass": False,
                            "reason": "no joined mirror pairs"})
            else:
                ent["measured"] = (dsamples["agree"]
                                   / dsamples["pairs"])
                ent["pass"] = ent["measured"] >= ent["threshold"]
        if obj.get("name"):
            ent["name"] = obj["name"]
        results.append(ent)
    return {"pass": all(r["pass"] for r in results),
            "version": dsamples.get("version"),
            "pairs": dsamples.get("pairs", 0),
            "cand_requests": cand["requests"],
            "inc_requests": inc["requests"],
            "objectives": results}


# -- evaluation ------------------------------------------------------------

def evaluate(spec, samples):
    """-> verdict dict: {"name", "pass", "source", "requests",
    "errors", "objectives": [{metric, percentile?, threshold,
    measured, count, approximate, pass, reason?}]}. Pure function of
    (validated spec, samples) — the CLI and any CI/chaos gate share
    it."""
    spec = load_spec(spec)
    results = []
    for obj in spec["objectives"]:
        metric = obj["metric"]
        if _signals().is_budget_objective(obj):
            # error-budget burn verdict at the newest recorded
            # timestamp — the batch twin of the live alerting tier,
            # sharing its exact row-window math
            if metric == "error_rate":
                rows = samples.get("request_rows") or []
            else:
                # latency burn: good/bad over the metric's own
                # timestamped samples (the shape the live evaluator's
                # row mode uses), so staleness_s / step_latency budget
                # specs evaluate instead of failing "no samples"
                rows = [(ts, False, {metric: v}) for ts, v in
                        (samples.get("timed_samples") or {})
                        .get(metric, ())]
            now = max((r[0] for r in rows), default=None)
            ent = {"metric": metric, "burn": True,
                   "threshold": min(float(w["burn_rate"])
                                    for w in obj["windows"]),
                   "approximate": False}
            if now is None:
                ent.update({"measured": None, "count": 0,
                            "pass": False,
                            "reason": "no timestamped request rows "
                                      "on this surface"})
            else:
                pairs = _signals().burn_pairs(obj, rows, now)
                fired = [p for p in pairs if p["fired"]]
                # measured = the worst pair's min(burn_short,
                # burn_long) against ITS OWN rate — the figure the
                # fire condition actually gates (both windows must
                # exceed), so measured < threshold on a PASS line and
                # measured >= threshold on a FAIL line by
                # construction; fired pairs win the display
                def _score(p):
                    return min(p["burn_short"], p["burn_long"])
                scored = [p for p in (fired or pairs)
                          if p["burn_short"] is not None
                          and p["burn_long"] is not None]
                worst = max(scored, key=_score) if scored else None
                ent.update({
                    "measured": _score(worst) if worst else None,
                    "threshold": worst["burn_rate"] if worst
                    else ent["threshold"],
                    "count": max(p["n_long"] for p in pairs),
                    "windows": pairs,
                    "pass": not fired})
                if fired:
                    ent["reason"] = "burn >= %s in %s" % (
                        ", ".join("%g" % p["burn_rate"]
                                  for p in fired),
                        ", ".join("%gs/%gs" % (p["short_s"],
                                               p["long_s"])
                                  for p in fired))
                elif ent["measured"] is None:
                    ent.update({"pass": False,
                                "reason": "no samples in any window"})
        elif metric == "error_rate":
            threshold = float(obj["max_ratio"])
            n = samples.get("requests", 0)
            measured = (samples.get("errors", 0) / n) if n else None
            ent = {"metric": metric, "threshold": threshold,
                   "measured": measured, "count": n,
                   "approximate": False}
            if measured is None:
                ent.update({"pass": False,
                            "reason": "no requests observed"})
            else:
                ent["pass"] = measured <= threshold
        elif metric == "goodput_fraction":
            # higher-is-better ratio: the goodput ledger's productive
            # share of measured wall time (monitor/goodput.py), rolled
            # up per process on multi-log sources
            threshold = float(obj["min_ratio"])
            led = samples.get("goodput") or {}
            measured = led.get("goodput_fraction")
            ent = {"metric": metric, "threshold": threshold,
                   "measured": measured,
                   "count": led.get("rows", 0), "approximate": False}
            if measured is None:
                ent.update({"pass": False,
                            "reason": "no timestamped rows observed"})
            else:
                ent["pass"] = measured >= threshold
        else:
            gauge = metric in GAUGE_METRICS
            q = float(obj.get("percentile", 1.0 if gauge else 0.95))
            threshold = float(obj["max_value" if gauge
                                  else "max_seconds"])
            vals = sorted(samples.get(metric) or ())
            approx = False
            if vals:
                measured, count = _pct(vals, q), len(vals)
            else:
                hist = (samples.get("histograms") or {}).get(metric)
                if hist is not None:
                    measured = _hist_percentile(hist[0], hist[1], q)
                    count = sum(hist[1])
                    approx = True
                else:
                    measured, count = None, 0
            ent = {"metric": metric, "percentile": q,
                   "threshold": threshold, "measured": measured,
                   "count": count, "approximate": approx}
            if measured is None:
                ent.update({"pass": False,
                            "reason": "no samples observed"})
            else:
                ent["pass"] = measured <= threshold
        if obj.get("name"):
            ent["name"] = obj["name"]
        results.append(ent)
    return {"name": spec.get("name"),
            "pass": all(r["pass"] for r in results),
            "source": samples.get("source"),
            "requests": samples.get("requests", 0),
            "errors": samples.get("errors", 0),
            "skipped_lines": samples.get("skipped", 0),
            "objectives": results}


def _fmt(metric, v, burn=False):
    if v is None:
        return "n/a"
    if burn:
        return "%.2fx" % v
    if metric in ("error_rate", "goodput_fraction"):
        return "%.2f%%" % (100.0 * v)
    if metric in GAUGE_METRICS:
        return "%g" % v
    return "%.2fms" % (1000.0 * v)


def render(verdict):
    head = "SLO %s: %s  (%s; %d request(s), %d error(s))" % (
        verdict.get("name") or "<unnamed>",
        "PASS" if verdict["pass"] else "FAIL",
        verdict.get("source") or "?", verdict["requests"],
        verdict["errors"])
    lines = [head]
    for r in verdict["objectives"]:
        label = r["metric"]
        if r.get("burn"):
            label += " burn"
        elif "percentile" in r:
            label += " p%g" % (100.0 * r["percentile"])
        cmp_ = ">=" if r["metric"] == "goodput_fraction" else "<"
        if r["metric"] != "goodput_fraction" and not r.get("burn"):
            cmp_ = "<="
        line = "  %-4s %-18s %9s %s %-9s (n=%d%s)" % (
            "PASS" if r["pass"] else "FAIL", label,
            _fmt(r["metric"], r["measured"], r.get("burn")), cmp_,
            _fmt(r["metric"], r["threshold"], r.get("burn")),
            r["count"],
            ", approx" if r.get("approximate") else "")
        if r.get("reason"):
            line += "  [%s]" % r["reason"]
        lines.append(line)
    return "\n".join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.slo",
        description="Evaluate a serving SLO spec against recorded "
                    "telemetry; exit 0 = pass, 1 = fail")
    p.add_argument("spec", nargs="?", default=None,
                   help="SLO spec JSON path (default: the "
                        "PADDLE_TPU_SLO_SPEC flag)")
    p.add_argument("--log", nargs="+",
                   help="monitor flight-recorder .jsonl file(s) — "
                        "pass one per replica and the verdict covers "
                        "the fleet-wide union")
    p.add_argument("--spans", nargs="+",
                   help="trace span-log .jsonl file(s) — the merged "
                        "fleet-timeline surface")
    p.add_argument("--metrics",
                   help="metrics snapshot .json "
                        "(monitor.dump_metrics output; approximate)")
    p.add_argument("--json", action="store_true",
                   help="emit the verdict as one JSON object")
    args = p.parse_args(argv)

    spec_path = args.spec
    if not spec_path:
        from . import flags
        spec_path = flags.get_flag("slo_spec")
    if not spec_path:
        p.error("no spec: pass one or set PADDLE_TPU_SLO_SPEC")
    sources = [s for s in (args.log, args.spans, args.metrics)
               if s is not None]
    if len(sources) != 1:
        p.error("exactly one of --log / --spans / --metrics required")

    try:
        spec = load_spec(spec_path)
    except (OSError, ValueError) as e:
        print("paddle_tpu.slo: bad spec %s: %s" % (spec_path, e),
              file=sys.stderr)
        return 2
    try:
        if args.log:
            samples = samples_from_monitor_log(args.log)
        elif args.spans:
            samples = samples_from_span_logs(args.spans)
        else:
            samples = samples_from_metrics(args.metrics)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print("paddle_tpu.slo: unreadable telemetry: %s" % e,
              file=sys.stderr)
        return 2
    verdict = evaluate(spec, samples)
    print(json.dumps(verdict) if args.json else render(verdict))
    return 0 if verdict["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
