"""Tracer core: spans, ambient context, wire encoding, span log.

Reference parity: the platform layer's host profiler + device tracer
pair (platform/profiler.h:26-107, device_tracer.h:32) correlates events
from many sources into one unified timeline; here the "many sources"
are PROCESSES (trainer / pserver / master / membership KV), so the
correlation key is a Dapper-style SpanContext propagated in-band with
each RPC and the unifier is the merge CLI (trace/merge.py).

Design points:

  * One process-wide ``Tracer`` (``enable()``/``_TRACER``), mirroring
    resilience.faults' arming: every hook site in the runtime is a
    single ``_TRACER is None`` check when tracing is disarmed.
  * Client-side spans are AMBIENT (a thread-local stack): the executor
    opens a root span per step, RPC verb spans nest under it, retry
    attempts under the verb span — and ``wire_context()`` reads the
    stack top to inject into outgoing frames.
  * Server-side spans are EXPLICIT (never pushed on the stack): a
    dispatch thread's reply sends must not re-inject the request's
    context back at the client.
  * Sampling is decided once at the ROOT (Dapper head sampling) and
    inherited; unsampled spans still propagate locally (cheap) but are
    neither recorded nor injected, so a disarmed-or-unsampled fleet
    exchanges byte-identical old frames.
  * The span log reuses monitor's FlightRecorder (bounded JSONL,
    atomic-append, in-band truncation marker). Rows:
      span        {trace, span, parent, name, t0, dur, pid, proc, tid,
                   attrs?}
      clock       {peer, offset, rtt}      (clock.py midpoint samples)
      server_port {port}                   (port -> pid for the merge)
      proc_meta   {argv}                   (lane naming)
"""

import os
import random
import sys
import threading
import time

from ..monitor import runtime as _mon
from ..monitor.recorder import FlightRecorder

__all__ = [
    "SpanContext", "Span", "Tracer", "enable", "disable", "enabled",
    "tracer", "span", "annotate", "current_span", "active_trace_id",
    "extract", "maybe_enable_from_flags", "detached_span", "child_span",
]

_DEFAULT_MAX_BYTES = 64 << 20
_ID_BITS = 8              # bytes of entropy per id (16 hex chars)


def _new_id():
    return os.urandom(_ID_BITS).hex()


class SpanContext:
    """The propagated triple + sampling decision (Dapper header)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "sampled")

    def __init__(self, trace_id, span_id, parent_id=None, sampled=True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = bool(sampled)

    def child(self):
        return SpanContext(self.trace_id, _new_id(), self.span_id,
                           self.sampled)

    def to_wire(self):
        """Compact wire form: b'<trace16>:<span16>:<0|1>'."""
        return ("%s:%s:%d" % (self.trace_id, self.span_id,
                              int(self.sampled))).encode()

    def __repr__(self):
        return "SpanContext(%s/%s parent=%s sampled=%s)" % (
            self.trace_id, self.span_id, self.parent_id, self.sampled)


def extract(wire):
    """Parse a wire context (bytes/str) -> SpanContext | None. Never
    raises: a malformed header from a mismatched peer degrades to
    untraced, not to a dead connection."""
    if wire is None:
        return None
    try:
        if isinstance(wire, (bytes, bytearray, memoryview)):
            wire = bytes(wire).decode("ascii")
        trace_id, span_id, sampled = wire.split(":")
        if not trace_id or not span_id:
            return None
        return SpanContext(trace_id, span_id, sampled=sampled != "0")
    except (ValueError, UnicodeDecodeError):
        return None


class Span:
    """One timed operation; a context manager. ``ambient`` spans push
    onto the tracer's thread-local stack (client side) so nested spans
    and ``wire_context()`` see them; server spans stay off the stack."""

    __slots__ = ("_trc", "ctx", "name", "attrs", "t0", "_pc0",
                 "_ambient")

    def __init__(self, trc, ctx, name, attrs, ambient):
        self._trc = trc
        self.ctx = ctx
        self.name = name
        self.attrs = attrs
        self._ambient = ambient
        self.t0 = None
        self._pc0 = None

    def annotate(self, **attrs):
        self.attrs.update(attrs)

    def start(self):
        """Explicit begin for spans whose lifetime cannot be a ``with``
        block (the serving request span opens at submit() on the caller
        thread and closes at retirement on the engine loop thread)."""
        return self.__enter__()

    def finish(self, error=None):
        """Explicit end pairing ``start()``; ``error`` lands in attrs
        the way an in-block exception would."""
        if error is not None:
            self.attrs["error"] = repr(error)
        return self.__exit__(None, None, None)

    def __enter__(self):
        self.t0 = time.time()
        self._pc0 = time.perf_counter()
        if self._ambient:
            self._trc._stack().append(self)
        return self

    def __exit__(self, etype, exc, tb):
        dur = time.perf_counter() - self._pc0
        if self._ambient:
            stack = self._trc._stack()
            if stack and stack[-1] is self:
                stack.pop()
            elif self in stack:            # never corrupt the ambient
                stack.remove(self)         # chain on exotic unwinds
        if self.ctx.sampled:
            if etype is not None:
                self.attrs["error"] = repr(exc)
            self._trc._record_span(self, dur)
        return False


class _NullSpan:
    """No-op stand-in so call sites can unconditionally ``with``."""

    ctx = None

    def annotate(self, **attrs):
        pass

    def start(self):
        return self

    def finish(self, error=None):
        return False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class Tracer:
    """Process-wide tracing state + span log writer."""

    def __init__(self, log_path=None, sample_rate=1.0, proc=None,
                 clock_interval=15.0, max_bytes=_DEFAULT_MAX_BYTES):
        self.proc = proc or _default_proc()
        self.pid = os.getpid()
        self.sample_rate = float(sample_rate)
        # <=0 means "every opportunity" (tests / short runs)
        self.clock_interval = float(clock_interval)
        self._local = threading.local()
        self._lock = threading.Lock()
        self._clock_last = {}           # peer endpoint -> monotonic ts
        self._rng = random.Random(os.urandom(8))
        self._rec = (FlightRecorder(log_path, max_bytes=max_bytes)
                     if log_path else None)
        if self._rec is not None:
            self._rec.record("proc_meta", pid=self.pid, proc=self.proc,
                             argv=sys.argv[:4])

    # -- ambient stack -----------------------------------------------------
    def _stack(self):
        s = getattr(self._local, "stack", None)
        if s is None:
            s = self._local.stack = []
        return s

    def current_span(self):
        s = getattr(self._local, "stack", None)
        return s[-1] if s else None

    def wire_context(self):
        """Bytes to inject into an outgoing frame, or None (no ambient
        span / sampled out). Called from rpc._send_msg under the armed
        branch only."""
        s = getattr(self._local, "stack", None)
        if not s:
            return None
        ctx = s[-1].ctx
        if not ctx.sampled:
            return None
        return ctx.to_wire()

    # -- span creation -----------------------------------------------------
    def span(self, name, **attrs):
        """Child of the ambient span, or a new (sampled-per-rate) root."""
        cur = self.current_span()
        if cur is not None:
            ctx = cur.ctx.child()
        else:
            sampled = (self.sample_rate >= 1.0
                       or self._rng.random() < self.sample_rate)
            ctx = SpanContext(_new_id(), _new_id(), sampled=sampled)
        return Span(self, ctx, name, dict(attrs), ambient=True)

    def server_span(self, name, wire_ctx, **attrs):
        """Child of an EXTRACTED remote context (the request's header).
        Not ambient: reply sends must not carry it back."""
        ctx = wire_ctx if isinstance(wire_ctx, SpanContext) \
            else extract(wire_ctx)
        if ctx is None:
            return _NULL_SPAN
        return Span(self, ctx.child(), name, dict(attrs), ambient=False)

    # -- log rows ----------------------------------------------------------
    def _record_span(self, span, dur):
        rec = self._rec
        row = {"trace": span.ctx.trace_id, "span": span.ctx.span_id,
               "parent": span.ctx.parent_id, "name": span.name,
               "t0": span.t0, "dur": dur, "pid": self.pid,
               "proc": self.proc, "tid": threading.get_ident()}
        if span.attrs:
            row["attrs"] = span.attrs
        if rec is not None and rec.record("span", **row):
            _mon.TRACE_SPANS.inc(proc=self.proc)
        else:
            _mon.TRACE_DROPPED.inc()

    def record_server_port(self, port, endpoint=None):
        """Servers register their listening port (and, when known, the
        full host:port endpoint) so the merge can map a client clock
        sample's peer endpoint to this process — the endpoint
        disambiguates equal ports on different hosts."""
        if self._rec is not None:
            row = {"port": int(port), "pid": self.pid,
                   "proc": self.proc}
            if endpoint:
                row["endpoint"] = endpoint
            self._rec.record("server_port", **row)

    def clock_due(self, peer):
        """Rate-limit clock probing per peer (one probe per
        ``clock_interval`` seconds; <=0 probes at every opportunity)."""
        now = time.monotonic()
        with self._lock:
            last = self._clock_last.get(peer)
            if last is not None and now - last < self.clock_interval:
                return False
            self._clock_last[peer] = now
        return True

    def record_clock(self, peer, offset, rtt):
        if self._rec is not None:
            self._rec.record("clock", peer=peer, offset=offset, rtt=rtt,
                             pid=self.pid, proc=self.proc)

    def flush(self):
        if self._rec is not None:
            self._rec.flush()

    def close(self):
        if self._rec is not None:
            self._rec.close()


def _default_proc():
    base = os.path.basename(sys.argv[0] or "")
    if base.endswith(".py"):
        base = base[:-3]
    return base or ("pid%d" % os.getpid())


# -- process-wide arming ---------------------------------------------------

_TRACER = None


def enable(log_path=None, sample_rate=1.0, proc=None,
           clock_interval=15.0, max_bytes=_DEFAULT_MAX_BYTES):
    """Arm tracing process-wide; returns the Tracer. Re-arming replaces
    (and closes) the previous tracer."""
    global _TRACER
    disable()
    _TRACER = Tracer(log_path=log_path, sample_rate=sample_rate,
                     proc=proc, clock_interval=clock_interval,
                     max_bytes=max_bytes)
    return _TRACER


def disable():
    global _TRACER
    t, _TRACER = _TRACER, None
    if t is not None:
        t.close()


def enabled():
    return _TRACER is not None


def tracer():
    return _TRACER


def span(name, **attrs):
    """``with trace.span("round", step=i):`` — child of the ambient
    span or a new root; a no-op context manager when disarmed."""
    t = _TRACER
    if t is None:
        return _NULL_SPAN
    return t.span(name, **attrs)


def detached_span(name, **attrs):
    """A new ROOT span that is neither entered nor ambient: the caller
    owns its lifetime via ``start()``/``finish()``. This is the shape
    for operations that cross engine iterations AND threads — the
    serving request span opens at submit() on the caller thread and
    closes at retirement on the engine loop thread, where an ambient
    ``with`` block cannot reach. Head-sampled per the tracer rate like
    any root; a no-op when disarmed."""
    t = _TRACER
    if t is None:
        return _NULL_SPAN
    sampled = (t.sample_rate >= 1.0 or t._rng.random() < t.sample_rate)
    return Span(t, SpanContext(_new_id(), _new_id(), sampled=sampled),
                name, dict(attrs), ambient=False)


def child_span(name, parent, **attrs):
    """Non-ambient child of an EXPLICIT parent span (which may live on
    another thread's stack, or on no stack at all) — the per-prefill-
    chunk and first-token spans under a serving request span. No-op
    when disarmed, when the parent is a no-op, or when the parent was
    sampled out."""
    t = _TRACER
    ctx = getattr(parent, "ctx", None)
    if t is None or ctx is None or not ctx.sampled:
        return _NULL_SPAN
    return Span(t, ctx.child(), name, dict(attrs), ambient=False)


def annotate(**attrs):
    """Attach attributes to the current ambient span (no-op without
    one) — the hook retry/reconnect/re-resolution sites use."""
    t = _TRACER
    if t is None:
        return
    cur = t.current_span()
    if cur is not None:
        cur.attrs.update(attrs)


def current_span():
    t = _TRACER
    return t.current_span() if t is not None else None


def active_trace_id():
    """The sampled ambient trace id, or None — monitor stamps it onto
    flight-recorder rows so per-process telemetry joins the fleet
    timeline."""
    t = _TRACER
    if t is None:
        return None
    cur = t.current_span()
    if cur is None or not cur.ctx.sampled:
        return None
    return cur.ctx.trace_id


def _parse_rate(raw):
    """PADDLE_TPU_TRACE value -> sampling rate | None (off). '1'/'true'
    arm at rate 1.0; a float in (0, 1] samples that fraction of roots."""
    raw = str(raw).strip().lower()
    if not raw or raw in ("0", "false", "off", "no"):
        return None
    if raw in ("1", "true", "on", "yes"):
        return 1.0
    try:
        rate = float(raw)
    except ValueError:
        print("paddle_tpu.trace: unparseable PADDLE_TPU_TRACE=%r — "
              "tracing stays off" % raw, file=sys.stderr)
        return None
    if rate <= 0:
        return None
    return min(rate, 1.0)


def maybe_enable_from_flags():
    """Flag-driven arming (called from package import):
    ``PADDLE_TPU_TRACE[=rate]`` arms, ``PADDLE_TPU_TRACE_LOG`` names the
    span log ('{pid}' substitutes the process id — every process of a
    fleet needs its own file), ``PADDLE_TPU_TRACE_PROC`` labels the
    timeline lane."""
    from .. import flags
    try:
        rate = _parse_rate(flags.get_flag("trace"))
    except KeyError:
        return None
    if rate is None:
        return None
    log = flags.get_flag("trace_log") or "ptpu_trace_{pid}.jsonl"
    log = log.replace("{pid}", str(os.getpid()))
    proc = flags.get_flag("trace_proc") or None
    interval = flags.get_flag("trace_clock_interval")
    try:
        return enable(log_path=log, sample_rate=rate, proc=proc,
                      clock_interval=interval)
    except OSError as e:
        # tracing must never take the process down: an unwritable log
        # path leaves tracing off instead of failing the import
        print("paddle_tpu.trace: span log disabled (%s); tracing stays "
              "off" % e, file=sys.stderr)
        return None
