"""fluid.layers-compatible DSL surface."""

from . import ops  # noqa: F401
from .conv_layers import (  # noqa: F401
    conv2d, conv2d_transpose, conv3d, conv3d_transpose, pool2d, pool3d,
    roi_pool, row_conv, spp,
)
from .io_ops import data  # noqa: F401
from . import learning_rate_scheduler  # noqa: F401
from .learning_rate_scheduler import (  # noqa: F401
    exponential_decay, inverse_time_decay, natural_exp_decay, noam_decay,
    piecewise_decay, polynomial_decay,
)
from .nn import *  # noqa: F401,F403
from .nn import (  # noqa: F401
    accuracy, auc, batch_norm, cross_entropy, dropout, embedding, fc,
    layer_norm, matmul, mean, one_hot, reduce_max, reduce_mean, reduce_min,
    reduce_prod, reduce_sum, softmax, softmax_with_cross_entropy,
    square_error_cost, topk,
)
from .ops import *  # noqa: F401,F403
from .math_ops import scale  # noqa: F401
from .parallel_layers import (  # noqa: F401
    pipelined_decoder_stack, sequence_parallel_attention, sparse_moe,
)
from .sequence_layers import *  # noqa: F401,F403
from .compat import *  # noqa: F401,F403
from .control_flow import *  # noqa: F401,F403
from . import control_flow  # noqa: F401
from .rnn_layers import *  # noqa: F401,F403
from .tensor import (  # noqa: F401
    argmax, argmin, assign, cast, concat, create_global_var, create_tensor,
    expand, fill_constant, fill_constant_batch_size_like, gather, increment,
    ones, reshape, scatter, slice, split, sums, transpose, zeros,
)
