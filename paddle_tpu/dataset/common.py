"""Dataset infrastructure.

Reference parity: python/paddle/dataset/common.py (cached download + reader
conventions). This environment has no network egress, so every dataset module
provides a deterministic *synthetic* generator with the same reader API,
shapes, and vocabulary sizes as the real dataset; if the real files are
already present under _data_home() they are used instead.
"""

import hashlib
import os

import numpy as np

def _data_home():
    # resolved per call through the central flag table so
    # flags.set_flag("data_home", ...) and late env changes are honored
    from .. import flags
    return os.path.expanduser(flags.get_flag("data_home"))


def data_path(module, filename):
    return os.path.join(_data_home(), module, filename)


def have_file(module, filename):
    return os.path.exists(data_path(module, filename))


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """No-egress stub: returns the cache path if the file exists, else raises
    with a clear message (synthetic readers never call this)."""
    path = data_path(module_name, save_name or url.split("/")[-1])
    if os.path.exists(path):
        return path
    raise RuntimeError(
        "dataset file %s not present and downloads are disabled; "
        "synthetic data is used automatically by the reader API" % path)


def synthetic_rng(name, seed=0):
    """Deterministic per-dataset RNG so synthetic data is stable across runs."""
    h = int(hashlib.md5(name.encode()).hexdigest()[:8], 16)
    return np.random.RandomState((h + seed) % (2 ** 31))
