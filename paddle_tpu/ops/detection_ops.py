"""Detection ops (SSD pipeline).

Reference parity: operators/{prior_box,box_coder,iou_similarity,
bipartite_match,target_assign,mine_hard_examples,multiclass_nms,
detection_map}_op.cc and layers/detection.py.

TPU-first: everything is fixed-shape masked math — NMS keeps a static
max_detections budget with -1 padding instead of dynamic result counts;
bipartite match is a fori_loop of argmax eliminations.
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register


@register("prior_box")
def _prior_box(ctx, op):
    """Generate SSD prior boxes for a feature map (prior_box_op.cc).
    Input: feature map [N,C,H,W] + Image [N,3,IH,IW]."""
    feat = ctx.in1(op, "Input")
    img = ctx.in1(op, "Image")
    min_sizes = [float(s) for s in op.attr("min_sizes", [])]
    max_sizes = [float(s) for s in op.attr("max_sizes", [])]
    ratios = [float(r) for r in op.attr("aspect_ratios", [1.0])]
    flip = op.attr("flip", False)
    clip = op.attr("clip", False)
    step_w = float(op.attr("step_w", 0.0))
    step_h = float(op.attr("step_h", 0.0))
    offset = float(op.attr("offset", 0.5))
    variances = [float(v) for v in op.attr("variances",
                                           [0.1, 0.1, 0.2, 0.2])]
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    sw = step_w or iw / w
    sh = step_h or ih / h

    ars = [1.0]
    for r in ratios:
        if all(abs(r - a) > 1e-6 for a in ars):
            ars.append(r)
            if flip:
                ars.append(1.0 / r)

    wh = []
    for k, ms in enumerate(min_sizes):
        for a in ars:
            wh.append((ms * np.sqrt(a), ms / np.sqrt(a)))
            if a == 1.0 and k < len(max_sizes):
                big = np.sqrt(ms * max_sizes[k])
                wh.append((big, big))
    wh = np.asarray(wh, np.float32)          # [P, 2]
    p = wh.shape[0]

    cx = (np.arange(w) + offset) * sw
    cy = (np.arange(h) + offset) * sh
    cxg, cyg = np.meshgrid(cx, cy)           # [H, W]
    boxes = np.zeros((h, w, p, 4), np.float32)
    boxes[..., 0] = (cxg[:, :, None] - wh[None, None, :, 0] / 2) / iw
    boxes[..., 1] = (cyg[:, :, None] - wh[None, None, :, 1] / 2) / ih
    boxes[..., 2] = (cxg[:, :, None] + wh[None, None, :, 0] / 2) / iw
    boxes[..., 3] = (cyg[:, :, None] + wh[None, None, :, 1] / 2) / ih
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variances, np.float32),
                          boxes.shape).copy()
    ctx.set_out(op, "Boxes", jnp.asarray(boxes))
    ctx.set_out(op, "Variances", jnp.asarray(var))


def _iou_matrix(a, b, offset=0.0):
    """a [N,4], b [M,4] → [N,M] IoU (xmin,ymin,xmax,ymax). offset=1.0 for
    PIXEL (normalized=False) box conventions — widths count both edges."""
    area_a = jnp.maximum(a[:, 2] - a[:, 0] + offset, 0) * \
        jnp.maximum(a[:, 3] - a[:, 1] + offset, 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0] + offset, 0) * \
        jnp.maximum(b[:, 3] - b[:, 1] + offset, 0)
    ix0 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    iy0 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    ix1 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    iy1 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    inter = jnp.maximum(ix1 - ix0 + offset, 0) * \
        jnp.maximum(iy1 - iy0 + offset, 0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("iou_similarity")
def _iou_similarity(ctx, op):
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    ctx.set_out(op, "Out", _iou_matrix(x, y))


@register("box_coder")
def _box_coder(ctx, op):
    """Encode/decode boxes against priors (box_coder_op.cc)."""
    prior = ctx.in1(op, "PriorBox")            # [M,4]
    var = ctx.in1(op, "PriorBoxVar")           # [M,4]
    tb = ctx.in1(op, "TargetBox")
    code_type = op.attr("code_type", "encode_center_size")
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    if var is None:
        var = jnp.ones_like(prior)
    if "encode" in code_type:
        # tb [N,4] → [N,M,4]
        tw = tb[:, 2] - tb[:, 0]
        th = tb[:, 3] - tb[:, 1]
        tcx = tb[:, 0] + tw / 2
        tcy = tb[:, 1] + th / 2
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :] / var[None, :, 0]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :] / var[None, :, 1]
        ow = jnp.log(jnp.maximum(tw[:, None] / pw[None, :], 1e-10)) / \
            var[None, :, 2]
        oh = jnp.log(jnp.maximum(th[:, None] / ph[None, :], 1e-10)) / \
            var[None, :, 3]
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
    else:
        # decode: tb [N,M,4] deltas (or [M,4])
        if tb.ndim == 2:
            tb = tb[None]
        dcx = tb[..., 0] * var[None, :, 0] * pw[None, :] + pcx[None, :]
        dcy = tb[..., 1] * var[None, :, 1] * ph[None, :] + pcy[None, :]
        dw = jnp.exp(tb[..., 2] * var[None, :, 2]) * pw[None, :]
        dh = jnp.exp(tb[..., 3] * var[None, :, 3]) * ph[None, :]
        out = jnp.stack([dcx - dw / 2, dcy - dh / 2,
                         dcx + dw / 2, dcy + dh / 2], axis=-1)
    ctx.set_out(op, "OutputBox", out)


@register("bipartite_match")
def _bipartite_match(ctx, op):
    """Greedy bipartite matching (bipartite_match_op.cc): repeatedly take
    the globally-largest entry, eliminating its row and column."""
    dist = ctx.in1(op, "DistMat")            # [N, M] (rows=gt, cols=prior)
    n, m = dist.shape
    match_type = op.attr("match_type", "bipartite")

    def body(_, carry):
        d, row_match, col_match = carry
        flat = jnp.argmax(d)
        i, j = flat // m, flat % m
        valid = d[i, j] > 0
        row_match = jnp.where(valid, row_match.at[i].set(j), row_match)
        col_match = jnp.where(valid, col_match.at[j].set(i), col_match)
        d = jnp.where(valid, d.at[i, :].set(-1.0).at[:, j].set(-1.0), d)
        return d, row_match, col_match

    row_match = jnp.full((n,), -1, jnp.int32)
    col_match = jnp.full((m,), -1, jnp.int32)
    _, row_match, col_match = lax.fori_loop(
        0, min(n, m), body, (dist, row_match, col_match))

    if match_type == "per_prediction":
        thresh = float(op.attr("dist_threshold", 0.5))
        best_row = jnp.argmax(dist, axis=0)
        best_val = jnp.max(dist, axis=0)
        extra = (col_match < 0) & (best_val >= thresh)
        col_match = jnp.where(extra, best_row.astype(jnp.int32), col_match)

    dist_out = jnp.where(
        col_match >= 0,
        dist[jnp.clip(col_match, 0), jnp.arange(m)], 0.0)
    ctx.set_out(op, "ColToRowMatchIndices", col_match[None, :])
    ctx.set_out(op, "ColToRowMatchDist", dist_out[None, :])


@register("target_assign")
def _target_assign(ctx, op):
    """Assign per-prior targets from matched gt (target_assign_op.cc)."""
    x = ctx.in1(op, "X")                    # [N_gt, K] or [N_gt, 1, K]
    match = ctx.in1(op, "MatchIndices")     # [1, M]
    if x.ndim == 3:
        x = x[:, 0, :]
    mismatch_value = op.attr("mismatch_value", 0)
    m = match.shape[-1]
    idx = jnp.clip(match.reshape(-1), 0, x.shape[0] - 1)
    out = x[idx]
    neg = (match.reshape(-1) < 0)[:, None]
    out = jnp.where(neg, jnp.asarray(mismatch_value, x.dtype), out)
    wt = jnp.where(neg[:, 0], 0.0, 1.0)
    ctx.set_out(op, "Out", out[None])
    ctx.set_out(op, "OutWeight", wt[None, :, None])


@register("mine_hard_examples")
def _mine_hard_examples(ctx, op):
    """Select hard negatives by loss ranking with neg/pos ratio
    (mine_hard_examples_op.cc, max_negative mining)."""
    cls_loss = ctx.in1(op, "ClsLoss")        # [N, M]
    match = ctx.in1(op, "MatchIndices")      # [N, M]
    neg_pos_ratio = float(op.attr("neg_pos_ratio", 3.0))
    n, m = cls_loss.shape
    is_pos = match >= 0
    num_pos = jnp.sum(is_pos, axis=1)
    num_neg = jnp.minimum((num_pos * neg_pos_ratio).astype(jnp.int32),
                          m - num_pos)
    loss = jnp.where(is_pos, -jnp.inf, cls_loss)
    order = jnp.argsort(-loss, axis=1)
    rank = jnp.argsort(order, axis=1)
    neg_mask = rank < num_neg[:, None]
    # NegIndices as a masked [N, M] indicator (static shape; -1 padded list
    # semantics of the reference become a mask here)
    neg_idx = jnp.where(neg_mask, jnp.arange(m)[None, :], -1)
    ctx.set_out(op, "NegIndices", jnp.sort(neg_idx, axis=1)[:, ::-1])
    ctx.set_out(op, "UpdatedMatchIndices",
                jnp.where(neg_mask, -1, match))


def _nms_single_class(boxes, scores, score_thresh, nms_thresh, top_k,
                      offset=0.0, eta=1.0):
    """boxes [M,4], scores [M] → keep mask [M] after greedy NMS.

    eta < 1 is ADAPTIVE NMS (multiclass_nms_op.cc NMSFast /
    detection.py:54 nms_eta): after every kept box the threshold decays
    by eta while it stays above 0.5 — later (lower-score) boxes face an
    ever stricter overlap bar."""
    m = boxes.shape[0]
    valid = scores > score_thresh
    order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
    iou = _iou_matrix(boxes, boxes, offset)

    def body(i, carry):
        keep, th = carry
        cand = order[i]
        ok = valid[cand]
        # suppressed if high IoU with any already-kept higher-score box
        sup = jnp.any(keep & (iou[cand] > th))
        kept_now = jnp.logical_and(ok, ~sup)
        keep = keep.at[cand].set(kept_now)
        if eta < 1.0:
            th = jnp.where(kept_now & (th > 0.5), th * eta, th)
        return keep, th

    keep = jnp.zeros((m,), bool)
    keep, _ = lax.fori_loop(0, m if top_k < 0 else min(m, top_k), body,
                            (keep, jnp.float32(nms_thresh)))
    return keep


@register("multiclass_nms")
def _multiclass_nms(ctx, op):
    """Per-class NMS + cross-class keep_top_k (multiclass_nms_op.cc).
    Output: fixed [keep_top_k, 6] rows (label, score, x1,y1,x2,y2),
    -1-padded — the static-shape analog of the reference's LoD output."""
    boxes = ctx.in1(op, "BBoxes")            # [N, M, 4]
    scores = ctx.in1(op, "Scores")           # [N, C, M]
    score_thresh = float(op.attr("score_threshold", 0.0))
    nms_thresh = float(op.attr("nms_threshold", 0.3))
    nms_top_k = int(op.attr("nms_top_k", -1))
    keep_top_k = int(op.attr("keep_top_k", 100))
    background = int(op.attr("background_label", 0))
    offset = 0.0 if op.attr("normalized", True) else 1.0
    eta = float(op.attr("nms_eta", 1.0) or 1.0)

    def per_image(b, s):
        c, m = s.shape
        outs = []
        for cls in range(c):
            if cls == background:
                continue
            keep = _nms_single_class(b, s[cls], score_thresh, nms_thresh,
                                     nms_top_k, offset, eta)
            sc = jnp.where(keep, s[cls], -1.0)
            lbl = jnp.full((m,), cls, jnp.float32)
            outs.append(jnp.concatenate(
                [lbl[:, None], sc[:, None], b], axis=1))
        allr = jnp.concatenate(outs, axis=0)          # [(C-1)*M, 6]
        k = min(keep_top_k, allr.shape[0])
        topscore, topidx = lax.top_k(allr[:, 1], k)
        rows = allr[topidx]
        rows = jnp.where((rows[:, 1:2] > score_thresh), rows, -1.0)
        return rows

    out = jax.vmap(per_image)(boxes, scores)
    ctx.set_out(op, "Out", out)


@register("detection_map")
def _detection_map(ctx, op):
    """mAP metric op (detection_map_op.cc) — single-batch AP over the
    NMS output format above. attrs: ap_version "11point" (interpolated)
    or "integral" (recall-delta sum); evaluate_difficult=False with a
    Difficult input excludes difficult ground truth VOC-style (difficult
    GT leave the recall denominator, and detections matching ONLY
    difficult GT are ignored — neither TP nor FP)."""
    det = ctx.in1(op, "DetectRes")          # [K, 6] (label, score, box)
    gt_label = ctx.in1(op, "Label")         # [G, 6] (label, x1,y1,x2,y2..)
    overlap_t = float(op.attr("overlap_threshold", 0.5))
    ap_version = str(op.attr("ap_version", "11point") or "11point")
    eval_difficult = bool(op.attr("evaluate_difficult", True))
    det_valid = det[:, 1] > 0
    gt_boxes = gt_label[:, -4:]
    gt_cls = gt_label[:, 0]
    iou = _iou_matrix(det[:, 2:6], gt_boxes)
    same_cls = det[:, 0:1] == gt_cls[None, :]
    matched = (iou > overlap_t) & same_cls

    if not eval_difficult and op.input("Difficult"):
        difficult = ctx.in1(op, "Difficult").reshape(-1) > 0   # [G]
    else:
        difficult = jnp.zeros((gt_boxes.shape[0],), bool)
    n_gt = jnp.maximum(jnp.sum(~difficult), 1)

    # greedy one-to-one assignment in score order (VOC / the reference's
    # per-GT visited flags): each GT matches AT MOST one detection, so a
    # duplicate detection of an already-claimed GT is a false positive —
    # without this, duplicates each count as TP and AP leaves [0, 1].
    # Detections whose only matches are difficult GT are IGNORED
    # (neither TP nor FP, the VOC difficult contract).
    order = jnp.argsort(-det[:, 1])
    matched_s = matched[order]
    iou_s = iou[order]
    valid_s = det_valid[order]
    k = det.shape[0]

    def body(i, carry):
        used, tp, ign = carry
        cand = matched_s[i] & ~used & ~difficult
        hit = jnp.any(cand) & valid_s[i]
        j = jnp.argmax(jnp.where(cand, iou_s[i], -1.0))
        used = jnp.where(hit, used.at[j].set(True), used)
        tp = tp.at[i].set(hit)
        ign = ign.at[i].set(valid_s[i] & ~hit
                            & jnp.any(matched_s[i] & difficult))
        return used, tp, ign

    used0 = jnp.zeros((gt_boxes.shape[0],), bool)
    _, tp_sorted, ignored_s = lax.fori_loop(
        0, k, body, (used0, jnp.zeros((k,), bool), jnp.zeros((k,), bool)))

    counted = valid_s & ~ignored_s
    cls_sorted = det[order, 0]

    def _ap_over(mask, n_gt_cls):
        """AP restricted to detections where `mask` (score-sorted
        positions); precision/recall walk only that class's detections
        (detection_map_op.h computes per-class true/false-positive
        vectors)."""
        tp_m = tp_sorted & mask
        cum_tp = jnp.cumsum(tp_m)
        total = jnp.maximum(jnp.cumsum(counted & mask), 1)
        denom = jnp.maximum(n_gt_cls, 1)
        precision = cum_tp / total
        recall = cum_tp / denom
        if ap_version == "integral":
            # AP = sum of precision at each new true positive weighted
            # by its recall increment (GetAccumulation path)
            return jnp.sum(jnp.where(tp_m, precision, 0.0)) / denom
        ap = 0.0
        for r in np.arange(0.0, 1.1, 0.1):
            p = jnp.max(jnp.where((recall >= r) & mask, precision, 0.0))
            ap = ap + p / 11.0
        return ap

    class_num = int(op.attr("class_num", 0) or 0)
    if class_num > 0:
        # true mAP (detection_map_op.h GetMAP): per-class AP, averaged
        # over the classes that have (non-difficult) ground truth AND at
        # least one counted detection — the reference `continue`s past a
        # label whose true_pos/false_pos maps are empty, so a GT-but-
        # undetected class is skipped entirely rather than averaged in
        # as AP=0. vmapped over the class axis so the trace stays one AP
        # pipeline regardless of class count.
        background = int(op.attr("background_label", 0))
        cls_ids = jnp.asarray([c for c in range(class_num)
                               if c != background], jnp.float32)
        masks = cls_sorted[None, :] == cls_ids[:, None]        # [C', K]
        gt_counts = jnp.sum(
            (gt_cls[None, :] == cls_ids[:, None]) & ~difficult[None, :],
            axis=1)                                            # [C']
        ap_c = jax.vmap(_ap_over)(masks, gt_counts)
        det_present = jnp.any(masks & counted[None, :], axis=1)
        has = ((gt_counts > 0) & det_present).astype(jnp.float32)
        ap = jnp.sum(ap_c * has) / jnp.maximum(jnp.sum(has), 1.0)
    else:
        # class_num unknown: CLASS-POOLED AP — one ranked list across
        # classes (matching stays class-aware). This deviates from the
        # reference's per-class average when several classes are
        # present; pass class_num for true mAP.
        ap = _ap_over(jnp.ones_like(counted), n_gt)
    ctx.set_out(op, "MAP", ap.reshape(1))
    ctx.set_out(op, "AccumPosCount", jnp.asarray([det.shape[0]]))


def _greedy_bipartite(dist):
    """dist [G, M] → col_match [M] int32 (greedy global-argmax matching,
    bipartite_match_op.cc). Rows with all-zero dist never match."""
    g, m = dist.shape

    def body(_, carry):
        d, col_match = carry
        flat = jnp.argmax(d)
        i, j = flat // m, flat % m
        valid = d[i, j] > 0
        col_match = jnp.where(valid, col_match.at[j].set(i), col_match)
        d = jnp.where(valid, d.at[i, :].set(-1.0).at[:, j].set(-1.0), d)
        return d, col_match

    col_match = jnp.full((m,), -1, jnp.int32)
    _, col_match = lax.fori_loop(0, min(g, m), body, (dist, col_match))
    return col_match


@register("ssd_loss")
def _ssd_loss(ctx, op):
    """Fused SSD multibox loss (reference layers/detection.py ssd_loss
    composition: iou_similarity → bipartite_match → target_assign →
    mine_hard_examples → box_coder → softmax CE + smooth-l1). The
    reference chains 7 LoD-aware ops per image; on TPU one batch-aware
    lowering with padded ground truth and a vmapped matcher compiles to
    a single fused computation.

    Inputs: Loc [N, M, 4], Conf [N, M, C], GTBox flat [G, 4] (+@LOD
    lengths), GTLabel flat [G, 1], PriorBox [M, 4], PriorBoxVar [M, 4].
    Output: Loss [N, 1] (normalized by the matched count when attr
    `normalize`)."""
    loc = ctx.in1(op, "Loc")
    conf = ctx.in1(op, "Conf")
    gt_box = ctx.in1(op, "GTBox")
    gt_label = jnp.reshape(ctx.in1(op, "GTLabel"), (-1,)).astype(jnp.int32)
    prior = ctx.in1(op, "PriorBox")
    pvar_names = op.input("PriorBoxVar")
    pvar = ctx.in1(op, "PriorBoxVar") if pvar_names else \
        jnp.ones_like(prior)
    background = int(op.attr("background_label", 0))
    overlap_threshold = float(op.attr("overlap_threshold", 0.5))
    neg_pos_ratio = float(op.attr("neg_pos_ratio", 3.0))
    match_type = op.attr("match_type", "per_prediction")
    loc_w = float(op.attr("loc_loss_weight", 1.0))
    conf_w = float(op.attr("conf_loss_weight", 1.0))
    normalize = bool(op.attr("normalize", True))

    n, m, c = conf.shape
    g_total = gt_box.shape[0]
    if g_total == 0:
        # an all-background batch: no positives → no negatives mined →
        # zero loss (matches the num_neg = ratio * 0 limit below)
        ctx.set_out(op, "Loss", jnp.zeros((n, 1), jnp.float32))
        return
    lengths = ctx.maybe_get(op.input("GTBox")[0] + "@LOD")
    if lengths is None:
        lengths = jnp.asarray([g_total], jnp.int32)
    # pad flat gt to [N, Gmax] (Gmax = total rows: a safe static bound)
    ends = jnp.cumsum(lengths)
    seg = jnp.searchsorted(ends, jnp.arange(g_total), side="right")
    pos = jnp.arange(g_total) - (ends - lengths)[seg]
    pad_box = jnp.zeros((n, g_total, 4), gt_box.dtype)
    pad_box = pad_box.at[seg, pos].set(gt_box)
    pad_lab = jnp.full((n, g_total), background, jnp.int32)
    pad_lab = pad_lab.at[seg, pos].set(gt_label)
    gt_valid = jnp.arange(g_total)[None, :] < lengths[:, None]  # [N,Gmax]

    # per-image IoU + greedy matching (invalid gt rows have zero IoU)
    def match_one(boxes, valid):
        iou = _iou_matrix(boxes, prior)          # [Gmax, M]
        iou = jnp.where(valid[:, None], iou, 0.0)
        cm = _greedy_bipartite(iou)
        best_val = jnp.max(iou, axis=0)          # per-prior max overlap
        if match_type == "per_prediction":
            best_row = jnp.argmax(iou, axis=0)
            extra = (cm < 0) & (best_val >= overlap_threshold)
            cm = jnp.where(extra, best_row.astype(jnp.int32), cm)
        return cm, best_val                       # [M], [M]

    col_match, best_iou = jax.vmap(match_one)(pad_box, gt_valid)  # [N,M]
    is_pos = col_match >= 0

    # per-prior class targets (matched gt label, else background)
    midx = jnp.clip(col_match, 0)
    tgt_label = jnp.where(
        is_pos, jnp.take_along_axis(pad_lab, midx, axis=1), background)

    logp = jax.nn.log_softmax(conf.astype(jnp.float32), axis=-1)
    ce = -jnp.take_along_axis(logp, tgt_label[..., None],
                              axis=-1)[..., 0]              # [N, M]

    # hard negative mining: rank background priors by CE. Unmatched
    # priors whose best overlap is still >= neg_overlap are EXCLUDED
    # from the negative pool (mine_hard_examples_op.cc neg_dist
    # semantics): they straddle an object and must not be pushed
    # toward background.
    neg_overlap = float(op.attr("neg_overlap", 0.5))
    neg_cand = (~is_pos) & (best_iou < neg_overlap)
    num_pos = jnp.sum(is_pos, axis=1)
    num_neg = jnp.minimum((num_pos * neg_pos_ratio).astype(jnp.int32),
                          jnp.sum(neg_cand, axis=1))
    neg_loss = jnp.where(neg_cand, ce, -jnp.inf)
    order = jnp.argsort(-neg_loss, axis=1)
    rank = jnp.argsort(order, axis=1)
    is_neg = rank < num_neg[:, None]

    conf_loss = ce * (is_pos | is_neg).astype(jnp.float32)

    # localization: encode matched gt against priors, smooth-l1 on
    # positives only
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    mbox = jnp.take_along_axis(pad_box, midx[..., None], axis=1)  # [N,M,4]
    tw = mbox[..., 2] - mbox[..., 0]
    th = mbox[..., 3] - mbox[..., 1]
    tcx = mbox[..., 0] + tw / 2
    tcy = mbox[..., 1] + th / 2
    enc = jnp.stack([
        (tcx - pcx) / pw / pvar[:, 0],
        (tcy - pcy) / ph / pvar[:, 1],
        jnp.log(jnp.maximum(tw / pw, 1e-10)) / pvar[:, 2],
        jnp.log(jnp.maximum(th / ph, 1e-10)) / pvar[:, 3]], axis=-1)
    diff = loc.astype(jnp.float32) - enc
    ad = jnp.abs(diff)
    smooth = jnp.where(ad < 1.0, 0.5 * diff * diff, ad - 0.5)
    loc_loss = jnp.sum(smooth, axis=-1) * is_pos.astype(jnp.float32)

    loss = conf_w * conf_loss + loc_w * loc_loss            # [N, M]
    per_img = jnp.sum(loss, axis=1, keepdims=True)          # [N, 1]
    if normalize:
        denom = jnp.maximum(jnp.sum(num_pos).astype(jnp.float32), 1.0)
        per_img = per_img / denom
    ctx.set_out(op, "Loss", per_img)
