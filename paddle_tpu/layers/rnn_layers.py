"""Recurrent layer functions: dynamic_lstm, dynamic_lstmp, dynamic_gru,
lstm_unit, gru_unit (python/paddle/fluid/layers/nn.py parity)."""

from .layer_helper import LayerHelper

__all__ = ["dynamic_lstm", "dynamic_lstmp", "dynamic_gru", "lstm_unit",
           "gru_unit"]


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """input: [T, 4*hidden] (x already projected); size = 4*hidden."""
    helper = LayerHelper("lstm", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    hidden = size // 4
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[hidden, 4 * hidden], dtype=dtype)
    bias_size = [1, 7 * hidden if use_peepholes else 4 * hidden]
    bias = helper.create_parameter(attr=helper.bias_attr, shape=bias_size,
                                   dtype=dtype, is_bias=True)
    hidden_out = helper.create_variable_for_type_inference(
        dtype, shape=(-1, hidden))
    cell_out = helper.create_variable_for_type_inference(
        dtype, shape=(-1, hidden))
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    helper.append_op(
        type="lstm", inputs=inputs,
        outputs={"Hidden": [hidden_out], "Cell": [cell_out]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation})
    return hidden_out, cell_out


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None):
    helper = LayerHelper("lstmp", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    hidden = size // 4
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[proj_size, 4 * hidden], dtype=dtype)
    proj_weight = helper.create_parameter(
        attr=helper.param_attr, shape=[hidden, proj_size], dtype=dtype)
    bias_size = [1, 7 * hidden if use_peepholes else 4 * hidden]
    bias = helper.create_parameter(attr=helper.bias_attr, shape=bias_size,
                                   dtype=dtype, is_bias=True)
    proj_out = helper.create_variable_for_type_inference(
        dtype, shape=(-1, proj_size))
    helper.append_op(
        type="lstmp",
        inputs={"Input": [input], "Weight": [weight],
                "ProjWeight": [proj_weight], "Bias": [bias]},
        outputs={"Projection": [proj_out]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation,
               "proj_activation": proj_activation})
    return proj_out


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, origin_mode=False,
                name=None):
    """input: [T, 3*size]; returns hidden [T, size]."""
    helper = LayerHelper("gru", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    dtype = input.dtype
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[size, 3 * size], dtype=dtype)
    bias = helper.create_parameter(attr=helper.bias_attr,
                                   shape=[1, 3 * size], dtype=dtype,
                                   is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype,
                                                       shape=(-1, size))
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    helper.append_op(
        type="gru", inputs=inputs, outputs={"Hidden": [hidden]},
        attrs={"is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "activation": candidate_activation,
               "origin_mode": origin_mode})
    return hidden


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """One LSTM step from raw x_t: projects [x_t, h_prev] to 4*hidden gates
    with an fc, then applies the cell (layers/nn.py lstm_unit parity).
    Returns (hidden_t, cell_t)."""
    from .tensor import concat
    from .nn import fc
    helper = LayerHelper("lstm_unit_graph", name=name)
    size = cell_t_prev.shape[-1]
    concat_in = concat([x_t, hidden_t_prev], axis=-1)
    fc_out = fc(concat_in, 4 * size, param_attr=param_attr,
                bias_attr=bias_attr)
    h = helper.create_variable_for_type_inference(x_t.dtype,
                                                  shape=(-1, size))
    c = helper.create_variable_for_type_inference(x_t.dtype,
                                                  shape=(-1, size))
    helper.append_op(
        type="lstm_unit",
        inputs={"X": [fc_out], "C_prev": [cell_t_prev]},
        outputs={"C": [c], "H": [h]},
        attrs={"forget_bias": float(forget_bias)})
    return h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    """One GRU step: input [B, 3*hidden] (x proj), hidden [B, hidden].
    Returns (updated_hidden, reset_hidden_prev, gate)."""
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr)
    dtype = input.dtype
    size = size // 3
    weight = helper.create_parameter(attr=helper.param_attr,
                                     shape=[size, 3 * size], dtype=dtype)
    bias = helper.create_parameter(attr=helper.bias_attr,
                                   shape=[1, 3 * size], dtype=dtype,
                                   is_bias=True)
    gate = helper.create_variable_for_type_inference(dtype)
    reset_hidden_prev = helper.create_variable_for_type_inference(dtype)
    updated_hidden = helper.create_variable_for_type_inference(
        dtype, shape=(-1, size))
    helper.append_op(
        type="gru_unit",
        inputs={"Input": [input], "HiddenPrev": [hidden],
                "Weight": [weight], "Bias": [bias]},
        outputs={"Gate": [gate], "ResetHiddenPrev": [reset_hidden_prev],
                 "Hidden": [updated_hidden]},
        attrs={"activation": activation,
               "gate_activation": gate_activation,
               "origin_mode": origin_mode})
    return updated_hidden, reset_hidden_prev, gate
