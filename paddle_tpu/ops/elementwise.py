"""Elementwise binary ops, comparison and logical ops.

Reference parity: paddle/fluid/operators/elementwise_*.cc (add/sub/mul/div/
max/min/pow with `axis` mid-dimension broadcast), compare_op.cc, logical_op.cc.
Each lowers to one jnp call; XLA fuses chains of these into neighboring
matmuls/convs, which is what the reference needed hand-written fused kernels
for.
"""

import jax.numpy as jnp

from ..core.registry import register


def _broadcast_y(x, y, axis):
    """Reference broadcast rule: Y's dims align to X starting at `axis`
    (elementwise_op_function.h). axis == -1 → trailing alignment."""
    if x.ndim == y.ndim or y.ndim == 0:
        return y
    if axis is None or axis == -1:
        return y  # numpy trailing broadcast
    axis = int(axis)
    new_shape = (1,) * axis + tuple(y.shape) + (1,) * (x.ndim - axis - y.ndim)
    return y.reshape(new_shape)


_BINARY = {
    "elementwise_add": jnp.add,
    "elementwise_sub": jnp.subtract,
    "elementwise_mul": jnp.multiply,
    "elementwise_div": jnp.divide,
    "elementwise_max": jnp.maximum,
    "elementwise_min": jnp.minimum,
    "elementwise_pow": jnp.power,
    "elementwise_mod": jnp.mod,
    "elementwise_floordiv": jnp.floor_divide,
}

_COMPARE = {
    "less_than": jnp.less,
    "less_equal": jnp.less_equal,
    "greater_than": jnp.greater,
    "greater_equal": jnp.greater_equal,
    "equal": jnp.equal,
    "not_equal": jnp.not_equal,
}

_LOGICAL_BIN = {
    "logical_and": jnp.logical_and,
    "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
}


def _make_binary(fn, cast_bool=False):
    def lower(ctx, op):
        x = ctx.in1(op, "X")
        y = ctx.in1(op, "Y")
        y = _broadcast_y(x, y, op.attr("axis", -1))
        out = fn(x, y)
        scale = op.attr("scale")  # fused scale support (elementwise add_op)
        if scale is not None and scale != 1.0:
            out = out * scale
        ctx.set_out(op, "Out", out)
    return lower


for _name, _fn in _BINARY.items():
    register(_name, _make_binary(_fn))

for _name, _fn in _COMPARE.items():
    register(_name, _make_binary(_fn))

for _name, _fn in _LOGICAL_BIN.items():
    register(_name, _make_binary(_fn))


@register("logical_not")
def _logical_not(ctx, op):
    ctx.set_out(op, "Out", jnp.logical_not(ctx.in1(op, "X")))
