"""Fused recurrent ops: LSTM / GRU families.

Reference parity: operators/{lstm,lstmp,gru,lstm_unit,gru_unit}_op.cc and
the fused GPU kernels in operators/math/detail/ + cuda/src/hl_lstm*.

TPU-first: one ``lax.scan`` whose body is a single [B,4D] gate matmul — the
shape the MXU wants — over a *padded* batch with length masking, instead of
the reference's sequence2batch reordering of LoD batches. Inputs arrive in
flat-LoD layout ([T_total, ...] + ``@LOD`` lengths) and are padded/unpadded
in-graph; everything stays differentiable through scan.

Gate layouts match the reference ops' weight packing:
  lstm_op.cc: gates = x_proj + h @ W, W [D, 4D] packed [i, f, c̃, o]
              (bias may be [1,7D] with peephole weights W_ic, W_fc, W_oc)
  gru_op.cc:  gate_weight [D, 2D] packed [u, r]; candidate_weight [D, D]
"""

import jax.numpy as jnp
from jax import lax

from ..core.registry import register


def _act(name):
    import jax
    return {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
            "relu": jax.nn.relu, "identity": lambda x: x}[name]


def _pad_from_lod(ctx, op, slot="Input"):
    """flat [T,D] + lengths → (padded [B,Tmax,D], lengths, total_T,
    maxlen). Tmax comes from the executor's bucketed static_info
    (next-pow2 of the feed's real max length) so the scan runs
    ~max(lens) steps, not sum(lens)."""
    x = ctx.in1(op, slot)
    name = op.input(slot)[0]
    lens = ctx.maybe_get(name + "@LOD")
    t = x.shape[0]
    if lens is None:
        return x[None], jnp.asarray([t], jnp.int32), t, t
    n = lens.shape[0]
    maxlen = min(int(ctx.static_info.get(name + "@MAXLEN", t)), t)
    starts = jnp.cumsum(lens) - lens
    rows = starts[:, None] + jnp.arange(maxlen)[None, :]
    valid = jnp.arange(maxlen)[None, :] < lens[:, None]
    # invalid slots -> the OOB sentinel t, dropped by mode="fill": the
    # used indices are then UNIQUE (each valid (seq, step) owns one flat
    # row), so the gather's TRANSPOSE is a unique-indices scatter-add.
    # The old clip-to-t-1 + where form made XLA assume duplicate
    # indices and serialize the backward scatter — measured 6x-forward
    # backward scans on the TPU (PERF.md round 5 LSTM probe).
    rows = jnp.where(valid, rows, t)
    padded = x.at[rows].get(mode="fill", fill_value=0,
                            unique_indices=True)
    return padded, lens, t, maxlen


def _set_seq_out(ctx, op, slot, flat, lens, maxlen):
    """Write a sequence output + its lengths, PROPAGATING the bucketed
    @MAXLEN: the generic LoD propagation skips outputs whose @LOD the
    lowering sets explicitly, so without this a STACKED rnn's layer 2+
    loses the bound and scans the whole bucketed flat total — measured
    32x more scan steps (4096 vs 128) on the LSTM benchmark."""
    name = ctx.out_name(op, slot)
    if name is None:
        return
    ctx.env[name] = flat
    ctx.env[name + "@LOD"] = lens
    ctx.static_info.setdefault(name + "@MAXLEN", maxlen)


def _unpad_to_lod(padded, lens, total):
    """[B,Tmax,D] + lengths → flat [T,D] stably compacted."""
    n, maxlen = padded.shape[0], padded.shape[1]
    flat = padded.reshape((n * maxlen,) + padded.shape[2:])
    valid = (jnp.arange(maxlen)[None, :] < lens[:, None]).reshape(-1)
    order = jnp.argsort(~valid, stable=True)
    # a permutation: telling XLA the indices are unique keeps the
    # transpose on the fast vectorized-scatter path (see _pad_from_lod)
    return flat.at[order].get(unique_indices=True)[:total]


@register("lstm")
def _lstm(ctx, op):
    """dynamic_lstm: Input [T,4D] (already x@Wx), Weight [D,4D], Bias [1,4D]
    or [1,7D] w/ peepholes."""
    use_peepholes = op.attr("use_peepholes", True)
    is_reverse = op.attr("is_reverse", False)
    ga = _act(op.attr("gate_activation", "sigmoid"))
    ca = _act(op.attr("cell_activation", "tanh"))
    ha = _act(op.attr("candidate_activation", "tanh"))

    xp, lens, total, maxlen = _pad_from_lod(ctx, op, "Input")   # [B,T,4D]
    w = ctx.in1(op, "Weight")                           # [D,4D]
    d = w.shape[0]
    bias = ctx.in1(op, "Bias")
    b_gate = bias[:, :4 * d] if bias is not None else 0.0
    if use_peepholes and bias is not None and bias.shape[-1] >= 7 * d:
        w_ic = bias[0, 4 * d:5 * d]
        w_fc = bias[0, 5 * d:6 * d]
        w_oc = bias[0, 6 * d:7 * d]
    else:
        w_ic = w_fc = w_oc = None

    n, tmax = xp.shape[0], xp.shape[1]
    h0 = ctx.in1(op, "H0", jnp.zeros((n, d), xp.dtype))
    c0 = ctx.in1(op, "C0", jnp.zeros((n, d), xp.dtype))

    xs = jnp.moveaxis(xp, 1, 0)                          # [T,B,4D]
    tidx = jnp.arange(tmax)

    def step(carry, scanned):
        h, c = carry
        t, xt = scanned
        gates = xt + h @ w + b_gate                      # [B,4D]
        gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
        if w_ic is not None:
            gi = gi + c * w_ic
            gf = gf + c * w_fc
        i = ga(gi)
        f = ga(gf)
        c_new = f * c + i * ca(gc)
        if w_oc is not None:
            go = go + c_new * w_oc
        o = ga(go)
        h_new = o * ha(c_new)
        alive = (t < lens)[:, None]
        h_new = jnp.where(alive, h_new, h)
        c_new = jnp.where(alive, c_new, c)
        out = jnp.where(alive, h_new, jnp.zeros_like(h_new))
        return (h_new, c_new), (out, c_new)

    (_, _), (hs, cs) = lax.scan(step, (h0, c0), (tidx, xs),
                                reverse=is_reverse)
    hs = jnp.moveaxis(hs, 0, 1)                          # [B,T,D]
    cs = jnp.moveaxis(cs, 0, 1)
    _set_seq_out(ctx, op, "Hidden", _unpad_to_lod(hs, lens, total),
                 lens, maxlen)
    _set_seq_out(ctx, op, "Cell", _unpad_to_lod(cs, lens, total),
                 lens, maxlen)


@register("lstmp")
def _lstmp(ctx, op):
    """LSTM with recurrent projection (lstmp_op.cc): hidden h is projected
    to r = proj_act(h @ W_proj) which feeds back into the gates."""
    is_reverse = op.attr("is_reverse", False)
    ga = _act(op.attr("gate_activation", "sigmoid"))
    ca = _act(op.attr("cell_activation", "tanh"))
    ha = _act(op.attr("candidate_activation", "tanh"))
    pa = _act(op.attr("proj_activation", "tanh"))

    use_peepholes = op.attr("use_peepholes", True)
    xp, lens, total, maxlen = _pad_from_lod(ctx, op, "Input")    # [B,T,4D]
    w = ctx.in1(op, "Weight")                            # [P,4D]
    w_proj = ctx.in1(op, "ProjWeight")                   # [D,P]
    d = w_proj.shape[0]
    p = w_proj.shape[1]
    bias = ctx.in1(op, "Bias")
    b_gate = bias[:, :4 * d] if bias is not None else 0.0
    if use_peepholes and bias is not None and bias.shape[-1] >= 7 * d:
        w_ic = bias[0, 4 * d:5 * d]
        w_fc = bias[0, 5 * d:6 * d]
        w_oc = bias[0, 6 * d:7 * d]
    else:
        w_ic = w_fc = w_oc = None

    n, tmax = xp.shape[0], xp.shape[1]
    r0 = jnp.zeros((n, p), xp.dtype)
    c0 = jnp.zeros((n, d), xp.dtype)
    xs = jnp.moveaxis(xp, 1, 0)
    tidx = jnp.arange(tmax)

    def step(carry, scanned):
        r, c = carry
        t, xt = scanned
        gates = xt + r @ w + b_gate
        gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
        if w_ic is not None:
            gi = gi + c * w_ic
            gf = gf + c * w_fc
        i, f = ga(gi), ga(gf)
        c_new = f * c + i * ca(gc)
        if w_oc is not None:
            go = go + c_new * w_oc
        o = ga(go)
        h_new = o * ha(c_new)
        r_new = pa(h_new @ w_proj)
        alive = (t < lens)[:, None]
        r_new = jnp.where(alive, r_new, r)
        c_new = jnp.where(alive, c_new, c)
        return (r_new, c_new), (jnp.where(alive, r_new, 0.0), c_new)

    _, (rs, cs) = lax.scan(step, (r0, c0), (tidx, xs), reverse=is_reverse)
    rs = jnp.moveaxis(rs, 0, 1)
    _set_seq_out(ctx, op, "Projection", _unpad_to_lod(rs, lens, total),
                 lens, maxlen)


@register("gru")
def _gru(ctx, op):
    """dynamic_gru: Input [T,3D] (= x@Wx), Weight packed [D, 2D] update/reset
    + [D, D] candidate (gru_op.cc layout: Weight is [D, 3D] with the first
    2D columns the u/r gates)."""
    is_reverse = op.attr("is_reverse", False)
    ga = _act(op.attr("gate_activation", "sigmoid"))
    ca = _act(op.attr("activation", "tanh"))
    origin_mode = op.attr("origin_mode", False)

    xp, lens, total, maxlen = _pad_from_lod(ctx, op, "Input")    # [B,T,3D]
    w = ctx.in1(op, "Weight")                            # [D,3D]
    d = w.shape[0]
    w_gate = w[:, :2 * d]
    w_cand = w[:, 2 * d:]
    bias = ctx.in1(op, "Bias")
    b = bias if bias is not None else jnp.zeros((1, 3 * d), xp.dtype)

    n, tmax = xp.shape[0], xp.shape[1]
    h0 = ctx.in1(op, "H0", jnp.zeros((n, d), xp.dtype))
    xs = jnp.moveaxis(xp, 1, 0)
    tidx = jnp.arange(tmax)

    def step(h, scanned):
        t, xt = scanned
        xu, xr, xc = xt[:, :d], xt[:, d:2 * d], xt[:, 2 * d:]
        gh = h @ w_gate                                  # [B,2D]
        u = ga(xu + gh[:, :d] + b[:, :d])
        r = ga(xr + gh[:, d:] + b[:, d:2 * d])
        c = ca(xc + (r * h) @ w_cand + b[:, 2 * d:])
        if origin_mode:
            h_new = u * h + (1 - u) * c
        else:
            h_new = (1 - u) * h + u * c
        alive = (t < lens)[:, None]
        h_new = jnp.where(alive, h_new, h)
        return h_new, jnp.where(alive, h_new, 0.0)

    _, hs = lax.scan(step, h0, (tidx, xs), reverse=is_reverse)
    hs = jnp.moveaxis(hs, 0, 1)
    _set_seq_out(ctx, op, "Hidden", _unpad_to_lod(hs, lens, total),
                 lens, maxlen)


@register("lstm_unit")
def _lstm_unit(ctx, op):
    """Single-step LSTM cell (lstm_unit_op.cc): X = gates [B,4D], C_prev."""
    x = ctx.in1(op, "X")
    c_prev = ctx.in1(op, "C_prev")
    forget_bias = op.attr("forget_bias", 0.0)
    import jax
    gi, gf, gc, go = jnp.split(x, 4, axis=-1)
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf + forget_bias)
    c = f * c_prev + i * jnp.tanh(gc)
    h = jax.nn.sigmoid(go) * jnp.tanh(c)
    ctx.set_out(op, "C", c)
    ctx.set_out(op, "H", h)


@register("gru_unit")
def _gru_unit(ctx, op):
    """Single-step GRU cell (gru_unit_op.cc): Input [B,3D] = x proj,
    HiddenPrev [B,D], Weight [D,3D]."""
    import jax
    x = ctx.in1(op, "Input")
    h_prev = ctx.in1(op, "HiddenPrev")
    w = ctx.in1(op, "Weight")
    bias = ctx.in1(op, "Bias")
    d = h_prev.shape[-1]
    ga = _act({1: "sigmoid", 2: "tanh", 0: "identity",
               3: "relu"}.get(op.attr("gate_activation", 1), "sigmoid")
              if isinstance(op.attr("gate_activation", 1), int)
              else op.attr("gate_activation"))
    ca = _act({1: "sigmoid", 2: "tanh", 0: "identity",
               3: "relu"}.get(op.attr("activation", 2), "tanh")
              if isinstance(op.attr("activation", 2), int)
              else op.attr("activation"))
    if bias is not None:
        x = x + bias
    xu, xr, xc = x[:, :d], x[:, d:2 * d], x[:, 2 * d:]
    gh = h_prev @ w[:, :2 * d]
    u = ga(xu + gh[:, :d])
    r = ga(xr + gh[:, d:])
    c = ca(xc + (r * h_prev) @ w[:, 2 * d:])
    # gru_unit_op.h:118: h = u*(c - h_prev) + h_prev = u*c + (1-u)*h_prev;
    # origin_mode flips the gate like dynamic_gru
    if op.attr("origin_mode", False):
        h = u * h_prev + (1 - u) * c
    else:
        h = u * c + (1 - u) * h_prev
    ctx.set_out(op, "Gate", jnp.concatenate([u, r, c], axis=-1))
    ctx.set_out(op, "ResetHiddenPrev", r * h_prev)
    ctx.set_out(op, "Hidden", h)
