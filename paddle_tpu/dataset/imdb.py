"""IMDB sentiment — reference parity: python/paddle/dataset/imdb.py.

Readers yield (word-id list, label in {0,1}). word_dict() gives the vocab.
Synthetic data embeds class-correlated token distributions so
understand_sentiment-style tests converge.
"""

import numpy as np

from . import common

VOCAB_SIZE = 5148   # reference imdb vocab magnitude


def word_dict():
    return {("w%d" % i).encode(): i for i in range(VOCAB_SIZE)}


def _make_reader(n, seed):
    def reader():
        rng = common.synthetic_rng("imdb", seed)
        half = VOCAB_SIZE // 2
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(8, 64))
            base = 0 if label == 0 else half
            words = (base + rng.randint(0, half, size=length)).tolist()
            yield words, label
    return reader


def train(word_idx=None, n=2048):
    return _make_reader(n, seed=0)


def test(word_idx=None, n=512):
    return _make_reader(n, seed=1)


def fetch():
    pass
