"""SSD object detector — the detection model family of the reference era
(reference layers/detection.py multi_box_head/ssd_loss; the SSD ops are
operators/{prior_box,box_coder,bipartite_match,mine_hard_examples,
multiclass_nms}_op.cc).

A compact VGG-ish backbone feeding two detection feature maps; training
uses the fused batch-aware ``ssd_loss`` op, inference decodes + NMSes
with ``detection_output``.
"""

import paddle_tpu as fluid


def _backbone(image):
    """Two detection feature maps at strides 8 and 16."""
    x = image
    for width in (16, 32):
        x = fluid.layers.conv2d(x, num_filters=width, filter_size=3,
                                padding=1, act="relu")
        x = fluid.layers.pool2d(x, pool_size=2, pool_stride=2)
    f1 = fluid.layers.conv2d(x, num_filters=64, filter_size=3,
                             padding=1, stride=2, act="relu")
    f2 = fluid.layers.conv2d(f1, num_filters=64, filter_size=3,
                             padding=1, stride=2, act="relu")
    return f1, f2


def _head(image, image_shape, num_classes):
    """Shared backbone + multi_box_head config: train and infer nets MUST
    agree on the prior grid and conv shapes or a trained checkpoint
    stops matching the inference net."""
    f1, f2 = _backbone(image)
    return fluid.layers.multi_box_head(
        inputs=[f1, f2], image=image, base_size=image_shape[-1],
        num_classes=num_classes, aspect_ratios=[[2.0], [2.0, 3.0]],
        min_sizes=[image_shape[-1] * 0.2, image_shape[-1] * 0.5],
        max_sizes=[image_shape[-1] * 0.5, image_shape[-1] * 0.9])


def build_ssd_train_net(image_shape=(3, 64, 64), num_classes=5,
                        learning_rate=1e-3):
    """Returns (image, gt_box, gt_label, loss). gt_box/gt_label are
    flat-LoD ([Ng, 4] / [Ng, 1] with per-image lengths)."""
    image = fluid.layers.data("image", list(image_shape))
    gt_box = fluid.layers.data("gt_box", [4], lod_level=1)
    gt_label = fluid.layers.data("gt_label", [1], dtype="int64",
                                 lod_level=1)
    locs, confs, boxes, vars_ = _head(image, image_shape, num_classes)
    loss = fluid.layers.mean(fluid.layers.ssd_loss(
        locs, confs, gt_box, gt_label, boxes, vars_))
    fluid.optimizer.Adam(learning_rate=learning_rate).minimize(loss)
    return image, gt_box, gt_label, loss


def build_ssd_infer_net(image_shape=(3, 64, 64), num_classes=5,
                        nms_threshold=0.45, score_threshold=0.01,
                        keep_top_k=50):
    """Returns (image, detections [keep_top_k, 6] rows of
    (label, score, x1, y1, x2, y2), -1-padded)."""
    image = fluid.layers.data("image", list(image_shape))
    locs, confs, boxes, vars_ = _head(image, image_shape, num_classes)
    # detection_output softmaxes the raw [N, M, C] scores itself
    # (reference detection_output contract)
    dets = fluid.layers.detection_output(
        locs, confs, boxes, vars_, nms_threshold=nms_threshold,
        score_threshold=score_threshold, keep_top_k=keep_top_k)
    return image, dets


def zoo_spec():
    """(build_fn, feed_fn): SSD train step with LoD ground truth."""
    import numpy as np
    from paddle_tpu.core.lod import create_lod_tensor

    def build():
        _, _, _, loss = build_ssd_train_net(image_shape=(3, 64, 64))
        return (loss,)

    def feeds(rng):
        gt = np.array([[0.1, 0.1, 0.5, 0.5], [0.4, 0.4, 0.9, 0.9],
                       [0.2, 0.2, 0.6, 0.8]], np.float32)
        lab = np.array([[1], [2], [3]], np.int64)
        return {"image": rng.rand(2, 3, 64, 64).astype(np.float32),
                "gt_box": create_lod_tensor(gt, [[2, 1]]),
                "gt_label": create_lod_tensor(lab, [[2, 1]])}

    return build, feeds


def analysis_entry():
    """Static-analyzer entry: SSD train step with LoD ground truth (the
    analyzer sees the bucketed flat-LoD feed layout)."""
    from .harness import program_entry
    return program_entry(*zoo_spec())

