"""Book test: fit_a_line (reference
python/paddle/fluid/tests/book/test_fit_a_line.py) — linear regression on
uci_housing trained to a loss threshold, plus the save/load_inference_model
round-trip the reference does after training."""

import os
import tempfile

import numpy as np

import paddle_tpu as paddle
import paddle_tpu as fluid


def test_fit_a_line_trains_and_roundtrips():
    x = fluid.layers.data("x", [13])
    y = fluid.layers.data("y", [1])
    y_predict = fluid.layers.fc(x, 1)
    cost = fluid.layers.square_error_cost(y_predict, y)
    avg_cost = fluid.layers.mean(cost)
    fluid.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    train_reader = paddle.batch(
        paddle.reader.shuffle(paddle.dataset.uci_housing.train(), 500),
        batch_size=20)
    feeder = fluid.DataFeeder([x, y], fluid.CPUPlace())

    first = last = None
    for epoch in range(15):
        for batch in train_reader():
            feed = feeder.feed(batch)
            lv, = exe.run(feed=feed, fetch_list=[avg_cost])
            if first is None:
                first = float(lv)
            last = float(lv)
    assert last < 1.0, (first, last)   # reference threshold: avg loss < 10

    # save/load_inference_model round-trip (test_fit_a_line.py infer())
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "fit_a_line.model")
        fluid.io.save_inference_model(path, ["x"], [y_predict], exe)
        probe = np.random.RandomState(0).rand(4, 13).astype(np.float32)
        # the un-pruned program computes the loss too, so feed a dummy label
        want, = exe.run(feed={"x": probe,
                              "y": np.zeros((4, 1), np.float32)},
                        fetch_list=[y_predict])

        scope = fluid.Scope()
        exe2 = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            prog, feed_names, fetch_vars = \
                fluid.io.load_inference_model(path, exe2)
            got, = exe2.run(prog, feed={feed_names[0]: probe},
                            fetch_list=fetch_vars)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
