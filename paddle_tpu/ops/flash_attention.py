"""Flash attention — Pallas TPU kernel with streaming softmax.

The fused attention kernel the registry docstring promises: computes
softmax(QK^T * scale [+ causal mask]) V without materializing the [T, T]
score matrix in HBM. Forward keeps a running (max, denominator,
accumulator) per query block while streaming key/value blocks through
VMEM; backward recomputes per-block probabilities from the saved
log-sum-exp rows (the standard two-kernel dq / dk+dv scheme).

Reference capability: the reference's attention is composed matmul +
softmax ops (nets.py:168 scaled_dot_product_attention,
tests/unittests/transformer_model.py:41); SURVEY §7 marks attention as
the place where a hand kernel beats XLA fusion. Design follows
/opt/skills/guides/pallas_guide.md (grid + VMEM scratch carried across
the sequential k-block grid dimension; masks generated in-kernel with
broadcasted_iota).

Shapes: q, k, v [B, H, T, D]; T must be a multiple of the block size
(the sp bucketing guarantees powers of two); D is the head dim (any
multiple of 8 — lanes pad to 128 internally).

Dispatch: `flash_attention(q, k, v, causal, scale)` uses the kernel on
TPU and the dense jnp math elsewhere (CPU tests exercise the kernel via
interpret mode separately).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30
_LANES = 128
# None → auto block sizing in _resolve_path: large blocks win on the MXU
# (measured: 256² runs the executed matmuls at half the rate of 1024² at
# T=1024 — benchmarks/perf_probe_attn.py), while the causal block-skip
# needs nq, nk >= 2 to pay off; both push toward min(T, 1024)
DEFAULT_BLOCK_Q = None
DEFAULT_BLOCK_K = None
_AUTO_BLOCK = 1024


def _dense(q, k, v, causal, scale):
    return _dense_lse(q, k, v, causal, scale)[0]


def _dense_lse(q, k, v, causal, scale):
    """Dense math returning (out, lse) — lse[b,h,i] = logsumexp_j s_ij.
    The math-identical fallback for flash_attention_lse."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        t = s.shape[-1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", p / l,
                     v.astype(jnp.float32)).astype(q.dtype)
    return out, (m + jnp.log(l))[..., 0]


# --------------------------------------------------------------------------
# forward kernel: grid (BH, nQ, nK); scratch (m, l, acc) carried across the
# (sequential, innermost) nK dimension
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_s, l_s, acc_s, *, causal, scale, block_q, block_k, nk):
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    i = pl.program_id(1)   # hoisted: program_id inside a pl.when branch
                           # does not interpret/lower on all paths

    def _compute():
        q = q_ref[0].astype(jnp.float32)            # [Bq, D]
        kk = k_ref[0].astype(jnp.float32)           # [Bk, D]
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [Bq, Bk]
        if causal:
            qi = i * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kj = j * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(qi >= kj, s, _NEG_INF)

        m_prev = m_s[:]                              # [Bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                       # [Bq, Bk]
        l_new = alpha * l_s[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_s[:] = acc_s[:] * alpha + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[:] = m_new
        l_s[:] = l_new

    if causal:
        # causal block skip: a block whose every key index exceeds every
        # query index contributes exp(-inf)=0 — skip its matmuls (the
        # MXU time, ~half the grid for T >> block). The m/l/acc scratch
        # simply carries through.
        pl.when(i * block_q + block_q - 1 >= j * block_k)(_compute)
    else:
        _compute()

    @pl.when(j == nk - 1)
    def _final():
        l = jnp.maximum(l_s[:], 1e-30)
        o_ref[0] = (acc_s[:] / l).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(m_s[:] + jnp.log(l),
                                      lse_ref.shape[1:])


def _fwd_pallas(q, k, v, causal, scale, block_q, block_k, interpret):
    b, h, t, d = q.shape
    bh = b * h
    q3 = q.reshape(bh, t, d)
    k3 = k.reshape(bh, t, d)
    v3 = v.reshape(bh, t, d)
    bq = min(block_q, t)
    bk = min(block_k, t)
    nq, nk = t // bq, t // bk
    grid = (bh, nq, nk)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, scale=scale,
                          block_q=bq, block_k=bk, nk=nk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bhi, i, j: (bhi, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bhi, i, j: (bhi, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bhi, i, j: (bhi, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bhi, i, j: (bhi, i, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda bhi, i, j: (bhi, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            # scalar-per-row stats broadcast across one 128-lane tile (the
            # TPU block layout needs the last dim to be a full lane tile)
            jax.ShapeDtypeStruct((bh, t, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)
    return out.reshape(b, h, t, d), lse[:, :, 0].reshape(b, h, t)


# --------------------------------------------------------------------------
# backward kernels. delta = rowsum(dy * o) is computed outside; p is
# recomputed per block from the saved LSE.
def _bwd_dq_kernel(q_ref, k_ref, v_ref, dy_ref, lse_ref, delta_ref, dq_ref,
                   acc_s, *, causal, scale, block_q, block_k, nk):
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_s[:] = jnp.zeros_like(acc_s)

    i = pl.program_id(1)

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        kk = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, kk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qi = i * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kj = j * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(qi >= kj, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, :1])                   # [Bq, Bk]
        dy = dy_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(dy, v_ref[0].astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1]) * scale          # [Bq, Bk]
        acc_s[:] = acc_s[:] + jax.lax.dot_general(
            ds, kk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(i * block_q + block_q - 1 >= j * block_k)(_compute)
    else:
        _compute()

    @pl.when(j == nk - 1)
    def _final():
        dq_ref[0] = acc_s[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, dy_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_s, dv_s,
                    *, causal, scale, block_q, block_k, nq):
    i = pl.program_id(2)   # q blocks iterate innermost here

    @pl.when(i == 0)
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    jj = pl.program_id(1)

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        kk = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, kk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qi = i * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kj = jj * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(qi >= kj, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, :1])                   # [Bq, Bk]
        dy = dy_ref[0].astype(jnp.float32)
        dv_s[:] = dv_s[:] + jax.lax.dot_general(
            p, dy, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [Bk, D]
        dp = jax.lax.dot_general(dy, v_ref[0].astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        dk_s[:] = dk_s[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [Bk, D]

    if causal:
        pl.when(i * block_q + block_q - 1 >= jj * block_k)(_compute)
    else:
        _compute()

    @pl.when(i == nq - 1)
    def _final():
        dk_ref[0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[:].astype(dv_ref.dtype)


def _bwd_pallas(res, dy, causal, scale, block_q, block_k, interpret,
                dlse=None):
    q, k, v, o, lse = res
    b, h, t, d = q.shape
    bh = b * h
    bq = min(block_q, t)
    bk = min(block_k, t)
    # VMEM guard: the bwd kernels hold s/p/dp/ds [bq, bk] f32 plus six
    # [block, d] operands; at 1024^2 blocks with d > 128 that exceeds the
    # 16 MB scoped-vmem limit (measured: d=192 needs 21.3 MB). Clamp the
    # BACKWARD blocks only — the fwd kernel carries one [bq, bk] buffer
    # and fits. The clamp must keep dividing T (a non-divisor block
    # would silently drop query rows from dq/dk/dv): shrink to the
    # largest divisor of the incoming block, which also divides T.
    if d > 128:
        bq = _largest_divisor(bq, 512)
        bk = _largest_divisor(bk, 512)
    nq, nk = t // bq, t // bk
    delta = jnp.sum(dy.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                                  # [B,H,T]
    if dlse is not None:
        # lse output cotangent: d lse_i / d s_ij = p_ij, so it folds into
        # the shared ds = p * (dp - delta') term with delta' = delta - dlse
        delta = delta - dlse.astype(jnp.float32)
    q3, k3, v3 = (a.reshape(bh, t, d) for a in (q, k, v))
    dy3 = dy.reshape(bh, t, d)
    lse3 = jnp.broadcast_to(lse.reshape(bh, t)[:, :, None],
                            (bh, t, _LANES))
    delta3 = jnp.broadcast_to(delta.reshape(bh, t)[:, :, None],
                              (bh, t, _LANES))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, scale=scale,
                          block_q=bq, block_k=bk, nk=nk),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bhi, i, j: (bhi, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bhi, i, j: (bhi, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bhi, i, j: (bhi, j, 0)),
            pl.BlockSpec((1, bq, d), lambda bhi, i, j: (bhi, i, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda bhi, i, j: (bhi, i, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda bhi, i, j: (bhi, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bhi, i, j: (bhi, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, dy3, lse3, delta3)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, scale=scale,
                          block_q=bq, block_k=bk, nq=nq),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bhi, j, i: (bhi, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bhi, j, i: (bhi, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bhi, j, i: (bhi, j, 0)),
            pl.BlockSpec((1, bq, d), lambda bhi, j, i: (bhi, i, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda bhi, j, i: (bhi, i, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda bhi, j, i: (bhi, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda bhi, j, i: (bhi, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bhi, j, i: (bhi, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, dy3, lse3, delta3)

    shape4 = (b, h, t, d)
    return dq.reshape(shape4), dk.reshape(shape4), dv.reshape(shape4)


# --------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    out, _ = _fwd_pallas(q, k, v, causal, scale, block_q, block_k,
                         interpret)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _fwd_pallas(q, k, v, causal, scale, block_q, block_k,
                           interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, dy):
    return _bwd_pallas(res, dy, causal, scale, block_q, block_k, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


# --------------------------------------------------------------------------
# (out, lse) variant: same kernels, but the log-sum-exp rows are a public,
# differentiable output. Ring attention combines per-shard partial results
# with these (parallel/ring.py), so d(loss)/d(lse) is generally non-zero.
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_lse(q, k, v, causal, scale, block_q, block_k, interpret):
    return _fwd_pallas(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_lse_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _fwd_pallas(q, k, v, causal, scale, block_q, block_k,
                           interpret)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_bwd(causal, scale, block_q, block_k, interpret, res, dys):
    dy, dlse = dys
    return _bwd_pallas(res, dy, causal, scale, block_q, block_k, interpret,
                       dlse=dlse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _on_tpu(x):
    try:
        return list(x.devices())[0].platform == "tpu"
    except Exception:
        return jax.default_backend() == "tpu"


def _largest_divisor(n, limit):
    """Largest d <= limit with n % d == 0 (block-size fitting; trace-time
    only, n is a static shape)."""
    d = min(limit, n)
    while d > 1 and n % d:
        d -= 1
    return d


def _resolve_path(q, scale, block_q, block_k, force):
    """Shared dispatch: (path, scale, bq, bk). path: "pallas" /
    "interpret" / "dense" — auto picks the kernel on TPU when T divides
    the blocks and the head dim tiles onto the lanes. block None → auto:
    the largest divisor of T up to 1024 (the measured MXU sweet spot,
    see DEFAULT_BLOCK_Q) — a divisor, so non-power-of-two T (1536, ...)
    keeps the fused kernel instead of demoting to dense."""
    scale = float(scale) if scale else q.shape[-1] ** -0.5
    t = q.shape[2]
    auto_degenerate = False
    if not block_q or not block_k:
        auto = _largest_divisor(t, _AUTO_BLOCK)
        # a T with no divisor >= 128 below the cap (prime, 2*prime, ...)
        # would yield a near-T^2 grid of tiny blocks — far worse than
        # dense XLA; demote instead of silently compiling a cliff
        auto_degenerate = auto < min(128, t)
        block_q = block_q or auto
        block_k = block_k or auto
    path = force
    if path is None:
        usable = (t % min(block_q, t) == 0 and t % min(block_k, t) == 0
                  and t >= 128 and q.shape[-1] % 8 == 0
                  and not auto_degenerate)
        path = "pallas" if (usable and _on_tpu(q)) else "dense"
    return path, scale, min(block_q, t), min(block_k, t)


def flash_attention(q, k, v, causal=False, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    force=None):
    """Fused multi-head attention. q/k/v: [B, H, T, D].

    force: None = auto (Pallas kernel on TPU when T divides the blocks,
    dense XLA math otherwise), "pallas" / "interpret" / "dense" pin a path
    (tests use "interpret" to run the kernel on CPU).
    """
    path, scale, bq, bk = _resolve_path(q, scale, block_q, block_k, force)
    if path == "dense":
        return _dense(q, k, v, causal, scale)
    return _flash(q, k, v, causal, scale, bq, bk, path == "interpret")


def flash_attention_lse(q, k, v, causal=False, scale=None,
                        block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                        force=None):
    """Like flash_attention but returns (out, lse) with
    lse[b,h,i] = logsumexp_j(q_i·k_j*scale [+mask]) — the statistic ring
    attention needs to merge partial attention over K/V shards. Both
    outputs are differentiable (the lse cotangent folds into the shared
    backward kernels)."""
    path, scale, bq, bk = _resolve_path(q, scale, block_q, block_k, force)
    if path == "dense":
        return _dense_lse(q, k, v, causal, scale)
    return _flash_lse(q, k, v, causal, scale, bq, bk, path == "interpret")


# pallas imports placed at the end so a CPU-only environment that never
# takes the kernel path still imports this module (pl/pltpu are needed at
# trace time only)
from jax.experimental import pallas as pl                    # noqa: E402
from jax.experimental.pallas import tpu as pltpu             # noqa: E402
