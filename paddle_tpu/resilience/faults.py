"""Deterministic, seeded fault injection for the distributed runtime.

A ``FaultPlan`` is a JSON-able spec of failures to inject, armed
process-wide with ``arm(spec, seed)`` (or ``PADDLE_TPU_FAULTS`` /
``PADDLE_TPU_FAULTS_SEED`` at import — see ``maybe_arm_from_flags``).
Disarmed, every hook site is a single ``is None`` check, so production
paths pay nothing.

Spec keys (all optional)::

    {
      "rpc": {            # distributed/rpc.py _send_msg/_recv_msg hooks
        "drop": 0.02,             # P(frame never sent; conn breaks)
        "close_mid_frame": 0.01,  # P(partial header sent; conn breaks)
        "duplicate": 0.02,        # P(frame sent TWICE; conn breaks)
        "delay": 0.05,            # P(send delayed delay_s)
        "delay_s": 0.01,
        "recv_drop": 0.0,         # P(receiver abandons the frame)
        "recv_delay": 0.0,        # P(receive delayed delay_s)
        "ops": ["SEND", "BARR"],  # default: all request verbs
        "ports": [40123],         # restrict to these server ports
        "max": 25                 # total injection budget
      },
      "kill": [{"target": "pserver", "after": 6}],   # "master",
                                        # "replica" / "replica:<slot>";
                                        # "drain" (after = drains
                                        # started) and "roll" (after =
                                        # replicas replaced) crash the
                                        # cell whose graceful drain is
                                        # just beginning
                                        # (serving.autoscale)
      "stall": [{"target": "replica:1", "after": 4, "seconds": 3.0}],
                                        # one-shot dispatch wedge
      "ckpt": {"nth": 3, "mode": "bitflip"},         # or "truncate"
      "nan":  {"step": 9, "name": "img"}             # one-shot NaN batch
    }

The connection-breaking kinds model a frame lost / torn / delivered
twice followed by a broken connection — precisely the at-least-once
hazard the idempotent round tags (rpc.py SEND/BARR) and the
``resilience.retry`` reconnect path exist for. Decisions are drawn from
per-site ``random.Random(seed ^ crc32(site))`` streams, so the n-th
framing call at a site always sees the same decision regardless of how
threads interleave across sites — a fixed seed gives a reproducible
chaos run.

Every injection bumps ``ptpu_fault_injections_total{kind=...}`` and,
when a flight recorder is armed, writes a ``fault`` event.
"""

import json
import os
import random
import socket
import threading
import time
import zlib

import numpy as np

from ..monitor import runtime as _mon

__all__ = ["FaultPlan", "arm", "disarm", "active", "maybe_arm_from_flags",
           "corrupt_file"]

# request verbs of the rpc/master/kv protocols; replies (OK/VAL/...)
# are excluded by default so a plan faults requests unless it opts in.
# Every dispatch loop's verbs must appear here (or be classified
# 'admin' in resilience.retry.VERB_CLASSES) — enforced by
# `python -m paddle_tpu.analysis --runtime` (verb-conformance).
_DEFAULT_OPS = frozenset({
    "SEND", "PUT", "GET", "PRFT", "BARR", "CHNK",        # pserver
    "GETT", "DONE", "FAIL", "PING",                      # master
    "CAS", "DEL", "CAD", "LIST", "LEAS",                 # kv store
    "SUBM", "POLL", "CANC", "STAT",                      # serving fleet
    "VERD",                           # rollout verdict (serving/rollout)
    "CLKS", "METR", "HLTH", "DUMP",   # clock/telemetry/forensics
                                      # (every dispatcher)
})

_SEND_KINDS = ("drop", "close_mid_frame", "duplicate", "delay")
_RECV_KINDS = ("recv_drop", "recv_delay")


class FaultPlan:
    """One armed fault plan (see module docstring for the spec)."""

    def __init__(self, spec=None, seed=0):
        if isinstance(spec, str):
            spec = spec.strip()
            if spec.startswith("@"):
                with open(spec[1:]) as f:
                    spec = json.load(f)
            else:
                spec = json.loads(spec) if spec else {}
        self.spec = dict(spec or {})
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._rngs = {}                      # site -> random.Random
        self.trips = []                      # [(kind, site), ...]

        rpc = dict(self.spec.get("rpc") or {})
        self._rpc = rpc
        self._rpc_ops = (frozenset(rpc["ops"]) if rpc.get("ops")
                         else _DEFAULT_OPS)
        ports = rpc.get("ports")
        self._rpc_ports = (frozenset(int(p) for p in ports)
                           if ports else None)
        self._rpc_budget = int(rpc.get("max", 1 << 30))
        self._kills = [dict(k) for k in (self.spec.get("kill") or ())]
        self._stalls = [dict(k) for k in (self.spec.get("stall") or ())]
        self._ckpt = dict(self.spec.get("ckpt") or {})
        self._ckpt_count = 0
        self._nan = dict(self.spec.get("nan") or {})
        self._nan_done = False

    # -- internals ---------------------------------------------------------
    def _rng(self, site):
        # under self._lock
        r = self._rngs.get(site)
        if r is None:
            r = self._rngs[site] = random.Random(
                self.seed ^ zlib.crc32(site.encode()))
        return r

    def _port_ok(self, sock):
        if self._rpc_ports is None:
            return True
        try:
            ports = {sock.getpeername()[1], sock.getsockname()[1]}
        except OSError:
            return False
        return bool(ports & self._rpc_ports)

    def _draw(self, site, kinds):
        """One decision for this framing call: the injected kind, or
        None. Mutually exclusive draw over the plan's probabilities."""
        with self._lock:
            if self._rpc_budget <= 0:
                return None
            u = self._rng(site).random()
            acc = 0.0
            for kind in kinds:
                acc += float(self._rpc.get(kind, 0.0))
                if u < acc:
                    self._rpc_budget -= 1
                    self.trips.append((kind, site))
                    break
            else:
                return None
        _mon.on_fault(kind, site)
        return kind

    @staticmethod
    def _break_conn(sock, kind, op):
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass
        raise ConnectionError("injected fault: %s on %s" % (kind, op))

    # -- rpc framing hooks (called from distributed/rpc.py) ----------------
    def on_send(self, sock, op, frame):
        """May sleep (delay), or perform the faulty wire behavior itself
        and raise ConnectionError (drop / close_mid_frame / duplicate).
        Returning normally means the caller proceeds with the real send."""
        if not self._rpc or op not in self._rpc_ops \
                or not self._port_ok(sock):
            return
        kind = self._draw("send:" + op, _SEND_KINDS)
        if kind is None:
            return
        if kind == "delay":
            time.sleep(float(self._rpc.get("delay_s", 0.01)))
            return
        from ..distributed.rpc import _sendall_parts
        try:
            if kind == "duplicate":
                _sendall_parts(sock, frame)
                _sendall_parts(sock, frame)
            elif kind == "close_mid_frame":
                head = bytes(frame[0])
                sock.sendall(head[:max(1, len(head) // 2)])
            # drop: nothing reaches the wire
        except OSError:
            pass
        self._break_conn(sock, kind, op)

    def on_recv(self, sock):
        """Receive-side hook: delay, or abandon the frame (close + raise
        ConnectionError) before any bytes are read."""
        if not self._rpc or not self._port_ok(sock):
            return
        kind = self._draw("recv", _RECV_KINDS)
        if kind is None:
            return
        if kind == "recv_delay":
            time.sleep(float(self._rpc.get("delay_s", 0.01)))
            return
        self._break_conn(sock, kind, "recv")

    # -- kill-switches -----------------------------------------------------
    def has_kill(self, target):
        return any(k.get("target") == target for k in self._kills)

    def should_kill(self, target, value):
        """One-shot: True exactly once, when ``value`` (rounds applied,
        tasks done, ...) reaches the plan's ``after`` threshold."""
        with self._lock:
            for k in self._kills:
                if k.get("target") == target and not k.get("_fired") \
                        and value >= int(k.get("after", 0)):
                    k["_fired"] = True
                    self.trips.append(("kill", target))
                    break
            else:
                return False
        _mon.on_fault("kill", target)
        return True

    def should_stall(self, target, value):
        """One-shot wedge: returns the stall duration in seconds exactly
        once, when ``value`` reaches the plan's ``after`` threshold for
        this target; 0.0 otherwise. Models a live-but-unresponsive
        process (GC pause, runaway compile, wedged device): the lease
        keeps beating, so only a response-deadline watchdog — not lease
        expiry — can evict the member."""
        with self._lock:
            for k in self._stalls:
                if k.get("target") == target and not k.get("_fired") \
                        and value >= int(k.get("after", 0)):
                    k["_fired"] = True
                    self.trips.append(("stall", target))
                    secs = float(k.get("seconds", 1.0))
                    break
            else:
                return 0.0
        _mon.on_fault("stall", target)
        return secs

    # -- checkpoint corruption --------------------------------------------
    def maybe_corrupt_checkpoint(self, blob_path):
        """Called by io.write_checkpoint_arrays after a (blob, meta)
        pair lands: corrupts the n-th written blob on disk so the CRC
        recovery fallback is exercised. Returns True when it fired."""
        if not self._ckpt:
            return False
        with self._lock:
            self._ckpt_count += 1
            if self._ckpt_count != int(self._ckpt.get("nth", 1)):
                return False
            self.trips.append(("ckpt_corrupt",
                               os.path.basename(blob_path)))
        corrupt_file(blob_path, self._ckpt.get("mode", "bitflip"),
                     seed=self.seed)
        _mon.on_fault("ckpt_corrupt", os.path.basename(blob_path))
        return True

    # -- NaN batch ---------------------------------------------------------
    def maybe_poison_feeds(self, step, feeds):
        """One-shot NaN injection: at the plan's step, returns a COPY of
        ``feeds`` with NaNs written into the named (or first float)
        array — the poison propagates to the loss and every gradient,
        which is what the resilient_loop guard must catch."""
        if not self._nan or self._nan_done \
                or step != int(self._nan.get("step", -1)):
            return feeds
        with self._lock:
            if self._nan_done:
                return feeds
            self._nan_done = True
        name = self._nan.get("name")
        if name is not None and (name not in feeds or not np.issubdtype(
                np.asarray(feeds[name]).dtype, np.floating)):
            name = None       # int feeds can't carry NaN: auto-pick
        if name is None:
            for k in sorted(feeds):
                arr = np.asarray(feeds[k])
                if np.issubdtype(arr.dtype, np.floating):
                    name = k
                    break
        if name is None:
            return feeds
        out = dict(feeds)
        arr = np.array(out[name], copy=True)
        arr.reshape(-1)[:: max(1, arr.size // 4)] = np.nan
        out[name] = arr
        with self._lock:
            self.trips.append(("nan", name))
        _mon.on_fault("nan", name)
        return out


def corrupt_file(path, mode="bitflip", seed=0):
    """Corrupt a blob on disk the way real storage does: ``truncate``
    (torn write — the tail is gone) or ``bitflip`` (media error — one
    byte inverted at a seeded offset). Used by the armed plan and
    directly by the corrupt-checkpoint tests."""
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(size // 2)
        return
    off = random.Random(seed).randrange(max(1, size))
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")


# -- process-wide arming ---------------------------------------------------

_ACTIVE = None


def arm(spec=None, seed=0):
    """Arm a fault plan process-wide; returns the FaultPlan (exposing
    ``.trips`` for assertions). Re-arming replaces the previous plan."""
    global _ACTIVE
    plan = spec if isinstance(spec, FaultPlan) else FaultPlan(spec, seed)
    _ACTIVE = plan
    return plan


def disarm():
    global _ACTIVE
    _ACTIVE = None


def active():
    return _ACTIVE


def maybe_arm_from_flags():
    """Flag-driven arming (called from package import):
    ``PADDLE_TPU_FAULTS`` carries the JSON spec (or ``@path``) and
    ``PADDLE_TPU_FAULTS_SEED`` the decision seed."""
    from .. import flags
    try:
        spec = flags.get_flag("faults")
    except KeyError:
        return None
    if not spec:
        return None
    return arm(spec, seed=flags.get_flag("faults_seed"))
