"""Control-flow layer DSL.

Reference parity: python/paddle/fluid/layers/control_flow.py (While,
StaticRNN, DynamicRNN, IfElse, Switch, increment, array_read/array_write/
array_length, less_than, lod_rank_table, max_sequence_len).

TPU-first: RNN builders emit one ``recurrent`` op (lowered to lax.scan,
differentiable) instead of while+step-scopes; IfElse computes both branches
over the full batch and merges rows by mask (static shapes) instead of
physically partitioning the batch; Switch builds a select chain.
"""

import numpy as np

from .layer_helper import LayerHelper
from .tensor import fill_constant, cast
from ..core import unique_name
from ..core.program import default_main_program, Variable

__all__ = ["While", "StaticRNN", "DynamicRNN", "IfElse", "Switch",
           "increment", "array_read", "array_write", "array_length",
           "less_than", "equal", "lod_rank_table", "max_sequence_len",
           "create_array", "zeros_like", "recompute"]


from .tensor import increment  # noqa: F401  (single implementation)


from .ops import equal, less_than  # noqa: F401  (single implementation)


def create_array(dtype):
    helper = LayerHelper("array")
    return helper.create_variable(
        name=unique_name.generate("array"), dtype=dtype,
        type="tensor_array")


def array_write(x, i, array=None):
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    helper.append_op(type="write_to_array",
                     inputs={"X": [x], "I": [i]}, outputs={"Out": [array]})
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op(type="read_from_array",
                     inputs={"X": [array], "I": [i]},
                     outputs={"Out": [out]})
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int64", shape=(1,))
    helper.append_op(type="lod_array_length", inputs={"X": [array]},
                     outputs={"Out": [out]})
    return out


def lod_rank_table(x, level=0):
    helper = LayerHelper("lod_rank_table")
    out = helper.create_variable_for_type_inference("int32")
    helper.append_op(type="lod_rank_table", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"level": level})
    return out


def max_sequence_len(rank_table):
    helper = LayerHelper("max_sequence_len")
    out = helper.create_variable_for_type_inference("int64", shape=(1,))
    helper.append_op(type="max_sequence_len",
                     inputs={"RankTable": [rank_table]},
                     outputs={"Out": [out]})
    return out


def zeros_like(x):
    helper = LayerHelper("zeros_like")
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    helper.append_op(type="fill_zeros_like", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


class BlockGuard:
    def __init__(self, program):
        self.program = program

    def __enter__(self):
        self.program.create_block()
        return self

    def __exit__(self, *exc):
        self.program.rollback()
        return False


class recompute(BlockGuard):
    """Rematerialization region (``with layers.recompute(): ...``): ops
    built inside the block re-run during the backward pass instead of
    storing their activations (jax.checkpoint over the sub-block). Wrap
    each transformer layer to train longer sequences / bigger batches in
    the same HBM at ~1/3 extra forward FLOPs. Fetch intermediates
    OUTSIDE a region — exporting them would defeat the remat."""

    def __init__(self):
        super().__init__(default_main_program())

    def __exit__(self, *exc):
        program = self.program
        sub_block = program.current_block()
        super().__exit__(*exc)
        if exc[0] is None:
            # record the region's external reads and writes as REAL op
            # inputs/outputs so every name-based dependency scan (later
            # recompute regions, executor segmentation, prune) sees them
            reads, created = [], set()
            for o in sub_block.ops:
                for ns in o.inputs.values():
                    reads.extend(n for n in ns if n not in created)
                for ns in o.outputs.values():
                    created.update(ns)
            program.current_block().append_op(
                type="recompute_block",
                inputs={"X": list(dict.fromkeys(reads))},
                outputs={"Out": sorted(created)},
                attrs={"sub_block": sub_block})
        return False


class While:
    """fluid.layers.While parity: iterate a block while cond holds.

    Loop-carried vars must be declared via ``loop_vars`` (the reference
    discovers them from scope writes; explicit is required here because the
    compiled loop needs a static carry structure).
    """

    def __init__(self, cond, loop_vars=None, name=None, max_iters=None):
        self.cond_var = cond
        self.loop_vars = list(loop_vars or [])
        self.max_iters = max_iters
        self.helper = LayerHelper("while", name=name)

    def block(self):
        return _WhileBlock(self)


class _WhileBlock(BlockGuard):
    def __init__(self, while_op):
        super().__init__(default_main_program())
        self.w = while_op

    def __enter__(self):
        super().__enter__()
        return self

    def __exit__(self, *exc):
        program = self.program
        sub_block = program.current_block()
        super().__exit__(*exc)
        if exc[0] is None:
            parent = program.current_block()
            parent.append_op(
                type="while",
                inputs={"Condition": [self.w.cond_var]},
                outputs={"Out": [v.name for v in self.w.loop_vars]},
                attrs={"sub_block": sub_block,
                       "carry_names": [v.name for v in self.w.loop_vars],
                       "max_iters": self.w.max_iters})
        return False


class StaticRNN:
    """fluid.layers.StaticRNN parity: step over the 0th (time) axis of
    time-major [T, B, ...] inputs. Emits one `recurrent` op."""

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self._program = None
        self._sub_block = None
        self._step_inputs = []      # (outer var, inner var)
        self._memories = []         # (boot var, inner var, update inner name)
        self._outputs = []          # (inner var, outer var)
        self._in_step = False

    class _Step(BlockGuard):
        def __init__(self, rnn):
            super().__init__(default_main_program())
            self.rnn = rnn

        def __enter__(self):
            super().__enter__()
            self.rnn._in_step = True
            self.rnn._program = self.program
            self.rnn._sub_block = self.program.current_block()
            return self

        def __exit__(self, *exc):
            self.rnn._in_step = False
            super().__exit__(*exc)
            if exc[0] is None:
                self.rnn._complete()
            return False

    def step(self):
        return StaticRNN._Step(self)

    def _assert_in_step(self):
        if not self._in_step:
            raise ValueError("must be called inside rnn.step() block")

    def step_input(self, x):
        self._assert_in_step()
        blk = self._sub_block
        inner = blk.create_var(
            name=unique_name.generate("rnn_step_in"), dtype=x.dtype,
            shape=tuple(x.shape[1:]) if x.shape else None)
        self._step_inputs.append((x, inner))
        return inner

    def memory(self, init=None, shape=None, batch_ref=None, value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1, init_value=None):
        self._assert_in_step()
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory needs init var or shape+batch_ref")
            parent = self._program.block(self._sub_block.parent_idx)
            # batch_ref may be an inner step var — the boot op lives in the
            # parent block, so reference the outer sequence var instead
            # (its dim 1 is the batch of the time-major [T, B, ...] input)
            ref, ref_dim = batch_ref, ref_batch_dim_idx
            for outer, inner in self._step_inputs:
                if inner is batch_ref:
                    ref, ref_dim = outer, 1
                    break
            # carry dtype must match the updated state's dtype (lax.scan
            # rejects carry dtype changes), so follow the reference input
            mem_dtype = getattr(batch_ref, "dtype", "float32") or "float32"
            init = parent.create_var(
                name=unique_name.generate("rnn_mem_boot"), dtype=mem_dtype,
                shape=tuple(shape))
            parent.append_op(
                type="fill_constant_batch_size_like",
                inputs={"Input": [ref]}, outputs={"Out": [init]},
                attrs={"shape": [-1] + list(shape[1:] if len(shape) > 1
                                            else shape),
                       "value": float(init_value
                                      if init_value is not None else value),
                       "dtype": mem_dtype,
                       "input_dim_idx": ref_dim,
                       "output_dim_idx": init_batch_dim_idx})
        inner = self._sub_block.create_var(
            name=unique_name.generate("rnn_mem"), dtype=init.dtype,
            shape=init.shape)
        self._memories.append([init, inner, None])
        return inner

    def update_memory(self, mem, var):
        self._assert_in_step()
        for m in self._memories:
            if m[1] is mem:
                m[2] = var.name
                return
        raise ValueError("update_memory on unknown memory %r" % mem.name)

    def step_output(self, o):
        self._assert_in_step()
        outer = self._program.block(self._sub_block.parent_idx).create_var(
            name=unique_name.generate("rnn_out"), dtype=o.dtype)
        self._outputs.append((o, outer))

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _complete(self):
        for m in self._memories:
            if m[2] is None:
                raise ValueError("memory %r never updated" % m[1].name)
        parent = self._program.current_block()
        final_states = [
            parent.create_var(name=unique_name.generate("rnn_final"),
                              dtype=m[0].dtype) for m in self._memories]
        parent.append_op(
            type="recurrent",
            inputs={"inputs": [x.name for x, _ in self._step_inputs],
                    "initial_states": [m[0].name for m in self._memories]},
            outputs={"outputs": [outer.name for _, outer in self._outputs],
                     "final_states": [v.name for v in final_states]},
            attrs={"sub_block": self._sub_block,
                   "inner_input_names": [i.name for _, i in
                                         self._step_inputs],
                   "inner_state_names": [m[1].name for m in self._memories],
                   "inner_state_out_names": [m[2] for m in self._memories],
                   "inner_output_names": [o.name for o, _ in self._outputs],
                   "time_major": True, "reverse": False})

    def __call__(self):
        outs = [outer for _, outer in self._outputs]
        return outs[0] if len(outs) == 1 else outs


class DynamicRNN:
    """fluid.layers.DynamicRNN parity over flat-LoD inputs.

    The reference sorts sequences by length (lod_rank_table), buckets
    timesteps and shrinks the live batch as sequences end. The static-shape
    equivalent: pad inside the graph, scan with per-sequence length masks
    (state freezes once a sequence ends), unpad back to flat LoD.
    """

    BEFORE_RNN = 0
    IN_RNN = 1
    AFTER_RNN = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self.status = DynamicRNN.BEFORE_RNN
        self._program = None
        self._sub_block = None
        self._step_inputs = []      # (padded outer var, inner var)
        self._memories = []
        self._outputs = []
        self._lens_var = None
        self._src_lod_var = None

    class _Block(BlockGuard):
        def __init__(self, rnn):
            super().__init__(default_main_program())
            self.rnn = rnn

        def __enter__(self):
            super().__enter__()
            self.rnn.status = DynamicRNN.IN_RNN
            self.rnn._program = self.program
            self.rnn._sub_block = self.program.current_block()
            return self

        def __exit__(self, *exc):
            self.rnn.status = DynamicRNN.AFTER_RNN
            super().__exit__(*exc)
            if exc[0] is None:
                self.rnn._complete()
            return False

    def block(self):
        return DynamicRNN._Block(self)

    def step_input(self, x, level=0):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError("step_input must be called inside block()")
        parent = self._program.block(self._sub_block.parent_idx)
        # pad flat LoD [T,D] -> [B,Tmax,D] in the parent block
        from .sequence_layers import sequence_pad
        # sequence_pad appends to the *current* block; temporarily switch
        cur = self._program._current_block_idx
        self._program._current_block_idx = parent.idx
        try:
            padded, lens = sequence_pad(x)
        finally:
            self._program._current_block_idx = cur
        if self._lens_var is None:
            self._lens_var = lens
            self._src_lod_var = x
        inner = self._sub_block.create_var(
            name=unique_name.generate("drnn_step_in"), dtype=x.dtype,
            shape=(None if x.shape is None else (-1,) + tuple(x.shape[1:])))
        self._step_inputs.append((padded, inner))
        return inner

    def memory(self, init=None, shape=None, value=0.0, dtype="float32",
               need_reorder=False):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError("memory must be called inside block()")
        if init is None:
            if shape is None:
                raise ValueError("memory needs init or shape")
            if not self._step_inputs:
                raise ValueError("declare step_input before value memories")
            parent = self._program.block(self._sub_block.parent_idx)
            ref = self._step_inputs[0][0]   # padded [B,T,D]
            init = parent.create_var(
                name=unique_name.generate("drnn_mem_boot"), dtype=dtype,
                shape=(-1,) + tuple(shape))
            parent.append_op(
                type="fill_constant_batch_size_like",
                inputs={"Input": [ref]}, outputs={"Out": [init]},
                attrs={"shape": [-1] + list(shape), "value": float(value),
                       "dtype": dtype, "input_dim_idx": 0,
                       "output_dim_idx": 0})
        inner = self._sub_block.create_var(
            name=unique_name.generate("drnn_mem"), dtype=init.dtype,
            shape=init.shape)
        self._memories.append([init, inner, None])
        return inner

    def update_memory(self, ex_mem, new_mem):
        for m in self._memories:
            if m[1] is ex_mem:
                m[2] = new_mem.name
                return
        raise ValueError("update_memory on unknown memory")

    def output(self, *outputs):
        for o in outputs:
            outer = self._program.block(
                self._sub_block.parent_idx).create_var(
                name=unique_name.generate("drnn_out"), dtype=o.dtype)
            self._outputs.append((o, outer))

    def _complete(self):
        parent = self._program.current_block()
        padded_outs = [
            parent.create_var(name=unique_name.generate("drnn_padded_out"),
                              dtype=o.dtype) for o, _ in self._outputs]
        final_states = [
            parent.create_var(name=unique_name.generate("drnn_final"),
                              dtype=m[0].dtype) for m in self._memories]
        parent.append_op(
            type="recurrent",
            inputs={"inputs": [p.name for p, _ in self._step_inputs],
                    "initial_states": [m[0].name for m in self._memories],
                    "sequence_length": [self._lens_var.name]},
            outputs={"outputs": [v.name for v in padded_outs],
                     "final_states": [v.name for v in final_states]},
            attrs={"sub_block": self._sub_block,
                   "inner_input_names": [i.name for _, i in
                                         self._step_inputs],
                   "inner_state_names": [m[1].name for m in self._memories],
                   "inner_state_out_names": [m[2] for m in self._memories],
                   "inner_output_names": [o.name for o, _ in self._outputs],
                   "time_major": False, "reverse": False})
        # unpad back to flat LoD
        from .sequence_layers import sequence_unpad
        self._flat_outs = [sequence_unpad(p, self._lens_var)
                           for p in padded_outs]

    def __call__(self):
        outs = self._flat_outs
        return outs[0] if len(outs) == 1 else outs


class IfElse:
    """fluid.layers.IfElse parity. The reference splits batch rows by a
    boolean mask, runs each branch on its subset and merges
    (split_lod_tensor/merge_lod_tensor). Static-shape equivalent: both
    branches run on the full batch; outputs merge row-wise by mask."""

    OUT_IF_ELSE_TRUE_BLOCKS = 0
    OUT_IF_ELSE_FALSE_BLOCKS = 1

    def __init__(self, cond, name=None):
        self.cond = cond
        self.helper = LayerHelper("ifelse", name=name)
        self._true_outs = []
        self._false_outs = []
        self._in_true = None

    class _Branch:
        def __init__(self, ie, is_true):
            self.ie = ie
            self.is_true = is_true

        def __enter__(self):
            self.ie._in_true = self.is_true
            return self

        def __exit__(self, *exc):
            self.ie._in_true = None
            return False

    def true_block(self):
        return IfElse._Branch(self, True)

    def false_block(self):
        return IfElse._Branch(self, False)

    def input(self, x):
        if self._in_true is None:
            raise ValueError("IfElse.input must be inside a branch block")
        return x  # full batch; mask applied at merge

    def output(self, *outs):
        if self._in_true is None:
            raise ValueError("IfElse.output must be inside a branch block")
        (self._true_outs if self._in_true else self._false_outs).extend(outs)

    def __call__(self):
        if len(self._true_outs) != len(self._false_outs):
            raise ValueError("true/false branches produced different "
                             "output counts")
        helper = self.helper
        merged = []
        for t, f in zip(self._true_outs, self._false_outs):
            out = helper.create_variable_for_type_inference(
                t.dtype, shape=t.shape)
            helper.append_op(
                type="select_rows_by_mask",
                inputs={"Mask": [self.cond], "TrueOut": [t],
                        "FalseOut": [f]},
                outputs={"Out": [out]})
            merged.append(out)
        return merged


class Switch:
    """fluid.layers.Switch parity for scalar conditions (LR schedules):
    builds a chained select. Usage:

        with switch.case(cond1): assign(v1, out)
        with switch.default():   assign(v2, out)
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self._cases = []       # (cond var or None, [(target, value)])
        self._current = None

    class _Case:
        def __init__(self, sw, cond):
            self.sw = sw
            self.cond = cond

        def __enter__(self):
            self.sw._current = (self.cond, [])
            return self

        def __exit__(self, *exc):
            self.sw._cases.append(self.sw._current)
            self.sw._current = None
            return False

    def case(self, cond):
        return Switch._Case(self, cond)

    def default(self):
        return Switch._Case(self, None)

    def assign(self, value, target):
        """Record `target = value` for the active case."""
        if self._current is None:
            raise ValueError("Switch.assign outside case block")
        self._current[1].append((target, value))

    def finalize(self):
        """Emit the select chain: first matching case wins."""
        helper = self.helper
        targets = {}
        for cond, assigns in self._cases:
            for target, value in assigns:
                targets.setdefault(target, []).append((cond, value))
        for target, arms in targets.items():
            taken = None      # running "already matched" flag
            acc = None
            default_val = None
            for cond, value in arms:
                if cond is None:
                    default_val = value
                    continue
                c = cast(cond, "float32")
                use = c if taken is None else c * (1.0 - taken)
                term = use * value
                acc = term if acc is None else acc + term
                taken = use if taken is None else taken + use
            if default_val is None:
                # reference Switch executes no assign when nothing matches:
                # the target keeps its previous value
                default_val = target
            rest = (1.0 - taken) if taken is not None else 1.0
            term = rest * default_val
            acc = term if acc is None else acc + term
            helper.append_op(type="assign", inputs={"X": [acc]},
                             outputs={"Out": [target]})
