"""Beam search: dense step op vs brute-force numpy, backtrack decode, and
the whole-loop scan decoder vs a pure-numpy reference beam search."""

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.ops.beam_search import beam_search_step, beam_search_decode
from paddle_tpu.models import decoding


def _np_beam_step(pre_ids, pre_scores, logp, beam, end_id, first):
    """Brute-force reference for one step."""
    bw, vocab = logp.shape
    batch = bw // beam
    sel = np.zeros(bw, np.int32)
    sc = np.zeros(bw, np.float32)
    par = np.zeros(bw, np.int32)
    for b in range(batch):
        cands = []  # (score, parent_row, token)
        for w in range(beam):
            r = b * beam + w
            if first and w > 0:
                continue
            if pre_ids[r] == end_id:
                cands.append((pre_scores[r], r, end_id))
                continue
            for v in range(vocab):
                cands.append((pre_scores[r] + logp[r, v], r, v))
        cands.sort(key=lambda c: -c[0])
        for w in range(beam):
            s, r, v = cands[w]
            sel[b * beam + w] = v
            sc[b * beam + w] = s
            par[b * beam + w] = r
    return sel, sc, par


def test_beam_search_step_vs_numpy(rng):
    beam, vocab, batch = 3, 7, 2
    bw = batch * beam
    pre_ids = rng.randint(0, vocab, bw).astype(np.int32)
    pre_ids[1] = 0  # one finished beam (end_id=0)
    pre_scores = rng.randn(bw).astype(np.float32)
    logp = np.log(rng.dirichlet(np.ones(vocab), bw)).astype(np.float32)

    sel, sc, par = beam_search_step(jnp.asarray(pre_ids),
                                    jnp.asarray(pre_scores),
                                    jnp.asarray(logp), beam, 0)
    rsel, rsc, rpar = _np_beam_step(pre_ids, pre_scores, logp, beam, 0,
                                    False)
    np.testing.assert_array_equal(np.asarray(sel), rsel)
    np.testing.assert_allclose(np.asarray(sc), rsc, rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(par), rpar)


def test_beam_search_step_first_step(rng):
    beam, vocab, batch = 2, 5, 2
    bw = batch * beam
    pre_ids = np.full(bw, 1, np.int32)
    pre_scores = np.zeros(bw, np.float32)
    logp = np.log(rng.dirichlet(np.ones(vocab), bw)).astype(np.float32)
    sel, sc, par = beam_search_step(jnp.asarray(pre_ids),
                                    jnp.asarray(pre_scores),
                                    jnp.asarray(logp), beam, 0,
                                    first_step=True)
    rsel, rsc, rpar = _np_beam_step(pre_ids, pre_scores, logp, beam, 0, True)
    np.testing.assert_array_equal(np.asarray(sel), rsel)
    # first step: all parents are beam 0 of each source
    np.testing.assert_array_equal(np.asarray(par), [0, 0, 2, 2])


def test_beam_search_decode_backtrack():
    # batch=1, beam=2, T=3; hand-built tree:
    # t0: rows pick tokens [5, 3], parents [0, 0]
    # t1: rows pick tokens [7, 8], parents [0, 1] (row1 descends from beam1)
    # t2: rows pick tokens [2, 0], parents [1, 0]
    ids = jnp.asarray([[5, 3], [7, 8], [2, 0]], jnp.int32)
    parents = jnp.asarray([[0, 0], [0, 1], [1, 0]], jnp.int32)
    scores = jnp.asarray([-1.0, -2.0], jnp.float32)
    sent, sc = beam_search_decode(ids, parents, scores, 2, 0)
    sent = np.asarray(sent)
    # final row 0 ← t2 parent 1 ← t1 row 1 (token 8, parent beam 1) ← t0 row 1 (3)
    np.testing.assert_array_equal(sent[0, 0], [3, 8, 2])
    # final row 1 ← t2 parent 0 ← t1 row 0 (7) ← t0 row 0 (5); then EOS pads
    np.testing.assert_array_equal(sent[0, 1], [5, 7, 0])
    np.testing.assert_allclose(np.asarray(sc)[0], [-1.0, -2.0])


def _np_full_beam(trans, bos, end_id, max_len, beam):
    """Pure-numpy full beam search over a fixed Markov logits table."""
    vocab = trans.shape[0]
    beams = [([bos], 0.0)]
    for t in range(max_len):
        cands = []
        for seq, sc in beams:
            if len(seq) > 1 and seq[-1] == end_id:
                cands.append((seq + [end_id], sc))
                continue
            logp = trans[seq[-1]]
            for v in range(vocab):
                cands.append((seq + [v], sc + logp[v]))
        cands.sort(key=lambda c: -c[1])
        # stable dedup not needed: all scores distinct by construction
        beams = cands[:beam]
    return [(s[1:], sc) for s, sc in beams]


def test_full_beam_search_vs_numpy(rng):
    vocab, beam, max_len = 11, 3, 6
    trans = np.log(rng.dirichlet(np.ones(vocab), vocab)).astype(np.float32)
    end_id, bos = 0, 1

    def logits_fn(tok, state, t):
        return jnp.asarray(trans)[tok] * 3.0, state  # sharpen → few ties

    trans3 = jax.nn.log_softmax(jnp.asarray(trans) * 3.0, axis=-1)
    sent, sc = decoding.beam_search(logits_fn, {}, bos, end_id, max_len,
                                    batch=1, beam_size=beam)
    ref = _np_full_beam(np.asarray(trans3), bos, end_id, max_len, beam)
    sent, sc = np.asarray(sent), np.asarray(sc)
    for w, (rseq, rsc) in enumerate(ref):
        np.testing.assert_allclose(sc[0, w], rsc, rtol=1e-4)
        np.testing.assert_array_equal(sent[0, w], rseq)


def test_greedy_search_matches_beam1(rng):
    vocab, max_len = 9, 5
    trans = np.log(rng.dirichlet(np.ones(vocab), vocab)).astype(np.float32)

    def logits_fn(tok, state, t):
        return jnp.asarray(trans)[tok] * 2.0, state

    toks_g, sc_g = decoding.greedy_search(logits_fn, {}, 1, 0, max_len,
                                          batch=2)
    toks_b, sc_b = decoding.beam_search(logits_fn, {}, 1, 0, max_len,
                                        batch=2, beam_size=1)
    np.testing.assert_array_equal(np.asarray(toks_g),
                                  np.asarray(toks_b)[:, 0, :])


def test_beam_state_reorder(rng):
    """KV-cache-style state rows follow their beam (parent gather)."""
    vocab, beam, max_len, batch = 8, 2, 4, 1

    def logits_fn(tok, state, t):
        # logits depend on the running per-row state so a wrong reorder
        # changes the result: state counts tokens emitted per row
        bias = state["acc"][:, None] * 0.01
        logits = jnp.asarray(trans)[tok] * 3.0 + bias
        state = {"acc": state["acc"] + tok}
        return logits, state

    trans = np.log(rng.dirichlet(np.ones(vocab), vocab)).astype(np.float32)
    init = {"acc": jnp.zeros((batch * beam,), jnp.float32)}
    sent, sc = decoding.beam_search(logits_fn, init, 1, 0, max_len,
                                    batch=batch, beam_size=beam)
    assert np.asarray(sc)[0, 0] >= np.asarray(sc)[0, 1]


def test_beam_search_ops_in_program(rng):
    """Program-IR path: beam_search + beam_search_decode ops lower and run."""
    beam, vocab = 2, 6
    bw = beam  # batch=1
    pre_ids = np.full((bw, 1), 1, np.int64)
    pre_scores = np.zeros((bw, 1), np.float32)
    logp = np.log(rng.dirichlet(np.ones(vocab), bw)).astype(np.float32)

    pi = fluid.layers.data("pre_ids", [1], dtype="int64")
    ps = fluid.layers.data("pre_scores", [1])
    sc = fluid.layers.data("scores", [vocab])
    blk = fluid.default_main_program().current_block()
    sel = blk.create_var(name="sel_ids", dtype="int64")
    ssc = blk.create_var(name="sel_scores")
    par = blk.create_var(name="parent_idx", dtype="int32")
    blk.append_op(type="beam_search",
                  inputs={"pre_ids": [pi], "pre_scores": [ps],
                          "scores": [sc]},
                  outputs={"selected_ids": [sel], "selected_scores": [ssc],
                           "parent_idx": [par]},
                  attrs={"beam_size": beam, "end_id": 0,
                         "is_first_step": True})
    exe = fluid.Executor(fluid.CPUPlace())
    got_sel, got_sc, got_par = exe.run(
        feed={"pre_ids": pre_ids, "pre_scores": pre_scores, "scores": logp},
        fetch_list=[sel, ssc, par])
    rsel, rsc, rpar = _np_beam_step(pre_ids.reshape(-1), pre_scores.reshape(-1),
                                    logp, beam, 0, True)
    np.testing.assert_array_equal(np.asarray(got_sel).reshape(-1), rsel)
    np.testing.assert_allclose(np.asarray(got_sc).reshape(-1), rsc,
                               rtol=1e-5)
