"""Model zoo: Program-building functions for the reference's benchmark
models (benchmark/fluid/{mnist,resnet,vgg,machine_translation,
stacked_dynamic_lstm}.py + tests/unittests/transformer_model.py), built
TPU-first with the paddle_tpu layers DSL.

``ZOO`` maps every workload to its static-analyzer entry point — a
callable returning ``(fn, example_args)`` for
``paddle_tpu.analysis.check_program`` (see models/harness.py). Modules
resolve lazily so listing the zoo stays import-cheap.
"""

import importlib

from . import mlp, resnet, ssd, vgg  # noqa: F401

# name -> (module, entry attribute). Every entry traces device-free.
ZOO = {
    "mlp": ("paddle_tpu.models.mlp", "analysis_entry"),
    "cnn": ("paddle_tpu.models.mlp", "analysis_entry_cnn"),
    "resnet": ("paddle_tpu.models.resnet", "analysis_entry"),
    "vgg": ("paddle_tpu.models.vgg", "analysis_entry"),
    "ssd": ("paddle_tpu.models.ssd", "analysis_entry"),
    "deepfm": ("paddle_tpu.models.deepfm", "analysis_entry"),
    "transformer": ("paddle_tpu.models.transformer", "analysis_entry"),
    "transformer_moe": ("paddle_tpu.models.transformer",
                        "analysis_entry_moe"),
    "transformer_infer": ("paddle_tpu.models.transformer_infer",
                          "analysis_entry_infer"),
    "serving_megastep": ("paddle_tpu.models.transformer_infer",
                         "analysis_entry_serving_megastep"),
}


def zoo_entry(name):
    """Resolve + call a zoo entry: returns (fn, example_args)."""
    try:
        mod_name, attr = ZOO[name]
    except KeyError:
        raise KeyError("unknown zoo model %r (have: %s)"
                       % (name, ", ".join(sorted(ZOO))))
    return getattr(importlib.import_module(mod_name), attr)()
