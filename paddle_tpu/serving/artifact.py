"""Serving cold-start from a saved inference artifact (ISSUE 15, the
ROADMAP direction-2(b) seam).

PR 8's fleet shared the model OBJECT in-process; a real deployment's
replica boots from a checkpoint. This module closes that gap over the
``io.save_inference_model`` artifact: the manifest's ``config`` block
carries the decode model's hyperparameters, so a fresh process —
holding nothing but the directory path — rebuilds the
``TransformerLMInfer`` (via ``extract_params`` over the specialized
Program, fused ops included) and serves token-identically to the
source-model engine (greedy decode is deterministic; pinned in
tests/test_specialize.py including a REAL fresh-process round trip).

Surfaces:
  save_lm_artifact(dirname, program, scope, targets, cfg...)  writer
  model_from_artifact(dirname)      -> TransformerLMInfer
  engine_from_artifact(dirname)     -> serving.Engine
  serving.Engine(model=<dirname>)   the same seam inline — and since
                                    fleet.Replica hands its ``model``
                                    straight to Engine, a Replica cold-
                                    starts from the directory too.
"""

import os

from .. import io as _io
from ..io import ArtifactError

LM_KIND = "transformer_lm"

__all__ = ["LM_KIND", "save_lm_artifact", "model_from_artifact",
           "engine_from_artifact", "ArtifactError"]


def save_lm_artifact(dirname, program, scope, targets, n_layer, n_head,
                     d_model, max_len, bos_id=1, end_id=2, feeds=(),
                     bf16=False, dtype=None, specialize=True):
    """Write a decode-servable artifact for a ``transformer_lm``
    training program: the specialized Program + params via
    ``io.save_inference_model``, plus the model config the serving
    boot needs. ``targets`` must keep the whole forward live (the
    logits head — pruning to the loss would also work; pruning to an
    intermediate would drop parameter ops the replayer expects).
    ``dtype='bfloat16'`` makes the loaded engine run the PR-5 bf16
    serving cast; ``bf16=True`` additionally stores matmul-class
    params half-width via the transform tier's opt-in cast pass."""
    cfg = {"kind": LM_KIND, "n_layer": int(n_layer),
           "n_head": int(n_head), "d_model": int(d_model),
           "max_len": int(max_len), "bos_id": int(bos_id),
           "end_id": int(end_id)}
    if dtype is not None:
        cfg["dtype"] = str(dtype)
    _io.save_inference_model(
        dirname, list(feeds), list(targets), None,
        main_program=program, scope=scope, specialize=specialize,
        bf16=bf16, config=cfg)
    return dirname


def load_artifact_config(dirname):
    manifest = _io.load_inference_manifest(dirname)
    if manifest is None:
        raise ArtifactError(
            "%s has no artifact manifest — not a serving artifact "
            "(legacy save_inference_model output predates the config "
            "block serving cold-start needs)" % (dirname,))
    return manifest, dict(manifest.get("config") or {})


def model_from_artifact(dirname):
    """Boot the decode model from an artifact directory: verified
    load (CRC manifest) into a PRIVATE scope, then the parameter-
    stream replay into a ``TransformerLMInfer``. Raises
    ``ArtifactError`` on corruption or a config this module cannot
    serve."""
    import jax.numpy as jnp
    import paddle_tpu as fluid
    from ..models.transformer_infer import TransformerLMInfer

    manifest, cfg = load_artifact_config(dirname)
    kind = cfg.get("kind")
    if kind != LM_KIND:
        raise ArtifactError(
            "artifact %s config kind %r is not servable by the decode "
            "engine (want %r); ScoringEngine.from_artifact serves "
            "dense scoring programs" % (dirname, kind, LM_KIND))
    for key in ("n_layer", "n_head", "d_model", "max_len"):
        if key not in cfg:
            raise ArtifactError(
                "artifact %s config is missing %r — cannot rebuild "
                "the decode model" % (dirname, key))
    scope = fluid.Scope()
    program, _feeds, _fetches = _io.load_inference_model(
        dirname, None, scope=scope)
    dtype = jnp.bfloat16 if cfg.get("dtype") == "bfloat16" else None
    try:
        return TransformerLMInfer(
            program, scope, int(cfg["n_layer"]), int(cfg["n_head"]),
            int(cfg["d_model"]), int(cfg["max_len"]),
            bos_id=int(cfg.get("bos_id", 1)),
            end_id=int(cfg.get("end_id", 2)), dtype=dtype)
    except AssertionError as e:
        # the cursor's loud parameter-stream mismatch: surface it as
        # an artifact problem, with the artifact named
        raise ArtifactError(
            "artifact %s parameter stream does not replay into a "
            "%s(%s layers): %s"
            % (dirname, LM_KIND, cfg.get("n_layer"), e)) from e


def engine_from_artifact(dirname, **engine_kwargs):
    """One-call serving cold-start: artifact directory -> running
    ``serving.Engine``."""
    from .engine import Engine
    return Engine(model_from_artifact(dirname), **engine_kwargs)


def is_artifact_path(model):
    return isinstance(model, (str, os.PathLike))
