"""transform/autoparallel planner: candidate enumeration validity, the
cost model's pinned orderings against PERF.md's measurements (pipeline
microbatch throughput order M=1<2<4<8<16; sparse-over-dense for the
pserver-sharded embedding shape), the ranked recommendation for the
transformer zoo model at 8 virtual devices, and apply() of the top
recommendation running under ParallelExecutor to a loss matching the
hand-picked strategy's (the ISSUE-9 acceptance pin)."""

import numpy as np
import pytest

from paddle_tpu.transform import autoparallel as ap

# pure-math spec: compute-DOMINATED model so the pipeline bubble term
# isolates cleanly (the measured PERF.md pipeline bench ran on a
# virtual mesh where stage-boundary comm was negligible next to
# compute; params=0 removes the dp all-reduce term too)
BUBBLE_SPEC = ap.ModelSpec(
    "bubble", flops=1e15, bytes=1e9, param_bytes=0.0, batch=32,
    seq=256, d_model=512, n_layer=8, n_head=8)

TOY_SPEC = ap.ModelSpec(
    "toy", flops=1e12, bytes=1e9, param_bytes=100e6, batch=32,
    seq=256, d_model=512, n_layer=8, n_head=8, num_experts=4)


# -- enumeration -----------------------------------------------------------

def test_candidates_are_valid_factorizations():
    cands = ap.candidates(TOY_SPEC, 8)
    assert cands
    seen = set()
    for axes, m in cands:
        n = 1
        for v in axes.values():
            n *= v
        assert n == 8
        assert TOY_SPEC.batch % axes["dp"] == 0
        if axes["tp"] > 1:
            assert TOY_SPEC.n_head % axes["tp"] == 0
            assert TOY_SPEC.d_model % axes["tp"] == 0
        if axes["pp"] > 1:
            assert TOY_SPEC.n_layer % axes["pp"] == 0
        if axes["sp"] > 1:
            assert TOY_SPEC.seq % axes["sp"] == 0
        if axes["ep"] > 1:
            assert TOY_SPEC.num_experts % axes["ep"] == 0
        seen.add(tuple(sorted(axes.items())) + (m,))
    assert len(seen) == len(cands)          # no duplicates


def test_candidates_respect_model_structure():
    # no experts -> no ep plans; 6 heads reject tp=4
    no_moe = ap.ModelSpec("d", 1e12, 1e9, 1e6, batch=32, seq=256,
                          d_model=512, n_layer=8, n_head=8)
    assert all(a["ep"] == 1 for a, _ in ap.candidates(no_moe, 8))
    odd_heads = ap.ModelSpec("h6", 1e12, 1e9, 1e6, batch=32, seq=256,
                             d_model=528, n_layer=8, n_head=6)
    assert all(a["tp"] in (1, 2) for a, _ in ap.candidates(odd_heads, 8))


# -- pipeline bubble calibration (PERF.md round 3) -------------------------

# measured throughput ratio vs M=16 (pp=4 virtual mesh, PERF.md table)
MEASURED_PP4 = {1: 0.32, 2: 0.44, 4: 0.62, 8: 0.85, 16: 1.00}


def test_pipeline_cost_reproduces_measured_microbatch_order():
    """The planner's cost ordering must reproduce the MEASURED pipeline
    throughput order M=1<2<4<8<16, and the modeled throughput ratios
    must track the measured table (U(M) calibration)."""
    axes = {"dp": 2, "tp": 1, "pp": 4, "sp": 1, "ep": 1}
    costs = {m: ap.plan_cost(BUBBLE_SPEC, axes, m)[0]
             for m in MEASURED_PP4}
    # throughput order: more microbatches, cheaper step
    assert costs[16] < costs[8] < costs[4] < costs[2] < costs[1]
    for m, measured in MEASURED_PP4.items():
        modeled = costs[16] / costs[m]
        assert abs(modeled - measured) < 0.1, \
            "M=%d: modeled ratio %.3f vs measured %.3f" % (
                m, modeled, measured)


def test_rank_orders_pp_plans_by_microbatches():
    plans = ap.rank(BUBBLE_SPEC, 8)
    pp4 = [p for p in plans
           if p.axes["pp"] == 4 and p.axes["dp"] == 2
           and p.axes["tp"] == p.axes["sp"] == 1]
    assert len(pp4) >= 3
    ms = [p.microbatches for p in pp4]
    assert ms == sorted(ms, reverse=True)    # best M first


def test_pipeline_utilization_formula():
    assert ap.pipeline_utilization(16, 4) == pytest.approx(16 / 19)
    assert ap.pipeline_utilization(1, 4) == pytest.approx(0.25)
    assert ap.pipeline_utilization(5, 1) == 1.0


# -- DCN embedding placement (PERF.md round 3) -----------------------------

def test_sparse_over_dense_for_pserver_embedding_shape():
    """The measured shape: [200k x 64] table, a few hundred touched
    rows/step — sparse shipped 131 KB where dense shipped ~105 MB and
    measured 7046 vs 335 samples/s. The planner must rank sparse first
    and reproduce the wire-byte asymmetry."""
    ranked = ap.recommend_embedding_placement(200_000, 64,
                                              touched_rows=512)
    assert ranked[0][0] == "sparse"
    assert ranked[0][1] < ranked[1][1] / 100    # orders of magnitude
    costs = ap.embedding_wire_costs(200_000, 64, 512)
    # dense wire per step ~2 x 51.2 MB (PERF.md measured ~105 MB)
    assert costs["dense_wire_bytes"] == pytest.approx(102.4e6, rel=0.01)
    assert costs["sparse_wire_bytes"] < 0.5e6


def test_dense_wins_when_every_row_is_touched():
    # touching the whole tiny table: sparse pays the per-row id tax
    ranked = ap.recommend_embedding_placement(64, 8, touched_rows=64)
    assert ranked[0][0] == "dense"


# -- HBM capacity term (ISSUE 10) ------------------------------------------

# on 2 devices only dp2 and tp2 are valid: n_layer=3 rejects pp2,
# seq=255 rejects sp2, no experts rejects ep2 — so the capacity filter
# decides between a replicated-params plan (tp=1) and a sharded one
HBM_SPEC = ap.ModelSpec("hbm", flops=1e12, bytes=1e9, param_bytes=1e9,
                        batch=8, seq=255, d_model=512, n_layer=3,
                        n_head=8)


def test_plan_hbm_bytes_accounting():
    """params shard * (1 + optimizer mult) + the paged-KV pool priced
    through kvpool.bytes_per_block — hand-computed for both plans."""
    from paddle_tpu.serving.kvpool import bytes_per_block
    dp2 = {"dp": 2, "tp": 1, "pp": 1, "sp": 1, "ep": 1}
    tp2 = {"dp": 1, "tp": 2, "pp": 1, "sp": 1, "ep": 1}
    total, bd = ap.plan_hbm_bytes(HBM_SPEC, dp2)
    # dp replicates the FULL 1 GB of params (+3x optimizer state);
    # KV: 4 rows/chip * ceil(255/16)=16 blocks of the full L/H shard
    assert bd["hbm_param_bytes"] == pytest.approx(4e9)
    assert bd["hbm_kv_bytes"] == pytest.approx(
        4 * 16 * bytes_per_block(3, 8, 16, 64, 4))
    assert total == pytest.approx(bd["hbm_param_bytes"]
                                  + bd["hbm_kv_bytes"])
    total_tp, bd_tp = ap.plan_hbm_bytes(HBM_SPEC, tp2)
    assert bd_tp["hbm_param_bytes"] == pytest.approx(2e9)  # sharded
    assert total_tp < total


def test_hbm_capacity_filters_tp1_keeps_tp2():
    """The ISSUE-10 pin: with a per-chip capacity between the two
    plans' footprints, the over-capacity tp1 (dp2) candidate is
    REJECTED — not merely ranked worse — while tp2 survives; an
    impossible capacity fails loudly naming the constraint."""
    plans = ap.rank(HBM_SPEC, 2)
    axes = {tuple(sorted(p.axes.items())) for p in plans}
    assert len(axes) == 2                   # dp2 and tp2 only
    assert all(p.hbm_bytes is not None for p in plans)
    fit = ap.rank(HBM_SPEC, 2, hbm_bytes=3e9)
    assert fit and all(p.axes["tp"] == 2 for p in fit)
    assert all(p.hbm_bytes <= 3e9 for p in fit)
    with pytest.raises(ValueError, match="HBM capacity"):
        ap.rank(HBM_SPEC, 2, hbm_bytes=1e6)


# -- zoo surface: transformer at 8 virtual devices -------------------------

@pytest.fixture(scope="module")
def tf_spec():
    return ap.model_spec("transformer")


def test_model_spec_traces_real_costs(tf_spec):
    assert tf_spec.flops > 0 and tf_spec.param_bytes > 0
    assert (tf_spec.batch, tf_spec.seq, tf_spec.n_layer,
            tf_spec.n_head) == (8, 32, 2, 4)


def test_recommend_transformer_at_8_devices(tf_spec):
    plans = ap.recommend("transformer", 8, spec=tf_spec)
    assert len(plans) >= 5
    assert all(plans[i].cost <= plans[i + 1].cost
               for i in range(len(plans) - 1))
    # every plan really uses the 8 chips
    for p in plans:
        n = 1
        for v in p.axes.values():
            n *= v
        assert n == 8
    # pp plans carry the bubble: no pipeline plan can beat the best
    # bubble-free plan at equal device count (U(M) < 1)
    best_no_pp = min(p.cost for p in plans if p.axes["pp"] == 1)
    assert plans[0].axes["pp"] == 1
    for p in plans:
        if p.axes["pp"] > 1:
            assert p.cost > best_no_pp
    # within one pp assignment, measured microbatch order holds
    pp_groups = {}
    for p in plans:
        if p.axes["pp"] > 1:
            pp_groups.setdefault(
                tuple(sorted(p.axes.items())), []).append(p)
    for group in pp_groups.values():
        by_cost = sorted(group, key=lambda p: p.cost)
        ms = [p.microbatches for p in by_cost]
        assert ms == sorted(ms, reverse=True)


@pytest.mark.slow  # ISSUE-11 durations audit: >10 s on tier-1
def test_apply_top_plan_matches_handpicked_strategy(tf_spec):
    """ISSUE-9 acceptance: apply() of the planner's top recommendation
    runs under ParallelExecutor at 8 virtual devices, and its per-step
    training losses match the hand-picked strategy's (dp=4 x tp=2, the
    composition test_parallel_integration pins against single-device
    math). Both builds share the init RNG stream, so matching losses
    mean matching math, not luck."""
    plans = ap.recommend("transformer", 8, spec=tf_spec)
    top = plans[0]
    assert top.axes["pp"] == 1   # bubble-free wins at equal n (U(M)<1)
    hand = ap.Plan({"dp": 4, "tp": 2, "pp": 1, "sp": 1, "ep": 1}, 1,
                   0.0, {})
    applied = []
    losses = []
    for plan in (top, hand):
        a = ap.apply(plan, "transformer")
        applied.append(a)
        rng = np.random.RandomState(7)     # same feeds for both plans
        per = []
        for _ in range(2):
            out, = a.run(a.feed_fn(rng))
            per.append(float(np.asarray(out)))
        losses.append(per)
    got, want = losses
    assert all(np.isfinite(got)) and all(np.isfinite(want))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-5)
    # the strategies really differ (this is not comparing a plan to
    # itself) and the applied mesh matches the plan
    assert applied[0].plan.axes != applied[1].plan.axes or \
        top.axes == hand.axes
    assert int(np.prod(applied[0].pexe.mesh.devices.shape)) == 8
