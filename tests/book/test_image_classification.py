"""Book test: image_classification (reference
python/paddle/fluid/tests/book/test_image_classification.py) — the CIFAR
resnet (and a VGG-style net) trained to an accuracy/loss threshold, with
a save/load_inference_model round-trip."""

import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu as fluid
from paddle_tpu.models import resnet, vgg


def _train(build_net, passes, lr=0.01):
    images = fluid.layers.data("pixel", [3, 32, 32])
    label = fluid.layers.data("label", [1], dtype="int64")
    predict = build_net(images)
    cost = fluid.layers.cross_entropy(predict, label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(predict, label)
    fluid.optimizer.Adam(learning_rate=lr).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    reader = paddle.batch(
        paddle.reader.shuffle(paddle.dataset.cifar.train10(256), 256),
        batch_size=32)
    feeder = fluid.DataFeeder([images, label], fluid.CPUPlace())

    epoch_losses = []
    accs = []
    for _ in range(passes):
        accs, losses = [], []
        for batch in reader():
            feed = feeder.feed(batch)
            lv, av = exe.run(feed=feed, fetch_list=[avg_cost, acc])
            losses.append(float(lv))
            accs.append(float(np.asarray(av).ravel()[0]))
        epoch_losses.append(float(np.mean(losses)))
    return (exe, images, predict, epoch_losses[0], epoch_losses[-1],
            float(np.mean(accs)))


@pytest.mark.slow  # ISSUE-11 durations audit: >10 s on tier-1
def test_image_classification_resnet():
    exe, images, predict, first, last, acc = _train(
        lambda img: resnet.resnet_cifar10(img, depth=20), passes=4)
    assert last < first, (first, last)
    # ABSOLUTE threshold (VERDICT r4 weak #6): uniform-10-class CE is
    # ln(10)=2.30; a converging run must be well under 2.0
    assert last < 2.0, (first, last)
    assert acc > 0.3, acc    # reference threshold: acc converging

    # save/load_inference_model round-trip (book test infer() path)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model")
        fluid.io.save_inference_model(path, [images.name], [predict], exe)
        probe = np.random.RandomState(0).rand(2, 3, 32, 32).astype(
            np.float32)
        scope = fluid.Scope()
        exe2 = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            prog, feeds, fetches = fluid.io.load_inference_model(path, exe2)
            out, = exe2.run(prog, feed={feeds[0]: probe},
                            fetch_list=fetches)
    out = np.asarray(out)
    assert out.shape == (2, 10)
    np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-4)  # softmax


@pytest.mark.slow  # ISSUE-11 durations audit: >10 s on tier-1
def test_image_classification_vgg():
    # epoch-MEAN losses (single-batch endpoints are too noisy for VGG at
    # this scale); last epoch must beat the first on average
    exe, images, predict, first, last, acc = _train(
        lambda img: vgg.vgg16_bn_drop(img), passes=4)
    assert last < first * 0.95, (first, last)
