"""Executor tests: compiled-step execution, feed/fetch, state threading,
autodiff, optimizer updates — the M1 "minimum end-to-end slice"."""

import numpy as np

import paddle_tpu as fluid


def _run_startup(exe):
    exe.run(fluid.default_startup_program())


def test_simple_forward():
    x = fluid.layers.data("x", [4])
    y = fluid.layers.fc(x, 3, act="relu",
                        param_attr=fluid.ParamAttr(
                            initializer=fluid.initializer.Constant(0.5)),
                        bias_attr=fluid.ParamAttr(
                            initializer=fluid.initializer.Constant(0.1)))
    exe = fluid.Executor(fluid.CPUPlace())
    _run_startup(exe)
    data = np.ones((2, 4), np.float32)
    out, = exe.run(feed={"x": data}, fetch_list=[y])
    assert out.shape == (2, 3)
    np.testing.assert_allclose(out, 4 * 0.5 + 0.1, rtol=1e-6)


def test_fetch_multiple_and_cache():
    x = fluid.layers.data("x", [4])
    h = fluid.layers.fc(x, 8)
    y = fluid.layers.fc(h, 2)
    exe = fluid.Executor(fluid.CPUPlace())
    _run_startup(exe)
    d = np.random.rand(3, 4).astype(np.float32)
    o1, o2 = exe.run(feed={"x": d}, fetch_list=[h, y])
    assert o1.shape == (3, 8) and o2.shape == (3, 2)
    # second run hits the compiled cache; same results for same params
    o1b, _ = exe.run(feed={"x": d}, fetch_list=[h, y])
    np.testing.assert_allclose(o1, o1b, rtol=1e-6)
    assert len(exe._cache) == 2  # startup + main


def test_append_backward_grads():
    x = fluid.layers.data("x", [4])
    y = fluid.layers.fc(x, 1, bias_attr=False,
                        param_attr=fluid.ParamAttr(
                            name="w0",
                            initializer=fluid.initializer.Constant(1.0)))
    loss = fluid.layers.mean(y)
    fluid.append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    _run_startup(exe)
    d = np.arange(8, dtype=np.float32).reshape(2, 4)
    loss_v, gw = exe.run(feed={"x": d}, fetch_list=[loss, "w0@GRAD"])
    np.testing.assert_allclose(loss_v, d.sum(1).mean(), rtol=1e-5)
    # d(mean(x @ w))/dw = mean over batch of x
    np.testing.assert_allclose(gw.reshape(-1), d.mean(0), rtol=1e-5)


def test_sgd_training_decreases_loss():
    # lr=0.05 x 60 steps left SGD mid-descent (final/first ~ 0.20,
    # deterministically missing the 10x bar in this environment);
    # lr=0.2 x 120 steps reaches ratio ~1e-4 with everything pinned
    # (np seed 0 fixes data AND the fc init draw), so the 10x bar now
    # holds with >100x margin instead of riding the convergence knee.
    np.random.seed(0)
    x = fluid.layers.data("x", [4])
    label = fluid.layers.data("label", [1])
    pred = fluid.layers.fc(x, 1)
    loss = fluid.layers.mean(
        fluid.layers.square_error_cost(pred, label))
    opt = fluid.optimizer.SGD(learning_rate=0.2)
    opt.minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    _run_startup(exe)
    w_true = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    losses = []
    for i in range(120):
        xs = np.random.rand(16, 4).astype(np.float32)
        ys = xs @ w_true + 0.7
        lv, = exe.run(feed={"x": xs, "label": ys}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.1, losses[::10]


def test_adam_training():
    np.random.seed(1)
    x = fluid.layers.data("x", [4])
    label = fluid.layers.data("label", [1])
    pred = fluid.layers.fc(x, 1)
    loss = fluid.layers.mean(
        fluid.layers.square_error_cost(pred, label))
    fluid.optimizer.Adam(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    _run_startup(exe)
    w_true = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    losses = []
    for i in range(80):
        xs = np.random.rand(16, 4).astype(np.float32)
        ys = xs @ w_true + 0.7
        lv, = exe.run(feed={"x": xs, "label": ys}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.1


def test_state_persists_in_scope():
    x = fluid.layers.data("x", [2])
    y = fluid.layers.fc(x, 2, bias_attr=False, param_attr="w_persist")
    loss = fluid.layers.mean(y)
    fluid.optimizer.SGD(learning_rate=1.0).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    _run_startup(exe)
    w0 = np.array(fluid.global_scope().find_var("w_persist")).copy()
    exe.run(feed={"x": np.ones((1, 2), np.float32)}, fetch_list=[loss])
    w1 = np.array(fluid.global_scope().find_var("w_persist"))
    assert not np.allclose(w0, w1)


def test_calc_gradient():
    x = fluid.layers.data("x", [3])
    y = fluid.layers.fc(x, 1, bias_attr=False,
                        param_attr=fluid.ParamAttr(
                            name="wcg",
                            initializer=fluid.initializer.Constant(2.0)))
    grads = fluid.gradients(y, [x])
    exe = fluid.Executor(fluid.CPUPlace())
    _run_startup(exe)
    d = np.ones((2, 3), np.float32)
    gx, = exe.run(feed={"x": d}, fetch_list=[grads[0]])
    np.testing.assert_allclose(gx, np.full((2, 3), 2.0), rtol=1e-6)
