"""Inference specialization (ISSUE 15): the Fluid deploy path
(``save_inference_model`` — SURVEY row: prune to the inference subgraph
and emit a servable artifact) rebuilt on the PR-9 pass framework.

``specialize_for_inference(program, feeds, fetches)`` carves the
inference subgraph (``Program.prune`` + ``clone(for_test=True)``) and
drives it through dead_op -> constant_fold -> cse -> fusion to a fixed
point — every pass bitwise-gated by the PR-9 verifier. The opt-in
``bf16=True`` additionally runs ``Bf16CastPass``: matmul/conv/embedding
compute moves to bfloat16 while every op's OUTPUT is cast back to f32,
so softmax / layer-norm / batch-norm statistics and reductions always
accumulate in f32 (the analysis dtype rule's bf16-serving contract).
bf16 is NOT bitwise — it is exempt from ``verify_bitwise`` and gated by
a pinned rtol contract instead (tests/test_specialize.py), and it is
off by default everywhere.

``io.save_inference_model`` runs this pipeline and serializes the
result; the artifact round-trip (CRC manifest, fresh-process load)
lives in ``paddle_tpu/io.py``.
"""

import collections

from ..core.program import Variable
from .passes import (PassManager, Pass, ConstantFoldPass, CSEPass,
                     DeadOpEliminationPass)
from .fusion import FusionPass

# compute ops whose float32 inputs move to bf16 under Bf16CastPass:
# op type -> (castable input slots, output slot). Ids / indices are
# never cast; every output is cast BACK to f32 (stats contract).
_BF16_SITES = {
    "mul": (("X", "Y"), "Out"),
    "matmul": (("X", "Y"), "Out"),
    "fused_matmul_bias_act": (("X", "Y", "Bias"), "Out"),
    "conv2d": (("Input", "Filter"), "Output"),
    "depthwise_conv2d": (("Input", "Filter"), "Output"),
    "lookup_table": (("W",), "Out"),
}


class Bf16CastPass(Pass):
    """Opt-in bf16 compute cast for inference programs.

    For every matmul-class op (see ``_BF16_SITES``) whose operands are
    float32: insert ``cast -> bfloat16`` on each operand, run the op in
    bf16 (matmuls still accumulate f32 via preferred_element_type in
    their lowerings), and cast the result straight back to float32 —
    downstream softmax/normalization/reduction math is f32-identical in
    structure to the unfused program (the f32-stats contract the
    analysis dtype rule audits). Parameters consumed ONLY by cast sites
    flip their var dtype to bfloat16, so the saved artifact stores
    half-width weights and the inserted operand cast becomes an
    identity at load time.

    NOT semantics-preserving bitwise: matmul operands are rounded to
    bf16. Excluded from ``default_passes()``/'all'; selectable by name
    and via ``specialize_for_inference(bf16=True)``; gated by a pinned
    rtol contract, not ``verify_bitwise``."""

    name = "bf16_cast"
    doc = ("opt-in bf16 operand cast for matmul-class inference "
           "compute (f32 stats preserved; rtol-gated, not bitwise)")

    def rewrite(self, program, keep):
        gb = program.global_block()
        uses = collections.Counter()
        for op in gb.ops:
            for n in op.input_names:
                uses[n] += 1
        cast_in = {}        # source name -> casted name (dedup)
        rewrote = 0
        param_casts = collections.Counter()  # name -> rewritten uses
        new_ops = []

        def _var(name):
            return gb.vars.get(name)

        def _to_bf16(name):
            if name in cast_in:
                return cast_in[name]
            v = _var(name)
            casted = name + "@bf16"
            gb.vars[casted] = Variable(
                gb, name=casted, shape=v.shape, dtype="bfloat16",
                stop_gradient=True)
            new_ops.append(_mk_cast(gb, name, casted, "bfloat16"))
            cast_in[name] = casted
            return casted

        for op in gb.ops:
            site = _BF16_SITES.get(op.type)
            if site is None:
                new_ops.append(op)
                continue
            slots, out_slot = site
            out_names = op.output(out_slot)
            out_v = _var(out_names[0]) if len(out_names) == 1 else None
            eligible = out_v is not None and out_v.dtype == "float32" \
                and all(
                    len(op.input(s)) == 1
                    and _var(op.input(s)[0]) is not None
                    and _var(op.input(s)[0]).dtype == "float32"
                    for s in slots if op.input(s))
            if not eligible:
                new_ops.append(op)
                continue
            for s in slots:
                names = op.input(s)
                if not names:
                    continue
                src = names[0]
                op.inputs[s] = [_to_bf16(src)]
                v = _var(src)
                if v is not None and v.persistable:
                    param_casts[src] += 1
            out = out_names[0]
            raw = out + "@bf16raw"
            gb.vars[raw] = Variable(gb, name=raw, shape=out_v.shape,
                                    dtype="bfloat16",
                                    stop_gradient=True)
            op.outputs[out_slot] = [raw]
            new_ops.append(op)
            new_ops.append(_mk_cast(gb, raw, out, "float32"))
            rewrote += 1

        if not rewrote:
            return 0
        gb.ops = new_ops
        # params used ONLY at cast sites store bf16 in the artifact:
        # the operand cast is then an identity at load time and the
        # params blob halves
        for name, n in param_casts.items():
            if uses[name] == n:
                gb.vars[name].dtype = "bfloat16"
        program._bump_version()
        return rewrote


def _mk_cast(block, src, dst, out_dtype):
    from ..core.program import Operator
    return Operator(block, "cast", {"X": [src]}, {"Out": [dst]},
                    {"out_dtype": out_dtype})


class SpecializeResult:
    """specialize_for_inference output: the servable program + the
    accounting the artifact manifest records."""

    def __init__(self, program, feed_names, fetch_names, transform,
                 bf16, bf16_sites=0):
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.transform = transform        # TransformResult of the pipeline
        self.bf16 = bool(bf16)
        self.bf16_sites = int(bf16_sites)

    def to_dict(self):
        return {"feed_names": self.feed_names,
                "fetch_names": self.fetch_names,
                "bf16": self.bf16, "bf16_sites": self.bf16_sites,
                **self.transform.to_dict()}


def specialize_pipeline():
    """The inference pipeline, in order: carve first (prune happens
    before this), then drop dead chains, fold constants, dedup, fuse."""
    return [DeadOpEliminationPass(), ConstantFoldPass(), CSEPass(),
            FusionPass()]


def specialize_for_inference(program, feeds, fetches, bf16=False):
    """Prune ``program`` to the subgraph computing ``fetches`` from
    ``feeds``, clone in test mode (dropout/BN eval lowering), and run
    the optimizing pipeline to a fixed point. Returns a
    ``SpecializeResult`` whose ``.program`` a fresh process can execute
    with nothing but the feeds (the ``io.save_inference_model``
    payload).

    ``feeds``/``fetches`` are names or Variables. Every pass but the
    opt-in bf16 cast is bitwise-gated (tests pin the full zoo); bf16
    rounds matmul-class operands and is covered by an rtol contract."""
    feed_names = [v.name if isinstance(v, Variable) else str(v)
                  for v in feeds]
    fetch_names = [v.name if isinstance(v, Variable) else str(v)
                   for v in fetches]
    gb = program.global_block()
    for n in feed_names + fetch_names:
        if not gb.has_var(n):
            raise ValueError(
                "specialize_for_inference: %r is not a variable of the "
                "program's global block" % (n,))
    pruned = program.prune(fetch_names).clone(for_test=True)
    result = PassManager(specialize_pipeline()).run(pruned,
                                                    keep=fetch_names)
    prog = result.program
    sites = 0
    if bf16:
        sites = Bf16CastPass().rewrite(prog, fetch_names)
        if sites:
            prog._transform_meta = dict(prog._transform_meta or {})
            prog._transform_meta["bf16_sites"] = sites
            prog._transform_meta["version"] = prog._version
    return SpecializeResult(prog, feed_names, fetch_names, result,
                            bf16, sites)
