"""SP/PP/EP integration through the Program IR + ParallelExecutor (VERDICT
r1 #4): the same fluid-built flagship program must produce the same loss
single-device (dense fallbacks) and sharded on a mesh (ring attention /
GPipe / MoE all-to-all), proving the parallel subsystem is a framework
feature, not a library."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import parallel
from paddle_tpu.models import transformer as T

BATCH, MAX_LEN, VOCAB, D_MODEL, N_LAYER, N_HEAD = 8, 16, 50, 32, 2, 4


def _feeds(rng):
    f = T.make_lm_batch(rng, BATCH, MAX_LEN, VOCAB)
    return {k: np.asarray(v) for k, v in f.items()}


def _build(strategy=None, num_experts=0):
    avg_cost, _ = T.transformer_lm_parallel(
        vocab_size=VOCAB, max_len=MAX_LEN, n_layer=N_LAYER, n_head=N_HEAD,
        d_model=D_MODEL, d_inner=64, strategy=strategy,
        num_experts=num_experts)
    return avg_cost


def _copy_scope(src_scope, names):
    dst = fluid.Scope()
    for n in names:
        v = src_scope.find_var(n)
        if v is not None:
            dst.set(n, np.array(np.asarray(v)))
    return dst


def _parity(strategy, mesh_axes, num_experts=0, rtol=2e-4, n_steps=3):
    """N>=3 optimizer steps on both paths: per-step loss parity plus
    final-weight parity — multi-step catches RNG-stream, accumulator-
    sharding and LR-counter drift that a single step cannot see
    (round-3 VERDICT weak #5)."""
    batches = [_feeds(np.random.RandomState(7 + 31 * i))
               for i in range(n_steps)]
    avg_cost = _build(strategy, num_experts)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(avg_cost)

    names = [v.name for v in
             fluid.default_main_program().global_block().vars.values()
             if v.persistable]
    # init once, clone the params, run the SAME steps single-device and
    # sharded from identical state
    scope2 = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope2):
        exe.run(fluid.default_startup_program())
    scope1b = _copy_scope(scope2, names)
    singles = []
    with fluid.scope_guard(scope1b):
        for feeds in batches:
            l, = exe.run(feed=feeds, fetch_list=[avg_cost])
            singles.append(float(np.asarray(l)))

    mesh = parallel.make_mesh(mesh_axes)
    pexe = parallel.ParallelExecutor(loss_name=avg_cost.name, mesh=mesh,
                                     scope=scope2)
    for i, feeds in enumerate(batches):
        l, = pexe.run(fetch_list=[avg_cost], feed=feeds)
        loss2 = float(np.asarray(l))
        assert np.isfinite(loss2)
        np.testing.assert_allclose(loss2, singles[i], rtol=rtol,
                                   atol=1e-5,
                                   err_msg="step %d of %d" % (i, n_steps))
    # and the updated params match after ALL steps (the optimizer ran
    # sharded with its accumulators/counters sharded alongside)
    for n in names:
        a = np.asarray(scope1b.find_var(n))
        b = np.asarray(scope2.find_var(n))
        np.testing.assert_allclose(a, b, rtol=5e-3, atol=2e-4,
                                   err_msg="weight %s after %d steps"
                                   % (n, n_steps))


def test_flagship_dp_tp_parity():
    _parity(parallel.DistributedStrategy(dp=4, tp=2),
            {"dp": 4, "tp": 2})


def test_flagship_sp_ring_parity():
    _parity(parallel.DistributedStrategy(dp=2, sp=4),
            {"dp": 2, "sp": 4}, rtol=5e-4)


def test_flagship_pp_parity():
    _parity(parallel.DistributedStrategy(dp=2, pp=2),
            {"dp": 2, "pp": 2})


def test_flagship_moe_ep_parity():
    _parity(parallel.DistributedStrategy(dp=2, ep=4),
            {"dp": 2, "ep": 4}, num_experts=4)


def test_flagship_3d_dp_tp_sp_parity():
    # 3-axis composition on one mesh: batch on dp, Megatron weight shards
    # on tp, ring attention over sp — all through the same Program
    _parity(parallel.DistributedStrategy(dp=2, tp=2, sp=2),
            {"dp": 2, "tp": 2, "sp": 2}, rtol=5e-4)


def test_sp_attention_op_matches_dense_numpy(rng):
    b, h, t, d = 2, 2, 8, 4
    qv = rng.randn(b, h, t, d).astype(np.float32)
    kv = rng.randn(b, h, t, d).astype(np.float32)
    vv = rng.randn(b, h, t, d).astype(np.float32)
    q = fluid.layers.data("q", [h, t, d])
    k = fluid.layers.data("k", [h, t, d])
    v = fluid.layers.data("v", [h, t, d])
    out = fluid.layers.sequence_parallel_attention(q, k, v, causal=True)
    exe = fluid.Executor(fluid.CPUPlace())
    got, = exe.run(feed={"q": qv, "k": kv, "v": vv}, fetch_list=[out])

    s = np.einsum("bhqd,bhkd->bhqk", qv, kv) * (d ** -0.5)
    mask = np.tril(np.ones((t, t), bool))
    s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bhkd->bhqd", p, vv)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-4, atol=1e-5)


def test_moe_layer_trains_single_device(rng):
    x = fluid.layers.data("x", [6, 16])
    out, aux = fluid.layers.sparse_moe(x, num_experts=4, d_inner=32)
    loss = fluid.layers.mean(out) + fluid.layers.scale(aux, 0.01)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = rng.randn(4, 6, 16).astype(np.float32)
    l1, = exe.run(feed={"x": xv}, fetch_list=[loss])
    assert np.isfinite(np.asarray(l1)).all()
