"""WMT16 en-de (multi-lingual) — reference parity:
python/paddle/dataset/wmt16.py. Same triple format as wmt14 with
configurable vocab sizes."""

from . import wmt14


def train(src_dict_size=10000, trg_dict_size=10000, src_lang="en", n=2048):
    return wmt14._make_reader(n, 2, min(src_dict_size, trg_dict_size))


def test(src_dict_size=10000, trg_dict_size=10000, src_lang="en", n=256):
    return wmt14._make_reader(n, 3, min(src_dict_size, trg_dict_size))


def validation(src_dict_size=10000, trg_dict_size=10000, src_lang="en",
               n=256):
    return wmt14._make_reader(n, 4, min(src_dict_size, trg_dict_size))


def get_dict(lang, dict_size, reverse=False):
    d = {i: "%s_w%d" % (lang, i) for i in range(dict_size)}
    return d if reverse else {v: k for k, v in d.items()}


def fetch():
    pass
