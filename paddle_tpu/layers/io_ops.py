"""Data layers — parity with python/paddle/fluid/layers/io.py `data`."""

from ..core.program import default_main_program, default_startup_program


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         type=None, stop_gradient=True, main_program=None):
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    prog = main_program or default_main_program()
    var = prog.global_block().create_var(
        name=name, shape=shape, dtype=dtype, lod_level=lod_level,
        stop_gradient=stop_gradient, is_data=True)
    # mirror into startup program so executors over either program see it
    default_startup_program().global_block().create_var(
        name=name, shape=shape, dtype=dtype, lod_level=lod_level,
        stop_gradient=stop_gradient, is_data=True)
    return var
