"""Built-in lint rules. Importing this package populates the registry;
add new rules by defining a Rule subclass with @register_rule anywhere
and importing it before check_program runs."""

from . import dtypes        # noqa: F401  R001 dtype-promotion
from . import recompile     # noqa: F401  R002 recompile-hazard
from . import sharding      # noqa: F401  R003 sharding-transfer
from . import numerics      # noqa: F401  R004 numerical-risk
from . import deadcode      # noqa: F401  R005 dead-code
from . import cost_rule     # noqa: F401  R006 cost-model
