"""Stall watchdog: dumps every thread's stack when training stops
stepping.

A hung collective, a deadlocked pserver barrier, or a wedged host-op
thread shows up as "no step completed for N seconds" long before anyone
can attach a debugger. The watchdog is a daemon thread that checks a
liveness timestamp (touched by every completed step AND every compile —
a first XLA compile legitimately takes minutes) and, past the deadline,
writes a ``stall`` event carrying all thread stacks plus a full metrics
snapshot to the flight recorder and stderr. It fires ONCE per stall and
re-arms when stepping resumes, so a long hang produces one loud report,
not a spam loop.
"""

import sys
import threading
import time
import traceback

__all__ = ["Watchdog", "thread_stacks"]


def thread_stacks():
    """{thread_name/ident: [stack lines]} for every live thread."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        label = "%s(%d)" % (names.get(ident, "?"), ident)
        out[label] = [ln.rstrip("\n")
                      for ln in traceback.format_stack(frame)]
    return out


class Watchdog:
    def __init__(self, deadline_s, on_stall, check_interval=None):
        """on_stall(idle_seconds, stacks_dict) is invoked from the
        watchdog thread on each NEW stall."""
        self.deadline_s = float(deadline_s)
        self.on_stall = on_stall
        self._interval = check_interval or min(
            1.0, max(0.05, self.deadline_s / 4.0))
        self._last = time.monotonic()
        # the countdown ARMS on the first touch (first step/compile):
        # pre-training setup (dataset download, preprocessing) longer
        # than the deadline must not read as a stall
        self._armed = False
        self._fired = False
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.stall_count = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ptpu-monitor-watchdog")

    def start(self):
        self._thread.start()
        return self

    def touch(self):
        """Mark liveness (called on every step / compile completion)."""
        with self._lock:
            self._last = time.monotonic()
            self._armed = True
            self._fired = False

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2 * self._interval + 1.0)

    def _run(self):
        while not self._stop.wait(self._interval):
            with self._lock:
                idle = time.monotonic() - self._last
                should_fire = self._armed and idle > self.deadline_s \
                    and not self._fired
                if should_fire:
                    self._fired = True
                    self.stall_count += 1
            if should_fire:
                try:
                    self.on_stall(idle, thread_stacks())
                except Exception:
                    # the watchdog must never take the process down
                    traceback.print_exc(file=sys.stderr)
