"""Version-guarded shard_map import (round-2 verdict weak #7).

jax has moved shard_map across releases (jax.experimental.shard_map →
jax.shard_map) and changed its keyword surface (`check_rep` →
`check_vma`). Every parallel module imports from HERE so a toolchain
bump breaks exactly one file — and usually zero, because the wrapper
adapts the keyword at call time.
"""

import inspect

try:                                    # current export (jax >= 0.4.35)
    from jax import shard_map as _shard_map_raw
except ImportError:                     # older experimental home
    from jax.experimental.shard_map import shard_map as _shard_map_raw

_PARAMS = None


def _supported(kw):
    global _PARAMS
    if _PARAMS is None:
        try:
            _PARAMS = set(inspect.signature(_shard_map_raw).parameters)
        except (TypeError, ValueError):
            _PARAMS = set()
    return kw in _PARAMS


def shard_map(f=None, **kwargs):
    """Drop-in shard_map that tolerates the replication-check keyword
    rename: callers pass check_vma; older jax gets check_rep instead,
    and a jax without either keyword gets neither."""
    if "check_vma" in kwargs and not _supported("check_vma"):
        val = kwargs.pop("check_vma")
        if _supported("check_rep"):
            kwargs["check_rep"] = val
    if f is None:
        return lambda g: _shard_map_raw(g, **kwargs)
    return _shard_map_raw(f, **kwargs)
