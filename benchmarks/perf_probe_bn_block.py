"""Bottleneck-block BN probe: does the ResNet BN tax reproduce in pure
jax once the real block structure (1x1 -> 3x3 -> 1x1 + shortcut add,
stride-2 stage entry) is present?  Compares train-BN / test-BN / no-BN
for a stack of stage-2 bottleneck blocks at bs256 — pure jax, no
framework. If the tax shows here, it is XLA-structural; if not, the
framework lowering is the suspect."""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def time_fn(name, fn, *args, iters=10, windows=5):
    f = jax.jit(fn)
    r = f(*args)
    float(r)
    times = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(iters):
            r = f(*args)
        float(r)
        times.append((time.perf_counter() - t0) / iters)
    times.sort()
    med = times[len(times) // 2]
    print("%-28s %8.3f ms" % (name, med * 1000), flush=True)
    return med


def conv(x, w, stride=1, pad=0):
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), [(pad, pad)] * 2,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))


def bn(y, gamma, mode):
    if mode == "none":
        return y, 0.0
    yf = y.astype(jnp.float32)
    if mode == "train":
        m = jnp.mean(yf, axis=(0, 2, 3))
        v = jnp.maximum(jnp.mean(yf * yf, axis=(0, 2, 3)) - m * m, 0.0)
    else:
        m = jnp.zeros(y.shape[1], jnp.float32)
        v = jnp.ones(y.shape[1], jnp.float32)
    inv = jax.lax.rsqrt(v + 1e-5)
    a = (gamma * inv).astype(y.dtype).reshape(1, -1, 1, 1)
    b = (-m * gamma * inv).astype(y.dtype).reshape(1, -1, 1, 1)
    return y * a + b, jnp.sum(m)


def block(x, p, mode, stride=1):
    sc = x if stride == 1 and x.shape[1] == p["w3"].shape[0] else \
        bn(conv(x, p["ws"], stride), p["gs"], mode)[0]
    y1, t1 = bn(conv(x, p["w1"], stride), p["g1"], mode)
    y1 = jax.nn.relu(y1)
    y2, t2 = bn(conv(y1, p["w2"], 1, pad=1), p["g2"], mode)
    y2 = jax.nn.relu(y2)
    y3, t3 = bn(conv(y2, p["w3"], 1), p["g3"], mode)
    return jax.nn.relu(y3 + sc), t1 + t2 + t3


def main():
    n = 256
    cin, cmid, cout, hw = 256, 128, 512, 28
    depth = 4
    rng = np.random.RandomState(0)

    def mk(*shape):
        return jnp.asarray(rng.randn(*shape), jnp.bfloat16) * 0.05

    params = []
    for i in range(depth):
        ci = cin if i == 0 else cout
        params.append({
            "w1": mk(cmid, ci, 1, 1), "g1": jnp.ones(cmid, jnp.float32),
            "w2": mk(cmid, cmid, 3, 3), "g2": jnp.ones(cmid, jnp.float32),
            "w3": mk(cout, cmid, 1, 1), "g3": jnp.ones(cout, jnp.float32),
            "ws": mk(cout, ci, 1, 1), "gs": jnp.ones(cout, jnp.float32),
        })
    x = jnp.asarray(rng.randn(n, cin, hw * 2, hw * 2), jnp.bfloat16) * 0.3

    for mode in ("train", "test", "none"):
        def body(x, params, mode=mode):
            tot = 0.0
            cur = x
            for i, p in enumerate(params):
                cur, t = block(cur, p, mode, stride=2 if i == 0 else 1)
                tot = tot + t
            return jnp.sum(cur.astype(jnp.float32)) + tot

        def run(x, params, body=body):
            l, g = jax.value_and_grad(body, argnums=1)(x, params)
            return l

        time_fn("blocks %s" % mode, run, x, params)


if __name__ == "__main__":
    main()
