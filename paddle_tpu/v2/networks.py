"""v2 network helper groups (python/paddle/v2/networks.py →
trainer_config_helpers/networks.py parity): the composite blocks the v2
book scripts call, built from fluid layers.

Shape note: v2 image data arrives as a FLAT dense_vector; the conv
groups reshape it to [C, H, W] with H = W inferred from the vector
width and num_channel (the reference inferred the same from the data
layer's height/width fields)."""

import math

from .. import layers as fluid_layers
from .. import nets as fluid_nets
from .layer import _act_name
from .pooling import pool_name


def _to_chw(input, num_channel):
    """Flat [N, D] v2 image input → [N, C, H, W]; pass-through when the
    input is already 4-D."""
    if len(input.shape) >= 4:
        return input
    d = int(input.shape[-1])
    c = int(num_channel or 1)
    hw = int(math.isqrt(d // c))
    if c * hw * hw != d:
        raise ValueError(
            "cannot infer square image from width %d with %d channels"
            % (d, c))
    return fluid_layers.reshape(input, [-1, c, hw, hw])


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_type=None, act=None, groups=1,
                         conv_stride=1, conv_padding=0, num_channel=None,
                         pool_stride=1, pool_padding=0, **kwargs):
    """Img input => Conv => Pooling (networks.py:144 parity; the
    composite body is fluid nets.simple_img_conv_pool)."""
    img = _to_chw(input, num_channel)
    if conv_stride != 1 or conv_padding != 0 or groups != 1 \
            or pool_padding != 0:
        conv = fluid_layers.conv2d(
            img, num_filters=num_filters, filter_size=filter_size,
            stride=conv_stride, padding=conv_padding, groups=groups,
            act=_act_name(act))
        return fluid_layers.pool2d(
            conv, pool_size=pool_size,
            pool_type=pool_name(pool_type, aliases={"average": "avg"},
                                allowed=("max", "avg")),
            pool_stride=pool_stride, pool_padding=pool_padding)
    return fluid_nets.simple_img_conv_pool(
        img, num_filters=num_filters, filter_size=filter_size,
        pool_size=pool_size, pool_stride=pool_stride,
        act=_act_name(act),
        pool_type=pool_name(pool_type, aliases={"average": "avg"},
                            allowed=("max", "avg")))


def sequence_conv_pool(input, context_len, hidden_size, pool_type=None,
                       act=None, **kwargs):
    """Text input => Context Projection => FC => Pooling
    (networks.py:40 parity; composite body is fluid
    nets.sequence_conv_pool). The v2 default activation is tanh; an
    explicit Linear() means none."""
    act_name = "tanh" if act is None else _act_name(act)
    return fluid_nets.sequence_conv_pool(
        input, num_filters=hidden_size, filter_size=context_len,
        act=act_name, pool_type=pool_name(pool_type))


__all__ = ["simple_img_conv_pool", "sequence_conv_pool"]
