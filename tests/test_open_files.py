"""Multi-file sharded recordio ingestion (reader.open_files — reference
layers/io.py:360 open_files + operators/reader/open_files_op.cc parity,
reshaped as a reader-creator for the TPU data plane)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import recordio
from paddle_tpu.reader import open_files


def _write_files(tmp_path, n_files=4, per=5):
    """File f holds samples (f*100 + i, vec) for i < per."""
    paths = []
    for f in range(n_files):
        p = str(tmp_path / ("part-%02d.recordio" % f))

        def creator(f=f):
            for i in range(per):
                yield (np.int64(f * 100 + i),
                       np.full((3,), f, np.float32))
        recordio.convert_reader_to_recordio_file(p, creator)
        paths.append(p)
    return paths


def _ids(reader):
    return sorted(int(s[0]) for s in reader())


def test_open_files_reads_all_samples_threaded(tmp_path):
    paths = _write_files(tmp_path)
    want = sorted(f * 100 + i for f in range(4) for i in range(5))
    # single thread and multi-thread both see every sample exactly once
    assert _ids(open_files(paths)) == want
    assert _ids(open_files(paths, thread_num=3, buffer_size=4)) == want
    # a second pass over the same creator works (fresh iterators)
    r = open_files(paths, thread_num=2)
    assert _ids(r) == want
    assert _ids(r) == want


def test_open_files_shards_are_disjoint_and_cover(tmp_path):
    paths = _write_files(tmp_path)
    s0 = _ids(open_files(paths, shard_id=0, num_shards=2))
    s1 = _ids(open_files(paths, shard_id=1, num_shards=2))
    assert not (set(s0) & set(s1))
    assert sorted(s0 + s1) == sorted(
        f * 100 + i for f in range(4) for i in range(5))
    with pytest.raises(ValueError, match="no files"):
        open_files(paths[:1], shard_id=1, num_shards=2)


def test_open_files_pass_num_and_shuffle(tmp_path):
    paths = _write_files(tmp_path, n_files=2, per=3)
    ids = [int(s[0]) for s in open_files(paths, pass_num=2)()]
    assert len(ids) == 12
    assert sorted(ids) == sorted(2 * [f * 100 + i
                                      for f in range(2)
                                      for i in range(3)])
    # layers-level alias (reference signature shape)
    r = fluid.layers.open_files(paths, shapes=[[3]], dtypes=["float32"],
                                thread_num=2)
    assert len(list(r())) == 6


def test_open_files_propagates_scan_errors(tmp_path):
    """A missing/corrupt file must raise in the CONSUMER, not silently
    truncate the dataset."""
    paths = _write_files(tmp_path, n_files=2)
    paths.append(str(tmp_path / "missing.recordio"))
    with pytest.raises(Exception):
        list(open_files(paths, thread_num=2)())


def test_open_files_early_abandon_reaps_threads(tmp_path):
    """Breaking out of a pass (firstn-style) must release the blocked
    scan threads instead of leaving them stuck on the full queue."""
    import threading as _t
    paths = _write_files(tmp_path, n_files=4, per=50)
    before = _t.active_count()
    it = open_files(paths, thread_num=4, buffer_size=2)()
    for _, s in zip(range(3), it):
        pass
    it.close()
    deadline = 50
    while _t.active_count() > before and deadline:
        import time
        time.sleep(0.1)
        deadline -= 1
    assert _t.active_count() <= before, "scan threads leaked"


def test_open_files_shuffle_differs_across_epochs(tmp_path):
    paths = _write_files(tmp_path, n_files=8, per=1)
    r = open_files(paths, shuffle_files=True, seed=4)
    e1 = [int(s[0]) for s in r()]
    e2 = [int(s[0]) for s in r()]
    assert sorted(e1) == sorted(e2)
    assert e1 != e2, "epoch order must reshuffle"


def test_open_files_feeds_training(tmp_path):
    """The multi-file reader plugs into batch + DataFeeder + Executor —
    the reference's open_files -> read_file -> train loop."""
    paths = _write_files(tmp_path)
    x = fluid.layers.data("x", [3])
    y = fluid.layers.data("y", [1], dtype="int64")
    pred = fluid.layers.fc(x, 4, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    reader = fluid.reader.batch(
        fluid.reader.map_readers(
            lambda s: (s[1], np.int64(int(s[0]) % 4)),
            open_files(paths, thread_num=2)), batch_size=5)
    feeder = fluid.DataFeeder([x, y], fluid.CPUPlace())
    seen = 0
    for batch in reader():
        feed = feeder.feed(batch)
        feed["y"] = np.asarray(feed["y"]).reshape(-1, 1)
        l, = exe.run(feed=feed, fetch_list=[loss])
        assert np.isfinite(np.asarray(l)).all()
        seen += len(batch)
    assert seen == 20


_WORKER = r"""
import os, sys
sys.path.insert(0, %(repo)r)
import numpy as np
import paddle_tpu as fluid
from paddle_tpu.distributed import launch
from paddle_tpu.reader import open_files

launch.init_parallel_env()
rank = launch.trainer_id()
paths = sorted(os.path.join(%(data)r, f)
               for f in os.listdir(%(data)r) if f.endswith(".recordio"))
# default sharding = jax process index/count: each host reads its shard
ids = sorted(int(s[0]) for s in open_files(paths, thread_num=2)())
print("RESULT rank=%%d ids=%%s" %% (rank, ",".join(map(str, ids))),
      flush=True)
"""


def test_open_files_multihost_disjoint_shards(tmp_path):
    """Two real processes in one jax.distributed group: with no shard
    args, each host reads the file shard matching its process index —
    disjoint and jointly complete (the multi-host input story)."""
    import socket
    paths = _write_files(tmp_path)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(_WORKER % {"repo": repo, "data": str(tmp_path)})
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.update({
            "PADDLE_COORDINATOR": "127.0.0.1:%d" % port,
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_TRAINER_ID": str(r),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    shards = {}
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, out[-3000:]
        line = [ln for ln in out.splitlines()
                if ln.startswith("RESULT")][0]
        kv = dict(tok.split("=") for tok in line.split()[1:])
        shards[int(kv["rank"])] = [int(t) for t in kv["ids"].split(",")]
    assert set(shards) == {0, 1}
    assert not (set(shards[0]) & set(shards[1]))
    assert sorted(shards[0] + shards[1]) == sorted(
        f * 100 + i for f in range(4) for i in range(5))


def test_open_files_half_shard_spec_raises(tmp_path):
    paths = _write_files(tmp_path, n_files=2)
    with pytest.raises(ValueError, match="num_shards"):
        open_files(paths, shard_id=0)
    with pytest.raises(ValueError, match="shard_id"):
        open_files(paths, num_shards=2)
    with pytest.raises(ValueError, match="out of range"):
        open_files(paths, shard_id=2, num_shards=2)
