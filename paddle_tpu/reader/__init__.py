"""Reader-decorator combinators + minibatching.

Reference parity: python/paddle/reader/decorator.py:29-236 (map_readers,
shuffle, chain, compose, buffered, firstn, xmap_readers) and
python/paddle/v2/minibatch.py (batch). A reader is a zero-arg callable
returning an iterator of samples.
"""

import itertools
import queue
import random as _random
import threading

__all__ = ["map_readers", "buffered", "compose", "chain", "shuffle",
           "firstn", "xmap_readers", "batch", "cache",
           "ComposeNotAligned"]


class ComposeNotAligned(ValueError):
    pass


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)
    return reader


def shuffle(reader, buf_size):
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return data_reader


def chain(*readers):
    def reader():
        return itertools.chain(*[r() for r in readers])
    return reader


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum((make_tuple(x) for x in outputs), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(x) for x in outputs), ())
    return reader


def buffered(reader, size):
    """Prefetch up to `size` samples on a background thread (the host half of
    the reference's double_buffer reader op)."""
    class _End:
        pass

    def data_reader():
        r = reader()
        q = queue.Queue(maxsize=size)

        def feed():
            try:
                for d in r:
                    q.put(d)
                q.put(_End)
            except BaseException as exc:   # propagate to the consumer
                q.put(exc)

        t = threading.Thread(target=feed, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            if isinstance(e, BaseException):
                raise e
            yield e
    return data_reader


def firstn(reader, n):
    def data_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item
    return data_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over samples with worker threads (decorator.py:236)."""
    end = object()

    def data_reader():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)

        def read_worker():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample) if order else sample)
            for _ in range(process_num):
                in_q.put(end)

        def map_worker():
            while True:
                sample = in_q.get()
                if sample is end:
                    out_q.put(end)
                    return
                if order:
                    i, s = sample
                    out_q.put((i, mapper(s)))
                else:
                    out_q.put(mapper(sample))

        threading.Thread(target=read_worker, daemon=True).start()
        workers = [threading.Thread(target=map_worker, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()

        finished = 0
        if order:
            pending = {}
            want = 0
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                i, s = item
                pending[i] = s
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            for i in sorted(pending):
                yield pending[i]
        else:
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                yield item
    return data_reader


def cache(reader):
    all_data = []
    filled = []

    def data_reader():
        if not filled:
            all_data.extend(reader())
            filled.append(True)
        return iter(all_data)
    return data_reader


def batch(reader, batch_size, drop_last=False):
    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return batch_reader


from .device_loader import DeviceLoader, repeat_feed  # noqa: F401,E402
__all__ += ["DeviceLoader", "repeat_feed"]
