"""v2 layer aliases (python/paddle/v2/layer.py + trainer_config_helpers
parity, minimal set): v2 names over the fluid layer DSL. Each returns a
fluid Variable, so v2 and fluid layers compose freely."""

from .. import layers as fluid_layers
from ..core.program import Program
from . import data_type as dtype_mod


def data(name, type, **kwargs):
    """v2: paddle.layer.data(name=..., type=paddle.data_type.*)."""
    if not isinstance(type, dtype_mod.InputType):
        raise TypeError("type must be a paddle.v2.data_type InputType")
    shape = [1] if type.dtype == "int64" else [type.dim]
    var = fluid_layers.data(name, shape, dtype=type.dtype,
                            lod_level=1 if type.seq_type else 0)
    if type.dtype == "int64":
        var._v2_vocab = type.dim   # integer range -> embedding vocab size
    return var


def fc(input, size, act=None, param_attr=None, bias_attr=None, **kwargs):
    act_name = _act_name(act)
    if isinstance(input, (list, tuple)):
        input = fluid_layers.concat(list(input), axis=1)
    return fluid_layers.fc(input, size, act=act_name,
                           param_attr=_param_attr(param_attr),
                           bias_attr=bias_attr
                           if bias_attr in (None, False)
                           else _param_attr(bias_attr))


def _param_attr(attr):
    """v2 attr.Param → fluid ParamAttr (pass ParamAttr/None through)."""
    if attr is None or not hasattr(attr, "to_param_attr"):
        return attr
    return attr.to_param_attr()


def embedding(input, size, **kwargs):
    # v2: `size` is the embedding WIDTH; vocab comes from the data layer's
    # declared integer range — the trainer records it on the Variable
    vocab = getattr(input, "_v2_vocab", None)
    if vocab is None:
        raise ValueError(
            "v2 embedding needs the input from paddle.v2.layer.data with "
            "an integer_value(_sequence) type")
    return fluid_layers.embedding(input, size=[vocab, size])


def lstmemory(input, size=None, reverse=False, **kwargs):
    width = input.shape[-1]
    h, _ = fluid_layers.dynamic_lstm(input, size=width, is_reverse=reverse)
    return h


def simple_gru(input, size, **kwargs):
    proj = fluid_layers.fc(input, size * 3)
    return fluid_layers.dynamic_gru(proj, size=size)


def pooling(input, pooling_type="max", **kwargs):
    from .pooling import pool_name
    return fluid_layers.sequence_pool(input, pool_name(pooling_type))


def img_conv(input, filter_size, num_filters, num_channel=None,
             stride=1, padding=0, act=None, **kwargs):
    """v2 paddle.layer.img_conv (trainer_config_helpers
    img_conv_layer:2510 capability)."""
    from .networks import _to_chw
    return fluid_layers.conv2d(
        _to_chw(input, num_channel), num_filters=num_filters,
        filter_size=filter_size, stride=stride, padding=padding,
        act=_act_name(act))


def img_pool(input, pool_size, pool_type=None, stride=1, padding=0,
             **kwargs):
    """v2 paddle.layer.img_pool (img_pool_layer:2728 capability)."""
    from .pooling import pool_name
    return fluid_layers.pool2d(
        input, pool_size=pool_size,
        pool_type=pool_name(pool_type, aliases={"average": "avg"},
                            allowed=("max", "avg")),
        pool_stride=stride, pool_padding=padding)


def max_id(input, **kwargs):
    """v2 paddle.layer.max_id: argmax over the class dim (the book
    scripts' inference head)."""
    return fluid_layers.argmax(input, axis=-1)


def first_seq(input, **kwargs):
    return fluid_layers.sequence_first_step(input)


def last_seq(input, **kwargs):
    return fluid_layers.sequence_last_step(input)


def concat(input, **kwargs):
    return fluid_layers.concat(list(input), axis=1)


def dropout(input, dropout_rate=0.5, **kwargs):
    return fluid_layers.dropout(input, dropout_prob=dropout_rate)


def classification_cost(input, label, **kwargs):
    cost = fluid_layers.cross_entropy(input, label)
    return fluid_layers.mean(cost)


def cross_entropy_cost(input, label, **kwargs):
    return classification_cost(input, label)


def square_error_cost(input, label, **kwargs):
    return fluid_layers.mean(
        fluid_layers.square_error_cost(input, label))


regression_cost = square_error_cost


def _act_name(act):
    if act is None:
        return None
    if isinstance(act, str):
        return act.lower()
    name = type(act).__name__.lower()    # v2 activation objects
    for known in ("softmax", "relu", "sigmoid", "tanh", "linear"):
        if known in name:
            return None if known == "linear" else known
    return None
