"""paddle_tpu.serving.sparse: sharded-embedding recsys serving
(ISSUE 12).

Tiers:

  * Hot-ID cache UNIT contracts, clock-injected (no sleeps): LRU
    capacity eviction, bounded-staleness re-fetch, version-bump
    staling, incarnation-change invalidation.
  * SparseClient against LIVE row shards: deduplicated batched PRFT,
    hit/miss/stale counters, version observation, measured miss cost
    feeding the autoparallel placement hook.
  * ScoringEngine: bitwise equality with a direct Executor run of the
    same program over the same rows; the serving_step/serving_request
    telemetry rows + the watch dashboard's sparse cache line.
  * THE ACCEPTANCE GATE: routed DeepFM scoring through KV registry +
    Router + scoring Replica is BITWISE-identical to the direct
    engine at a pinned cache version; the chaos smoke kills a pserver
    mid-serve WITH online updates landing (recover from checkpoint,
    resolver follows, incarnation bump invalidates the cache, no
    stale-forever rows) and every request completes exactly once with
    measured staleness under the SLO ``staleness_s`` bound. A 3x
    deterministic soak runs behind ``-m slow``.
"""

import json
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor, slo
from paddle_tpu.core.selected_rows import SelectedRows
from paddle_tpu.distributed.membership import KVServer, KVClient
from paddle_tpu.distributed import membership as _membership
from paddle_tpu.distributed.rpc import VariableServer
from paddle_tpu.models import deepfm as dfm
from paddle_tpu.serving import fleet
from paddle_tpu.serving.sparse import (HotIDCache, SparseClient,
                                       ScoringEngine, OnlineTrainer,
                                       measure_staleness)

VOCAB, DIM, F, NSHARD = 64, 4, 3, 2
LR = 0.5


def _make_tables(seed=0):
    rng = np.random.RandomState(seed)
    return {"fm_first_w": rng.rand(VOCAB, 1).astype(np.float32),
            "fm_second_w": rng.rand(VOCAB, DIM).astype(np.float32)}


def _spawn_shard(shard, tables, store_override=None):
    """One live row shard: PRFT serves global ids, the optimize_fn is
    the server-side lazy sparse SGD the online trainer lands on."""
    meta = {t: {"shard": shard, "num_shards": NSHARD, "height": VOCAB}
            for t in tables}

    def opt_fn(store, merged):
        for g, val in merged.items():
            t = g[:-5] if g.endswith("@GRAD") else g
            if t in store and isinstance(val, SelectedRows):
                local = np.asarray(val.rows) // NSHARD
                store[t][local] -= LR * val.value

    srv = VariableServer(fan_in=1, sparse_tables=meta,
                         optimize_fn=opt_fn)
    src = store_override if store_override is not None else tables
    for t in tables:
        srv.store[t] = np.asarray(src[t])[shard::NSHARD].copy()
    srv.start()
    return srv, "127.0.0.1:%d" % srv.port


# -- hot-ID cache unit contracts (clock-injected, no sleeps) ----------------

def test_cache_lru_capacity_eviction():
    c = HotIDCache(capacity=3, staleness_s=100.0)
    ver = {"round": 0, "inc": "a"}
    for i in range(5):
        c.insert("t", [i], [np.full(2, i, np.float32)], ver, now=0.0)
    assert len(c) == 3
    assert c.stats["evictions"] == 2
    served, need = c.split("t", [0, 1, 2, 3, 4], 1, now=0.0)
    # the two OLDEST inserts were LRU-evicted
    assert sorted(served) == [2, 3, 4] and sorted(need) == [0, 1]


def test_cache_bounded_staleness_refetches():
    c = HotIDCache(capacity=10, staleness_s=1.0)
    c.insert("t", [7], [np.ones(2, np.float32)],
             {"round": 0, "inc": "a"}, now=0.0)
    served, need = c.split("t", [7], 1, now=0.5)
    assert 7 in served and not need          # within the bound
    served, need = c.split("t", [7], 1, now=1.5)
    assert not served and need == [7]        # past the bound: re-fetch
    assert c.stats["stale"] == 1


def test_cache_version_bump_stales_round_and_inc():
    c = HotIDCache(capacity=10, staleness_s=100.0)
    c.observe_version("t", 0, {"round": 1, "inc": "a"})
    c.insert("t", [4], [np.ones(2, np.float32)],
             {"round": 1, "inc": "a"}, now=0.0)
    served, _ = c.split("t", [4], 1, now=0.0)
    assert 4 in served
    # a fresh fetch elsewhere revealed round 3: the cached round-1 row
    # is stale on next touch, clock notwithstanding
    c.observe_version("t", 0, {"round": 3, "inc": "a"})
    served, need = c.split("t", [4], 1, now=0.0)
    assert not served and need == [4]
    assert c.stats["stale"] == 1
    # incarnation change (respawned server) drops the shard outright
    c.insert("t", [4], [np.ones(2, np.float32)],
             {"round": 3, "inc": "a"}, now=0.0)
    c.observe_version("t", 0, {"round": 0, "inc": "B"})
    assert len(c) == 0
    assert c.stats["invalidations"] == 1


# -- SparseClient against live shards ---------------------------------------

def test_sparse_client_dedup_batched_prefetch_and_hits():
    tables = _make_tables()
    servers, eps = [], []
    for s in range(NSHARD):
        srv, ep = _spawn_shard(s, tables)
        servers.append(srv)
        eps.append(ep)
    try:
        cache = HotIDCache(capacity=100, staleness_s=60.0)
        cli = SparseClient("fm_second_w", eps, cache=cache)
        ids = [3, 8, 3, 8, 11, 3]           # duplicates dedup on wire
        rows = cli.lookup(ids)
        np.testing.assert_array_equal(rows,
                                      tables["fm_second_w"][ids])
        assert cli.stats["wire_rows"] == 3   # unique ids only
        rows2 = cli.lookup(ids)
        np.testing.assert_array_equal(rows2, rows)
        assert cli.stats["wire_rows"] == 3   # all hits, zero wire
        assert cache.stats["hits"] >= 3
        # version coordinates observed per shard
        vers = cli.latest_versions()
        assert set(vers) == {0, 1}
        assert all(v["inc"] for v in vers.values())
        # the measured miss path prices the placement hook: a LIVE
        # EWMA exists after the wire pulls, and the ranking follows
        # whatever it says (fast rows -> sparse, a catastrophically
        # slow measured path -> dense), with the cost marked measured
        from paddle_tpu.transform.autoparallel import (
            embedding_wire_costs, recommend_embedding_placement)
        per_row = cli.miss_row_seconds()
        assert per_row is not None and per_row > 0
        costs = embedding_wire_costs(200000, 64, 512,
                                     measured_sparse_row_s=per_row)
        assert costs["sparse_measured"] is True
        assert costs["sparse"] == pytest.approx(512 * per_row)
        ranked = recommend_embedding_placement(
            200000, 64, 512, measured_sparse_row_s=1e-6)
        assert ranked[0][0] == "sparse"
        ranked = recommend_embedding_placement(
            200000, 64, 512, measured_sparse_row_s=10.0)
        assert ranked[0][0] == "dense"
        cli.close()
    finally:
        for srv in servers:
            srv.stop()


def test_incarnation_bump_invalidates_after_respawn(tmp_path):
    """A replacement pserver recovered from checkpoint carries a NEW
    incarnation: one wire fetch against it invalidates the shard's
    cached rows, so a row mutated after recovery is re-served fresh
    even though its cache entry was nowhere near the staleness
    bound."""
    tables = _make_tables()
    kvs = KVServer(sweep_interval=0.05).start()
    kv = KVClient(kvs.endpoint)
    servers, eps, leases = [], [], []
    try:
        for s in range(NSHARD):
            srv, ep = _spawn_shard(s, tables)
            servers.append(srv)
            eps.append(ep)
            _, lease = _membership.register_endpoint(
                kv, "ps", NSHARD, ep, ttl=0.5)
            leases.append(lease)
        cache = HotIDCache(capacity=100, staleness_s=600.0)
        cli = SparseClient("fm_second_w", eps, kv=kv, cache=cache)
        pid = 2                              # shard 0 (2 % 2 == 0)
        row0 = cli.lookup([pid])[0].copy()
        np.testing.assert_array_equal(row0, tables["fm_second_w"][pid])

        ckpt = str(tmp_path / "shard0.ckpt")
        servers[0].checkpoint(ckpt)
        leases[0].revoke()                   # the old cell dies
        servers[0].stop()
        repl, new_ep = _spawn_shard(0, tables,
                                    store_override=tables)
        assert repl.recover(ckpt) is not None
        # the recovered store then diverges (post-respawn update the
        # cache must not hide forever)
        repl.store["fm_second_w"][pid // NSHARD] = 9.25
        servers[0] = repl
        _membership.register_endpoint(kv, "ps", NSHARD, new_ep,
                                      ttl=0.5)
        # a MISS on the respawned shard (new id) reveals the new
        # incarnation -> the shard's cached rows invalidate
        cli.lookup([4])                      # shard 0, cold id
        fresh = cli.lookup([pid])[0]
        assert fresh[0] == pytest.approx(9.25), \
            "cached pre-respawn row served after incarnation bump"
        assert cache.stats["invalidations"] >= 1
        cli.close()
    finally:
        for srv in servers:
            srv.stop()
        kv.shutdown_server()
        kv.close()


# -- scoring engine ---------------------------------------------------------

@pytest.fixture()
def scoring_setup():
    tables = _make_tables(seed=3)
    servers, eps = [], []
    for s in range(NSHARD):
        srv, ep = _spawn_shard(s, tables)
        servers.append(srv)
        eps.append(ep)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        prob, _ = dfm.build_scoring_net(F, DIM, dnn_dims=(8,))
        fluid.Executor(fluid.CPUPlace()).run(startup)

    def make_engine(name="scoring", staleness_s=60.0, batch=4):
        cache = HotIDCache(capacity=1000, staleness_s=staleness_s)
        c1 = SparseClient("fm_first_w", eps, cache=cache)
        c2 = SparseClient("fm_second_w", eps, cache=cache)
        feat = dfm.make_featurizer(c1, c2, F, DIM)
        return ScoringEngine(main, scope, prob.name, feat,
                             clients=[c1, c2], batch=batch, name=name)

    yield {"tables": tables, "servers": servers, "eps": eps,
           "main": main, "scope": scope, "prob": prob,
           "make_engine": make_engine}
    for srv in servers:
        srv.stop()


def _feats(rng, n):
    return [{"f%d" % f: [int(rng.randint(0, VOCAB))]
             for f in range(F)} for _ in range(n)]


def test_scoring_engine_bitwise_matches_direct_executor(scoring_setup):
    s = scoring_setup
    rng = np.random.RandomState(1)
    feats = _feats(rng, 6)
    # ragged multi-hot: one request's field carries 3 ids (sum-pooled),
    # another drops a field entirely (pools to zero)
    feats[1]["f0"] = [2, 5, 9]
    del feats[2]["f1"]
    eng = s["make_engine"]()
    try:
        got = eng.score_many(feats)
        # reference: hand-gather the SAME rows, one direct run per
        # example padded into the engine's batch shape
        exe = fluid.Executor(fluid.CPUPlace())
        for i, feats_i in enumerate(feats):
            first = np.zeros((4, F), np.float32)
            second = np.zeros((4, F, DIM), np.float32)
            for f in range(F):
                for tid in feats_i.get("f%d" % f, ()):
                    first[0, f] += s["tables"]["fm_first_w"][tid, 0]
                    second[0, f] += s["tables"]["fm_second_w"][tid]
            out, = exe.run(s["main"],
                           feed={"fm_first_rows": first,
                                 "fm_second_rows": second},
                           fetch_list=[s["prob"].name],
                           scope=s["scope"])
            want = float(np.asarray(out).reshape(-1)[0])
            assert got[i] == want, (i, got[i], want)
    finally:
        eng.close()
        for c in eng._clients:
            c.close()


def test_scoring_telemetry_rows_and_watch_line(scoring_setup,
                                               tmp_path):
    from paddle_tpu.monitor.watch import watch
    s = scoring_setup
    rng = np.random.RandomState(2)
    log = str(tmp_path / "scoring.jsonl")
    with monitor.session(log_path=log):
        eng = s["make_engine"](name="recsys")
        try:
            eng.score_many(_feats(rng, 8))
            eng.score_many(_feats(rng, 8))   # warm window: cache hits
        finally:
            eng.close()
            for c in eng._clients:
                c.close()
    rows = [json.loads(ln) for ln in open(log) if ln.strip()]
    steps = [r for r in rows if r.get("ev") == "serving_step"]
    reqs = [r for r in rows if r.get("ev") == "serving_request"]
    assert steps and reqs
    assert steps[-1]["engine"] == "recsys"
    # cumulative cache counters ride the rows (last-row arithmetic)
    assert steps[-1]["cache_hits"] > 0
    assert steps[-1]["cache_misses"] > 0
    # the TTFT-analogue lands per request
    assert all(r["ttft"] is not None for r in reqs)
    assert all(r["queue_wait"] is not None for r in reqs)
    frame = watch(log, once=True)
    sp = [ln for ln in frame.split("\n") if ln.startswith("sparse")]
    assert sp, "watch frame misses the sparse cache line:\n%s" % frame
    assert "hit rate" in sp[0] and "stale" in sp[0]


def test_fleet_lines_render_sparse_counters():
    from paddle_tpu.monitor.watch import fleet_lines
    snap = {
        "__meta__": {"processes": 1, "scrapes": 1, "endpoints": []},
        "ptpu_sparse_cache_hits_total": {
            "kind": "counter", "series": {"": 40}},
        "ptpu_sparse_cache_misses_total": {
            "kind": "counter", "series": {"": 10}},
        "ptpu_sparse_cache_stale_total": {
            "kind": "counter", "series": {"": 3}},
        "ptpu_sparse_prefetch_rows_total": {
            "kind": "counter", "series": {"": 13}},
    }
    lines = fleet_lines(snap)
    sp = [ln for ln in lines if "sparse" in ln]
    assert sp and "hit rate 80%" in sp[0] and "prefetch rows 13" in sp[0]


# -- SLO staleness_s objective ----------------------------------------------

def test_slo_staleness_objective_exit_codes(tmp_path):
    log = tmp_path / "staleness.jsonl"
    t = time.time()
    rows = [{"ts": t + i, "ev": "sparse_staleness",
             "value": v, "table": "emb"}
            for i, v in enumerate([0.05, 0.12, 0.31])]
    log.write_text("".join(json.dumps(r) + "\n" for r in rows))
    passing = tmp_path / "pass.json"
    passing.write_text(json.dumps({"objectives": [
        {"metric": "staleness_s", "percentile": 1.0,
         "max_seconds": 0.5}]}))
    failing = tmp_path / "fail.json"
    failing.write_text(json.dumps({"objectives": [
        {"metric": "staleness_s", "percentile": 1.0,
         "max_seconds": 0.1}]}))
    assert slo.main([str(passing), "--log", str(log)]) == 0
    assert slo.main([str(failing), "--log", str(log)]) == 1
    # spec schema: staleness_s needs max_seconds, like every latency
    with pytest.raises(ValueError):
        slo.load_spec({"objectives": [{"metric": "staleness_s"}]})
    # measured-value check: p100 over the exact samples
    samples = slo.samples_from_monitor_log(str(log))
    assert samples["staleness_s"] == [0.05, 0.12, 0.31]
    v = slo.evaluate({"objectives": [
        {"metric": "staleness_s", "max_seconds": 0.5}]}, samples)
    assert v["objectives"][0]["measured"] == pytest.approx(0.31)


# -- device loader satellite ------------------------------------------------

def test_device_loader_mixed_lod_dense_rides_plan_cache():
    """A batch mixing ragged (LoD) and dense feeds — the scoring
    pipeline shape — keeps its DENSE subset on the worker-thread plan
    cache; the LoD value passes through host-side intact."""
    from paddle_tpu.core.lod import LoDTensor
    from paddle_tpu.reader.device_loader import DeviceLoader
    import jax

    lod = LoDTensor(np.arange(6, dtype=np.int64).reshape(6, 1),
                    [[0, 2, 6]])
    dense = np.ones((4, 3), np.float32)
    feeds = [{"ids": lod, "x": dense} for _ in range(3)]
    loader = DeviceLoader(iter(feeds))
    out = list(loader)
    assert len(out) == 3
    for batch in out:
        assert isinstance(batch["ids"], LoDTensor)   # LoD intact
        assert isinstance(batch["x"], jax.Array)     # staged dense
    # the dense subset derived ONE plan and hit it afterwards
    plans = loader._plans
    assert plans is not None and len(plans._plans) == 1
    assert plans.hits == 2 and plans.misses == 1


# -- acceptance: routed bitwise identity + chaos ----------------------------

def _routed_vs_direct(s, rng, kvs, kv, n=8):
    feats = _feats(rng, n)
    direct = s["make_engine"](name="direct")
    cell = fleet.Replica(kv, None, desired=1, ttl=0.5,
                         engine_factory=lambda name:
                         s["make_engine"](name="replica"))
    router = fleet.Router(kvs.endpoint, refresh_interval=0.05)
    try:
        router.wait_for_replicas(1)
        want = direct.score_many(feats)
        handles = [router.submit(features=f) for f in feats]
        got = [h.result(timeout=60) for h in handles]
        assert all(toks == [] for toks, _ in got)
        assert [sc for _, sc in got] == want      # BITWISE
        # pinned cache version: both engines served the same shard
        # coordinates, comparable without key juggling (versions()
        # stringifies shard keys — the wire shape)
        assert handles[0].versions == direct.versions()
        assert router.stats["completed"] == n
        assert router.stats["failed"] == 0
        # malformed scoring payload -> BADR typed reject: THIS request
        # fails terminally, the replica stays in dispatch
        bad = router.submit(features="not-a-dict")
        with pytest.raises(RuntimeError, match="failed"):
            bad.result(timeout=30)
        # schema errors reject at SUBMIT (BADR surface), terminally —
        # an unknown field can never fail a co-admitted batch
        bad2 = router.submit(features={"f99": [1]})
        with pytest.raises(RuntimeError, match="failed"):
            bad2.result(timeout=30)
        with pytest.raises(ValueError, match="unknown feature"):
            direct.submit({"f99": [1]})
        # numpy ids normalize at the front door (wire-safe journal)
        ok = router.submit(features={
            k: [np.int64(v[0])] for k, v in feats[0].items()})
        assert ok.result(timeout=30)[1] == want[0]
        assert router.stats["failed"] == 2
    finally:
        router.close()
        cell.shutdown()
        for eng in (direct, cell.engine):
            for c in eng._clients:
                c.close()
        direct.close()


def test_routed_scoring_bitwise_identical(scoring_setup):
    """Acceptance: routed DeepFM scoring == direct single-process
    executor scoring, bitwise, at a pinned cache version (the LM
    token-identity contract, ported)."""
    kvs = KVServer(sweep_interval=0.05).start()
    kv = KVClient(kvs.endpoint)
    try:
        _routed_vs_direct(scoring_setup, np.random.RandomState(5),
                          kvs, kv)
    finally:
        kv.shutdown_server()
        kv.close()


def _chaos_round(tmp_path, seed):
    """One chaos pass: routed scoring under online updates, pserver 0
    killed mid-serve, recovered from checkpoint on a new port, the
    resolver follows, the cache invalidates on the incarnation bump —
    every request exactly once, staleness measured and SLO-gated."""
    from paddle_tpu.resilience import faults

    tables = _make_tables(seed=seed)
    kvs = KVServer(sweep_interval=0.05).start()
    kv = KVClient(kvs.endpoint)
    servers, eps, leases = [], [], []
    rng = np.random.RandomState(seed)
    log = str(tmp_path / ("chaos_%d.jsonl" % seed))
    try:
        for sh in range(NSHARD):
            srv, ep = _spawn_shard(sh, tables)
            servers.append(srv)
            eps.append(ep)
            _, lease = _membership.register_endpoint(
                kv, "ps", NSHARD, ep, ttl=0.5)
            leases.append(lease)

        main, startup = fluid.Program(), fluid.Program()
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope):
            prob, _ = dfm.build_scoring_net(F, DIM, dnn_dims=(8,))
            fluid.Executor(fluid.CPUPlace()).run(startup)

        with monitor.session(log_path=log):
            cache = HotIDCache(capacity=1000, staleness_s=0.2)
            c1 = SparseClient("fm_first_w", eps, kv=kv, cache=cache)
            c2 = SparseClient("fm_second_w", eps, kv=kv, cache=cache)
            feat = dfm.make_featurizer(c1, c2, F, DIM)
            eng = ScoringEngine(main, scope, prob.name, feat,
                                clients=[c1, c2], batch=4,
                                name="chaos-scoring")
            cell = fleet.Replica(
                kv, None, desired=1, ttl=0.5, role="scoring",
                engine_factory=lambda name: eng)
            router = fleet.Router(kvs.endpoint, role="scoring",
                                  refresh_interval=0.05,
                                  stall_timeout=8.0)
            router.wait_for_replicas(1)

            # online updates land while serving reads
            hot = rng.randint(0, VOCAB, 6)
            trainer = OnlineTrainer(
                "fm_second_w", eps, height=VOCAB, interval=0.03,
                kv=kv,
                update_fn=lambda: (hot, rng.rand(len(hot), DIM)
                                   .astype(np.float32) * 0.01))
            trainer.start()

            # seeded frame faults on the pserver wire (PRFT reads +
            # tagged SEND/BARR updates): drops/dups the retry policy
            # must ride out without double-applying
            faults.arm({"rpc": {"drop": 0.03, "duplicate": 0.03,
                                "ops": ["PRFT", "SEND", "BARR"],
                                "max": 12}}, seed=seed)

            handles = []
            n_reqs = 24
            for i in range(n_reqs):
                handles.append(
                    router.submit(features=_feats(rng, 1)[0]))
                if i == 9:
                    # kill shard 0 mid-serve: checkpoint first (the
                    # durable state a real pserver already has), then
                    # the process dies
                    ckpt = str(tmp_path / ("sh0_%d.ckpt" % seed))
                    servers[0].checkpoint(ckpt)
                    leases[0].revoke()
                    servers[0].stop()
                if i == 11:
                    # supervisor respawns: recover + re-register at a
                    # NEW port; the client resolver follows
                    repl, new_ep = _spawn_shard(0, tables)
                    assert repl.recover(ckpt) is not None
                    servers[0] = repl
                    _, leases[0] = _membership.register_endpoint(
                        kv, "ps", NSHARD, new_ep, ttl=0.5)
                time.sleep(0.02)
            results = [h.result(timeout=120) for h in handles]
            faults.disarm()
            assert len(results) == n_reqs
            assert router.stats["completed"] == n_reqs
            assert router.stats["failed"] == 0
            assert router.stats["requests"] == n_reqs
            # no stale-forever rows: an update landed AFTER the
            # respawn becomes serve-visible, measured end-to-end
            trainer.stop()
            st = measure_staleness(trainer, c2,
                                   probe_id=int(hot[0]),
                                   timeout=30.0)
            assert st < 5.0, "staleness %.3fs past the bound" % st
            # the incarnation bump actually invalidated shard 0
            assert cache.stats["invalidations"] >= 1

            trainer.close()
            router.close()
            cell.shutdown()
            for c in (c1, c2):
                c.close()
        # SLO gate over the recorded rows: the measured staleness
        # sample must pass the staleness_s objective
        spec = tmp_path / ("slo_%d.json" % seed)
        spec.write_text(json.dumps({"objectives": [
            {"metric": "staleness_s", "percentile": 1.0,
             "max_seconds": 5.0},
            {"metric": "error_rate", "max_ratio": 0.0}]}))
        assert slo.main([str(spec), "--log", log]) == 0
    finally:
        from paddle_tpu.resilience import faults
        faults.disarm()
        for srv in servers:
            try:
                srv.stop()
            except Exception:
                pass
        kv.shutdown_server()
        kv.close()


def test_chaos_pserver_kill_mid_serve_smoke(tmp_path):
    _chaos_round(tmp_path, seed=4242)


@pytest.mark.slow
def test_chaos_pserver_kill_soak(tmp_path):
    for seed in (4242, 1301, 7):
        _chaos_round(tmp_path, seed=seed)
