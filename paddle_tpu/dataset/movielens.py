"""MovieLens-1M — reference parity: python/paddle/dataset/movielens.py.

Readers yield (user_id, gender_id, age_id, job_id, movie_id, category_ids,
title_ids, rating) like the reference's recommender book test expects.
"""

import numpy as np

from . import common

MAX_USER_ID = 6040
MAX_MOVIE_ID = 3952
MAX_JOB_ID = 20
AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]
CATEGORIES = 18
TITLE_VOCAB = 5174


def max_user_id():
    return MAX_USER_ID


def max_movie_id():
    return MAX_MOVIE_ID


def max_job_id():
    return MAX_JOB_ID


def age_table():
    return AGE_TABLE


def _make_reader(n, seed):
    def reader():
        rng = common.synthetic_rng("movielens", seed)
        for _ in range(n):
            user = int(rng.randint(1, MAX_USER_ID + 1))
            gender = int(rng.randint(0, 2))
            age = int(rng.randint(0, len(AGE_TABLE)))
            job = int(rng.randint(0, MAX_JOB_ID + 1))
            movie = int(rng.randint(1, MAX_MOVIE_ID + 1))
            cats = rng.randint(0, CATEGORIES,
                               size=rng.randint(1, 4)).tolist()
            title = rng.randint(0, TITLE_VOCAB,
                                size=rng.randint(1, 6)).tolist()
            # learnable signal: rating correlates with (user+movie) parity
            base = 3.0 + ((user + movie) % 3) - 1
            rating = float(np.clip(base + 0.5 * rng.randn(), 1, 5))
            yield (user, gender, age, job, movie, cats, title,
                   np.array([rating], np.float32))
    return reader


def train(n=4096):
    return _make_reader(n, seed=0)


def test(n=512):
    return _make_reader(n, seed=1)


def fetch():
    pass
