"""Distributed training tier.

Two layers, per SURVEY.md §5.8 / §7:
  * ICI tier (dense): SPMD mesh sharding via paddle_tpu.parallel — XLA
    collectives replace NCCL; nothing to do here.
  * DCN tier (sparse / cross-slice): a parameter-server service with the
    reference's RPC semantics (SendVariable / GetVariable /
    PrefetchVariable — operators/detail/send_recv.proto:17-25), used for
    pserver-mode DistributeTranspiler programs and the distributed sparse
    lookup table.
"""

from .rpc import VariableServer, RPCClient  # noqa: F401
from .transpiler import DistributeTranspiler  # noqa: F401
from .membership import (KVServer, KVClient, register_pserver,  # noqa: F401
                         wait_for_pservers, TrainerLease)
from . import ops  # noqa: F401  (registers host ops)
from . import launch  # noqa: F401
