"""Book test: rnn_encoder_decoder (reference
python/paddle/fluid/tests/book/notest_rnn_encoder_decoer.py) — GRU encoder
whose final state initializes a GRU decoder; teacher-forced training on
wmt14-style (src, trg, trg_next) triples to a loss threshold.

The per-token cross-entropy rows are pooled per sequence (sequence_pool sum
-> mean over sequences) so the loss is exact under the executor's
flat-total bucketing (pad rows are dropped by the segment pooling)."""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu as fluid


EMB = 16
GRU = 32


def seq_to_seq_net(src, trg, label, dict_size):
    src_emb = fluid.layers.embedding(src, size=[dict_size, EMB])
    enc_in = fluid.layers.fc(src_emb, GRU * 3)
    enc = fluid.layers.dynamic_gru(enc_in, size=GRU)
    enc_last = fluid.layers.sequence_last_step(enc)

    trg_emb = fluid.layers.embedding(trg, size=[dict_size, EMB])
    dec_in = fluid.layers.fc(trg_emb, GRU * 3)
    dec = fluid.layers.dynamic_gru(dec_in, size=GRU, h_0=enc_last)
    prediction = fluid.layers.fc(dec, dict_size, act="softmax")

    cost = fluid.layers.cross_entropy(prediction, label)   # [T, 1] rows
    seq_cost = fluid.layers.sequence_pool(cost, "sum")     # [N, 1] exact
    return fluid.layers.mean(seq_cost), prediction


def test_rnn_encoder_decoder_trains():
    dict_size = paddle.dataset.wmt14.DICT_SIZE
    src = fluid.layers.data("src_word_id", [1], dtype="int64", lod_level=1)
    trg = fluid.layers.data("target_language_word", [1], dtype="int64",
                            lod_level=1)
    label = fluid.layers.data("target_language_next_word", [1],
                              dtype="int64", lod_level=1)
    avg_cost, prediction = seq_to_seq_net(src, trg, label, dict_size)
    fluid.optimizer.Adam(learning_rate=0.005).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    feeder = fluid.DataFeeder([src, trg, label], fluid.CPUPlace())
    batches = list(paddle.batch(paddle.dataset.wmt14.train(dict_size),
                                batch_size=8)())[:10]

    first = last = None
    for epoch in range(8):
        for batch in batches:
            feed = feeder.feed(batch)
            lv, = exe.run(feed=feed, fetch_list=[avg_cost])
            if first is None:
                first = float(lv)
            last = float(lv)
    assert np.isfinite(last)
    # reference stops at avg_cost < 2 (per-token); ours is per-sequence
    # summed cost — require a real drop from the initial uniform entropy
    assert last < first * 0.6, (first, last)
