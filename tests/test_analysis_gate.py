"""CI gate (tier-1): the static analyzer runs over the whole model zoo
on the CPU backend and every shipped model must be free of
error-severity diagnostics. A PR that leaks fp16 into a serving path,
breaks the bf16 softmax/LN f32-stats contract, or wires a model so a
parameter goes unused at error level fails here — no TPU time needed.

Equivalent CLI: ``JAX_PLATFORMS=cpu python -m paddle_tpu.analysis --all``
"""

import pytest

from paddle_tpu import analysis


@pytest.mark.parametrize("model", analysis.zoo_names())
def test_zoo_model_is_error_free(model):
    report = analysis.analyze_model(model)
    errors = report.by_severity(analysis.ERROR)
    assert not errors, "\n" + report.render_text()


def test_import_check_gate_is_clean():
    """The CLI gate also import-checks runtime-only packages the jaxpr
    analyzer cannot lint (resilience, monitor, distributed) — a broken
    import there must fail `python -m paddle_tpu.analysis --all`."""
    from paddle_tpu.analysis.__main__ import (IMPORT_CHECK_PACKAGES,
                                              import_check)
    assert import_check() == []
    assert "paddle_tpu.resilience" in IMPORT_CHECK_PACKAGES
    assert import_check(("paddle_tpu.no_such_module",)) != []


def test_every_shipped_rule_ran_against_the_zoo():
    """All six built-in rules must exist and be enabled by default —
    a rule silently dropped from the registry would turn the gate into
    a no-op for its failure class."""
    names = {cls.name for cls in
             (r.__class__ for r in analysis.default_rules())}
    assert {"dtype-promotion", "recompile-hazard", "sharding-transfer",
            "numerical-risk", "dead-code", "cost-model"} <= names
