"""Error-layer tests: enforce-style op context on lowering failures
(enforce.h:203 parity) and the every-op-output NaN/Inf guard
(framework/executor.cc:27-94 parity)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.enforce import EnforceError


def test_lowering_error_carries_op_context():
    a = fluid.layers.data("a", [4])
    b = fluid.layers.data("b", [5])
    # elementwise_add of incompatible shapes must fail with op context,
    # not a raw JAX broadcast error.
    c = fluid.layers.elementwise_add(a, b)
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(EnforceError) as ei:
        exe.run(feed={"a": np.ones((2, 4), np.float32),
                      "b": np.ones((2, 5), np.float32)},
                fetch_list=[c])
    msg = str(ei.value)
    assert "elementwise_add" in msg
    assert "float32[2, 4]" in msg and "float32[2, 5]" in msg
    assert "'a'" in msg and "'b'" in msg


def test_nan_guard_catches_internal_nan(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_CHECK_NAN_INF", "1")
    x = fluid.layers.data("x", [3])
    bad = fluid.layers.log(x)            # log of negative input -> NaN
    good = fluid.layers.scale(x, 2.0)    # finite; the only fetched var
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(FloatingPointError) as ei:
        exe.run(feed={"x": -np.ones((2, 3), np.float32)},
                fetch_list=[good])
    # the guard names the op that produced the NaN even though only the
    # finite var was fetched
    assert "log" in str(ei.value)
    assert bad is not None


def test_nan_guard_passes_finite_program(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_CHECK_NAN_INF", "1")
    x = fluid.layers.data("x", [3])
    y = fluid.layers.fc(x, 2)
    loss = fluid.layers.mean(y)
    opt = fluid.optimizer.SGD(learning_rate=0.1)
    opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    out, = exe.run(feed={"x": np.ones((2, 3), np.float32)},
                   fetch_list=[loss])
    assert np.isfinite(out).all()


def test_nan_guard_flag_zero_means_off(monkeypatch):
    # gflags semantics: FLAGS_check_nan_inf=0 disables the check
    monkeypatch.setenv("PADDLE_TPU_CHECK_NAN_INF", "0")
    x = fluid.layers.data("x", [3])
    fluid.layers.log(x)                  # NaN on negative input
    good = fluid.layers.scale(x, 2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    out, = exe.run(feed={"x": -np.ones((2, 3), np.float32)},
                   fetch_list=[good])    # must NOT raise
    assert np.isfinite(out).all()


def test_nan_guard_orders_forward_before_optimizer(monkeypatch):
    # the FIRST reported op must be the forward op that produced the NaN,
    # not the optimizer op the NaN propagated into (guard-index continuity
    # across the backward marker)
    monkeypatch.setenv("PADDLE_TPU_CHECK_NAN_INF", "1")
    x = fluid.layers.data("x", [3])
    y = fluid.layers.fc(x, 2, param_attr=fluid.ParamAttr(
        initializer=fluid.initializer.Constant(1.0)))
    bad = fluid.layers.log(y)            # y < 0 for negative x -> NaN
    loss = fluid.layers.mean(bad)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    with pytest.raises(FloatingPointError) as ei:
        exe.run(feed={"x": -np.ones((4, 3), np.float32) * 10},
                fetch_list=[loss])
    assert "'log'" in str(ei.value) or "log" in str(ei.value)
    assert "sgd" not in str(ei.value)


def test_nan_guard_honored_by_parallel_executor(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_CHECK_NAN_INF", "1")
    from paddle_tpu import parallel
    x = fluid.layers.data("x", [4])
    bad = fluid.layers.log(x)
    good = fluid.layers.scale(x, 2.0)
    mesh = parallel.make_mesh({"dp": 2})
    pexe = fluid.ParallelExecutor(mesh=mesh)
    with pytest.raises(FloatingPointError):
        pexe.run([good], feed={"x": -np.ones((4, 4), np.float32)})
    assert bad is not None


def test_flags_table(monkeypatch):
    from paddle_tpu import flags
    assert flags.get_flag("lod_bucketing") is True
    monkeypatch.setenv("PADDLE_TPU_LOD_BUCKETING", "off")
    assert flags.get_flag("lod_bucketing") is False
    monkeypatch.setenv("PADDLE_TPU_CHECK_NAN_INF", "1")
    assert flags.get_flag("check_nan_inf") is True
    assert "check_nan_inf" in flags.flags_help()
    flags.set_flag("check_nan_inf", False)
    assert flags.get_flag("check_nan_inf") is False
    flags.set_flag("check_nan_inf", None)   # drop override
    assert flags.get_flag("check_nan_inf") is True
