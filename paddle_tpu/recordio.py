"""RecordIO-equivalent durable data files.

Reference parity: paddle/fluid/recordio/ (chunk.h:26 chunked + checksummed
+ compressed records, scanner.h:26 sequential Scanner) and
python/paddle/fluid/recordio_writer.py (convert_reader_to_recordio_file).
The byte format is implemented natively (paddle_tpu/native/recordio) and
bound via ctypes; this module adds the record<->sample codec (numpy-aware,
pickle-free for plain arrays) and the reader-creator that plugs recordio
files into the paddle.batch / DeviceLoader data plane.
"""

import ctypes
import io
import struct

import numpy as np

from . import native

COMPRESSOR_NONE = 0
COMPRESSOR_DEFLATE = 1


def _lib():
    lib = native.load("recordio")
    if not getattr(lib, "_rio_configured", False):
        lib.rio_writer_open.restype = ctypes.c_void_p
        lib.rio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                        ctypes.c_uint64]
        lib.rio_writer_write.restype = ctypes.c_int
        lib.rio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_uint64]
        lib.rio_writer_close.restype = ctypes.c_uint64
        lib.rio_writer_close.argtypes = [ctypes.c_void_p]
        lib.rio_scanner_open.restype = ctypes.c_void_p
        lib.rio_scanner_open.argtypes = [ctypes.c_char_p]
        lib.rio_scanner_next.restype = ctypes.POINTER(ctypes.c_char)
        lib.rio_scanner_next.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ctypes.c_uint64)]
        lib.rio_scanner_close.argtypes = [ctypes.c_void_p]
        lib.rio_last_error.restype = ctypes.c_char_p
        lib._rio_configured = True
    return lib


def _err(lib):
    return lib.rio_last_error().decode("utf-8", "replace")


class Writer:
    """Sequential record writer (recordio_writer.py Writer parity)."""

    def __init__(self, path, compressor=COMPRESSOR_DEFLATE,
                 max_chunk_bytes=1 << 20):
        self._lib = _lib()
        self._h = self._lib.rio_writer_open(
            path.encode(), int(compressor), int(max_chunk_bytes))
        if not self._h:
            raise IOError(_err(self._lib))
        self._closed = False

    def write(self, record):
        if self._closed:
            raise ValueError("writer is closed")
        if not isinstance(record, (bytes, bytearray)):
            raise TypeError("record must be bytes, got %r" % type(record))
        if self._lib.rio_writer_write(self._h, bytes(record),
                                      len(record)) != 0:
            raise IOError(_err(self._lib))

    def close(self):
        if not self._closed:
            self._closed = True
            total = self._lib.rio_writer_close(self._h)
            if total == (1 << 64) - 1:
                raise IOError(_err(self._lib))
            return int(total)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Scanner:
    """Sequential record reader (recordio scanner.h:26 parity); iterable."""

    def __init__(self, path):
        self._lib = _lib()
        self._h = self._lib.rio_scanner_open(path.encode())
        if not self._h:
            raise IOError(_err(self._lib))
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:                  # exhausted/closed: never touch
            raise StopIteration           # the freed native handle
        n = ctypes.c_uint64()
        p = self._lib.rio_scanner_next(self._h, ctypes.byref(n))
        if not p:
            if n.value == (1 << 64) - 1:
                self.close()
                raise IOError(_err(self._lib))
            self.close()
            raise StopIteration
        return ctypes.string_at(p, n.value)

    def close(self):
        if not self._closed:
            self._closed = True
            self._lib.rio_scanner_close(self._h)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# --------------------------------------------------------------------------
# sample codec: tuples of numpy arrays / scalars <-> bytes. Arrays use the
# .npy wire format (allow_pickle=False — no arbitrary code execution from
# data files, unlike the reference's cPickle records).
_SCALAR = b"s"
_ARRAY = b"a"


def encode_sample(sample):
    if not isinstance(sample, (tuple, list)):
        sample = (sample,)
    buf = io.BytesIO()
    buf.write(struct.pack("<I", len(sample)))
    for field in sample:
        arr = np.asarray(field)
        kind = _SCALAR if arr.ndim == 0 and arr.dtype.kind in "if" \
            else _ARRAY
        sub = io.BytesIO()
        np.save(sub, arr, allow_pickle=False)
        data = sub.getvalue()
        buf.write(kind)
        buf.write(struct.pack("<I", len(data)))
        buf.write(data)
    return buf.getvalue()


def decode_sample(record):
    buf = io.BytesIO(record)
    n, = struct.unpack("<I", buf.read(4))
    fields = []
    for _ in range(n):
        kind = buf.read(1)
        ln, = struct.unpack("<I", buf.read(4))
        arr = np.load(io.BytesIO(buf.read(ln)), allow_pickle=False)
        fields.append(arr.item() if kind == _SCALAR else arr)
    return tuple(fields)


# --------------------------------------------------------------------------
# data-plane integration (recordio_writer.py / reader ops parity)
def convert_reader_to_recordio_file(filename, reader_creator,
                                    compressor=COMPRESSOR_DEFLATE,
                                    max_chunk_bytes=1 << 20,
                                    feeder=None):
    """Materialize a python reader into a recordio file; returns the
    record count (reference recordio_writer.py:convert_reader_to_recordio_file)."""
    if feeder is not None:
        raise NotImplementedError(
            "feeder-transformed serialization is not supported; samples "
            "are encoded with the numpy codec — pre-transform the reader "
            "instead")
    with Writer(filename, compressor, max_chunk_bytes) as w:
        count = 0
        for sample in reader_creator():
            w.write(encode_sample(sample))
            count += 1
        w.close()
    return count


def reader(filename):
    """Reader creator over a recordio file: plugs into paddle.batch /
    shuffle / DeviceLoader exactly like an in-memory reader (the role of
    the reference's create_recordio_file_reader op)."""
    def _reader():
        scanner = Scanner(filename)
        try:
            for record in scanner:
                yield decode_sample(record)
        finally:
            scanner.close()   # early-abandoned passes must not leak FILE*
    return _reader
