"""Driver benchmark entry: prints ONE JSON line with the headline metric.

Flagship: ResNet-50 ImageNet training throughput, bf16, one TPU chip
(BASELINE.json north star metric #1: ResNet-50 images/sec/chip).

vs_baseline anchor: the reference's only in-tree ResNet-50 *training*
number — 81.69 imgs/sec (Intel MKL-DNN, 2×Xeon 6148, bs=64,
benchmark/IntelOptimizedPaddle.md; BASELINE.md). The reference has no
single-GPU ResNet-50 number; its closest GPU figure is AlexNet at 383
imgs/sec on a K40m.

Data is generated in-graph (reference parity: create_random_data_generator
reader op), so the steady state measures the training step, not the
host→device tunnel of this sandbox.
"""

import json
import os
import sys

# ResNet-50 train step ~3x fwd FLOPs (fwd 4.1 GFLOP/img @224); v5e peak
# 197 bf16 TFLOP/s — MFU printed alongside throughput per VERDICT r1 #2.
FLOPS_PER_IMG_TRAIN = 3 * 4.1e9
PEAK_BF16 = 197e12


def main():
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "benchmarks"))
    sys.argv = [sys.argv[0], "--batch_size", "256", "--iterations", "20",
                "--skip_batch_num", "3", "--device", "TPU",
                "--dtype", "bfloat16"]
    from resnet import main as resnet_main
    ips = resnet_main()
    baseline = 81.69
    mfu = ips * FLOPS_PER_IMG_TRAIN / PEAK_BF16
    print("MFU %.1f%% (%.1f img/s, %.0f GFLOP/img, %.0f TFLOP/s peak)"
          % (mfu * 100, ips, FLOPS_PER_IMG_TRAIN / 1e9, PEAK_BF16 / 1e12),
          file=sys.stderr)
    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec_per_chip",
        "value": round(float(ips), 1),
        "unit": "imgs/sec",
        "vs_baseline": round(float(ips) / baseline, 2),
        "mfu_pct": round(mfu * 100, 1),
    }))


if __name__ == "__main__":
    main()
