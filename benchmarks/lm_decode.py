"""KV-cached LM decode benchmark: tokens/sec and per-token latency.

The generation-deployment workload (reference parity: the
RecurrentGradientMachine beam-search path,
gserver/gradientmachines/RecurrentGradientMachine.h:32) on the
decoder-only flagship LM — one jitted XLA while-loop over a static KV
cache (models/transformer_infer.TransformerLMInfer), greedy or beam.
"""

import numpy as np

from common import parse_args, get_place, time_loop  # noqa: E402

import jax

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.models import transformer as T  # noqa: E402
from paddle_tpu.models.transformer_infer import TransformerLMInfer  # noqa: E402


def main():
    args = parse_args(
        "lm_decode", batch_size=32, iterations=10,
        extra=lambda p: (
            p.add_argument("--max_len", type=int, default=128),
            p.add_argument("--out_len", type=int, default=96),
            p.add_argument("--n_layer", type=int, default=4),
            p.add_argument("--n_head", type=int, default=8),
            p.add_argument("--d_model", type=int, default=512),
            p.add_argument("--beam", type=int, default=1),
            p.add_argument("--vocab", type=int, default=8192)))
    T.transformer_lm(
        vocab_size=args.vocab, max_len=args.max_len,
        n_layer=args.n_layer, n_head=args.n_head, d_model=args.d_model,
        d_inner=args.d_model * 4)
    exe = fluid.Executor(get_place(args))
    exe.run(fluid.default_startup_program())
    import jax.numpy as jnp
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else None
    infer = TransformerLMInfer(fluid.default_main_program(),
                               fluid.global_scope(), args.n_layer,
                               args.n_head, args.d_model, args.max_len,
                               dtype=dtype)

    gen = jax.jit(lambda: infer.generate(
        args.batch_size, max_out_len=args.out_len,
        beam_size=args.beam))
    out = [gen()]

    def step(i):
        out[:] = [gen()]

    def sync():
        # a device->host VALUE fetch orders the tunnel timeline
        # (block_until_ready is a no-op on axon — PERF.md)
        leaf = jax.tree_util.tree_leaves(out[0])[0]
        np.asarray(leaf).ravel()[:1]

    tps = time_loop(step, args, args.batch_size * args.out_len, "tokens",
                    sync=sync)
    print("=> %.2f ms/token (bs=%d beam=%d)"
          % (1000.0 * args.batch_size / tps, args.batch_size, args.beam))
    return tps


if __name__ == "__main__":
    main()
