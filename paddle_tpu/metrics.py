"""Python-side metric accumulators.

Reference parity: python/paddle/fluid/metrics.py (MetricBase, CompositeMetric,
Accuracy, ChunkEvaluator, EditDistance, DetectionMAP, Auc) — host-side
accumulation over fetched numpy values.
"""

import numpy as np

__all__ = ["MetricBase", "CompositeMetric", "Accuracy", "Precision", "Recall",
           "ChunkEvaluator", "EditDistance", "Auc"]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for k, v in self.__dict__.items():
            if k.startswith("_"):
                continue
            if isinstance(v, (int, float)):
                setattr(self, k, 0 if isinstance(v, int) else 0.0)
            elif isinstance(v, (list, tuple)):
                setattr(self, k, [])
            elif isinstance(v, dict):
                setattr(self, k, {})

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        if not isinstance(metric, MetricBase):
            raise TypeError("metric must be a MetricBase")
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(value) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no data updated into Accuracy")
        return self.value / self.weight


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class ChunkEvaluator(MetricBase):
    """Accumulates chunk_eval op outputs → (precision, recall, f1)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(num_infer_chunks)
        self.num_label_chunks += int(num_label_chunks)
        self.num_correct_chunks += int(num_correct_chunks)

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances, np.float64)
        self.instance_error += int(np.sum(distances != 0))
        self.total_distance += float(np.sum(distances))
        self.seq_num += int(seq_num)

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("no data updated into EditDistance")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class Auc(MetricBase):
    def __init__(self, name=None, curve="ROC", num_thresholds=200):
        super().__init__(name)
        self._curve = curve
        self._num_thresholds = num_thresholds
        self.tp_list = np.zeros((num_thresholds,))
        self.fn_list = np.zeros((num_thresholds,))
        self.tn_list = np.zeros((num_thresholds,))
        self.fp_list = np.zeros((num_thresholds,))

    def reset(self):
        n = self._num_thresholds
        self.tp_list = np.zeros((n,))
        self.fn_list = np.zeros((n,))
        self.tn_list = np.zeros((n,))
        self.fp_list = np.zeros((n,))

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        kepsilon = 1e-7
        thresholds = [(i + 1) * 1.0 / (self._num_thresholds - 1)
                      for i in range(self._num_thresholds - 2)]
        thresholds = [0.0 - kepsilon] + thresholds + [1.0 + kepsilon]
        for i, thresh in enumerate(thresholds):
            pos = preds >= thresh
            self.tp_list[i] += np.sum(pos & (labels > 0))
            self.fp_list[i] += np.sum(pos & (labels <= 0))
            self.fn_list[i] += np.sum(~pos & (labels > 0))
            self.tn_list[i] += np.sum(~pos & (labels <= 0))

    def eval(self):
        epsilon = 1e-6
        tpr = self.tp_list / (self.tp_list + self.fn_list + epsilon)
        fpr = self.fp_list / (self.fp_list + self.tn_list + epsilon)
        # trapezoid over decreasing thresholds
        auc = 0.0
        for i in range(self._num_thresholds - 1):
            auc += (fpr[i] - fpr[i + 1]) * (tpr[i] + tpr[i + 1]) / 2.0
        return abs(float(auc))
