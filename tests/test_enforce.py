"""Error-layer tests: enforce-style op context on lowering failures
(enforce.h:203 parity) and the every-op-output NaN/Inf guard
(framework/executor.cc:27-94 parity)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.enforce import EnforceError


def test_lowering_error_carries_op_context():
    a = fluid.layers.data("a", [4])
    b = fluid.layers.data("b", [5])
    # elementwise_add of incompatible shapes must fail with op context,
    # not a raw JAX broadcast error.
    c = fluid.layers.elementwise_add(a, b)
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(EnforceError) as ei:
        exe.run(feed={"a": np.ones((2, 4), np.float32),
                      "b": np.ones((2, 5), np.float32)},
                fetch_list=[c])
    msg = str(ei.value)
    assert "elementwise_add" in msg
    assert "float32[2, 4]" in msg and "float32[2, 5]" in msg
    assert "'a'" in msg and "'b'" in msg


def test_nan_guard_catches_internal_nan(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_CHECK_NAN_INF", "1")
    x = fluid.layers.data("x", [3])
    bad = fluid.layers.log(x)            # log of negative input -> NaN
    good = fluid.layers.scale(x, 2.0)    # finite; the only fetched var
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(FloatingPointError) as ei:
        exe.run(feed={"x": -np.ones((2, 3), np.float32)},
                fetch_list=[good])
    # the guard names the op that produced the NaN even though only the
    # finite var was fetched
    assert "log" in str(ei.value)
    assert bad is not None


def test_nan_guard_passes_finite_program(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_CHECK_NAN_INF", "1")
    x = fluid.layers.data("x", [3])
    y = fluid.layers.fc(x, 2)
    loss = fluid.layers.mean(y)
    opt = fluid.optimizer.SGD(learning_rate=0.1)
    opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    out, = exe.run(feed={"x": np.ones((2, 3), np.float32)},
                   fetch_list=[loss])
    assert np.isfinite(out).all()
