"""Built-in datasets (synthetic, egress-free) — parity with
python/paddle/dataset/ (15 datasets; see each module)."""

from . import common, mnist  # noqa: F401
