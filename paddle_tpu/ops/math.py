"""Core math ops: mul/matmul (MXU path), reductions, scale, norms.

Reference parity: operators/mul_op.cc (x_num_col_dims flattening),
matmul_op.cc (batched + transpose flags), sum_op, mean_op, scale_op,
clip/clip_by_norm, reduce_op.cc family, cumsum, l1/l2 norms, cos_sim,
bilinear_tensor_product, top_k.

Matmuls accumulate in float32 via preferred_element_type so bf16 inputs use
the MXU at full throughput without losing accumulation precision.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .common import I64, lod_valid_mask
from ..core.registry import register


def _acc_type(x):
    if x.dtype in (jnp.bfloat16, jnp.float16):
        return jnp.float32
    from ..amp import amp_enabled
    return jnp.float32 if amp_enabled() else None


def _amp_cast(*arrays):
    from ..amp import maybe_bf16
    return maybe_bf16(*arrays)


def _flatten2d(x, num_col_dims):
    lead = 1
    for s in x.shape[:num_col_dims]:
        lead *= s
    return x.reshape(lead, -1), x.shape


@register("mul")
def _mul(ctx, op):
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    out_dtype = x.dtype
    x, y = _amp_cast(x, y)
    xn = op.attr("x_num_col_dims", 1)
    yn = op.attr("y_num_col_dims", 1)
    x2, xshape = _flatten2d(x, xn)
    y2 = y.reshape(functools.reduce(lambda a, b: a * b, y.shape[:yn], 1), -1)
    out = jnp.matmul(x2, y2, preferred_element_type=_acc_type(x))
    from ..amp import amp_out
    out = amp_out(out, out_dtype)
    out = out.reshape(xshape[:xn] + y.shape[yn:])
    ctx.set_out(op, "Out", out)


@register("matmul")
def _matmul(ctx, op):
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    out_dtype = x.dtype
    x, y = _amp_cast(x, y)
    if op.attr("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if op.attr("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    out = jnp.matmul(x, y, preferred_element_type=_acc_type(x))
    from ..amp import amp_out
    out = amp_out(out, out_dtype)
    alpha = op.attr("alpha", 1.0)
    if alpha != 1.0:
        out = out * alpha
    ctx.set_out(op, "Out", out)


@register("sum")
def _sum(ctx, op):
    xs = ctx.in_list(op, "X")
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    ctx.set_out(op, "Out", out)


@register("mean")
def _mean(ctx, op):
    x = ctx.in1(op, "X")
    out = _masked_mean(ctx, op, x, axes=None, keep=False)
    ctx.set_out(op, "Out", jnp.mean(x) if out is None else out)


@register("scale")
def _scale(ctx, op):
    x = ctx.in1(op, "X")
    scale = op.attr("scale", 1.0)
    bias = op.attr("bias", 0.0)
    if op.attr("bias_after_scale", True):
        out = x * scale + bias
    else:
        out = (x + bias) * scale
    ctx.set_out(op, "Out", out)


@register("clip")
def _clip(ctx, op):
    x = ctx.in1(op, "X")
    ctx.set_out(op, "Out",
                jnp.clip(x, op.attr("min", -1.0), op.attr("max", 1.0)))


@register("clip_by_norm")
def _clip_by_norm(ctx, op):
    x = ctx.in1(op, "X")
    max_norm = op.attr("max_norm", 1.0)
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    ctx.set_out(op, "Out",
                jnp.where(norm > max_norm, x * (max_norm / norm), x))


def _row_mask(valid, x):
    return valid.reshape((x.shape[0],) + (1,) * (x.ndim - 1))


def _fill_value(fill, dtype):
    """dtype-preserving neutral element ('min'/'max' map to the dtype's
    extremes so integer reductions stay integer)."""
    if fill == "min":
        return jnp.iinfo(dtype).min if jnp.issubdtype(dtype, jnp.integer) \
            else -jnp.inf
    if fill == "max":
        return jnp.iinfo(dtype).max if jnp.issubdtype(dtype, jnp.integer) \
            else jnp.inf
    return jnp.asarray(fill, dtype)


def _masked_rows(ctx, op, x, fill=0):
    """x with bucket-pad rows replaced by the neutral `fill` (no-op when
    the input carries no LoD)."""
    valid, _ = lod_valid_mask(ctx, op)
    if valid is None:
        return x
    return jnp.where(_row_mask(valid, x), x, _fill_value(fill, x.dtype))


def _masked_mean(ctx, op, x, axes, keep):
    """Mean over the REAL rows of a bucketed LoD input (None when the
    input carries no LoD and the plain mean applies)."""
    valid, n_valid = lod_valid_mask(ctx, op)
    if valid is None:
        return None
    red = tuple(range(x.ndim)) if axes is None else axes
    other = 1
    for a in red:
        if a != 0:
            other *= x.shape[a]
    s = jnp.sum(jnp.where(_row_mask(valid, x), x, 0), axis=axes,
                keepdims=keep)
    acc = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32
    return s / (n_valid.astype(acc) * other)


def _reduce(fn, fill=None):
    def lower(ctx, op):
        x = ctx.in1(op, "X")
        dim = op.attr("dim", [0])
        if op.attr("reduce_all", False):
            axes = None
        else:
            if isinstance(dim, int):
                dim = [dim]
            axes = tuple(d % x.ndim for d in dim)
        keep = op.attr("keep_dim", False)
        if axes is None or 0 in axes:
            # bucketed LoD input: neutralize pad rows before reducing the
            # row axis (sum: 0; max/min: dtype extremes; prod: 1)
            if fn is jnp.mean:
                out = _masked_mean(ctx, op, x, axes, keep)
                if out is not None:
                    ctx.set_out(op, "Out", out)
                    return
            else:
                x = _masked_rows(ctx, op, x, fill)
        ctx.set_out(op, "Out", fn(x, axis=axes, keepdims=keep))
    return lower


register("reduce_sum", _reduce(jnp.sum, fill=0))
register("reduce_mean", _reduce(jnp.mean))
register("reduce_max", _reduce(jnp.max, fill="min"))
register("reduce_min", _reduce(jnp.min, fill="max"))
register("reduce_prod", _reduce(jnp.prod, fill=1))


@register("cumsum")
def _cumsum(ctx, op):
    x = ctx.in1(op, "X")
    axis = op.attr("axis", -1)
    out = jnp.cumsum(x, axis=axis)
    if op.attr("reverse", False):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
    if op.attr("exclusive", False):
        out = out - x
    ctx.set_out(op, "Out", out)


@register("l1_norm")
def _l1_norm(ctx, op):
    x = _masked_rows(ctx, op, ctx.in1(op, "X"))
    ctx.set_out(op, "Out", jnp.sum(jnp.abs(x)))


@register("squared_l2_norm")
def _squared_l2_norm(ctx, op):
    x = _masked_rows(ctx, op, ctx.in1(op, "X"))
    ctx.set_out(op, "Out", jnp.sum(jnp.square(x)))


@register("squared_l2_distance")
def _squared_l2_distance(ctx, op):
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    d = x - y
    ctx.set_out(op, "sub_result", d)
    ctx.set_out(op, "Out", jnp.sum(jnp.square(d), axis=-1, keepdims=True))


@register("norm")
def _norm(ctx, op):
    x = ctx.in1(op, "X")
    axis = op.attr("axis", 1)
    eps = op.attr("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    ctx.set_out(op, "Norm", norm)
    ctx.set_out(op, "Out", x / norm)


@register("cos_sim")
def _cos_sim(ctx, op):
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / (xn * yn + 1e-12)
    ctx.set_out(op, "Out", out)
    ctx.set_out(op, "XNorm", xn)
    ctx.set_out(op, "YNorm", yn)


@register("bilinear_tensor_product")
def _bilinear(ctx, op):
    x = ctx.in1(op, "X")          # [B, M]
    y = ctx.in1(op, "Y")          # [B, N]
    w = ctx.in1(op, "Weight")     # [O, M, N]
    out = jnp.einsum("bm,omn,bn->bo", x, w, y)
    b = ctx.in1(op, "Bias")
    if b is not None:
        out = out + b
    ctx.set_out(op, "Out", out)


@register("top_k")
def _top_k(ctx, op):
    x = ctx.in1(op, "X")
    k = op.attr("k", 1)
    vals, idx = lax.top_k(x, k)
    ctx.set_out(op, "Out", vals)
    ctx.set_out(op, "Indices", idx.astype(I64()))


@register("arg_max")
def _arg_max(ctx, op):
    ctx.set_out(op, "Out", jnp.argmax(
        ctx.in1(op, "X"), axis=op.attr("axis", -1)).astype(I64()))


@register("arg_min")
def _arg_min(ctx, op):
    ctx.set_out(op, "Out", jnp.argmin(
        ctx.in1(op, "X"), axis=op.attr("axis", -1)).astype(I64()))


@register("minus")
def _minus(ctx, op):
    ctx.set_out(op, "Out", ctx.in1(op, "X") - ctx.in1(op, "Y"))


@register("conv_shift")
def _conv_shift(ctx, op):
    # circular correlation (operators/conv_shift_op.cc)
    x = ctx.in1(op, "X")          # [B, M]
    y = ctx.in1(op, "Y")          # [B, N], N odd, N <= M
    m = x.shape[1]
    n = y.shape[1]
    half = n // 2
    idx = (jnp.arange(m)[:, None] + jnp.arange(-half, half + 1)[None, :]) % m
    gathered = x[:, idx]                     # [B, M, N]
    ctx.set_out(op, "Out", jnp.einsum("bmn,bn->bm", gathered, y))
