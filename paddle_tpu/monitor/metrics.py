"""Thread-safe metrics registry: counters, gauges, histograms with labels.

The runtime half of the observability story (the static half is
paddle_tpu.analysis). Reference parity: the 2018 framework had no
metrics registry at all — its closest analogs are the profiler's
per-event count/total table (platform/profiler.h) and the pserver's
ad-hoc stderr logs; here every subsystem (executor, distributed runtime,
watchdog) reports into ONE process-wide registry that exports Prometheus
text or a JSON snapshot at any moment, the always-on production shape.

Design: metric objects are cheap to update (one lock + dict store per
observation — sub-microsecond, invisible next to a training step or a
socket round-trip) and are safe to create at import time; creating a
metric never starts threads or touches files. `registry()` returns the
process default; tests may build private `Registry()` instances.
"""

import bisect
import json
import threading

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "registry",
           "bucket_percentile"]

# step latencies span ~100us (tiny CPU graphs) to minutes (first XLA
# compile included in a run() call); exponential buckets, factor ~2.
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)


def bucket_percentile(buckets, counts, q):
    """Bucket-interpolated q-quantile (0..1) over NON-cumulative
    per-bucket counts (``len(buckets) + 1`` entries, overflow last);
    None when empty. ONE algorithm shared by ``Histogram.percentile``
    (live) and the SLO evaluator's offline snapshot math
    (paddle_tpu/slo.py) — a fix to either must be a fix to both, or
    --metrics verdicts drift from live percentiles."""
    total = sum(counts)
    if not total:
        return None
    target = q * total
    acc = 0.0
    for i, c in enumerate(counts):
        if acc + c >= target and c:
            lo = buckets[i - 1] if i > 0 else 0.0
            hi = buckets[i] if i < len(buckets) else buckets[-1]
            frac = (target - acc) / c
            return lo + (hi - lo) * min(1.0, max(0.0, frac))
        acc += c
    return buckets[-1]


def _label_key(label_names, labels):
    if set(labels) != set(label_names):
        raise ValueError(
            "metric labels %s do not match declared %s"
            % (sorted(labels), sorted(label_names)))
    return tuple(str(labels[n]) for n in label_names)


class _Metric:
    kind = "untyped"

    def __init__(self, name, help_="", label_names=()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._series = {}       # label-value tuple -> stored value

    def _fmt_labels(self, key, extra=()):
        pairs = list(zip(self.label_names, key)) + list(extra)
        if not pairs:
            return ""
        return "{%s}" % ",".join(
            '%s="%s"' % (k, str(v).replace('"', r'\"')) for k, v in pairs)

    def clear(self):
        with self._lock:
            self._series.clear()


class Counter(_Metric):
    """Monotonically increasing count (resets only with the process)."""

    kind = "counter"

    def inc(self, value=1, **labels):
        if value < 0:
            raise ValueError("counter increment must be >= 0")
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + value

    def value(self, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            return self._series.get(key, 0)

    def snapshot(self):
        with self._lock:
            return dict(self._series)

    def render(self):
        lines = ["# HELP %s %s" % (self.name, self.help),
                 "# TYPE %s counter" % self.name]
        for key, v in sorted(self.snapshot().items()):
            lines.append("%s%s %s" % (self.name, self._fmt_labels(key), v))
        return lines


class Gauge(_Metric):
    """Point-in-time value (can go up and down)."""

    kind = "gauge"

    def set(self, value, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, value=1, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def value(self, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            return self._series.get(key)

    def snapshot(self):
        with self._lock:
            return dict(self._series)

    def render(self):
        lines = ["# HELP %s %s" % (self.name, self.help),
                 "# TYPE %s gauge" % self.name]
        for key, v in sorted(self.snapshot().items()):
            lines.append("%s%s %s" % (self.name, self._fmt_labels(key), v))
        return lines


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics) with a cheap
    bucket-interpolated percentile for in-process reporting."""

    kind = "histogram"

    def __init__(self, name, help_="", label_names=(), buckets=None):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))

    def observe(self, value, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            ent = self._series.get(key)
            if ent is None:
                ent = self._series[key] = {
                    "counts": [0] * (len(self.buckets) + 1),
                    "sum": 0.0, "count": 0}
            idx = bisect.bisect_left(self.buckets, value)
            ent["counts"][idx] += 1
            ent["sum"] += float(value)
            ent["count"] += 1

    def count(self, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            ent = self._series.get(key)
            return ent["count"] if ent else 0

    def sum(self, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            ent = self._series.get(key)
            return ent["sum"] if ent else 0.0

    def percentile(self, q, **labels):
        """Approximate q-quantile (0..1) by linear interpolation inside
        the containing bucket. None when empty."""
        key = _label_key(self.label_names, labels)
        with self._lock:
            ent = self._series.get(key)
            if not ent or not ent["count"]:
                return None
            counts = list(ent["counts"])
        return bucket_percentile(self.buckets, counts, q)

    def snapshot(self):
        with self._lock:
            return {k: {"counts": list(v["counts"]), "sum": v["sum"],
                        "count": v["count"]}
                    for k, v in self._series.items()}

    def render(self):
        lines = ["# HELP %s %s" % (self.name, self.help),
                 "# TYPE %s histogram" % self.name]
        for key, ent in sorted(self.snapshot().items()):
            acc = 0
            for b, c in zip(self.buckets, ent["counts"]):
                acc += c
                lines.append("%s_bucket%s %d" % (
                    self.name, self._fmt_labels(key, [("le", repr(b))]),
                    acc))
            lines.append("%s_bucket%s %d" % (
                self.name, self._fmt_labels(key, [("le", "+Inf")]),
                ent["count"]))
            lines.append("%s_sum%s %s" % (
                self.name, self._fmt_labels(key), ent["sum"]))
            lines.append("%s_count%s %d" % (
                self.name, self._fmt_labels(key), ent["count"]))
        return lines


class Registry:
    """Named collection of metrics. get-or-create semantics: asking for
    an existing name with the same type and labels returns the SAME
    object (so modules can declare their metrics independently); a
    conflicting re-registration raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    def _get_or_create(self, cls, name, help_, label_names, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or \
                        m.label_names != tuple(label_names):
                    raise ValueError(
                        "metric %r already registered as %s%s"
                        % (name, type(m).__name__, m.label_names))
                want_buckets = kw.get("buckets")
                if want_buckets is not None and \
                        m.buckets != tuple(sorted(want_buckets)):
                    raise ValueError(
                        "histogram %r already registered with buckets %s"
                        % (name, m.buckets))
                return m
            m = cls(name, help_, label_names, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help_="", label_names=()):
        return self._get_or_create(Counter, name, help_, label_names)

    def gauge(self, name, help_="", label_names=()):
        return self._get_or_create(Gauge, name, help_, label_names)

    def histogram(self, name, help_="", label_names=(), buckets=None):
        return self._get_or_create(Histogram, name, help_, label_names,
                                   buckets=buckets)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def names(self):
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self):
        """{name: {"kind", "labels", "series": {"l1,l2": value}}} — the
        JSON-able dump the flight recorder and watchdog embed.
        Histograms additionally carry their "buckets" boundaries so a
        dumped snapshot stays percentile-evaluable offline (the SLO
        engine's --metrics source)."""
        with self._lock:
            metrics = list(self._metrics.values())
        out = {}
        for m in metrics:
            series = {",".join(k): v for k, v in m.snapshot().items()}
            ent = {"kind": m.kind,
                   "labels": list(m.label_names),
                   "series": series}
            if m.kind == "histogram":
                ent["buckets"] = list(m.buckets)
            out[m.name] = ent
        return out

    def render_prometheus(self):
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def dump_json(self, path):
        with open(path, "w") as f:
            json.dump(self.snapshot(), f, indent=1, sort_keys=True)

    def reset(self):
        """Clear every series (metric objects survive — references held
        by modules stay valid). Test isolation helper."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.clear()


_default = Registry()


def registry():
    """The process-wide default registry."""
    return _default
