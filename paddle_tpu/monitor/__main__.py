"""CLI: summarize or live-watch a flight-recorder JSONL log.

    python -m paddle_tpu.monitor run.jsonl [--json]
    python -m paddle_tpu.monitor watch run.jsonl [--interval S]
        [--window N] [--once] [--slo spec.json]
    python -m paddle_tpu.monitor watch rep0.jsonl rep1.jsonl ...
        # serving fleet: one log per replica, dashboard over the union
    python -m paddle_tpu.monitor watch --fleet <kv-endpoint>
        # LIVE fleet scrape over RPC (monitor/collector.py) — no files
    python -m paddle_tpu.monitor goodput run.jsonl [rep1.jsonl ...]
        # goodput/badput wall-time attribution (monitor/goodput.py)
    python -m paddle_tpu.monitor alerts --fleet <kv-endpoint>
        # LIVE streaming rule engine (monitor/signals.py): SLO burn-
        # rate + sustained-condition alerts over the scraped fleet
    python -m paddle_tpu.monitor alerts run.jsonl [--spec slo.json]
        # offline replay: the same rules over a recorded log
    python -m paddle_tpu.monitor alerts --incident run.jsonl ...
        # timeline splicing alert rows with the goodput ledger's
        # badput intervals ("what happened at 14:32")
    python -m paddle_tpu.monitor bundle <dir>
        # incident forensics (monitor/forensics.py): verify a bundle's
        # CRC manifest and render the skew-corrected cross-process
        # timeline centered on the offender traces
    python -m paddle_tpu.monitor bundle --capture --fleet <kv> <dir>
        # on-demand black-box capture: DUMP every fleet process into a
        # new bundle under <dir>, then render it

The summary covers BOTH workloads a log may carry: training `step`
rows (step count, latency percentiles, compile/recompile causes, MFU,
tokens/s) and serving `serving_step`/`serving_request` rows (engine
step p50/p95, occupancy, queue depth, TTFT/TPOT percentiles, error
count) — one command reports whatever ran. `--json` emits the same
summary as one JSON object for scripts (bench.py consumes this shape).
`watch` tails a (possibly live) log and renders a refreshing terminal
dashboard; `--once` renders a single frame and exits (scripts/tests).
"""

import argparse
import json
import sys

from .recorder import percentile_sorted as _percentile
from .recorder import read_jsonl_tolerant


def summarize_log(path):
    # tolerant parse: a LIVE run's log legitimately ends mid-record
    # when the writer is killed — skip-and-count instead of raising
    events, skipped = read_jsonl_tolerant(path)
    steps = [e for e in events if e["ev"] == "step"]
    compiles = [e for e in events if e["ev"] == "compile"]
    # a megastep row is ONE dispatch advancing k logical steps with
    # dt = per-logical-step wall time — counts and totals weight by k
    # so figures stay comparable across K (the ISSUE-7 contract)
    def _k(e):
        return int(e.get("k") or 1)
    # latency percentiles use SYNCED samples only: unsynced steps
    # (monitor_sync_every amortization) logged dispatch time, not wall
    dts = sorted(e["dt"] for e in steps
                 if e.get("dt") is not None and e.get("synced", True))
    mfus = [e["mfu"] for e in steps if e.get("mfu")]
    tps = [e["tokens_per_sec"] for e in steps if e.get("tokens_per_sec")]
    reasons = {}
    for c in compiles:
        reasons[c.get("reason", "?")] = reasons.get(
            c.get("reason", "?"), 0) + 1
    # device info rides a separate lazy `devices` event (run_meta is
    # written at enable() time, before the jax backend may exist)
    dev = next((e for e in events if e["ev"] == "devices"), {})
    out = {
        "path": path,
        "events": len(events),
        "platform": dev.get("platform"),
        "device_kind": dev.get("device_kind"),
        "steps": sum(_k(e) for e in steps),
        "p50_s": _percentile(dts, 0.50),
        "p95_s": _percentile(dts, 0.95),
        "total_step_s": sum(e["dt"] * _k(e) for e in steps
                            if e.get("dt") is not None
                            and e.get("synced", True)),
        "compiles": len(compiles),
        "compile_reasons": reasons,
        "recompiles": sum(1 for c in compiles if c.get("recompile")),
        "xla_compile_s": sum(e.get("seconds", 0.0) for e in events
                             if e["ev"] == "xla_compile"),
        "feed_bytes": sum(e.get("feed_bytes") or 0 for e in steps),
        "mean_mfu": (sum(mfus) / len(mfus)) if mfus else None,
        "mean_tokens_per_sec": (sum(tps) / len(tps)) if tps else None,
        "nan_trips": sum(1 for e in events if e["ev"] == "nan_guard"),
        "stalls": sum(1 for e in events if e["ev"] == "stall"),
        "truncated": any(e["ev"] == "truncated" for e in events),
        "skipped_lines": skipped,
        "serving": _summarize_serving(events),
    }
    return out


def _summarize_serving(events):
    """Aggregate serving_step / serving_request rows (None when the
    log carries neither — a pure training log stays unchanged). The
    latency samples come from the SLO engine's ONE rows->samples
    extraction (failed-request exclusion included), so this summary
    and `python -m paddle_tpu.slo --log` always agree on a file."""
    sstep = [e for e in events if e["ev"] == "serving_step"]
    sreq = [e for e in events if e["ev"] == "serving_request"]
    if not sstep and not sreq:
        return None
    from .. import slo as _slo
    # latency/request fields only — the goodput ledger has its own
    # subcommand, no need to sweep the whole file here
    s = _slo.samples_from_events(events, compute_goodput=False)
    sdts = sorted(s["step_latency"])
    ttft = sorted(s["ttft"])
    tpot = sorted(s["tpot"])
    qw = sorted(s["queue_wait"])
    occ = [e["active"] / e["slots"] for e in sstep if e.get("slots")]
    return {
        # fused serving_step rows (megastep) advance k decode steps
        "steps": sum(int(e.get("k") or 1) for e in sstep),
        "step_p50_s": _percentile(sdts, 0.50),
        "step_p95_s": _percentile(sdts, 0.95),
        "mean_occupancy": (sum(occ) / len(occ)) if occ else None,
        "max_queue_depth": max(
            (e.get("queue_depth") or 0 for e in sstep), default=0),
        "tokens": sum(e.get("emitted") or 0 for e in sstep),
        "requests": s["requests"],
        "errors": s["errors"],
        "ttft_p50_s": _percentile(ttft, 0.50),
        "ttft_p95_s": _percentile(ttft, 0.95),
        "tpot_p50_s": _percentile(tpot, 0.50),
        "tpot_p95_s": _percentile(tpot, 0.95),
        "queue_wait_p95_s": _percentile(qw, 0.95),
    }


def _fmt_ms(v):
    return "n/a" if v is None else "%.2f ms" % (1000 * v)


def render(s):
    lines = [
        "flight log %s: %d events%s" % (
            s["path"], s["events"],
            " [TRUNCATED]" if s["truncated"] else ""),
        "  device      %s %s" % (s.get("platform") or "?",
                                 s.get("device_kind") or ""),
        "  steps       %d  (p50 %s, p95 %s, total %.2f s)" % (
            s["steps"], _fmt_ms(s["p50_s"]), _fmt_ms(s["p95_s"]),
            s["total_step_s"]),
        "  compiles    %d  (%s)  recompiles %d  xla wall %.2f s" % (
            s["compiles"],
            ", ".join("%s=%d" % kv
                      for kv in sorted(s["compile_reasons"].items()))
            or "-",
            s["recompiles"], s["xla_compile_s"]),
        "  feed bytes  %d" % s["feed_bytes"],
    ]
    if s["mean_mfu"] is not None:
        lines.append("  MFU         %.1f%%" % (100 * s["mean_mfu"]))
    if s["mean_tokens_per_sec"] is not None:
        lines.append("  tokens/s    %.0f" % s["mean_tokens_per_sec"])
    sv = s.get("serving")
    if sv:
        lines.append(
            "  serving     %d step(s)  (p50 %s, p95 %s)  occupancy "
            "%s  max queue %d  tokens %d" % (
                sv["steps"], _fmt_ms(sv["step_p50_s"]),
                _fmt_ms(sv["step_p95_s"]),
                "n/a" if sv["mean_occupancy"] is None
                else "%.2f" % sv["mean_occupancy"],
                sv["max_queue_depth"], sv["tokens"]))
        if sv["requests"]:
            lines.append(
                "  requests    %d  TTFT p50 %s p95 %s  TPOT p50 %s "
                "p95 %s  queue_wait p95 %s%s" % (
                    sv["requests"],
                    _fmt_ms(sv["ttft_p50_s"]), _fmt_ms(sv["ttft_p95_s"]),
                    _fmt_ms(sv["tpot_p50_s"]), _fmt_ms(sv["tpot_p95_s"]),
                    _fmt_ms(sv["queue_wait_p95_s"]),
                    "  ERRORS %d" % sv["errors"] if sv["errors"]
                    else ""))
    if s["nan_trips"]:
        lines.append("  NaN trips   %d" % s["nan_trips"])
    if s["stalls"]:
        lines.append("  STALLS      %d" % s["stalls"])
    if s.get("skipped_lines"):
        lines.append("  skipped     %d partial/torn line(s) (live or "
                     "killed writer)" % s["skipped_lines"])
    return "\n".join(lines)


def _watch_main(argv):
    from .watch import watch, watch_fleet
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.monitor watch",
        description="Tail a flight-recorder log and render a live "
                    "terminal dashboard (or --fleet for the live "
                    "scraped fleet view — no files)")
    p.add_argument("log", nargs="*",
                   help="flight-recorder .jsonl path(s) — one per "
                        "replica for a serving fleet; the dashboard "
                        "aggregates the union")
    p.add_argument("--fleet", default=None, metavar="KV_ENDPOINT",
                   help="live fleet scrape: discover processes from "
                        "this membership KV registry (host:port) and "
                        "scrape their metrics over RPC instead of "
                        "tailing files")
    p.add_argument("--endpoint", action="append", default=[],
                   metavar="ROLE=HOST:PORT",
                   help="extra static scrape endpoint for --fleet "
                        "(e.g. master=127.0.0.1:7164; repeatable — "
                        "the master and KV server are not "
                        "lease-registered)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between refreshes (default 2)")
    p.add_argument("--window", type=int, default=256,
                   help="rolling-window rows per series (default 256)")
    p.add_argument("--once", action="store_true",
                   help="render one frame from the current log "
                        "contents and exit")
    p.add_argument("--slo", default=None,
                   help="SLO spec JSON evaluated live over the "
                        "rolling request window (default: the "
                        "PADDLE_TPU_SLO_SPEC flag when set)")
    args = p.parse_args(argv)
    if not args.log and args.fleet is None and not args.endpoint:
        p.error("pass log file(s), or --fleet/--endpoint for the "
                "live scrape")
    slo_spec = args.slo
    if slo_spec is None:
        from .. import flags
        slo_spec = flags.get_flag("slo_spec") or None
    if slo_spec is not None:
        # validate up front: a typo'd --slo path (or a bad flag-named
        # spec) must be a clean exit 2, like the slo CLI, not a
        # traceback out of the render loop
        from .. import slo as _slo
        try:
            slo_spec = _slo.load_spec(slo_spec)
        except (OSError, ValueError) as e:
            print("watch: bad SLO spec %s: %s" % (args.slo or
                                                  "(from flag)", e),
                  file=sys.stderr)
            return 2
    if args.fleet is not None or args.endpoint:
        if args.log:
            print("watch: --fleet scrapes live endpoints; log files "
                  "are ignored with it", file=sys.stderr)
        static = []
        for s in args.endpoint:
            if "=" not in s:
                print("watch: --endpoint wants ROLE=HOST:PORT, got %r"
                      % s, file=sys.stderr)
                return 2
            role, ep = s.split("=", 1)
            static.append((role, ep))
        frame = watch_fleet(kv_endpoint=args.fleet, static=static,
                            interval=args.interval,
                            window=args.window, once=args.once,
                            slo_spec=slo_spec)
        return 1 if args.once and frame is None else 0
    frame = watch(args.log, interval=args.interval, window=args.window,
                  once=args.once, slo_spec=slo_spec)
    # --once on a log that does not exist is a scripting error (1);
    # the live loop instead waits for the file and exits 0 on Ctrl-C
    return 1 if args.once and frame is None else 0


def _alerts_main(argv):
    from . import signals as sg
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.monitor alerts",
        description="SLO burn-rate + sustained-condition alerting "
                    "(monitor/signals.py): stream against a live "
                    "scraped fleet (--fleet/--endpoint), replay a "
                    "recorded log, or render an --incident timeline")
    p.add_argument("log", nargs="*",
                   help="flight-recorder .jsonl path(s) to replay "
                        "offline (or to splice with --incident)")
    p.add_argument("--fleet", default=None, metavar="KV_ENDPOINT",
                   help="live mode: discover processes from this "
                        "membership KV registry and scrape them over "
                        "RPC each --interval")
    p.add_argument("--endpoint", action="append", default=[],
                   metavar="ROLE=HOST:PORT",
                   help="extra static scrape endpoint for live mode "
                        "(repeatable)")
    p.add_argument("--spec", default=None,
                   help="SLO/signals spec JSON: error-budget "
                        "objectives arm burn rules, its 'rules' "
                        "object overrides the sustained-condition "
                        "defaults (default: the PADDLE_TPU_SIGNALS_"
                        "SPEC flag, then PADDLE_TPU_SLO_SPEC)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between live scrape rounds "
                        "(default 2)")
    p.add_argument("--rounds", type=int, default=None,
                   help="stop the live loop after N rounds "
                        "(default: run until Ctrl-C)")
    p.add_argument("--round-s", type=float, default=1.0,
                   dest="round_s",
                   help="offline replay round granularity in seconds "
                        "of ROW time (default 1)")
    p.add_argument("--incident", action="store_true",
                   help="render the incident timeline of the given "
                        "log(s): alert rows spliced with badput "
                        "intervals and recovery markers")
    p.add_argument("--json", action="store_true",
                   help="emit transitions (or the incident entries) "
                        "as JSON")
    args = p.parse_args(argv)

    if args.incident:
        if not args.log:
            p.error("--incident needs flight-recorder log file(s)")
        try:
            entries, ledgers = sg.incident_entries(args.log)
        except OSError as e:
            print("alerts: unreadable log: %s" % e, file=sys.stderr)
            return 2
        print(json.dumps({"entries": entries}) if args.json
              else sg.render_incident(entries, ledgers))
        return 0

    spec_src = args.spec
    if spec_src is None:
        from .. import flags
        spec_src = flags.get_flag("signals_spec") \
            or flags.get_flag("slo_spec") or None
    spec = None
    if spec_src:
        from .. import slo as _slo
        try:
            spec = _slo.load_spec(spec_src)
        except (OSError, ValueError) as e:
            print("alerts: bad spec %s: %s" % (spec_src, e),
                  file=sys.stderr)
            return 2
    try:
        sig = sg.Signals(spec=spec)
    except ValueError as e:
        print("alerts: bad rule config: %s" % e, file=sys.stderr)
        return 2

    if args.fleet is not None or args.endpoint:
        from .collector import Collector
        static = []
        for s in args.endpoint:
            if "=" not in s:
                print("alerts: --endpoint wants ROLE=HOST:PORT, got "
                      "%r" % s, file=sys.stderr)
                return 2
            role, ep = s.split("=", 1)
            static.append((role, ep))
        col = Collector(kv_endpoint=args.fleet, static=static)
        rounds = 0
        n_transitions = 0       # count only: the loop may run for
        try:                    # weeks, transitions must not pile up
            while args.rounds is None or rounds < args.rounds:
                events = col.scrape_once()
                trs = sig.observe(snapshot=col.fleet_snapshot(),
                                  events=events)
                for tr in trs:
                    print(json.dumps(tr) if args.json
                          else sg.render_transition(tr))
                n_transitions += len(trs)
                rounds += 1
                if args.rounds is None or rounds < args.rounds:
                    import time as _time
                    _time.sleep(args.interval)
        except KeyboardInterrupt:
            pass
        finally:
            col.close()
        if not args.json:
            hint = sig.scale_hint()
            print("%d round(s), %d transition(s)   %s\n"
                  "scale hint: %s x%d  (%s)"
                  % (rounds, n_transitions,
                     sg.active_alerts_line(sig).strip(),
                     hint.direction, hint.magnitude, hint.reason))
        return 0

    if not args.log:
        p.error("pass log file(s), or --fleet/--endpoint for the "
                "live scrape")
    events = []
    try:
        for path in args.log:
            evs, _ = read_jsonl_tolerant(path)
            events.extend(evs)
    except OSError as e:
        print("alerts: unreadable log: %s" % e, file=sys.stderr)
        return 2
    # one log = one process's timeline: the goodput rule evaluates a
    # rolling ledger per round; a multi-log UNION would collapse
    # concurrent processes' intervals, so it stays off there (use
    # watch's per-source rollup for fleets)
    transitions = sig.replay(events, round_s=args.round_s,
                             goodput=len(args.log) == 1)
    if args.json:
        print(json.dumps({"transitions": transitions,
                          "active": sig.active(),
                          "scale_hint": list(sig.scale_hint())}))
    else:
        for tr in transitions:
            print(sg.render_transition(tr))
        hint = sig.scale_hint()
        print("%d transition(s)   %s\nscale hint: %s x%d  (%s)"
              % (len(transitions), sg.active_alerts_line(sig).strip(),
                 hint.direction, hint.magnitude, hint.reason))
    return 0


def _goodput_main(argv):
    from . import goodput as gp
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.monitor goodput",
        description="Goodput/badput wall-time attribution over "
                    "flight-recorder log(s) — one per process; "
                    "several render a fleet rollup")
    p.add_argument("log", nargs="+",
                   help="flight-recorder .jsonl path(s)")
    p.add_argument("--json", action="store_true",
                   help="emit the ledger as one JSON object")
    args = p.parse_args(argv)
    try:
        report = gp.ledger(args.log)
    except OSError as e:
        print("goodput: unreadable log: %s" % e, file=sys.stderr)
        return 2
    print(json.dumps(report) if args.json else gp.render(report))
    return 0


def _bundle_main(argv):
    from . import forensics as fx
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.monitor bundle",
        description="Incident forensics (monitor/forensics.py): "
                    "verify a bundle's CRC manifest and render the "
                    "one-screen incident summary + offender-centered "
                    "cross-process timeline; --capture assembles a "
                    "fresh bundle from a live fleet first")
    p.add_argument("dir",
                   help="bundle directory to render — with --capture, "
                        "the base directory the new bundle is "
                        "created under")
    p.add_argument("--capture", action="store_true",
                   help="fan DUMP out across the fleet (discovery "
                        "via --fleet/--endpoint) and assemble a new "
                        "bundle under <dir> before rendering it")
    p.add_argument("--fleet", default=None, metavar="KV_ENDPOINT",
                   help="membership KV registry (host:port) for "
                        "--capture discovery")
    p.add_argument("--endpoint", action="append", default=[],
                   metavar="ROLE=HOST:PORT",
                   help="extra static capture endpoint (repeatable — "
                        "the master and KV server are not "
                        "lease-registered)")
    p.add_argument("--deadline", type=float, default=2.0,
                   help="per-process DUMP deadline in seconds; a "
                        "slower process is dropped and recorded as "
                        "missing (default 2)")
    args = p.parse_args(argv)
    path = args.dir
    if args.capture:
        if args.fleet is None and not args.endpoint:
            p.error("--capture needs --fleet and/or --endpoint")
        static = []
        for s in args.endpoint:
            if "=" not in s:
                print("bundle: --endpoint wants ROLE=HOST:PORT, got "
                      "%r" % s, file=sys.stderr)
                return 2
            role, ep = s.split("=", 1)
            static.append((role, ep))
        path = fx.capture(kv_endpoint=args.fleet, static=static,
                          deadline_s=args.deadline, out_dir=args.dir)
    try:
        return fx.render(path)
    except (OSError, ValueError) as e:
        # missing directory / unreadable or non-bundle manifest: a
        # usage error on the analysis/slo convention
        print("bundle: %s: %s" % (path, e), file=sys.stderr)
        return 2


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    try:
        return _main(argv)
    except BrokenPipeError:
        # `... | head` closed the pipe mid-render: a truncated listing
        # is what the reader asked for, not a traceback. Re-point
        # stdout at devnull so the interpreter's exit flush stays
        # quiet too.
        import os
        os.dup2(os.open(os.devnull, os.O_WRONLY),
                sys.stdout.fileno())
        return 0


def _main(argv):
    if argv and argv[0] == "watch":
        return _watch_main(argv[1:])
    if argv and argv[0] == "goodput":
        return _goodput_main(argv[1:])
    if argv and argv[0] == "alerts":
        return _alerts_main(argv[1:])
    if argv and argv[0] == "bundle":
        return _bundle_main(argv[1:])
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.monitor",
        description="Summarize a paddle_tpu.monitor flight-recorder "
                    "log (or `watch <log>` for a live dashboard)")
    p.add_argument("log", help="flight-recorder .jsonl path")
    p.add_argument("--json", action="store_true",
                   help="emit the summary as one JSON object")
    args = p.parse_args(argv)
    s = summarize_log(args.log)
    if args.json:
        print(json.dumps(s))
    else:
        print(render(s))
    return 0


if __name__ == "__main__":
    sys.exit(main())
