"""Continuous-batching decode engine: slot state + iteration scheduler.

Reference parity: the reference served generation through the C-API's
one-request-at-a-time ``GradientMachine::forward`` loop (capi/
gradient_machine.h) — PERF.md round 4/5 measured the equivalent path
here (bs1 KV-cached decode) at the per-step dispatch floor, ~23x below
the same chip's bs32 throughput. The engine is the standard fix, after
Orca (iteration-level scheduling) and vLLM (slot/block-managed caches):

  * **Slot-based decode state** — ONE compiled step over a fixed
    [slots, ...] KV cache (models/transformer_infer
    ``_step_logits_slots``) with per-slot write positions, active masks
    and sampling state (greedy + cumulative log-prob). The compiled
    shape never changes as requests of different lengths come and go.
  * **Iteration-level scheduler** — a thread-safe queue feeding
    admissions at step boundaries: slots retire on EOS / max_new and
    refill mid-flight; an admitted prompt prefills CHUNK by chunk
    (``_prefill_chunk_slot``, one chunk per engine iteration) so one
    long prompt cannot stall the running batch; the admission policy is
    greedy fill by default with an optional wait-for-batch window.
  * **Megastep decode** (ISSUE 7) — with ``megastep=K`` (flag
    ``serving_megastep``) an iteration with no pending admissions or
    prefills fuses K decode steps into ONE dispatch (``lax.scan`` over
    the slot step), attacking the measured bs1 per-step dispatch floor
    (PERF.md rounds 5/6) while staying token-identical; pending work
    forces a K→1 boundary first. ``warmup()`` compiles both dispatch
    paths before traffic.
  * **Paged KV + prefix reuse + sampling** (ISSUE 10, default on; flag
    ``serving_paged``) — instead of a dense per-slot ``max_len``
    stripe, K/V live in a SHARED ``[num_blocks, n_layer, n_head,
    block_size, dk]`` pool addressed through per-slot block tables
    (``serving.kvpool.BlockPool``): blocks allocate at admission /
    as decode crosses block boundaries and free at retirement, so a
    short request no longer reserves ``max_len`` worth of cache. A
    radix prefix cache (``kvpool.RadixCache``) maps full-block prompt
    prefixes to refcounted block chains — an admission whose prompt
    shares a cached prefix SKIPS those prefill chunks entirely
    (copy-on-write resolves the one case a shared block would be
    written; LRU eviction of unreferenced chains bounds the cache at
    the pool size). When the pool runs dry anyway, the LOWEST-priority
    (latest-admitted) request is PREEMPTED: its blocks free, it
    re-queues for re-prefill, and deterministic decode (greedy, or
    counter-keyed seeded sampling) makes the resumed output identical
    — exactly-once survives. Per-request ``SamplingParams``
    (temperature / top-k / top-p / seed) execute in-step with per-slot
    PRNG state; temperature-0 requests stay BITWISE-greedy (the
    megastep/fleet token-identity contracts are untouched).
  * **Speculative decode** (ISSUE 13; flags ``serving_speculative`` /
    ``serving_spec_gamma`` / ``serving_spec_drafter``) — the lever
    PR 10 deferred: a cheap drafter proposes up to γ tokens per live
    slot (tier A: prompt/n-gram lookup over the request's own token
    chain plus the radix cache's published chains, ``serving/spec.py``;
    tier B: a truncated-layer pass over the same weights), the full
    model scores all γ+1 positions in ONE paged-attention dispatch
    (``_spec_logits_paged`` — multi-position masked writes, per-slot
    ragged draft lengths through the block-table gather), and the
    longest prefix of drafts matching the model's own tokens is
    accepted IN-STEP — every dispatch lands 1..γ+1 VERIFIED tokens.
    Correctness never depends on the drafter: temp-0 output stays
    BITWISE the non-speculative engine's (accepted tokens ARE the
    greedy tokens), seeded sampling replays identically (acceptance is
    keyed on the same ``fold_in(seed, tokens_generated)`` draws), and
    megastep / preemption / fleet exactly-once compose unchanged (a
    no-draft iteration runs the existing programs cost-for-cost).

Every engine iteration is instrumented: monitor gauges/counters
(``ptpu_serving_*``), a ``serving_step`` flight-recorder row carrying
the active trace id, and an ``engine.step`` trace span. Every REQUEST
is instrumented too (the unit a user experiences, which Orca-style
iteration scheduling makes a product of policy, not just kernel time):
lifecycle stamps at enqueue/admit/first-token/retire on the ``Request``
handle, derived queue_wait / TTFT / TPOT, a ``serving_request``
recorder row + ``ptpu_serving_{ttft,tpot,queue_wait}_seconds``
histograms at retirement, and a ``serving.request`` trace span (child
spans per prefill chunk, a first-token mark, step-span links) so
``trace merge`` shows request lanes across the fleet timeline.
"""

import collections
import itertools
import os
import threading
import time

import numpy as np
import jax
import jax.numpy as jnp

from ..monitor import runtime as _monrt
from ..ops import paged_attention as _paged_ops
from ..trace import runtime as _trc
from . import kvpool as _kvpool
from . import spec as _spec
from .sampling import SamplingParams, sample as _sample, \
    step_keys as _step_keys

__all__ = ["Engine", "Request", "sequential_generate"]


class Request:
    """One submitted generation request; also the result handle.

    ``result()`` blocks until the engine retires the request and returns
    ``(tokens, score)`` — the greedy continuation (EOS included when hit,
    at most ``max_new`` tokens) and the sum of token log-probs.

    Lifecycle attribution (ISSUE 6): the engine stamps four monotonic
    (``time.perf_counter``) timestamps — ``t_enqueue`` (submit),
    ``t_admit`` (decode-slot admission), ``t_first_token`` (first
    decoded token lands), ``t_retire`` (EOS / max_new / failure) — and
    the handle derives the three per-request latency figures a serving
    SLO is written against: ``queue_wait``, ``ttft`` and ``tpot``.
    Stamps later in the lifecycle are ``None`` until reached; reading
    them after ``result()`` returns is race-free (the engine writes
    them before resolving the future)."""

    __slots__ = ("prompt", "max_new", "tokens", "score", "_event",
                 "_error", "t_enqueue", "t_admit", "t_first_token",
                 "t_retire", "prefill_chunks", "_span", "rid",
                 "sampling", "preemptions", "_seq")

    def __init__(self, prompt, max_new, request_id=None, sampling=None):
        self.prompt = [int(t) for t in prompt]
        self.max_new = int(max_new)
        # per-request sampling (ISSUE 10): None = bitwise-greedy (the
        # temperature-0 default every identity pin rides on)
        self.sampling = sampling
        self.preemptions = 0
        # admission priority: set once at FIRST admission and preserved
        # across preemption, so a preempted request re-admits at its
        # original priority instead of re-entering as "newest"
        self._seq = None
        # durable caller-assigned id (serving.fleet router): a request
        # RE-EXECUTED on a second replica after churn carries the SAME
        # id, so its serving.request spans on both replicas share the
        # rid attr — the resubmission hop is joinable in `trace merge`
        self.rid = request_id
        self.tokens = []
        self.score = None
        self._event = threading.Event()
        self._error = None
        self.t_enqueue = time.perf_counter()
        self.t_admit = None
        self.t_first_token = None
        self.t_retire = None
        self.prefill_chunks = 0
        attrs = {"prompt_len": len(self.prompt),
                 "max_new": self.max_new}
        if request_id is not None:
            attrs["rid"] = str(request_id)
        self._span = _trc.detached_span("serving.request", **attrs)
        self._span.start()

    @property
    def queue_wait(self):
        """Seconds from submit to decode-slot admission (None until
        admitted)."""
        if self.t_admit is None:
            return None
        return self.t_admit - self.t_enqueue

    @property
    def ttft(self):
        """Time to first token: submit -> first decoded token (the
        latency a streaming user perceives before output starts)."""
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_enqueue

    @property
    def tpot(self):
        """Mean per-token decode latency AFTER the first token (the
        steady streaming rate); 0.0 for single-token requests, None
        until retired."""
        if self.t_first_token is None or self.t_retire is None:
            return None
        n = len(self.tokens)
        if n <= 1:
            return 0.0
        return (self.t_retire - self.t_first_token) / (n - 1)

    def latency(self):
        """The request's lifecycle attribution as one JSON-able dict
        (what the ``serving_request`` recorder row carries)."""
        return {"queue_wait": self.queue_wait, "ttft": self.ttft,
                "tpot": self.tpot, "tokens": len(self.tokens),
                "prefill_chunks": self.prefill_chunks}

    def _finish(self, score):
        self.score = score
        self._event.set()

    def _fail(self, err):
        self._error = err
        self._event.set()

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                "request not finished within %r s" % (timeout,))
        if self._error is not None:
            raise RuntimeError(
                "serving engine failed: %r" % (self._error,))
        return list(self.tokens), self.score


def _flag(name, default):
    from .. import flags
    try:
        return flags.get_flag(name)
    except KeyError:
        return default


# the per-slot sampling state a greedy (default) request activates with
_GREEDY = SamplingParams()


class Engine:
    """Continuous-batching engine over a KV-cached incremental decoder.

    ``model`` is a ``models.transformer_infer.TransformerLMInfer`` (or
    anything exposing the same slot-step protocol: ``_init_state``,
    ``_step_logits_slots``, ``_prefill_chunk_slot``, ``max_len``,
    ``end_id``, ``bos_id``). ``slots`` is the fixed decode batch
    capacity; ``prefill_chunk`` the per-iteration prompt chunk length
    (flag ``serving_prefill_chunk``); ``admission_wait`` an optional
    wait-for-batch window in seconds applied when the engine is idle
    (flag ``serving_admission_wait``; 0 = greedy fill); ``megastep``
    fuses K decode iterations into ONE device dispatch whenever no
    admissions or prefills are pending (flag ``serving_megastep``;
    1 = one dispatch per decode step) — token-identical output with
    K-1 fewer host round-trips per K tokens, at the cost of TTFT/TPOT
    stamps coarsening to megastep granularity and admissions landing
    at megastep boundaries (a pending admission forces a K→1 boundary
    first).

    Paged KV (ISSUE 10; flags ``serving_paged`` /
    ``serving_block_size`` / ``serving_kv_blocks`` /
    ``serving_prefix_cache``): ``paged=True`` (the default) stores K/V
    in a shared block pool with per-slot block tables, a radix prefix
    cache over full-block prompt prefixes, copy-on-write for shared
    blocks, and preemption (lowest-priority request re-queued for
    re-prefill) when the pool runs dry. ``paged=False`` restores the
    PR-5 dense ``[slots, ...]`` layout. ``num_blocks`` defaults to
    ``slots * ceil(max_len / block_size)`` — dense-capacity parity,
    with the savings coming from short requests and shared prefixes.
    Greedy output is token-identical across both layouts; per-request
    ``sampling`` (``SamplingParams``) rides either.

    Speculative decode (ISSUE 13; flags ``serving_speculative`` /
    ``serving_spec_gamma`` / ``serving_spec_drafter`` /
    ``serving_spec_ngram`` / ``serving_spec_layers``):
    ``speculative=True`` drafts up to ``spec_gamma`` tokens per live
    slot each iteration and verifies all of them in one scoring
    dispatch — requires the paged layout (the ragged per-slot draft
    lengths ride the block-table gather). ``spec_drafter``: ``ngram``
    (default; host-side prompt/n-gram lookup, ``serving/spec.py``) or
    ``truncated`` (a ``spec_layers``-deep pass over the same weights,
    one extra fused dispatch per drafted iteration). ``spec_gamma=0``
    disables speculation outright — the engine is program-for-program
    the non-speculative one."""

    def __init__(self, model, slots=8, prefill_chunk=None,
                 admission_wait=None, name="engine", megastep=None,
                 paged=None, block_size=None, num_blocks=None,
                 prefix_cache=None, speculative=None, spec_gamma=None,
                 spec_drafter=None, spec_layers=None,
                 block_kernel=None, kv_quant=None):
        if slots < 1:
            raise ValueError("slots must be >= 1, got %r" % (slots,))
        from .artifact import is_artifact_path, model_from_artifact
        if is_artifact_path(model):
            # serving cold-start (ISSUE 15 / ROADMAP 2(b)): a
            # load_inference_model artifact directory in place of a
            # live model object — fleet.Replica passes its ``model``
            # straight here, so replicas boot from the artifact too
            model = model_from_artifact(model)
        self.model = model
        self.slots = int(slots)
        self.name = name
        # canary analysis plane (serving.fleet / serving.rollout):
        # `shadow` marks every row/metric this engine emits as mirrored
        # traffic — scored, never served — so the incumbent's SLO
        # histograms and the autoscaler's load signals never see it
        # (the PR-6 failed-request exclusion discipline, applied to
        # shadow decodes). `version` stamps the artifact version on
        # serving_request rows so candidate-vs-incumbent delta
        # objectives can split samples by version.
        self.shadow = False
        self.version = None
        self._chunk = int(prefill_chunk
                          if prefill_chunk is not None
                          else _flag("serving_prefill_chunk", 16))
        self._chunk = max(1, min(self._chunk, model.max_len))
        self._admission_wait = float(
            admission_wait if admission_wait is not None
            else _flag("serving_admission_wait", 0.0))
        # megastep K (ISSUE 7): decode iterations fused into ONE device
        # dispatch (lax.scan over _step_impl) whenever no admissions or
        # prefills are pending — K-1 fewer host round-trips per K
        # tokens, attacking the measured bs1 per-step dispatch floor
        # (PERF.md round 5). Admissions/retirement bookkeeping land at
        # megastep boundaries; output stays token-identical (same
        # per-iteration math, composed by scan). TTFT/TPOT attribution
        # coarsens to megastep granularity: all K tokens of one
        # dispatch land at the same host timestamp.
        self._megastep = max(1, int(megastep if megastep is not None
                                    else _flag("serving_megastep", 1)))
        # paged KV (ISSUE 10): host-side block accounting; the device
        # pool arrays live in self._state. Block tables are rebuilt as
        # a small [slots, max_blocks] int32 array per dispatch and
        # passed as a plain (non-donated) argument to the compiled
        # step — the compiled SHAPE never changes as tables do.
        self._paged = bool(paged if paged is not None
                           else _flag("serving_paged", True))
        if self._paged:
            bs = int(block_size if block_size is not None
                     else _flag("serving_block_size", 16))
            self._block_size = max(1, min(bs, model.max_len))
            self._max_blocks = -(-model.max_len // self._block_size)
            nb = int(num_blocks if num_blocks is not None
                     else _flag("serving_kv_blocks", 0))
            if nb <= 0:
                # capacity parity with the dense layout by default —
                # the paged win is that SHORT requests no longer pin
                # max_len worth of it, and shared prefixes share it
                nb = self.slots * self._max_blocks
            if nb < self._max_blocks:
                raise ValueError(
                    "num_blocks %d cannot hold one max_len request "
                    "(%d blocks of %d positions)"
                    % (nb, self._max_blocks, self._block_size))
            self._pool = _kvpool.BlockPool(nb, self._block_size)
            use_prefix = bool(
                prefix_cache if prefix_cache is not None
                else _flag("serving_prefix_cache", True))
            self._prefix = (_kvpool.RadixCache(self._block_size,
                                               self._pool)
                            if use_prefix else None)
            # block-native attention kernel (ISSUE 20): the default
            # decode path walks only each slot's live block chain
            # (ops/paged_attention online softmax); block_kernel=False
            # (flag serving_block_kernel=0) is the PR-10 dense-gather
            # escape hatch. attn_unroll: lax-fallback blocks per loop
            # trip. kv_quant ('int8' / 'fp8', OFF by default): pool
            # stores codes + per-vector scales — validated here so a
            # bad flag fails at construction, not at first trace.
            self._attn_unroll = max(1, int(_flag("serving_attn_unroll",
                                                 1)))
            kvq = (kv_quant if kv_quant is not None
                   else _flag("serving_kv_quant", ""))
            kvq = str(kvq or "").strip().lower()
            self._kv_quant = kvq if kvq not in ("", "none", "off") \
                else None
            _paged_ops.kv_quant_spec(self._kv_quant)   # validate
            # the kernel accumulates in fp32 — a DIFFERENT reduction
            # order than the dense row math, so the bf16 serving
            # cast's bitwise contract (engine == bf16 sequential
            # baseline) only holds on the gather path: low-precision
            # un-quantized pools keep gather by DEFAULT (explicit
            # block_kernel=True still opts in; quantized pools are
            # rtol-pinned, not bitwise, so they stay on the kernel)
            kern_ok = (self._kv_quant is not None
                       or jnp.dtype(model.word_emb.dtype)
                       == jnp.dtype(jnp.float32))
            self._block_kernel = bool(
                block_kernel if block_kernel is not None
                else (_flag("serving_block_kernel", True) and kern_ok))
            dk = model.d_model // model.n_head
            self._block_bytes = _kvpool.bytes_per_block(
                model.n_layer, model.n_head, self._block_size, dk,
                dtype_bytes=jnp.dtype(model.word_emb.dtype).itemsize,
                kv_quant=self._kv_quant)
        else:
            if kv_quant:
                raise ValueError(
                    "kv_quant requires the paged KV layout "
                    "(per-block scales live beside the block pool); "
                    "pass paged=True or drop kv_quant")
            self._pool = None
            self._prefix = None
            self._block_kernel = False
            self._attn_unroll = 1
            self._kv_quant = None
            self._block_bytes = 0
        # speculative decode (ISSUE 13): γ drafted tokens per live slot
        # verified in ONE scoring dispatch. γ is a STATIC shape
        # constant of the scoring program ([S, γ+1] feed), so one γ =
        # one compile (warmup() pays it up front); γ=0 or
        # speculative=False leaves every existing program untouched.
        self._spec_gamma = max(0, int(
            spec_gamma if spec_gamma is not None
            else _flag("serving_spec_gamma", 4)))
        spec_on = bool(speculative if speculative is not None
                       else _flag("serving_speculative", False))
        self._speculative = spec_on and self._spec_gamma > 0
        self._spec_fn = None
        self._draft_fn = None
        self._drafter = None
        self._spec_kind = None
        if self._speculative:
            if not self._paged:
                raise ValueError(
                    "speculative decode requires the paged KV layout "
                    "(per-slot ragged draft lengths ride the "
                    "block-table gather); pass paged=True or drop "
                    "speculative")
            kind = str(spec_drafter if spec_drafter is not None
                       else _flag("serving_spec_drafter", "ngram"))
            if kind not in ("ngram", "truncated"):
                raise ValueError(
                    "serving_spec_drafter must be 'ngram' or "
                    "'truncated', got %r" % (kind,))
            self._spec_kind = kind
            self._drafter = _spec.NgramDrafter(
                max_n=_flag("serving_spec_ngram", 3),
                min_n=_flag("serving_spec_ngram_min", 2))
            if kind == "truncated":
                nl = int(spec_layers if spec_layers is not None
                         else _flag("serving_spec_layers", 0))
                if nl <= 0:
                    nl = max(1, model.n_layer // 2)
                self._spec_layers = min(nl, model.n_layer)
                self._draft_fn = jax.jit(self._draft_truncated_impl,
                                         donate_argnums=0)
            self._spec_fn = jax.jit(self._spec_step_impl,
                                    donate_argnums=0, static_argnums=3)
        self._admit_seq = itertools.count()  # admission priority order
        self._preempted_iter = 0
        self._cv = threading.Condition()
        self._queue = collections.deque()
        self._recs = [None] * self.slots   # loop-thread-only slot records
        self._stop = False
        self._error = None                 # loop-death cause, if any
        self._state = self._init_state()
        # `sampled` is static (arg 2): two cached compiles — the
        # all-greedy program (bitwise PR-5) and, only once stochastic
        # traffic actually lands, the sampling-tail program
        self._step_fn = jax.jit(self._step_impl, donate_argnums=0,
                                static_argnums=2)
        self._megastep_fn = None           # built lazily (jit) at K > 1
        self._prefill_fn = jax.jit(self._prefill_impl, donate_argnums=0)
        self._activate_fn = jax.jit(self._activate_impl, donate_argnums=0)
        self._release_fn = None            # built lazily (preemption)
        self._copy_fn = None               # built lazily (COW)
        self.stats = {"steps": 0, "decode_steps": 0, "tokens": 0,
                      "admissions": 0, "retirements": 0,
                      "active_slot_steps": 0, "prefill_chunks": 0,
                      "megastep_dispatches": 0, "prefix_hits": 0,
                      "prefix_misses": 0, "prefix_hit_tokens": 0,
                      "prefix_evictions": 0, "preemptions": 0,
                      "cow_copies": 0, "kv_peak_blocks": 0,
                      "spec_dispatches": 0, "spec_drafted": 0,
                      "spec_accepted": 0, "spec_emitted": 0}
        # optional completion hook (serving.fleet's ReplicaServer):
        # called with each Request AFTER its future resolves — retired
        # or failed — so an RPC front can deliver results event-driven
        # instead of polling handles. Exceptions are swallowed: a
        # delivery hook must never kill the decode loop.
        self.on_retire = None
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="ptpu-" + name)
        self._thread.start()

    # -- public API --------------------------------------------------------
    def warmup(self, sampled=False):
        """Compile the GREEDY decode dispatch paths up front: the
        single step (paged or dense) and, with ``megastep`` > 1, the
        fused K-step twin. One decode over the ALL-INACTIVE slot state
        is semantically a no-op — the active mask gates every cache
        write (paged writes of masked rows drop out of bounds) and
        every sampling-state update — so this pays only the compiles.
        Call before submitting traffic (the scheduler loop never
        touches decode state while the queue and slots are empty).
        Without it a megastep engine compiles the single-step path
        lazily on its first mid-flight admission, stalling that
        iteration by a full XLA compile — and a PAGED K>1 engine
        previously compiled both paged paths mid-traffic (the
        PR-7-measured 660 ms stall). A SPECULATIVE engine additionally
        pre-compiles the γ-position scoring program (and the
        truncated-layer draft program with the tier-B drafter): γ is a
        static shape constant, so the first drafted batch would
        otherwise eat that compile mid-traffic. ``sampled=True`` additionally
        pre-compiles the sampling-tail variants — pass it when the
        workload will carry ``SamplingParams``, otherwise the first
        stochastic request eats those compiles mid-traffic (the
        greedy-only default keeps greedy benches from paying for
        programs they never dispatch)."""
        # the whole body holds _cv: a submit() racing in after the
        # guard would otherwise let the loop thread activate a slot in
        # self._state concurrently with warmup donating it (_step_fn
        # donate_argnums=0) or have the trailing reassignment discard
        # the activation — while _cv is held the loop stays parked in
        # its idle wait and submits block until warmup finishes
        with self._cv:
            if self._queue or any(r is not None for r in self._recs):
                raise RuntimeError(
                    "warmup() must run before traffic is submitted "
                    "(the scheduler loop owns the decode state once a "
                    "request is in flight)")
            btab = self._btab_all()
            variants = (False, True) if sampled else (False,)
            state = self._state
            for v in variants:
                state, _, _ = self._step_fn(state, btab, v)
                if self._megastep > 1:
                    if self._megastep_fn is None:
                        self._megastep_fn = jax.jit(
                            self._megastep_impl, donate_argnums=0,
                            static_argnums=2)
                    state, _, _ = self._megastep_fn(state, btab, v)
                if self._speculative:
                    # the speculative scoring program too (ISSUE 13
                    # satellite): γ is a static shape constant, so
                    # without this the first DRAFTED batch eats the
                    # scoring compile mid-traffic — the exact stall
                    # PR 7/10 killed twice for the step/megastep paths
                    zdn = jnp.zeros(
                        (self.slots, self._spec_gamma + 1), jnp.int32)
                    state, _ = self._spec_fn(state, btab, zdn, v)
            if self._speculative and self._draft_fn is not None:
                state, _ = self._draft_fn(
                    state, btab, jnp.zeros((self.slots,), jnp.int32))
            self._state = state
        return self

    def submit(self, prompt, max_new_tokens, request_id=None,
               sampling=None):
        """Enqueue one request; returns its Request handle. ``prompt``
        is the token-id prefix (≥ 1 token — pass ``[model.bos_id]`` for
        unconditional generation). ``request_id``: optional durable id
        (the fleet router's exactly-once key) stamped on the handle and
        its trace span — admission itself never dedups; the fleet tier
        (ReplicaServer journal) is where resubmitted ids are made
        idempotent BEFORE they reach the engine. ``sampling``: a
        ``SamplingParams`` (or its dict form, the fleet wire shape);
        None / temperature 0 = bitwise-greedy."""
        prompt = [int(t) for t in (prompt or [self.model.bos_id])]
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise ValueError(
                "max_new_tokens must be >= 1, got %d" % max_new)
        # cache positions used: prompt at 0..P-1, generated tokens
        # continue to P+max_new-2 — past max_len the pos-emb gather and
        # the cache writes would clamp and corrupt state; fail loudly
        if len(prompt) + max_new - 1 > self.model.max_len:
            raise ValueError(
                "prompt len %d + max_new %d exceeds model max_len %d"
                % (len(prompt), max_new, self.model.max_len))
        # validate BEFORE the handle exists (same ValueError surface as
        # the bounds above, so the fleet's BADR typed-reject covers it)
        sp = (SamplingParams.from_dict(sampling)
              if sampling is not None else None)
        if sp is not None and sp.greedy:
            # temperature 0 is argmax no matter what top_k/top_p/seed
            # say — fold to the default so a temp-0 request never
            # forces co-scheduled traffic onto the sampled program
            sp = None
        with self._cv:
            if self._stop:
                err = getattr(self, "_error", None)
                if err is not None:
                    raise RuntimeError(
                        "engine is closed (loop died: %r)" % (err,))
                raise RuntimeError("engine is closed")
            # construct after the closed-check: a rejected submit must
            # not open a request span nobody will ever finish
            req = Request(prompt, max_new, request_id=request_id,
                          sampling=sp)
            self._queue.append(req)
            self._cv.notify_all()
        return req

    @staticmethod
    def result(request, timeout=None):
        return request.result(timeout)

    def generate_many(self, prompts, max_new_tokens):
        """Synchronous convenience: submit every prompt, block for all
        results (in input order). ``max_new_tokens`` is a scalar or a
        per-prompt sequence."""
        n = len(prompts)
        if not hasattr(max_new_tokens, "__len__"):
            max_new_tokens = [max_new_tokens] * n
        reqs = [self.submit(p, m)
                for p, m in zip(prompts, max_new_tokens)]
        return [r.result() for r in reqs]

    def occupancy(self):
        """Mean active-slot fraction over the decode steps run so far."""
        d = self.stats["decode_steps"] * self.slots
        return self.stats["active_slot_steps"] / d if d else 0.0

    def close(self):
        """Stop the engine loop. Requests still queued or in flight are
        failed (their ``result()`` raises)."""
        with self._cv:
            already = self._stop
            self._stop = True
            self._cv.notify_all()
        if already:
            return
        self._thread.join()
        self._fail_all(RuntimeError("engine closed"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- compiled pieces ---------------------------------------------------
    def _init_state(self):
        if self._paged:
            s = self.model._init_paged_state(self._pool.num_blocks,
                                             self._block_size,
                                             kv_quant=self._kv_quant)
        else:
            s = self.model._init_state(self.slots)
        z = lambda dt: jnp.zeros((self.slots,), dt)
        s["tok"], s["pos"], s["count"] = z(jnp.int32), z(jnp.int32), \
            z(jnp.int32)
        s["active"] = z(bool)
        s["score"] = z(jnp.float32)
        s["max_new"] = jnp.ones((self.slots,), jnp.int32)
        # per-slot sampling state (ISSUE 10): zeros = bitwise-greedy
        s["temp"] = z(jnp.float32)
        s["topk"] = z(jnp.int32)
        s["topp"] = jnp.ones((self.slots,), jnp.float32)
        s["seed"] = z(jnp.uint32)
        return s

    def _step_impl(self, state, btab, sampled=False):
        """One decode iteration over all slots: sample every active
        slot (argmax for temperature-0 slots — the bitwise-greedy
        default — a per-slot counter-keyed draw otherwise), advance
        its cache position, flag retirements. ``btab`` is the
        [slots, max_blocks] block-table array in paged mode, None in
        dense mode (the PR-5 layout). ``sampled`` is STATIC (a
        separate compile per value): the host dispatches the sampled
        program only while a stochastic request is live, so the
        all-greedy hot path never pays the per-slot PRNG + two vocab
        sorts (measured ~0.33 ms/step on this CPU — ~2.7x the whole
        greedy step) and stays instruction-for-instruction the PR-5
        program."""
        state = dict(state)
        tok, pos, active = state["tok"], state["pos"], state["active"]
        if self._paged:
            logits, state = self.model._step_logits_paged(
                tok, state, pos, btab, write_mask=active,
                block_kernel=self._block_kernel,
                attn_unroll=self._attn_unroll)
        else:
            logits, state = self.model._step_logits_slots(
                tok, state, pos, write_mask=active)
        logits32 = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits32)
        greedy = jnp.argmax(logp, axis=-1).astype(jnp.int32)
        if sampled:
            # per-slot draw, SELECTED per slot: temperature-0 slots
            # take the greedy value through an elementwise where, so
            # their tokens are bitwise the greedy program's
            keys = _step_keys(state["seed"], state["count"])
            drawn = _sample(logits32, state["temp"], state["topk"],
                            state["topp"], keys)
            nxt = jnp.where(state["temp"] > 0.0, drawn, greedy)
        else:
            nxt = greedy
        tok_logp = jnp.take_along_axis(logp, nxt[:, None], axis=-1)[:, 0]
        end = jnp.int32(self.model.end_id)
        emit = jnp.where(active, nxt, end)
        count = state["count"] + active.astype(jnp.int32)
        fin = active & ((nxt == end) | (count >= state["max_new"]))
        state["score"] = state["score"] + jnp.where(active, tok_logp, 0.0)
        state["tok"] = jnp.where(active, nxt, tok)
        state["pos"] = pos + active.astype(jnp.int32)
        state["count"] = count
        state["active"] = active & ~fin
        return state, emit, fin

    def _megastep_impl(self, state, btab, sampled=False):
        """K decode iterations fused into one device program: a
        lax.scan over ``_step_impl``, streaming each sub-iteration's
        (emit, fin) rows out as ``[K, S]`` stacks. A slot that retires
        at sub-iteration j goes inactive in the carry, so later
        sub-iterations emit end_id for it and write nothing — the host
        loop skips those rows, keeping output token-identical to K
        single steps. In paged mode the host pre-allocates blocks for
        all K write positions, so one table serves the whole fused
        dispatch. ``sampled`` is static, like ``_step_impl``'s."""
        def body(st, _):
            st, emit, fin = self._step_impl(st, btab, sampled)
            return st, (emit, fin)

        state, (emits, fins) = jax.lax.scan(
            body, dict(state), None, length=self._megastep)
        return state, emits, fins

    def _spec_step_impl(self, state, btab, dn, sampled=False):
        """Speculative scoring + in-step acceptance (ISSUE 13): ONE
        paged-attention dispatch scores every slot's current token plus
        its drafted tokens, then accepts the longest prefix of drafts
        matching the model's OWN next tokens — greedy argmax for
        temperature-0 slots, the counter-keyed draw
        (``fold_in(seed, tokens_generated + j)``) for sampled slots,
        position-indexed exactly as j successive single steps would
        have drawn. Emitting only those tokens is what makes
        speculative output bitwise the non-speculative engine's: a
        WRONG draft costs a rejection, never a wrong token.

        ``dn`` [S, γ+1] int32 packs the per-slot draft length (column
        0, ragged 0..γ) with the γ draft tokens — ONE host→device
        transfer per dispatch; the reply packs emits/n_emit/fin into
        one int32 fetch the same way (the per-dispatch host tax is on
        the bs1 floor this feature exists to break).

        Returns ``(state, out [S, γ+3])``: columns 0..γ are the
        emitted tokens (end_id filler past each slot's count), column
        γ+1 the per-slot emit count (1..γ+1 for active slots — the
        bonus token the scoring logits buy rides every dispatch,
        truncated at EOS inside an accepted draft and at the slot's
        ``max_new`` budget), column γ+2 the retirement flag. Cache
        position / count / score / PRNG counter advance by the emit
        count, so the next dispatch (speculative or not) continues
        exactly where K single steps would have."""
        state = dict(state)
        tok, pos, active = state["tok"], state["pos"], state["active"]
        count = state["count"]
        drafts = dn[:, 1:]
        c = drafts.shape[1] + 1
        toks = jnp.concatenate([tok[:, None], drafts], axis=1)
        nd = jnp.where(active, dn[:, 0], 0)
        logits, state = self.model._spec_logits_paged(
            toks, state, pos, btab, nd, write_mask=active,
            block_kernel=self._block_kernel,
            attn_unroll=self._attn_unroll)
        logits32 = logits.astype(jnp.float32)        # [S, C, V]
        logp = jax.nn.log_softmax(logits32)
        greedy = jnp.argmax(logp, axis=-1).astype(jnp.int32)
        if sampled:
            s = tok.shape[0]
            counts = count[:, None] + jnp.arange(c)[None, :]
            keys = _step_keys(jnp.repeat(state["seed"], c),
                              counts.reshape(-1))
            rep = lambda a: jnp.repeat(a, c)
            drawn = _sample(logits32.reshape(s * c, -1),
                            rep(state["temp"]), rep(state["topk"]),
                            rep(state["topp"]), keys).reshape(s, c)
            target = jnp.where((state["temp"] > 0.0)[:, None], drawn,
                               greedy)
        else:
            target = greedy
        # accept-longest-prefix: draft j+1 must equal the model's own
        # token at position j (cumprod stops at the first mismatch)
        match = (toks[:, 1:] == target[:, :-1]) \
            & (jnp.arange(c - 1)[None, :] < nd[:, None])
        m = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                    axis=1)                          # accepted drafts
        ncap = jnp.minimum(m + 1, state["max_new"] - count)
        jj = jnp.arange(c)[None, :]
        is_end = (target == jnp.int32(self.model.end_id)) \
            & (jj < ncap[:, None])
        end_pos = jnp.min(jnp.where(is_end, jj, c), axis=1)
        n_emit = jnp.where(active, jnp.minimum(ncap, end_pos + 1), 0)
        fin = active & ((end_pos < ncap)
                        | (count + n_emit >= state["max_new"]))
        emit_mask = jj < n_emit[:, None]
        tok_logp = jnp.take_along_axis(
            logp, target[:, :, None], axis=-1)[:, :, 0]
        state["score"] = state["score"] + jnp.sum(
            jnp.where(emit_mask, tok_logp, 0.0), axis=1)
        last = jnp.maximum(n_emit - 1, 0)
        new_tok = jnp.take_along_axis(target, last[:, None],
                                      axis=1)[:, 0]
        state["tok"] = jnp.where(active, new_tok, tok)
        state["pos"] = pos + n_emit
        state["count"] = count + n_emit
        state["active"] = active & ~fin
        emits = jnp.where(emit_mask, target,
                          jnp.int32(self.model.end_id))
        out = jnp.concatenate(
            [emits, n_emit[:, None], fin.astype(jnp.int32)[:, None]],
            axis=1)
        return state, out

    def _draft_truncated_impl(self, state, btab, n_draft):
        """Tier-B drafter: γ greedy decode steps through only the
        FIRST ``spec_layers`` transformer layers (same weights, same
        paged pool), scanned into ONE dispatch. Draft K/V lands only
        at the truncated layers of positions the scoring dispatch
        immediately re-writes at FULL depth, so the drafter needs no
        KV state of its own; writes beyond a slot's ``n_draft`` budget
        are masked (they would fall past its block table). Returns
        ``(state, drafts [S, γ])``. Draft quality only moves the
        acceptance rate — never the output."""
        state = dict(state)
        active = state["active"]
        pool = self.model._pool_slice(state)

        def body(carry, _):
            pool, tok, pos, j = carry
            wmask = active & (j <= n_draft)
            logits, pool = self.model._step_logits_paged(
                tok, pool, pos, btab, write_mask=wmask,
                n_layers=self._spec_layers,
                block_kernel=self._block_kernel,
                attn_unroll=self._attn_unroll)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (pool, nxt, pos + 1, j + 1), nxt

        (pool, _, _, _), drafts = jax.lax.scan(
            body,
            (pool, state["tok"], state["pos"],
             jnp.zeros((), jnp.int32)),
            None, length=self._spec_gamma)
        state.update(pool)
        return state, jnp.transpose(drafts)          # [γ,S] → [S,γ]

    def _prefill_impl(self, state, slot, toks, start, n_valid,
                      btab_row):
        if self._paged:
            return self.model._prefill_chunk_paged(
                dict(state), toks, start, n_valid, btab_row,
                block_kernel=self._block_kernel,
                attn_unroll=self._attn_unroll)
        return self.model._prefill_chunk_slot(
            dict(state), slot, toks, start, n_valid)

    def _activate_impl(self, state, slot, tok, pos, max_new, temp,
                       topk, topp, seed):
        state = dict(state)
        at = lambda n, v: state[n].at[slot].set(v)
        state["tok"] = at("tok", tok)
        state["pos"] = at("pos", pos)
        state["active"] = at("active", True)
        state["score"] = at("score", 0.0)
        state["count"] = at("count", 0)
        state["max_new"] = at("max_new", max_new)
        state["temp"] = at("temp", temp)
        state["topk"] = at("topk", topk)
        state["topp"] = at("topp", topp)
        state["seed"] = at("seed", seed)
        return state

    def _release_impl(self, state, slot):
        """Deactivate one slot (preemption): the write mask goes False
        so the slot's stale tok/pos can never write again; everything
        else resets at re-activation."""
        state = dict(state)
        state["active"] = state["active"].at[slot].set(False)
        return state

    def _copy_impl(self, state, src, dst):
        """Copy-on-write: duplicate one physical block's K/V (every
        layer) so a request whose FULLY block-aligned prompt matched
        the cache can write its first decode position privately."""
        state = dict(state)
        for name in ("pool_k", "pool_v", "pool_ks", "pool_vs"):
            if name not in state:
                continue
            a = state[name]
            state[name] = a.at[dst].set(a[src])
        return state

    # -- paged-KV host accounting (loop thread only) -----------------------
    def _btab_all(self):
        """The [slots, max_blocks] int32 block-table array the compiled
        step gathers through (dense mode: None). Unassigned entries
        read block 0, masked by the causal bias."""
        if not self._paged:
            return None
        arr = np.zeros((self.slots, self._max_blocks), np.int32)
        for s, rec in enumerate(self._recs):
            if rec is not None:
                t = rec["table"]
                arr[s, :len(t)] = t
        return arr

    def _btab_row(self, rec):
        row = np.zeros((self._max_blocks,), np.int32)
        t = rec["table"]
        row[:len(t)] = t
        return row

    def _ensure_blocks(self, rec, last_pos):
        """Grow ``rec``'s block table to cover cache position
        ``last_pos``, walking the pressure ladder on a dry pool:
        prefix-cache LRU eviction first, then PREEMPTION of the
        lowest-priority (latest-admitted) request. Returns False when
        ``rec`` itself was the preemption victim (the caller must stop
        touching it — its slot record is gone)."""
        last_pos = min(int(last_pos), self.model.max_len - 1)
        need = last_pos // self._block_size + 1 - len(rec["table"])
        for _ in range(need):
            b = self._alloc_one(rec)
            if b is None:
                return False
            rec["table"].append(b)
            rec["refs"].append(b)
        return True

    def _alloc_one(self, rec, preempt=True):
        """One block for ``rec``, or None when ``rec`` was preempted to
        make room (self-preemption: the pool cannot serve it without
        taking blocks from strictly HIGHER-priority — earlier-admitted
        — requests, so ``rec`` yields instead; with admission
        priorities preserved across preemption this cannot ping-pong,
        the oldest request always keeps its blocks and finishes).
        ``preempt=False`` stops the pressure ladder after the
        prefix-eviction rung and returns None with ``rec`` untouched —
        the speculative soft-growth contract (OPTIONAL draft positions
        must never evict committed work)."""
        while True:
            got = self._pool.alloc(1)
            if got is not None:
                return got[0]
            if self._prefix is not None:
                freed = self._prefix.evict(1)
                if freed:
                    self.stats["prefix_evictions"] += freed
                    _monrt.on_prefix_evictions(freed)
                    continue
            if not preempt:
                return None
            victim = self._pick_victim()
            if victim is None or victim["seq"] <= rec["seq"]:
                # nobody holds blocks, or every holder outranks rec
                # (rec included: victim is rec covers itself here) —
                # rec yields rather than evicting head-of-line work
                self._preempt(rec)
                return None
            self._preempt(victim)

    def _pick_victim(self):
        """Lowest-priority slot record = the latest-admitted (highest
        admission sequence) AMONG records that actually hold blocks:
        FIFO traffic keeps its head-of-line work running and pushes
        the tail back to the queue. A zero-block record (admitted,
        lazy allocation not yet run) cannot relieve pool pressure —
        preempting it would churn the request and inflate the
        preemption telemetry for nothing."""
        victim = None
        for r in self._recs:
            if r is not None and r["refs"] and (
                    victim is None or r["seq"] > victim["seq"]):
                victim = r
        return victim

    def _preempt(self, rec):
        """Free a record's blocks and RE-QUEUE its request (front of
        the queue — it keeps its priority) for re-prefill. Output
        stays identical on resume: greedy decode is deterministic and
        sampled decode draws through fold_in(seed, tokens_generated),
        which restarts with the request — so the caller-visible result
        (and the fleet's exactly-once dedup) cannot tell a preempted
        request from an undisturbed one. The partial tokens are
        discarded; TTFT keeps the FIRST first-token stamp (the user
        saw nothing either way, and a preemption must not flatter
        it)."""
        slot = next(s for s, r in enumerate(self._recs) if r is rec)
        req = rec["req"]
        self._release_blocks(rec)
        self._recs[slot] = None
        if rec["live"]:
            if self._release_fn is None:
                self._release_fn = jax.jit(self._release_impl,
                                           donate_argnums=0)
            self._state = self._release_fn(self._state, np.int32(slot))
        del req.tokens[:]
        req.score = None
        req.preemptions += 1
        req._span.annotate(preemptions=req.preemptions)
        self.stats["preemptions"] += 1
        self._preempted_iter += 1
        with self._cv:
            self._queue.appendleft(req)

    def _cow(self, rec, bi):
        """Copy-on-write of shared block ``bi`` in ``rec``'s table (the
        fully-block-aligned-prompt case: activation must write the
        last prompt position into a block the prefix cache shares).
        Returns False when the allocation preempted ``rec``."""
        new = self._alloc_one(rec)
        if new is None:
            return False
        old = rec["table"][bi]
        if self._copy_fn is None:
            self._copy_fn = jax.jit(self._copy_impl, donate_argnums=0)
        self._state = self._copy_fn(self._state, np.int32(old),
                                    np.int32(new))
        rec["table"][bi] = new
        rec["refs"][rec["refs"].index(old)] = new
        self._pool.free(old)           # drop the reader ref on the
        rec["shared"] = bi             # shared copy; cache keeps its own
        self.stats["cow_copies"] += 1
        return True

    def _grow_blocks_soft(self, rec, last_pos):
        """Best-effort table growth for SPECULATIVE write positions:
        the shared allocation ladder minus its preemption rung
        (``_alloc_one(preempt=False)``) — drafts are optional work,
        and taking committed blocks for a guess would churn real
        progress (worst case, a request self-preempting for its own
        drafts forever). Returns the highest position the table now
        covers; the caller shrinks the draft to fit."""
        last_pos = min(int(last_pos), self.model.max_len - 1)
        need = last_pos // self._block_size + 1 - len(rec["table"])
        for _ in range(max(0, need)):
            b = self._alloc_one(rec, preempt=False)
            if b is None:
                break
            rec["table"].append(b)
            rec["refs"].append(b)
        return len(rec["table"]) * self._block_size - 1

    def _publish_prefix(self, rec, req):
        """Publish a slot's full prompt blocks to the prefix cache
        after its first decode emit (position P-1 is then complete, so
        every full prompt block is). Refcounted — the request keeps
        its own refs. Keyed on the RECORD (fresh each admission), not
        t_first_token: a request preempted after its first token but
        before publishing must still publish on resume;
        re-publishing an already-cached chain dedups to a no-op."""
        if not self._paged or self._prefix is None or rec["inserted"]:
            return
        rec["inserted"] = True
        bs = self._block_size
        nfull = len(req.prompt) // bs
        if nfull:
            self._prefix.insert(req.prompt[:nfull * bs],
                                rec["table"][:nfull])

    def _release_blocks(self, rec):
        """Drop every pool ref the record holds (own allocations AND
        matched prefix-cache readers — the refcount protocol makes the
        two indistinguishable here)."""
        for b in rec["refs"]:
            self._pool.free(b)
        rec["refs"] = []
        rec["table"] = []

    # -- scheduler loop ----------------------------------------------------
    def _loop(self):
        try:
            while True:
                with self._cv:
                    while (not self._stop and not self._queue
                           and all(r is None for r in self._recs)):
                        self._cv.wait()
                    if self._stop:
                        return
                self._step_once()
        except BaseException as e:      # a dead loop must not hang callers
            with self._cv:
                # later submits must raise, not enqueue into a queue
                # nobody drains
                self._stop = True
                self._error = e
            self._fail_all(e)

    def _choose_k(self):
        """Megastep K for THIS iteration: fuse only when nothing needs
        a host decision between decode steps — no queued admissions, no
        prefilling slot. A pending admission/prefill forces a K→1
        boundary so scheduling latency never stretches to K steps."""
        if self._megastep <= 1:
            return 1
        with self._cv:
            if self._queue:
                return 1
        if any(r is not None and not r["live"] for r in self._recs):
            return 1
        return self._megastep

    def _step_once(self):
        """One engine iteration = admissions + one prefill chunk per
        prefilling slot + one decode dispatch (a single step, or a
        fused K-step megastep when no admissions/prefills pend) over
        the active batch."""
        finished = ()
        self._preempted_iter = 0
        try:
            with _trc.span("engine.step") as sp:
                admitted = self._admit()
                # dt clock starts AFTER _admit: the deliberate
                # wait-for-batch window (serving_admission_wait) is
                # admission POLICY, and folding its idle sleep into
                # step latency would fail a step_latency SLO for a
                # batching knob the operator chose
                t0 = time.perf_counter()
                self._advance_prefills()
                k = self._choose_k()
                (active, finished, steps_run, emitted,
                 trips) = self._decode(k)
                with self._cv:
                    depth = len(self._queue)
                self.stats["steps"] += 1
                self.stats["admissions"] += admitted
                self.stats["retirements"] += len(finished)
                dt = time.perf_counter() - t0
                # the span's DURATION covers the whole iteration
                # (admission wait included); the dt attr carries the
                # PER-LOGICAL-STEP figure — the post-admit wall time
                # divided by the scan trips the dispatch ran — same as
                # the recorder row, so the SLO --spans surface gates
                # the identical quantity as --log at any K. k = decode
                # steps actually consumed (a drain-tail megastep can
                # consume fewer than it dispatched).
                per = dt / max(1, trips)
                sp.annotate(active=active, admitted=admitted,
                            retired=len(finished), queue=depth, dt=per,
                            k=steps_run,
                            **({"megastep_dt": dt} if trips > 1
                               else {}))
                kv = {}
                if self._paged:
                    used = self._pool.used
                    self.stats["kv_peak_blocks"] = max(
                        self.stats["kv_peak_blocks"], used)
                    kv = {"kv_used": used,
                          "kv_total": self._pool.num_blocks,
                          "kv_bytes_used": used * self._block_bytes,
                          "kv_bytes_total": (self._pool.num_blocks
                                             * self._block_bytes),
                          "prefix_hits": self.stats["prefix_hits"],
                          "prefix_misses": self.stats["prefix_misses"],
                          "preempted": self._preempted_iter}
                    if self._speculative:
                        # CUMULATIVE like the prefix counters: a
                        # window's acceptance rate is last-row
                        # arithmetic, never a sum
                        kv["spec_drafted"] = self.stats["spec_drafted"]
                        kv["spec_accepted"] = \
                            self.stats["spec_accepted"]
                        kv["spec_emitted"] = self.stats["spec_emitted"]
                        kv["spec_dispatches"] = \
                            self.stats["spec_dispatches"]
                _monrt.on_serving_step(
                    active=active, slots=self.slots, queue_depth=depth,
                    emitted=emitted, admitted=admitted,
                    retired=len(finished), engine=self.name, dt=dt,
                    k=steps_run, dispatched=trips,
                    shadow=self.shadow, version=self.version, **kv)
                for req, _ in finished:
                    self._retire_telemetry(req)
        finally:
            # wake waiters LAST: a caller returning from result() must
            # see this iteration's stats/metrics/lifecycle stamps
            # already landed. finally: a request popped from its slot
            # by _decode is in `finished` ONLY — if instrumentation
            # throws (e.g. a full disk under an armed recorder),
            # _fail_all can no longer see it, so its future MUST
            # resolve here or result() blocks forever.
            for req, score in finished:
                req._finish(score)
            cb = self.on_retire
            if cb is not None:
                for req, _ in finished:
                    try:
                        cb(req)
                    except Exception:
                        pass

    def _retire_telemetry(self, req, error=None):
        """Per-request attribution at retirement: TTFT/TPOT/queue_wait
        histograms + a ``serving_request`` recorder row + the request
        span closed with the same figures annotated. Never raises —
        attribution is telemetry, and an exception here (mid-loop in
        _step_once or _fail_all) would strand the remaining requests'
        futures."""
        try:
            lat = req.latency()
            ctx = req._span.ctx
            _monrt.on_serving_request(
                engine=self.name, queue_wait=lat["queue_wait"],
                ttft=lat["ttft"],
                # a single-token request has NO inter-token interval:
                # its handle reports tpot 0.0 (documented), but 0.0 in
                # the histogram/samples would drag TPOT percentiles
                # toward a rate that was never measured
                tpot=lat["tpot"] if lat["tokens"] > 1 else None,
                tokens=lat["tokens"],
                prefill_chunks=lat["prefill_chunks"],
                prompt_len=len(req.prompt),
                # with the tail ring armed, unsampled traces are still
                # buffered in memory — stamp the id so a later
                # retention promotion can correlate this row to them
                trace_id=(ctx.trace_id
                          if ctx is not None
                          and (ctx.sampled or _trc.tail_armed())
                          else None),
                shadow=self.shadow, version=self.version,
                error=None if error is None else repr(error))
            req._span.annotate(
                **{k: v for k, v in lat.items() if v is not None})
        except Exception:
            pass
        try:
            req._span.finish(error=error)
        except Exception:
            pass

    @staticmethod
    def _step_span_id():
        """The ambient engine.step span id (loop thread), or None —
        stamped on request child spans so the merged timeline can join
        a request's lane to the engine iterations that drove it.
        Mirrors the sampled check _retire_telemetry does for the trace
        id: an UNSAMPLED step span is never written to the span log,
        and a dangling join reference would be worse than none — unless
        the tail ring is armed, in which case the unsampled step span
        IS buffered and a retention promotion can resolve the join."""
        cur = _trc.current_span()
        ctx = getattr(cur, "ctx", None)
        if ctx is None or not (ctx.sampled or _trc.tail_armed()):
            return None
        return ctx.span_id

    def _admit(self):
        admitted = 0
        with self._cv:
            if (self._admission_wait > 0 and self._queue
                    and all(r is None for r in self._recs)
                    and len(self._queue) < self.slots):
                # wait-for-batch window: the engine is idle, so give the
                # queue a beat to fill before compiling a sparse batch
                self._cv.wait_for(
                    lambda: self._stop
                    or len(self._queue) >= self.slots,
                    timeout=self._admission_wait)
            for slot in range(self.slots):
                if not self._queue:
                    break
                if self._recs[slot] is None:
                    req = self._queue.popleft()
                    req.t_admit = time.perf_counter()
                    req._span.annotate(slot=slot,
                                       queue_wait=req.queue_wait,
                                       admit_step=self._step_span_id())
                    if req._seq is None:      # re-admission after a
                        req._seq = next(self._admit_seq)  # preemption
                    rec = {"req": req, "cursor": 0, "live": False,
                           "seq": req._seq}   # keeps its priority
                    if self._paged:
                        self._admit_paged(rec)
                    self._recs[slot] = rec
                    admitted += 1
        return admitted

    def _admit_paged(self, rec):
        """Paged admission: look the prompt up in the radix prefix
        cache. A hit hands the record a refcounted chain of shared
        blocks holding the prefix's K/V, and the prefill cursor jumps
        past them — those chunks are never executed (the measured
        prefill-compute saving for shared-system-prompt traffic).
        Own-block allocation stays lazy (prefill/decode time): an
        admission allocates nothing it has not reached yet."""
        req = rec["req"]
        rec["table"], rec["refs"] = [], []
        rec["shared"] = 0
        rec["inserted"] = False
        rec["next_pos"] = None
        if self._prefix is None:
            return
        blocks, ntok = self._prefix.match(req.prompt)
        hit = bool(blocks)
        self.stats["prefix_hits" if hit else "prefix_misses"] += 1
        _monrt.on_prefix_lookup(hit)
        if not hit:
            return
        rec["table"] = list(blocks)
        rec["refs"] = list(blocks)
        rec["shared"] = len(blocks)
        # the teacher-forced prefill covers positions 0..P-2; a chain
        # covering the WHOLE block-aligned prompt leaves cursor at
        # need, and activation copy-on-writes the last shared block
        rec["cursor"] = min(ntok, len(req.prompt) - 1)
        self.stats["prefix_hit_tokens"] += rec["cursor"]
        req._span.annotate(prefix_hit_tokens=rec["cursor"])

    def _advance_prefills(self):
        """One prompt chunk per prefilling slot per iteration — long
        prompts interleave with the running batch instead of stalling
        it. A slot whose prefix is fully written activates (its LAST
        prompt token seeds the first decode step). Paged mode grows
        the slot's block table just ahead of the chunk's write
        positions (possibly evicting prefix chains / preempting), and
        a prefix-cache hit enters here with its cursor already past
        the cached positions."""
        for slot, rec in enumerate(self._recs):
            if rec is None or rec["live"]:
                continue
            req = rec["req"]
            need = len(req.prompt) - 1      # teacher-forced prefix
            cur = rec["cursor"]
            if cur < need:
                toks = req.prompt[cur:min(cur + self._chunk, need)]
                if self._paged and not self._ensure_blocks(
                        rec, cur + len(toks) - 1):
                    continue               # rec preempted back to queue
                chunk = np.zeros((self._chunk,), np.int32)
                chunk[:len(toks)] = toks
                with _trc.child_span(
                        "request.prefill_chunk", req._span, start=cur,
                        tokens=len(toks),
                        step_span=self._step_span_id()):
                    self._state = self._prefill_fn(
                        self._state, np.int32(slot), chunk,
                        np.int32(cur), np.int32(len(toks)),
                        self._btab_row(rec) if self._paged else None)
                rec["cursor"] = cur + len(toks)
                req.prefill_chunks += 1
                self.stats["prefill_chunks"] += 1
            if rec["cursor"] >= need:
                if self._paged:
                    # the first decode step writes position `need`
                    if not self._ensure_blocks(rec, need):
                        continue
                    bi = need // self._block_size
                    if bi < rec["shared"] and not self._cow(rec, bi):
                        continue
                    rec["next_pos"] = need
                sp = req.sampling or _GREEDY
                self._state = self._activate_fn(
                    self._state, np.int32(slot),
                    np.int32(req.prompt[-1]), np.int32(need),
                    np.int32(req.max_new),
                    np.float32(sp.temperature), np.int32(sp.top_k),
                    np.float32(sp.top_p), np.uint32(sp.seed))
                rec["live"] = True

    def _spec_cap(self, rec):
        """How many draft tokens this live slot can USE: bounded by γ,
        by its remaining ``max_new`` budget (n accepted drafts emit
        n+1 tokens), and by ``max_len`` (the scoring dispatch writes
        positions ``next_pos .. next_pos+n``)."""
        req = rec["req"]
        return min(self._spec_gamma,
                   req.max_new - len(req.tokens) - 1,
                   self.model.max_len - 1 - rec["next_pos"])

    def _build_drafts(self):
        """The drafting tier of one speculative iteration: propose up
        to γ tokens per live slot (tier A: host n-gram lookup over the
        request's own chain + the radix cache's published chains;
        tier B: one truncated-layer dispatch), then grow block tables
        to cover every drafted write position (the pressure ladder may
        preempt here — a vanished record's drafts are zeroed). Returns
        ``(drafts [S, γ] int32, n_draft [S] int32)``, or ``(None,
        None)`` when NO slot drafted — the caller then runs the
        existing plain/megastep programs, so a draftless iteration
        costs exactly what a non-speculative engine pays."""
        g = self._spec_gamma
        nd = np.zeros((self.slots,), np.int32)
        drafts = np.zeros((self.slots, g), np.int32)
        if self._spec_kind == "truncated":
            for slot in range(self.slots):
                rec = self._recs[slot]
                if rec is not None and rec["live"]:
                    nd[slot] = max(0, self._spec_cap(rec))
        else:
            chains = None
            for slot in range(self.slots):
                rec = self._recs[slot]
                if rec is None or not rec["live"]:
                    continue
                req = rec["req"]
                cap = self._spec_cap(rec)
                if cap <= 0:
                    continue
                if chains is None:       # one trie walk per iteration
                    chains = (self._prefix.token_chains()
                              if self._prefix is not None else ())
                prop = self._drafter.propose(req.prompt + req.tokens,
                                             cap, extra_chains=chains)
                if prop:
                    drafts[slot, :len(prop)] = prop
                    nd[slot] = len(prop)
        if not nd.any():
            return None, None
        # block coverage for the WHOLE dispatch, in two tiers. EVERY
        # live slot writes its next position even with zero drafts (it
        # rides the scoring dispatch as a plain step), so the
        # mandatory single-step coverage walks the full pressure
        # ladder exactly like the plain path — skipping a draftless
        # slot here would let its boundary-crossing write land in an
        # uncovered table entry (block 0: ANOTHER request's cache).
        # Draft positions are OPTIONAL work and only grow best-effort
        # (never preempting): evicting committed progress — worst
        # case, self-preempting in a loop — to make room for a guess
        # would turn speculation into churn. Re-read each record per
        # slot: an earlier slot's mandatory growth may have preempted
        # this one.
        for slot in range(self.slots):
            rec = self._recs[slot]
            if rec is None or not rec["live"]:
                nd[slot] = 0
                continue
            if not self._ensure_blocks(rec, rec["next_pos"]):
                nd[slot] = 0           # rec yielded its own slot
                continue
            if nd[slot]:
                covered = self._grow_blocks_soft(
                    rec, rec["next_pos"] + int(nd[slot]))
                nd[slot] = max(0, min(int(nd[slot]),
                                      covered - rec["next_pos"]))
        for slot in range(self.slots):  # a LATER slot's mandatory
            rec = self._recs[slot]      # growth may have preempted an
            if rec is None or not rec["live"]:  # earlier drafted one
                nd[slot] = 0
        if not nd.any():
            return None, None
        if self._spec_kind == "truncated":
            self._state, dr = self._draft_fn(
                self._state, self._btab_all(), jnp.asarray(nd))
            drafts = np.asarray(dr)
        return drafts, nd

    def _decode_spec(self, drafts, nd):
        """One speculative scoring dispatch over the active batch:
        γ+1 positions per slot verified at once, the accepted prefix
        (plus the bonus token) committed host-side. Counts as ONE
        decode step for occupancy/latency purposes — the whole point
        is that it emits MORE THAN ONE token."""
        live = [s for s, r in enumerate(self._recs)
                if r is not None and r["live"]]
        if not live:
            return 0, [], 0, 0, 0
        btab = self._btab_all()
        sampled = any(
            self._recs[s]["req"].sampling is not None for s in live)
        # ONE packed upload (draft lengths + tokens) and ONE packed
        # fetch (emits + counts + fins): per-dispatch host transfers
        # are exactly the tax this path exists to amortize
        dn = np.concatenate([nd[:, None], drafts], axis=1)
        self._state, out = self._spec_fn(self._state, btab,
                                         jnp.asarray(dn), sampled)
        out = np.asarray(out)
        g1 = self._spec_gamma + 1
        emits, n_emit, fins = out[:, :g1], out[:, g1], out[:, g1 + 1]
        drafted = int(nd.sum())
        accepted = 0
        emitted = 0
        scores = None
        finished = []
        self.stats["spec_dispatches"] += 1
        self.stats["spec_drafted"] += drafted
        self.stats["decode_steps"] += 1
        self.stats["active_slot_steps"] += len(live)
        now = time.perf_counter()
        for slot in live:
            rec = self._recs[slot]
            req = rec["req"]
            ne = int(n_emit[slot])
            for t in emits[slot, :ne]:
                req.tokens.append(int(t))
            emitted += ne
            accepted += max(0, ne - 1)
            rec["next_pos"] += ne
            self._publish_prefix(rec, req)
            if ne and req.t_first_token is None:
                req.t_first_token = now
                try:
                    # guarded like _decode's: an escaping span-log
                    # write must not strand earlier-popped slots
                    with _trc.child_span(
                            "request.first_token", req._span,
                            step_span=self._step_span_id()):
                        pass
                    req._span.annotate(ttft=req.ttft)
                except Exception:
                    pass
            if fins[slot]:
                req.t_retire = now
                if scores is None:  # one [S] fetch per dispatch
                    scores = np.asarray(self._state["score"])
                finished.append((req, float(scores[slot])))
                self._release_blocks(rec)
                self._recs[slot] = None
        self.stats["spec_accepted"] += accepted
        self.stats["spec_emitted"] += emitted
        self.stats["tokens"] += emitted
        _monrt.on_spec(drafted=drafted, accepted=accepted)
        return len(live), finished, 1, emitted, 1

    def _decode(self, k=1):
        """One decode dispatch over the active batch: a single step
        (k=1, the PR-5 path), or a fused K-step megastep — ONE device
        program, one emit/fin fetch, K logical steps. Paged mode first
        grows every live slot's block table to cover its next k write
        positions (one table serves the whole fused dispatch; the
        pressure ladder may preempt here). Returns (slots active at
        dispatch, finished, steps run, tokens emitted).

        A speculative engine first drafts (ISSUE 13): when any live
        slot has draft tokens this iteration, ONE scoring dispatch
        verifies them all and the plain/megastep paths don't run; a
        draftless iteration falls through to the EXISTING programs
        cost-for-cost (the all-greedy/no-draft contract megastep K
        composition rides — a fused dispatch still serves iterations
        the drafter has nothing for)."""
        if self._speculative:
            drafts, nd = self._build_drafts()
            if drafts is not None:
                return self._decode_spec(drafts, nd)
        if self._paged:
            for slot in range(self.slots):
                # re-read per iteration: an earlier slot's allocation
                # may have PREEMPTED this one — allocating for its
                # stale record would leak the blocks it appends
                rec = self._recs[slot]
                if rec is not None and rec["live"]:
                    # cover only the write positions this slot can
                    # actually consume: a request with 1 token left
                    # must not trigger the pressure ladder (evicting
                    # chains / preempting a peer) for K-1 positions
                    # its retirement will never write
                    rem = max(1, rec["req"].max_new
                              - len(rec["req"].tokens))
                    # a False return means rec was preempted — its
                    # slot record is already gone from _recs
                    self._ensure_blocks(
                        rec, rec["next_pos"] + min(k, rem) - 1)
        live = [s for s, r in enumerate(self._recs)
                if r is not None and r["live"]]
        if not live:
            return 0, [], 0, 0, 0
        btab = self._btab_all()
        # dispatch the sampling-tail program only while a stochastic
        # request is actually live (static per-variant compile): the
        # all-greedy path stays the PR-5 program, bit for bit and
        # cost for cost
        sampled = any(
            self._recs[s]["req"].sampling is not None for s in live)
        if k > 1:
            if self._megastep_fn is None:
                self._megastep_fn = jax.jit(self._megastep_impl,
                                            donate_argnums=0,
                                            static_argnums=2)
            self._state, emits, fins = self._megastep_fn(
                self._state, btab, sampled)
            self.stats["megastep_dispatches"] += 1
            emits, fins = np.asarray(emits), np.asarray(fins)
        else:
            self._state, emit, fin = self._step_fn(self._state, btab,
                                                   sampled)
            # host-side axis add: [None] on the DEVICE array would
            # dispatch a reshape per step on the k=1 hot path
            emits = np.asarray(emit)[None]
            fins = np.asarray(fin)[None]
        scores = None
        finished = []
        emitted = 0
        steps_run = 0
        active0 = len(live)
        now = time.perf_counter()
        # replay the K sub-iterations host-side: a slot retired at
        # sub-iteration j stops consuming rows (its later emits are
        # end_id filler from the inactive carry)
        for j in range(emits.shape[0]):
            if not live:
                break
            steps_run += 1
            self.stats["decode_steps"] += 1
            self.stats["active_slot_steps"] += len(live)
            for slot in list(live):
                rec = self._recs[slot]
                req = rec["req"]
                req.tokens.append(int(emits[j, slot]))
                emitted += 1
                if self._paged:
                    rec["next_pos"] += 1   # mirrors the device pos
                self._publish_prefix(rec, req)
                if req.t_first_token is None:
                    req.t_first_token = now
                    try:
                        # guarded: by this point in the loop EARLIER
                        # slots may already be popped into the local
                        # `finished` — an exception escaping here
                        # (span-log write) would lose them to both
                        # _step_once's finally and _fail_all,
                        # stranding their result() forever
                        with _trc.child_span(
                                "request.first_token", req._span,
                                step_span=self._step_span_id()):
                            pass        # zero-width timeline mark
                        req._span.annotate(ttft=req.ttft)
                    except Exception:
                        pass
                if fins[j, slot]:
                    req.t_retire = now
                    if scores is None:  # one [S] fetch per dispatch
                        # safe across sub-iterations: a retired slot's
                        # score is frozen by its inactive mask
                        scores = np.asarray(self._state["score"])
                    finished.append((req, float(scores[slot])))
                    if self._paged:
                        # retirement frees the request's pool refs;
                        # prefix-published blocks survive on the
                        # cache's own refs (evictable once cold)
                        self._release_blocks(rec)
                    self._recs[slot] = None
                    live.remove(slot)
        self.stats["tokens"] += emitted
        # trips = scan trips the DEVICE ran this dispatch (a drain-tail
        # megastep may consume fewer: every live slot can retire before
        # the last sub-iteration, the rest is inactive filler) — per-
        # step latency must divide by trips, not steps consumed
        return active0, finished, steps_run, emitted, emits.shape[0]

    def _fail_all(self, err):
        with self._cv:
            slotted = [r for r in self._recs if r is not None]
            pending = [r["req"] for r in slotted]
            pending += list(self._queue)
            self._queue.clear()
            self._recs = [None] * self.slots
        if self._paged:
            for rec in slotted:        # pool accounting stays clean
                self._release_blocks(rec)
        cb = self.on_retire
        for req in pending:
            # failed requests still retire for attribution purposes:
            # their row/span carries the error, and the SLO error
            # budget counts them
            if req.t_retire is None:
                req.t_retire = time.perf_counter()
            self._retire_telemetry(req, error=err)
            req._fail(err)
            if cb is not None:
                try:
                    cb(req)
                except Exception:
                    pass


# -- sequential baseline ---------------------------------------------------

def _seq_step_fn(model):
    """The jitted single-token greedy step (batch 1), cached on the
    model so repeated baselines share one compile."""
    fn = getattr(model, "_serving_seq_step", None)
    if fn is None:
        def _impl(tok, state, t):
            logits, state = model._step_logits(tok, state, t)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            nxt = jnp.argmax(logp, axis=-1).astype(jnp.int32)
            lp = jnp.take_along_axis(logp, nxt[:, None], axis=-1)[:, 0]
            return nxt, lp, state

        fn = model._serving_seq_step = jax.jit(_impl)
    return fn


def sequential_generate(model, requests):
    """One-at-a-time greedy decode — the pre-engine serving loop (the
    shape the C-API predictor and PERF.md's bs1 line measure): one
    jitted single-token step at batch 1, a host round-trip per token,
    requests processed back to back. ``requests``: iterable of
    ``(prompt, max_new_tokens)``. Returns ``[(tokens, score), ...]``,
    token-identical to ``Engine`` output (same per-row math)."""
    step = _seq_step_fn(model)
    out = []
    for prompt, max_new in requests:
        prompt = [int(t) for t in prompt]
        if len(prompt) + int(max_new) - 1 > model.max_len:
            # same loud bound as Engine.submit: past max_len the pos-emb
            # gather and cache writes clamp and silently corrupt output
            raise ValueError(
                "prompt len %d + max_new %d exceeds model max_len %d"
                % (len(prompt), int(max_new), model.max_len))
        state = model._init_state(1)
        for t, tk in enumerate(prompt[:-1]):    # teacher-forced prefix
            _, _, state = step(jnp.full((1,), tk, jnp.int32), state,
                               np.int32(t))
        tok, pos = prompt[-1], len(prompt) - 1
        toks, score = [], 0.0
        for _ in range(int(max_new)):
            nxt, lp, state = step(jnp.full((1,), tok, jnp.int32), state,
                                  np.int32(pos))
            tok = int(np.asarray(nxt)[0])
            score += float(np.asarray(lp)[0])
            toks.append(tok)
            pos += 1
            if tok == model.end_id:
                break
        out.append((toks, score))
    return out
