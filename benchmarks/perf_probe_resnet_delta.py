"""ResNet-50 throughput delta breakdown (round-2 verdict #2b).

The framework trains ResNet-50 at ~2500 img/s while a pure-JAX no-BN
ResNet reaches ~3272 (PERF.md). Attribute the delta by timing the SAME
framework program with components removed:
  full           conv+BN(train)+SGD           (the bench config)
  no_opt         conv+BN(train), no optimizer (grads still computed)
  bn_test        conv+BN(inference stats)+SGD (no batch stats/updates)
  no_bn          conv only (BN layers removed)+SGD
Run on the real chip: python benchmarks/perf_probe_resnet_delta.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.models import resnet  # noqa: E402
from common import synthetic_feeds  # noqa: E402

BS = 256
ITERS = 12
SKIP = 3
FLOPS_PER_IMG = 3 * 4.1e9
PEAK = 197e12


def bench(tag, use_bn=True, bn_train=True, optimize=True):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        synth = synthetic_feeds({
            "data": ((BS, 3, 224, 224), "float32", 1.0),
            "label": ((BS, 1), "int64", 1000)})
        image, label, avg_cost, acc = resnet.build_train_net(
            model="resnet_imagenet", depth=50,
            image_shape=(3, 224, 224), num_classes=1000,
            learning_rate=0.01, image=synth["data"],
            label=synth["label"], optimize=optimize)
        for op in main.global_block().ops:
            if op.type != "batch_norm":
                continue
            if not bn_train:
                op.attrs["is_test"] = True
            if not use_bn:
                # ablation surgery: BN becomes identity (the act lives
                # in a separate op appended by the layer helper)
                op.type = "assign"
                op.inputs = {"X": op.inputs["X"]}
                op.outputs = {"Out": op.outputs["Y"]}
                op.attrs = {}
        fetch = [avg_cost]
        if not optimize:
            # without optimizer ops nothing consumes the grads — XLA
            # would DCE (part of) the backward. Consume EVERY param grad
            # in-graph via a scalar grad-norm and fetch that: the full
            # backward must run, and only a scalar crosses the tunnel.
            gb = main.global_block()
            terms = []
            for p in gb.all_parameters():
                gname = p.name + "@GRAD"
                if gname in gb.vars:
                    terms.append(fluid.layers.reduce_sum(
                        fluid.layers.square(gb.var(gname))))
            fetch.append(fluid.layers.sums(terms))
        fluid.amp.enable_amp()
        try:
            exe = fluid.Executor(fluid.TPUPlace(0))
            exe.run(startup)
            outs = None
            for i in range(SKIP):
                outs = exe.run(main, feed={}, fetch_list=fetch,
                               return_numpy=False)
            float(np.asarray(outs[0]))
            best = None
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(ITERS):
                    outs = exe.run(main, feed={}, fetch_list=fetch,
                                   return_numpy=False)
                float(np.asarray(outs[0]))
                dt = (time.perf_counter() - t0) / ITERS
                best = dt if best is None else min(best, dt)
        finally:
            fluid.amp.enable_amp(False)
    ips = BS / best
    print("%-8s %7.0f img/s  (%5.1f ms/step, %4.1f%% MFU)"
          % (tag, ips, best * 1e3, 100 * ips * FLOPS_PER_IMG / PEAK))
    return ips


def main():
    full = bench("full")
    no_opt = bench("no_opt", optimize=False)
    bn_test = bench("bn_test", bn_train=False)
    no_bn = bench("no_bn", use_bn=False)
    print("\ndeltas vs full (%.0f img/s):" % full)
    print("  optimizer apply : %+5.0f img/s" % (no_opt - full))
    print("  BN batch stats  : %+5.0f img/s" % (bn_test - full))
    print("  BN entirely     : %+5.0f img/s" % (no_bn - full))


if __name__ == "__main__":
    main()
