"""Automatic parallelism planner: dp/tp/pp/sp/ep search over a cost
model calibrated against PERF.md's measurements.

The five-axis ``DistributedStrategy`` composition has been "user picks"
since the parallel subsystem landed; systems like GSPMD and Alpa showed
a cost-model-driven search over parallelism assignments beats
hand-tuning on real topologies. We own both halves of the input
already: the static per-step FLOPs/bytes roll-up
(``analysis/cost.step_costs``) prices compute, and PERF.md's measured
numbers calibrate the analytic comm/bubble terms:

  * pipeline bubble — the GPipe useful fraction U(M) = M/(S+M-1);
    PERF.md round 3 measured throughput ratios tracking it within a few
    points across M in {1,2,4,8,16} (pp=4, 8-device virtual mesh).
  * DCN wire — the pserver tier pushes dense params at ~0.8 GB/s and
    pulls at ~0.9 GB/s (round-3 scatter-gather numbers); the sparse
    path ships only touched rows (131 KB vs 105 MB for the [200k x 64]
    benchmark table) and measured 7046 vs 335 samples/s.
  * ICI — mesh collectives (grad all-reduce on dp, Megatron per-layer
    all-reduces on tp, ring passes on sp, all-to-all on ep) price at an
    assumed per-link ICI bandwidth. The absolute constant is a
    placeholder until a chip round measures it; every ranking the tests
    pin is ordinal, and orderings are stable across plausible values.

API:  candidates(spec, devices)       valid strategy assignments
      rank(spec, devices)             -> [Plan] cheapest first;
                                      hbm_bytes= REJECTS candidates
                                      over per-chip capacity (params +
                                      optimizer state + paged-KV pool
                                      via kvpool.bytes_per_block —
                                      flag autoparallel_hbm_gb)
      plan_hbm_bytes(spec, axes)      the capacity term itself
      recommend(model, devices)       zoo surface (traces + prices)
      apply(plan, ...)                top plan -> configured
                                      ParallelExecutor + built program
      recommend_embedding_placement   sparse-vs-dense pserver wire call
CLI:  python -m paddle_tpu.transform --plan transformer 8
"""

import os

import numpy as np

# -- calibration constants (provenance: PERF.md) ---------------------------
# GPipe bubble: U(M) = M/(S+M-1), measured round 3 (pipeline bench table)
DCN_DENSE_PUSH_BPS = 0.8e9     # round 3: RPC push 52 MB at 0.8 GB/s
DCN_DENSE_PULL_BPS = 0.9e9     # round 3: RPC pull 52 MB at 0.9 GB/s
DCN_SPARSE_ROW_OVERHEAD = 8.0  # bytes per shipped row id (int64 index)
ICI_BPS = 45e9                 # assumed per-link ICI; ordinal use only
PEAK_FLOPS = 180e12            # per-chip peak for the compute term;
                               # cancels out of every same-device-count
                               # comparison, kept for readable seconds
# HBM capacity term (ISSUE 10): weights + grads + Adam m/v alongside
# the parameter shard — 4x the shard bytes total (1 + this multiplier)
OPTIMIZER_STATE_MULT = 3.0
KV_BLOCK_SIZE = 16             # pool granule priced per plan (matches
                               # the serving_block_size flag default)


_CALIB_CACHE = {}          # path -> (mtime, record)
_CALIB_WARNED = set()


def calibration():
    """(peak_flops, ici_bps, source) for the cost model. The
    ``autoparallel_calib`` flag names a ``calibrate.write_calibration``
    record; unset / unreadable falls back to the documented
    placeholders (a bad record warns once per path, never raises —
    rankings are ordinal either way)."""
    from .. import flags
    path = flags.get_flag("autoparallel_calib") or ""
    if not path:
        return PEAK_FLOPS, ICI_BPS, "placeholder"
    try:
        mtime = os.path.getmtime(path)
        cached = _CALIB_CACHE.get(path)
        if cached is None or cached[0] != mtime:
            from .calibrate import load_calibration
            _CALIB_CACHE[path] = (mtime, load_calibration(path))
        rec = _CALIB_CACHE[path][1]
    except Exception as e:
        if path not in _CALIB_WARNED:
            _CALIB_WARNED.add(path)
            import sys
            print("autoparallel_calib %r unusable (%s); using "
                  "placeholder constants" % (path, e), file=sys.stderr)
        return PEAK_FLOPS, ICI_BPS, "placeholder"
    peak = float(rec["peak_flops"])
    ici = rec.get("ici_bps")
    if ici:
        return peak, float(ici), "measured:%s" % path
    # single-device records carry no ring measurement: the comm terms
    # still price at the placeholder, and the provenance must say so
    return peak, ICI_BPS, "measured:%s (ici placeholder)" % path


def pipeline_utilization(m, s):
    """GPipe useful fraction U(M) = M/(S+M-1) — PERF.md round 3
    measured throughput ratios track this within a few points."""
    m, s = max(1, int(m)), max(1, int(s))
    return m / float(s + m - 1)


class ModelSpec:
    """Everything the cost model needs to price one model, detached
    from tracing so unit tests pin orderings with pure math.

    flops/bytes are per GLOBAL step (the analysis cost model's
    accounting); param_bytes the dense parameter footprint;
    act_bytes the per-layer boundary activation size (batch * seq *
    d_model * dtype) that tp all-reduces, sp ring-passes, ep
    all-to-alls and pp ships between stages."""

    def __init__(self, name, flops, bytes, param_bytes, batch, seq,
                 d_model, n_layer, n_head, num_experts=0,
                 dtype_bytes=4):
        self.name = name
        self.flops = float(flops)
        self.bytes = float(bytes)
        self.param_bytes = float(param_bytes)
        self.batch = int(batch)
        self.seq = int(seq)
        self.d_model = int(d_model)
        self.n_layer = int(n_layer)
        self.n_head = int(n_head)
        self.num_experts = int(num_experts)
        self.dtype_bytes = int(dtype_bytes)

    @property
    def act_bytes(self):
        return (self.batch * self.seq * self.d_model
                * float(self.dtype_bytes))


class Plan:
    """One priced strategy assignment, cheapest-first sortable."""

    def __init__(self, axes, microbatches, cost, breakdown,
                 hbm_bytes=None):
        self.axes = dict(axes)              # dp/tp/pp/sp/ep
        self.microbatches = int(microbatches)
        self.cost = float(cost)             # modeled seconds per step
        self.breakdown = dict(breakdown)
        self.hbm_bytes = hbm_bytes          # modeled per-chip bytes

    def strategy(self):
        from ..parallel import DistributedStrategy
        return DistributedStrategy(**self.axes)

    def mesh_axes(self):
        return {k: v for k, v in
                (("dp", self.axes["dp"]), ("pp", self.axes["pp"]),
                 ("sp", self.axes["sp"]), ("ep", self.axes["ep"]),
                 ("tp", self.axes["tp"]))
                if v > 1 or k == "dp"}

    def describe(self):
        ax = "x".join("%s%d" % (k, self.axes[k])
                      for k in ("dp", "tp", "pp", "sp", "ep")
                      if self.axes[k] > 1) or "dp1"
        mb = " M=%d" % self.microbatches if self.axes["pp"] > 1 else ""
        return "%s%s" % (ax, mb)

    def to_dict(self):
        out = {"axes": dict(self.axes),
               "microbatches": self.microbatches,
               "cost_s": self.cost,
               "breakdown": dict(self.breakdown),
               "describe": self.describe()}
        if self.hbm_bytes is not None:
            out["hbm_bytes"] = self.hbm_bytes
        return out

    def __repr__(self):
        return "Plan(%s, cost=%.3es)" % (self.describe(), self.cost)


def _factorizations(n, k):
    """All ordered k-tuples of positive ints whose product is n."""
    if k == 1:
        yield (n,)
        return
    for d in sorted(set(
            d for d in range(1, n + 1) if n % d == 0)):
        for rest in _factorizations(n // d, k - 1):
            yield (d,) + rest


def candidates(spec, devices):
    """Valid (strategy axes, microbatches) assignments for this model
    on ``devices`` chips. Validity mirrors what the model builders /
    mesh actually accept: every axis must divide its dimension (dp the
    batch, tp the head count and model dim, pp the layer count, sp the
    sequence, ep the expert count), and a pipeline schedule needs at
    least one microbatch per per-dp batch row."""
    devices = int(devices)
    out = []
    seen = set()
    for dp, tp, pp, sp, ep in _factorizations(devices, 5):
        if (dp, tp, pp, sp, ep) in seen:
            continue
        seen.add((dp, tp, pp, sp, ep))
        if spec.batch % dp:
            continue
        if tp > 1 and (spec.n_head % tp or spec.d_model % tp):
            continue
        if pp > 1 and spec.n_layer % pp:
            continue
        if sp > 1 and spec.seq % sp:
            continue
        if ep > 1 and (not spec.num_experts
                       or spec.num_experts % ep):
            continue
        axes = {"dp": dp, "tp": tp, "pp": pp, "sp": sp, "ep": ep}
        if pp > 1:
            per_dp = spec.batch // dp
            ms = [m for m in (1, 2, 4, 8, 16, 32)
                  if m <= per_dp and per_dp % m == 0]
            for m in ms or [1]:
                out.append((axes, m))
        else:
            out.append((axes, 1))
    return out


def plan_cost(spec, axes, microbatches=1,
              peak_flops=None, ici_bps=None):
    """Analytic per-step cost (seconds) of one strategy assignment:
    compute spread over every chip, inflated by the pipeline bubble
    1/U(M), plus the per-axis collective traffic at ICI rate. Each
    comm term uses the standard ring-collective volume for its
    collective (all-reduce 2(n-1)/n, all-to-all / ring pass (n-1)/n).
    Constants default to ``calibration()`` — a measured calib record
    when the ``autoparallel_calib`` flag names one, the documented
    placeholders otherwise."""
    if peak_flops is None or ici_bps is None:
        cal_peak, cal_ici, _ = calibration()
        peak_flops = cal_peak if peak_flops is None else peak_flops
        ici_bps = cal_ici if ici_bps is None else ici_bps
    dp, tp, pp, sp, ep = (axes["dp"], axes["tp"], axes["pp"],
                          axes["sp"], axes["ep"])
    n = dp * tp * pp * sp * ep
    util = pipeline_utilization(microbatches, pp) if pp > 1 else 1.0
    compute = spec.flops / (peak_flops * n) / util

    # per-chip shard of the dense params that dp replicates (tp/pp/ep
    # already shard them); ring all-reduce moves 2(dp-1)/dp of it
    dp_comm = 0.0
    if dp > 1:
        shard = spec.param_bytes / (tp * pp * max(1, ep))
        dp_comm = 2.0 * (dp - 1) / dp * shard / ici_bps
    # Megatron tp: one all-reduce per sublayer (2 per layer) of the
    # boundary activation, on each chip's dp/sp shard of the batch
    tp_comm = 0.0
    if tp > 1:
        act = spec.act_bytes / (dp * sp)
        tp_comm = (2.0 * spec.n_layer
                   * 2.0 * (tp - 1) / tp * act / ici_bps)
    # ring attention: K/V blocks circulate the sp ring once per layer
    sp_comm = 0.0
    if sp > 1:
        act = spec.act_bytes / (dp * tp)
        sp_comm = spec.n_layer * 2.0 * (sp - 1) / sp * act / ici_bps
    # MoE all-to-all: tokens scatter+gather across ep once per layer
    ep_comm = 0.0
    if ep > 1:
        act = spec.act_bytes / (dp * tp * sp)
        ep_comm = spec.n_layer * 2.0 * (ep - 1) / ep * act / ici_bps
    # pipeline point-to-point: each microbatch's activation crosses
    # every stage boundary (forward + backward)
    pp_comm = 0.0
    if pp > 1:
        act = spec.act_bytes / (dp * sp) / max(1, microbatches)
        pp_comm = (2.0 * (pp - 1) * microbatches * act / ici_bps)

    comm = dp_comm + tp_comm + sp_comm + ep_comm + pp_comm
    return compute + comm, {
        "compute_s": compute,
        "pipeline_util": util,
        "dp_comm_s": dp_comm, "tp_comm_s": tp_comm,
        "sp_comm_s": sp_comm, "ep_comm_s": ep_comm,
        "pp_comm_s": pp_comm,
    }


def plan_hbm_bytes(spec, axes, block_size=KV_BLOCK_SIZE,
                   optimizer_mult=OPTIMIZER_STATE_MULT,
                   kv_quant=None):
    """Modeled PER-CHIP HBM bytes of one assignment — the capacity
    term PR 9 left open (ISSUE 10): the dense parameter shard dp
    replicates (tp/pp/ep shard it) times (1 + optimizer_mult) for
    grads + Adam moments, plus the paged-KV pool a decode tier of the
    same shape reserves, priced with ``serving.kvpool.bytes_per_block``
    (each per-chip batch row keeps ceil(seq_shard / block_size) blocks
    of its layer/head shard). ``kv_quant`` (or ``spec.kv_quant`` when
    the caller leaves it None) prices an int8/fp8-quantized pool —
    the capacity filter then admits plans the dense pool would
    reject. Returns (total, breakdown)."""
    from ..serving.kvpool import bytes_per_block
    dp, tp, pp, sp, ep = (axes["dp"], axes["tp"], axes["pp"],
                          axes["sp"], axes["ep"])
    shard = spec.param_bytes / (tp * pp * max(1, ep))
    params = shard * (1.0 + float(optimizer_mult))
    dk = max(1, spec.d_model // max(1, spec.n_head))
    rows = max(1, spec.batch // dp)
    seq_shard = -(-spec.seq // sp)
    blocks = rows * (-(-seq_shard // int(block_size)))
    if kv_quant is None:
        kv_quant = getattr(spec, "kv_quant", None)
    kv = blocks * bytes_per_block(
        max(1, spec.n_layer // pp), max(1, spec.n_head // tp),
        block_size, dk, dtype_bytes=spec.dtype_bytes,
        kv_quant=kv_quant)
    return params + kv, {"hbm_param_bytes": params, "hbm_kv_bytes": kv}


def rank(spec, devices, peak_flops=None, ici_bps=None,
         hbm_bytes=None):
    """All valid plans for (spec, devices), cheapest first. Ties break
    on the axes tuple so the ranking is deterministic. ``hbm_bytes``
    (per-chip capacity) REJECTS over-capacity candidates instead of
    ranking them — an HBM-infeasible plan is not a slow plan, it is
    not a plan."""
    plans, rejected = [], 0
    for axes, m in candidates(spec, devices):
        hbm, hbm_bd = plan_hbm_bytes(spec, axes)
        if hbm_bytes is not None and hbm_bytes > 0 and hbm > hbm_bytes:
            rejected += 1
            continue
        cost, breakdown = plan_cost(spec, axes, m,
                                    peak_flops=peak_flops,
                                    ici_bps=ici_bps)
        breakdown.update(hbm_bd)
        plans.append(Plan(axes, m, cost, breakdown, hbm_bytes=hbm))
    plans.sort(key=lambda p: (p.cost,
                              tuple(sorted(p.axes.items())),
                              -p.microbatches))
    if not plans:
        if rejected:
            raise ValueError(
                "every valid assignment for %r on %d devices exceeds "
                "the %.2f GB per-chip HBM capacity (%d candidate(s) "
                "rejected) — raise autoparallel_hbm_gb or shard more"
                % (spec.name, devices, hbm_bytes / 1e9, rejected))
        raise ValueError(
            "no valid dp/tp/pp/sp/ep assignment for %r on %d devices "
            "(batch=%d heads=%d layers=%d seq=%d experts=%d)"
            % (spec.name, devices, spec.batch, spec.n_head,
               spec.n_layer, spec.seq, spec.num_experts))
    return plans


# -- zoo surface -----------------------------------------------------------

# models with a strategy-aware builder the planner can price AND apply
PLANNABLE = ("transformer",)


def model_spec(model, entry=None):
    """Trace + price one plannable zoo model into a ModelSpec: FLOPs
    and bytes from the analysis cost model over the real single-device
    train step, parameter bytes from the built Program."""
    ent = entry if entry is not None else _plan_entry(model)
    from ..analysis.cost import step_costs
    from ..models.harness import program_entry
    fn, args = program_entry(ent["build"], ent["feeds"])
    flops, nbytes = step_costs(fn, args)
    import paddle_tpu as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ent["build"]()
    param_bytes = 0.0
    for p in main.all_parameters():
        param_bytes += float(np.prod(p.shape)) * np.dtype(p.dtype).itemsize
    return ModelSpec(
        model, flops=flops, bytes=nbytes, param_bytes=param_bytes,
        batch=ent["batch"], seq=ent["seq"], d_model=ent["d_model"],
        n_layer=ent["n_layer"], n_head=ent["n_head"],
        num_experts=ent.get("num_experts", 0))


def _plan_entry(model):
    if model not in PLANNABLE:
        raise KeyError(
            "model %r is not plannable (strategy-aware builders exist "
            "for: %s)" % (model, ", ".join(PLANNABLE)))
    import importlib
    mod = importlib.import_module("paddle_tpu.models.%s" % model)
    return mod.plan_entry()


def recommend(model, devices, top=None, spec=None, hbm_gb=None):
    """Ranked plans for a zoo model at a device count. ``spec`` skips
    the trace (tests / repeated calls). ``hbm_gb`` (default: the
    ``autoparallel_hbm_gb`` flag; 0 = off) rejects candidates whose
    modeled per-chip bytes (params + optimizer state + paged-KV pool)
    exceed the capacity."""
    if hbm_gb is None:
        from .. import flags
        hbm_gb = flags.get_flag("autoparallel_hbm_gb")
    spec = spec or model_spec(model)
    plans = rank(spec, devices,
                 hbm_bytes=hbm_gb * 1e9 if hbm_gb else None)
    return plans[:top] if top else plans


class AppliedPlan:
    """A plan instantiated for real: built program (strategy-aware),
    configured ParallelExecutor over the plan's mesh, startup already
    run. ``run(feed)`` executes one step and returns the fetches."""

    def __init__(self, plan, pexe, main, startup, fetch_vars, feed_fn,
                 scope):
        self.plan = plan
        self.pexe = pexe
        self.main = main
        self.startup = startup
        self.fetch_vars = fetch_vars
        self.feed_fn = feed_fn
        self.scope = scope

    def run(self, feed):
        return self.pexe.run(fetch_list=list(self.fetch_vars),
                             feed=feed)


def apply(plan, model, devices=None):
    """Instantiate a plan: build the model WITH the plan's strategy
    (fresh programs), make the mesh, init params, and hand back a
    configured ParallelExecutor — "framework solves" made executable.
    ``devices`` optionally restricts the jax device list."""
    import jax
    import paddle_tpu as fluid
    from ..parallel import make_mesh, ParallelExecutor

    ent = _plan_entry(model)
    strategy = plan.strategy()
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        fetch_vars = ent["build"](strategy)
        if not isinstance(fetch_vars, (tuple, list)):
            fetch_vars = (fetch_vars,)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
    devs = list(devices if devices is not None else jax.devices())
    mesh = make_mesh(plan.mesh_axes(), devs)
    pexe = ParallelExecutor(loss_name=fetch_vars[0].name, mesh=mesh,
                            scope=scope, main_program=main,
                            strategy=strategy)
    return AppliedPlan(plan, pexe, main, startup, fetch_vars,
                       ent["feeds"], scope)


# -- pserver embedding placement (DCN tier) --------------------------------

def embedding_wire_costs(rows, dim, touched_rows, dtype_bytes=4,
                         measured_sparse_row_s=None):
    """Per-step DCN wire seconds for a pserver-sharded embedding,
    dense vs sparse. Dense ships the WHOLE table both ways every step
    (grad push + param pull — PERF.md round 3 measured ~105 MB
    wire/step for the 52 MB table); sparse ships only the touched rows
    plus their int64 ids (the measured 131 KB/step shape).

    ``measured_sparse_row_s`` (ISSUE 12 placement pricing hook): a
    LIVE per-row miss-path measurement —
    ``serving.sparse.SparseClient.miss_row_seconds()`` — overrides the
    modeled sparse wire term, so a serving deployment prices placement
    with ITS wire (loopback, DCN, the axon tunnel) instead of the
    PERF.md round-3 constants. The cost carries a
    ``sparse_measured`` marker so rankings say which model priced
    them."""
    rows, dim = int(rows), int(dim)
    touched = min(int(touched_rows), rows)
    dense_bytes = float(rows) * dim * dtype_bytes
    sparse_bytes = float(touched) * (dim * dtype_bytes
                                     + DCN_SPARSE_ROW_OVERHEAD)
    sparse_s = (sparse_bytes / DCN_DENSE_PUSH_BPS
                + sparse_bytes / DCN_DENSE_PULL_BPS)
    measured = measured_sparse_row_s is not None
    if measured:
        sparse_s = float(touched) * float(measured_sparse_row_s)
    return {
        "dense": (dense_bytes / DCN_DENSE_PUSH_BPS
                  + dense_bytes / DCN_DENSE_PULL_BPS),
        "sparse": sparse_s,
        "sparse_measured": measured,
        "dense_wire_bytes": 2.0 * dense_bytes,
        "sparse_wire_bytes": 2.0 * sparse_bytes,
    }


def recommend_embedding_placement(rows, dim, touched_rows,
                                  dtype_bytes=4,
                                  measured_sparse_row_s=None):
    """[(mode, cost_seconds)] cheapest first for a pserver-sharded
    embedding shape. Pinned against PERF.md: the [200k x 64] table with
    a few hundred touched rows/step ranks sparse over dense (measured
    7046 vs 335 samples/s). Pass a serving SparseClient's
    ``miss_row_seconds()`` as ``measured_sparse_row_s`` to rank with
    the deployment's own measured miss path instead of the modeled
    wire."""
    costs = embedding_wire_costs(
        rows, dim, touched_rows, dtype_bytes,
        measured_sparse_row_s=measured_sparse_row_s)
    ranked = sorted([("sparse", costs["sparse"]),
                     ("dense", costs["dense"])], key=lambda kv: kv[1])
    return ranked
