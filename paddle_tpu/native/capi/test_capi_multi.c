/* Multi-io C deployment test: drive a seq2seq-style inference model
 * (int64 token ids + float mask in; int64 predicted ids + float32 probs
 * out) from pure C — the reference capi's Arguments capability
 * (gradient_machine.h:36-62).
 * Usage: test_capi_multi <model_dir> <seq_len>
 * Feeds src = [1..T] (int64, [1,T]) and mask = ones (float, [1,T]);
 * prints "IDS ..." (output 0, int64) and "PROBS ..." (output 1, float32).
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

extern void* pt_predictor_create(const char* model_dir);
extern int pt_predictor_num_fetches(void* p);
extern int pt_predictor_run_multi(
    void* p, int n_in, const char** in_names, const void** in_bufs,
    const int64_t* const* in_shapes, const int* in_nds,
    const int* in_dtypes, int n_out, void** out_bufs,
    const int64_t* out_caps_bytes, int64_t* out_shapes, int* out_nds,
    int* out_dtypes);
extern void pt_predictor_destroy(void* p);
extern const char* pt_last_error(void);

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s <model_dir> <seq_len>\n", argv[0]);
    return 2;
  }
  int t = atoi(argv[2]);
  if (t < 1 || t > 64) {
    fprintf(stderr, "seq_len must be in [1, 64]\n");
    return 2;
  }
  void* p = pt_predictor_create(argv[1]);
  if (!p) {
    fprintf(stderr, "create failed: %s\n", pt_last_error());
    return 1;
  }
  if (pt_predictor_num_fetches(p) != 2) {
    fprintf(stderr, "expected a 2-fetch model, got %d\n",
            pt_predictor_num_fetches(p));
    return 1;
  }

  int64_t src[64];
  float mask[64];
  for (int i = 0; i < t; ++i) {
    src[i] = i + 1;
    mask[i] = 1.0f;
  }
  int64_t shape[2] = {1, t};
  const char* names[2] = {"src", "mask"};
  const void* bufs[2] = {src, mask};
  const int64_t* shapes[2] = {shape, shape};
  int nds[2] = {2, 2};
  int dtypes[2] = {2, 0}; /* int64, float32 */

  /* ids arrive int64 (code 2) or int32 (code 1) depending on the
   * engine's index width — a typed ABI must carry either */
  union {
    int64_t i64[64];
    int32_t i32[128];
  } out_ids;
  float out_probs[4096];
  void* obufs[2] = {&out_ids, out_probs};
  int64_t ocaps[2] = {sizeof(out_ids), sizeof(out_probs)};
  int64_t oshapes[16];
  int onds[2], odts[2];

  if (pt_predictor_run_multi(p, 2, names, bufs, shapes, nds, dtypes, 2,
                             obufs, ocaps, oshapes, onds, odts)) {
    fprintf(stderr, "run failed: %s\n", pt_last_error());
    return 1;
  }
  if ((odts[0] != 1 && odts[0] != 2) || odts[1] != 0) {
    fprintf(stderr, "unexpected output dtypes %d %d\n", odts[0], odts[1]);
    return 1;
  }
  int64_t n0 = 1, n1 = 1;
  for (int d = 0; d < onds[0]; ++d) n0 *= oshapes[d];
  for (int d = 0; d < onds[1]; ++d) n1 *= oshapes[8 + d];
  printf("IDS");
  for (int64_t i = 0; i < n0; ++i) {
    long long v = odts[0] == 2 ? (long long)out_ids.i64[i]
                               : (long long)out_ids.i32[i];
    printf(" %lld", v);
  }
  printf("\nPROBS");
  for (int64_t i = 0; i < n1; ++i) printf(" %.6f", out_probs[i]);
  printf("\n");
  pt_predictor_destroy(p);
  return 0;
}
