"""CLI: python -m paddle_tpu.transform [models...] [--all] [...]
     | python -m paddle_tpu.transform --plan MODEL DEVICES

Pass-pipeline mode runs the optimizing passes over the Program-level
model zoo and VERIFIES each transform by re-executing both programs
and comparing fetches bitwise — exit 1 on any verification failure
(the CI gate shape of python -m paddle_tpu.analysis). Planner mode
prints the ranked dp/tp/pp/sp/ep plans for a zoo model at a device
count. Exit codes: 0 clean, 1 gate failure, 2 bad usage (argparse).
Run under JAX_PLATFORMS=cpu; nothing here needs a chip.
"""

import argparse
import json
import sys
import time


def _run_pipeline(args):
    from ..models import TRANSFORM_ZOO, transform_zoo_entry
    from .passes import PassManager, resolve_passes, verify_bitwise

    names = (sorted(TRANSFORM_ZOO) if args.all or not args.models
             else args.models)
    unknown = set(names) - set(TRANSFORM_ZOO)
    if unknown:
        print("unknown model(s) %s; --list-models for the zoo"
              % ", ".join(sorted(unknown)), file=sys.stderr)
        return 2
    try:
        passes = resolve_passes(args.passes)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if not passes:
        print("no passes selected (transform_passes=none)",
              file=sys.stderr)
        return 0

    failed = 0
    rows = []
    for name in names:
        t0 = time.perf_counter()
        main, startup, feed_fn, fetch_names = transform_zoo_entry(name)
        result = PassManager(passes).run(main, keep=fetch_names)
        row = {"model": name, **result.to_dict()}
        if args.verify:
            ok, detail = verify_bitwise(main, startup, feed_fn,
                                        fetch_names, result.program,
                                        steps=args.steps)
            row["verified"] = ok
            if not ok:
                row["detail"] = detail
                failed += 1
        row["dt_s"] = round(time.perf_counter() - t0, 3)
        rows.append(row)
        if not args.json:
            tail = ""
            if args.verify:
                tail = ("  bitwise-identical" if row["verified"]
                        else "  VERIFICATION FAILED: %s"
                        % row.get("detail"))
            pats = ", ".join("%s %d" % (p, n)
                             for p, n in row["patterns"].items() if n)
            print("%-16s %4d -> %4d ops (-%d: %s)%s%s  %.1fs"
                  % (name, row["ops_before"], row["ops_after"],
                     row["ops_removed"],
                     ", ".join("%s %d" % (p, n)
                               for p, n in row["passes"].items()),
                     "  [%s]" % pats if pats else "",
                     tail, row["dt_s"]))
    if args.json:
        print(json.dumps({"models": rows, "failed": failed}))
    return 1 if failed else 0


def _run_plan_memory(args):
    """Compile-time memory planning view (ISSUE 15): liveness + greedy
    best-fit buffer reuse for a zoo model's program, before and after
    the optimizing pipeline — the BuddyAllocator question answered
    statically."""
    from ..models import TRANSFORM_ZOO, transform_zoo_entry
    from .memory import memory_plan
    from .passes import PassManager, resolve_passes

    name = args.plan_memory
    if name not in TRANSFORM_ZOO:
        print("unknown model %r; --list-models for the zoo" % name,
              file=sys.stderr)
        return 2
    main, _startup, _feed_fn, fetch_names = transform_zoo_entry(name)
    src_plan = memory_plan(main, keep=fetch_names, batch=args.batch)
    try:
        passes = resolve_passes(args.passes)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    out = {"model": name, "batch": args.batch,
           "source": src_plan.to_dict()}
    opt_plan = None
    if passes:
        result = PassManager(passes).run(main, keep=fetch_names)
        opt_plan = memory_plan(result.program, keep=fetch_names,
                               batch=args.batch)
        out["transformed"] = opt_plan.to_dict()
        out["transform"] = result.to_dict()
    if args.json:
        print(json.dumps(out))
        return 0
    print("== %s (batch=%d) — source program" % (name, args.batch))
    print(src_plan.render())
    if opt_plan is not None:
        print("== %s — after %s" % (name,
                                    ",".join(p.name for p in passes)))
        print(opt_plan.render())
        print("peak bytes source -> transformed: %d -> %d (arena), "
              "%d -> %d (naive)"
              % (src_plan.arena_bytes, opt_plan.arena_bytes,
                 src_plan.naive_bytes, opt_plan.naive_bytes))
    return 0


def _run_calibrate(args):
    """Measure the planner's cost-model constants on THIS backend and
    persist the platform-stamped record (flag ``autoparallel_calib``
    points plan_cost at it)."""
    from .calibrate import describe, run_calibration, write_calibration

    record = run_calibration()
    write_calibration(args.out, record)
    if args.json:
        print(json.dumps({"path": args.out, **record}))
    else:
        print(describe(record, args.out))
        print("wrote %s; set flag autoparallel_calib=%s (or "
              "PADDLE_TPU_AUTOPARALLEL_CALIB=%s) to price plans with "
              "it" % (args.out, args.out, args.out))
    return 0


def _run_plan(args):
    from .autoparallel import recommend

    model = args.plan[0]
    if len(args.plan) > 2:
        print("--plan takes MODEL [DEVICES], got %r" % (args.plan,),
              file=sys.stderr)
        return 2
    if len(args.plan) > 1:
        devices = args.plan[1]
    else:
        # DEVICES omitted: the autoparallel_devices flag, else the
        # visible device count
        from .. import flags
        devices = flags.get_flag("autoparallel_devices")
        if not devices:
            import jax
            devices = jax.device_count()
    try:
        devices = int(devices)
    except ValueError:
        print("--plan DEVICES must be an integer, got %r" % devices,
              file=sys.stderr)
        return 2
    if devices < 1:
        print("--plan needs devices >= 1", file=sys.stderr)
        return 2
    try:
        plans = recommend(model, devices, top=args.top or None)
    except KeyError as e:
        print(str(e.args[0]) if e.args else str(e), file=sys.stderr)
        return 2
    except ValueError as e:
        # e.g. no valid dp/tp/pp/sp/ep assignment at this device count
        print(str(e), file=sys.stderr)
        return 2
    from .autoparallel import calibration
    _, _, calib_src = calibration()
    if args.json:
        print(json.dumps({"model": model, "devices": devices,
                          "calibration": calib_src,
                          "plans": [p.to_dict() for p in plans]}))
        return 0
    print("ranked plans for %s at %d devices (modeled step seconds; "
          "calibration: %s):"
          % (model, devices,
             "PERF.md placeholders" if calib_src == "placeholder"
             else calib_src))
    for i, p in enumerate(plans):
        b = p.breakdown
        print("%2d. %-18s cost=%.3es  compute=%.3es util=%.2f  "
              "comm dp/tp/pp/sp/ep = %.1e/%.1e/%.1e/%.1e/%.1e"
              % (i + 1, p.describe(), p.cost, b["compute_s"],
                 b["pipeline_util"], b["dp_comm_s"], b["tp_comm_s"],
                 b["pp_comm_s"], b["sp_comm_s"], b["ep_comm_s"]))
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.transform",
        description="optimizing IR passes + automatic parallelism "
                    "planner over the paddle_tpu model zoo")
    p.add_argument("models", nargs="*",
                   help="Program-zoo model names (see --list-models)")
    p.add_argument("--all", action="store_true",
                   help="run the pass pipeline over every Program-zoo "
                        "model")
    p.add_argument("--passes", default=None,
                   help="comma list / 'all' / 'none' (default: the "
                        "transform_passes flag)")
    p.add_argument("--no-verify", dest="verify", action="store_false",
                   help="skip the bitwise re-execution verification "
                        "(rewrite + report only)")
    p.add_argument("--steps", type=int, default=2,
                   help="verification steps per model (default 2)")
    p.add_argument("--plan", nargs="+", metavar="MODEL [DEVICES]",
                   help="planner mode: ranked dp/tp/pp/sp/ep plans "
                        "for MODEL at DEVICES chips (DEVICES defaults "
                        "to the autoparallel_devices flag, else the "
                        "visible device count)")
    p.add_argument("--plan-memory", metavar="MODEL",
                   help="memory-planning mode: liveness + buffer-reuse "
                        "plan (naive / planned-arena / peak-live "
                        "bytes) for MODEL, before and after the pass "
                        "pipeline")
    p.add_argument("--batch", type=int, default=8,
                   help="batch size resolving -1 dims in memory-"
                        "planning mode (default 8)")
    p.add_argument("--calibrate", action="store_true",
                   help="run the matmul-FLOPs + ring-collective "
                        "microbenches and write a platform-stamped "
                        "calibration record for the planner's cost "
                        "model (flag autoparallel_calib)")
    p.add_argument("--out", default="calib.json",
                   help="--calibrate output path (default calib.json)")
    p.add_argument("--top", type=int, default=0,
                   help="planner mode: only the best N plans")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON object instead of text")
    p.add_argument("--list-passes", action="store_true")
    p.add_argument("--list-models", action="store_true")
    args = p.parse_args(argv)

    if args.list_passes:
        from .passes import default_passes
        for pas in default_passes():
            print("%-14s %s" % (pas.name, pas.doc))
        return 0
    if args.list_models:
        from ..models import TRANSFORM_ZOO
        from .autoparallel import PLANNABLE
        for name in sorted(TRANSFORM_ZOO):
            print("%s%s" % (name,
                            "  [plannable]" if name in PLANNABLE
                            else ""))
        return 0
    if args.passes is None:
        from .. import flags
        args.passes = flags.get_flag("transform_passes")
    if args.calibrate:
        return _run_calibrate(args)
    if args.plan_memory:
        return _run_plan_memory(args)
    if args.plan:
        return _run_plan(args)
    return _run_pipeline(args)


if __name__ == "__main__":
    sys.exit(main())
