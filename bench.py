"""Driver benchmark entry: prints ONE JSON line with the headline metric.

Current flagship: MNIST MLP training throughput on one chip (M1 slice).
Baseline anchor: reference AlexNet 1×K40m = 334 ms/batch @bs128 → 383 img/s
(BASELINE.md); MNIST MLP has no direct published reference number, so
vs_baseline is reported against the reference's LSTM/MLP-class throughput
proxy of 64/0.083s ≈ 771 samples/s (LSTM h=256 bs=64: 83 ms/batch).
This will switch to ResNet-50 / Transformer once those land (M3/M4).
"""

import json
import sys


def main():
    sys.argv = [sys.argv[0], "--batch_size", "128", "--iterations", "60",
                "--skip_batch_num", "10"]
    from benchmarks.mnist import main as mnist_main
    ips = mnist_main()
    baseline_proxy = 771.0
    print(json.dumps({
        "metric": "mnist_mlp_train_imgs_per_sec",
        "value": round(float(ips), 1),
        "unit": "imgs/sec",
        "vs_baseline": round(float(ips) / baseline_proxy, 3),
    }))


if __name__ == "__main__":
    main()
