"""v2 Parameters (python/paddle/v2/parameters.py parity): a dict-like view
of a model's trainable parameters with the reference's tar-archive
save/load (`to_tar`/`from_tar`, v2/trainer.py:130 save_parameter_to_tar).

Here a Parameters object owns the fluid Scope the trainer runs in; values
are numpy arrays keyed by parameter name."""

import io
import tarfile

import numpy as np

from ..core.scope import Scope


class Parameters:
    def __init__(self, scope=None):
        self._scope = scope or Scope()
        self._names = []

    # -- creation ----------------------------------------------------------
    @classmethod
    def create(cls, *topologies):
        """Track the parameters of the given cost layers' program(s)."""
        p = cls()
        for t in topologies:
            prog = t.block.program
            for param in prog.global_block().all_parameters():
                if param.name not in p._names:
                    p._names.append(param.name)
        return p

    # -- dict protocol -----------------------------------------------------
    def keys(self):
        return list(self._names)

    names = keys

    def has_key(self, key):
        return key in self._names

    def __contains__(self, key):
        return key in self._names

    def __iter__(self):
        return iter(self._names)

    def __len__(self):
        return len(self._names)

    def __getitem__(self, key):
        v = self._scope.find_var(key)
        if v is None:
            raise KeyError("parameter %r has no value yet (run the trainer "
                           "or from_tar first)" % key)
        return np.asarray(v)

    def __setitem__(self, key, value):
        if key not in self._names:
            self._names.append(key)
        self._scope.set(key, np.asarray(value))

    def get(self, key):
        return self.__getitem__(key)

    def set(self, key, value):
        self.__setitem__(key, value)

    # -- tar round-trip ----------------------------------------------------
    def to_tar(self, f):
        """Write one .npy member per parameter into an (uncompressed) tar —
        the v2 `parameters.to_tar(open(path, 'wb'))` contract."""
        with tarfile.open(fileobj=f, mode="w") as tar:
            for name in self._names:
                buf = io.BytesIO()
                np.save(buf, self[name], allow_pickle=False)
                data = buf.getvalue()
                info = tarfile.TarInfo(name=name + ".npy")
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))

    @classmethod
    def from_tar(cls, f):
        p = cls()
        with tarfile.open(fileobj=f, mode="r") as tar:
            for member in tar.getmembers():
                name = member.name
                if name.endswith(".npy"):
                    name = name[:-4]
                buf = io.BytesIO(tar.extractfile(member).read())
                p[name] = np.load(buf, allow_pickle=False)
        return p

    def init_from_tar(self, f):
        other = Parameters.from_tar(f)
        for name in other.keys():
            self[name] = other[name]


def create(*topologies):
    """Module-level alias: paddle.parameters.create(cost)."""
    return Parameters.create(*topologies)
