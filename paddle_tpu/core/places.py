"""Device places.

Parity with the reference's `Place` variant (paddle/fluid/platform/place.h:25-75)
but TPU-first: ``TPUPlace`` is the primary accelerator place and maps onto a
``jax.Device``. ``CUDAPlace`` is accepted as an alias for the accelerator place
so reference-style scripts (``fluid.CUDAPlace(0)``) run unchanged.

Unlike the reference there is no DeviceContext/stream plumbing here: streams,
allocators and cross-device copies are owned by the XLA runtime. A Place only
answers "which jax.Device does this program execute on".
"""

import functools

import jax


class Place:
    """Base device identity."""

    device_kind = None

    def __init__(self, device_id=0):
        self.device_id = int(device_id)

    def __eq__(self, other):
        return (
            type(self) is type(other) and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (type(self).__name__, self.device_id)

    # -- jax bridge ---------------------------------------------------------
    def jax_device(self):
        """Resolve to a concrete jax.Device (falls back to default device)."""
        devs = _devices_for_kind(self.device_kind)
        if not devs:
            return jax.local_devices()[0]
        return devs[self.device_id % len(devs)]

    def is_accelerator(self):
        return False


@functools.cache
def _devices_for_kind(kind):
    # LOCAL devices only: in a multi-process (multi-host) group,
    # jax.devices() lists every process's devices and [0] would be rank
    # 0's — a single-device executor on another rank would then commit
    # state to a device it cannot address.
    if kind == "cpu":
        # JAX_PLATFORMS=<accelerator-only> (the axon tunnel exports
        # JAX_PLATFORMS=axon) drops the CPU backend, silently turning
        # CPUPlace into the accelerator. Append "cpu" BEFORE the first
        # backend query — the platform list freezes once backends
        # initialize, so a post-failure retry would be too late. The
        # accelerator stays first in the list: default placement is
        # unchanged, only explicit CPUPlace resolves differently.
        try:
            plats = jax.config.jax_platforms
            if plats and "cpu" not in plats.split(","):
                jax.config.update("jax_platforms", plats + ",cpu")
        except Exception:
            pass
        try:
            # backend="cpu" queries the CPU backend explicitly — plain
            # local_devices() lists only the DEFAULT backend, which on a
            # TPU host would leave this empty and fall back to the TPU
            return tuple(jax.local_devices(backend="cpu"))
        except RuntimeError:
            return ()
    if kind == "accel":
        # Whatever non-CPU platform is live (tpu under axon, else cpu).
        devs = [d for d in jax.local_devices() if d.platform != "cpu"]
        return tuple(devs) if devs else tuple(jax.local_devices())
    return tuple(jax.local_devices())


class CPUPlace(Place):
    device_kind = "cpu"

    def __init__(self):
        super().__init__(0)


class TPUPlace(Place):
    device_kind = "accel"

    def is_accelerator(self):
        return True


class CUDAPlace(TPUPlace):
    """Reference-compat alias: routes to the accelerator (TPU) device."""


class CUDAPinnedPlace(CPUPlace):
    """Reference-compat alias; pinned host staging is managed by XLA."""

    def __init__(self):
        Place.__init__(self, 0)


def _default_place():
    devs = [d for d in jax.devices() if d.platform != "cpu"]
    return TPUPlace(0) if devs else CPUPlace()


def is_compiled_with_cuda():
    return False


def is_compiled_with_tpu():
    return any(d.platform != "cpu" for d in jax.devices())
