"""Online learning against live pservers, while serving reads.

The legacy async-SGD capability (ParameterServer2 async paths) recast
for the serving tier: a background updater pushes SelectedRows sparse
gradients to the SAME row shards the ScoringEngine's SparseClient
reads from — the pserver's server-side lazy sparse optimizer applies
them row-at-a-time, and the hot-ID cache's bounded staleness caps how
long a serve can keep returning the pre-update row.

Two pieces:

  * ``OnlineTrainer`` — routes deduplicated sparse grads per shard
    (global row ids, ``id % n`` placement — the ``send_sparse`` host
    op's wire shape) under ROUND-format idempotency tags, so the
    retry ``Policy`` may transparently re-issue a torn push without
    double-applying (the pserver's tag dedup is the same machinery
    the training tier rides). A per-push barrier closes the round on
    every shard (the pservers run ``fan_in`` = the updater count).
  * ``measure_staleness`` — the read-your-writes probe: land one
    update (push + barrier acked = t_land), then read the touched row
    THROUGH the serving cache until the value reflects it; the delta
    is the end-to-end staleness the SLO ``staleness_s`` objective
    gates (observed into ``ptpu_sparse_staleness_seconds`` + a
    ``sparse_staleness`` recorder row). By construction it is bounded
    by cache ``staleness_s`` + one pserver round + one wire trip —
    the contract the bound exists to give.
"""

import itertools
import os
import threading
import time
import uuid

import numpy as np

from ...core.selected_rows import SelectedRows
from ...distributed.rpc import RPCClient
from ...monitor import runtime as _monrt
from ...resilience.retry import default_policy

__all__ = ["OnlineTrainer", "measure_staleness"]


class OnlineTrainer:
    """Push sparse row gradients of ONE table to its live shards.

    ``grad_name`` defaults to ``<table>@GRAD`` (what the pserver's
    optimize block binds). ``update_fn``: optional callable returning
    ``(ids, grad_rows)`` per tick for the background loop; without it
    the trainer is push-driven (call ``push`` yourself)."""

    def __init__(self, table, endpoints, grad_name=None, height=None,
                 update_fn=None, interval=0.05, retry=None,
                 trainer_id=None, kv=None, role="ps"):
        self.table = table
        self.grad_name = grad_name or (table + "@GRAD")
        self.height = height
        self._eps = list(endpoints)
        if not self._eps:
            # an empty shard list would make push() report rounds as
            # landed while sending nothing — the config error must
            # fail HERE, not as a misleading staleness timeout later
            raise ValueError("OnlineTrainer needs >= 1 shard endpoint")
        self._kv = kv
        self._role = role
        self._retry = retry if retry is not None else default_policy()
        self._clients = {}
        self._update_fn = update_fn
        self._interval = float(interval)
        # ROUND-format tag prefix ('t<id>:i<inc>:s<seq>'): licenses
        # transparent retry re-issue — the pserver dedups by parsed
        # prefix + seq across rounds (rpc.py SEND/BARR)
        tid = trainer_id if trainer_id is not None \
            else "online%d" % os.getpid()
        self._pref = "t%s:i%016x%s" % (tid, int(time.time() * 1e6),
                                       uuid.uuid4().hex[:4])
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self.stats = {"pushes": 0, "rows": 0, "rounds": 0,
                      "errors": 0}

    def _client(self, shard):
        cli = self._clients.get(shard)
        if cli is None:
            resolver = None
            if self._kv is not None:
                # membership-backed resolver per shard slot, like
                # SparseClient's: a replacement pserver recovered from
                # checkpoint on a new port is followed transparently
                from ...distributed import membership as _membership
                key = _membership.role_prefix(self._role) + str(shard)
                kv = self._kv

                def resolver(key=key):
                    ep = kv.get(key)
                    if ep and not ep.startswith(
                            _membership.EVICTED_PREFIX):
                        return ep
                    return None

            cli = self._clients[shard] = RPCClient(
                self._eps[shard], timeout=10.0, retry=self._retry,
                resolver=resolver)
        return cli

    def _drop_client(self, shard):
        cli = self._clients.pop(shard, None)
        if cli is not None:
            cli.close()

    def push(self, ids, grad_rows):
        """Route one batch of (global id, grad row) pairs to their
        shards and close the round with a barrier on EVERY shard (a
        shard that received no rows this round still needs the round
        signal — listen_and_serv fan_in semantics). Duplicate ids are
        summed first (lookup_table_grad SelectedRows semantics).
        Returns the wall-clock instant the round was fully applied
        (every barrier acked) — the 'update landed' stamp the
        staleness probe measures from."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        rows = np.asarray(grad_rows, np.float32).reshape(len(ids), -1)
        uniq, inv = np.unique(ids, return_inverse=True)
        acc = np.zeros((len(uniq), rows.shape[1]), rows.dtype)
        np.add.at(acc, inv, rows)
        n = max(1, len(self._eps))
        height = self.height if self.height is not None else 0
        with self._lock:
            tag = "%s:s%d" % (self._pref, next(self._seq))
            try:
                for i in range(len(self._eps)):
                    mask = (uniq % n) == i
                    if mask.any():
                        self._client(i).send_var(
                            self.grad_name,
                            SelectedRows(uniq[mask], acc[mask],
                                         height),
                            tag=tag)
                for i in range(len(self._eps)):
                    self._client(i).barrier(tag=tag)
            except BaseException:
                # a push that died past the retry deadline may leave a
                # cached client mid-stream on a replaced endpoint —
                # rebuild lazily so the NEXT round re-resolves fresh
                for i in range(len(self._eps)):
                    self._drop_client(i)
                raise
            self.stats["pushes"] += 1
            self.stats["rows"] += int(len(uniq))
            self.stats["rounds"] += 1
        return time.perf_counter()

    # -- background loop ---------------------------------------------------
    def start(self):
        if self._update_fn is None:
            raise ValueError("start() needs an update_fn")
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="ptpu-online")
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self._interval):
            try:
                ids, rows = self._update_fn()
                if len(np.asarray(ids).reshape(-1)):
                    self.push(ids, rows)
            except Exception:
                # a torn push past the retry deadline (mid-respawn):
                # counted, retried next tick — the updater must not
                # die while the pserver recovers
                self.stats["errors"] += 1

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def close(self):
        self.stop()
        with self._lock:
            clients, self._clients = self._clients, {}
        for cli in clients.values():
            cli.close()


def measure_staleness(trainer, client, probe_id, delta=1.0,
                      timeout=30.0, poll_s=0.005):
    """End-to-end read-your-writes staleness for ONE update:

    1. read the probe row through the serving cache (pre-image),
    2. land an update moving it by ``delta`` (push + every barrier
       acked = t_land),
    3. poll the SAME serving read path until the returned row reflects
       the update; staleness = that instant - t_land.

    The serving path is the measured object: a cached pre-image row
    legitimately serves until the staleness bound stales it, so the
    measured figure ≈ cache residual age + one wire trip — the
    quantity the SLO ``staleness_s`` objective bounds. Observed into
    the ``ptpu_sparse_staleness_seconds`` histogram + a
    ``sparse_staleness`` recorder row."""
    probe_id = int(probe_id)
    before = np.asarray(client.lookup([probe_id])[0], np.float64)
    width = before.shape[-1]
    # the pserver applies -lr * grad; any sign works — we only need
    # the serve-visible value to MOVE
    grad = np.full((1, width), float(delta), np.float32)
    t_land = trainer.push([probe_id], grad)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        now_row = np.asarray(client.lookup([probe_id])[0], np.float64)
        if not np.allclose(now_row, before):
            staleness = time.perf_counter() - t_land
            _monrt.on_sparse_staleness(staleness, table=client.table)
            return staleness
        time.sleep(poll_s)
    raise TimeoutError(
        "update to id %d never became visible through the serving "
        "path within %.1fs (stale-forever row — the contract the "
        "staleness bound exists to forbid)" % (probe_id, timeout))
