"""Distributed lookup table end-to-end (round-2 verdict #3).

The reference shards an `is_distributed` embedding table across pservers
and rewrites the trainer: split_ids + prefetch of just the needed rows,
sparse SelectedRows grads routed per shard
(distribute_transpiler.py:201-255, operators/prefetch_op.cc,
lookup_table_op.cc:81). Here the DistributeTranspiler performs the same
rewrite over the Program IR: lookup_table → prefetch, table + optimizer
state row-sharded (mod placement, compact ceil(V/n) local stores) across
ALL servers, send_sparse routing deduped SelectedRows grads — and the
trainer's fwd+bwd still runs as ONE compiled XLA segment (the prefetched
rows are a concrete gradient leaf; no eager fallback).
"""

import threading
import time

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.distributed import ops as dist_ops
from paddle_tpu.distributed.rpc import RPCClient, VariableServer


VOCAB, DIM = 10, 4


def _probe_ports(n):
    eps = []
    for _ in range(n):
        probe = VariableServer()
        eps.append("127.0.0.1:%d" % probe.port)
        probe.stop()
    return eps


def _build_net(optimizer, is_distributed):
    """Embedding-MLP: ids -> distributed table -> fc -> mse loss."""
    ids = fluid.layers.data("ids", [1], dtype="int64")
    y = fluid.layers.data("y", [1])
    emb = fluid.layers.embedding(
        ids, size=[VOCAB, DIM], is_sparse=True,
        is_distributed=is_distributed,
        param_attr=fluid.ParamAttr(
            name="dist_emb",
            initializer=fluid.initializer.Constant(0.1)))
    pred = fluid.layers.fc(
        emb, 1, bias_attr=False,
        param_attr=fluid.ParamAttr(
            name="dist_fc_w",
            initializer=fluid.initializer.Constant(0.2)))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    optimizer().minimize(loss)
    return loss


def _batches(steps):
    # every vocab id appears in every batch: then dense Adam == lazy
    # (row-sparse) Adam exactly — a row absent from a step would still
    # get a moment-decay update under dense Adam but not under lazy
    # Adam (the reference's SelectedRows adam is lazy too)
    rng = np.random.RandomState(7)
    out = []
    for _ in range(steps):
        ids = np.concatenate([
            np.arange(VOCAB, dtype=np.int64),
            rng.randint(0, VOCAB, size=(6,)).astype(np.int64)])[:, None]
        yv = (ids.astype(np.float32) * 0.05 + 0.3)
        out.append({"ids": ids, "y": yv})
    return out


def _run_local(optimizer, steps=5):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        loss = _build_net(optimizer, is_distributed=False)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = []
        for feed in _batches(steps):
            l, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(l)))
        table = np.asarray(scope.find_var("dist_emb")).copy()
        fc_w = np.asarray(scope.find_var("dist_fc_w")).copy()
    return losses, table, fc_w


def _run_distributed(optimizer, n_servers=2, steps=5):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    server_threads, server_scopes = [], []
    eps = _probe_ports(n_servers)
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        loss = _build_net(optimizer, is_distributed=True)
        t = fluid.DistributeTranspiler(mode="pserver")
        t.transpile(trainer_id=0, program=main,
                    pservers=",".join(eps), trainers=1)

        # the trainer program must hold NO lookup_table op and not
        # initialize the [V, D] table (it lives only on the servers)
        trainer_ops = [o.type for o in main.global_block().ops]
        assert "lookup_table" not in trainer_ops
        assert "prefetch" in trainer_ops
        assert "send_sparse" in trainer_ops
        startup_outs = {n for o in startup.global_block().ops
                        for ns in o.outputs.values() for n in ns}
        assert "dist_emb" not in startup_outs

        for ep in eps:
            pserver_prog = t.get_pserver_program(ep)
            pstartup = t.get_startup_program(ep)
            sscope = fluid.Scope()
            exe_s = fluid.Executor(fluid.CPUPlace())
            with fluid.scope_guard(sscope):
                exe_s.run(pstartup)
            # each server holds only its ceil(V/n) row shard
            shard = np.asarray(sscope.find_var("dist_emb"))
            assert shard.shape == (-(-VOCAB // n_servers), DIM), shard.shape

            def run(prog=pserver_prog, sc=sscope):
                fluid.Executor(fluid.CPUPlace()).run(
                    prog, feed={}, fetch_list=[], scope=sc)

            th = threading.Thread(target=run, daemon=True)
            th.start()
            server_threads.append(th)
            server_scopes.append(sscope)
        time.sleep(0.5)

        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = []
        try:
            for feed in _batches(steps):
                l, = exe.run(main, feed=feed, fetch_list=[loss])
                losses.append(float(np.asarray(l)))
            # trainer fwd+bwd ran as compiled segments, not the op
            # interpreter (the lifted eager fallback)
            assert [k for k in exe._cache if k[0] == "segment"], \
                "sharded-table trainer was not segment compiled"
            fc_w = np.asarray(scope.find_var("dist_fc_w")).copy()
        finally:
            for ep in eps:
                try:
                    cli = RPCClient(ep)
                    cli.shutdown_server()
                    cli.close()
                except OSError:
                    pass
            dist_ops.reset_clients()
        # the server commits its store to the scope after listen_and_serv
        # returns — join before reading the shards
        for th in server_threads:
            th.join(timeout=5)
        # reassemble the global table from the shards for comparison
        table = np.zeros((VOCAB, DIM), np.float32)
        for i, sscope in enumerate(server_scopes):
            shard = np.asarray(sscope.find_var("dist_emb"))
            for local in range(shard.shape[0]):
                g = local * n_servers + i
                if g < VOCAB:
                    table[g] = shard[local]
    return losses, table, fc_w


def test_sharded_table_sgd_matches_local():
    l_local, t_local, w_local = _run_local(
        lambda: fluid.optimizer.SGD(learning_rate=0.1))
    l_dist, t_dist, w_dist = _run_distributed(
        lambda: fluid.optimizer.SGD(learning_rate=0.1))
    np.testing.assert_allclose(l_dist, l_local, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(t_dist, t_local, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(w_dist, w_local, rtol=1e-5, atol=1e-6)


def test_sharded_table_lazy_adam_matches_local():
    # dense Adam == lazy (row-sparse) Adam when moments start at zero:
    # untouched rows see zero grads and zero moments, so they hold still
    l_local, t_local, w_local = _run_local(
        lambda: fluid.optimizer.Adam(learning_rate=0.05))
    l_dist, t_dist, w_dist = _run_distributed(
        lambda: fluid.optimizer.Adam(learning_rate=0.05))
    np.testing.assert_allclose(l_dist, l_local, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(t_dist, t_local, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(w_dist, w_local, rtol=1e-4, atol=1e-5)


def test_deepfm_distributed_tables_train():
    """DeepFM with both FM tables `is_distributed` across two pservers:
    the CTR workload SURVEY §7 M5 names, trained end-to-end sharded."""
    from paddle_tpu.models import deepfm as dfm

    eps = _probe_ports(2)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    server_threads = []
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        fields = [fluid.layers.data("field_%d" % i, [1], dtype="int64")
                  for i in range(4)]
        label = fluid.layers.data("click", [1])
        prob, logit = dfm.deepfm(fields, vocab_size=50, embed_dim=4,
                                 dnn_dims=(16,), is_sparse=True,
                                 is_distributed=True)
        loss = fluid.layers.mean(
            fluid.layers.sigmoid_cross_entropy_with_logits(logit, label))
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)

        t = fluid.DistributeTranspiler(mode="pserver")
        t.transpile(trainer_id=0, program=main,
                    pservers=",".join(eps), trainers=1)
        assert len(t._dist_tables) == 2   # fm_first_w, fm_second_w

        for ep in eps:
            pserver_prog = t.get_pserver_program(ep)
            pstartup = t.get_startup_program(ep)
            sscope = fluid.Scope()
            with fluid.scope_guard(sscope):
                fluid.Executor(fluid.CPUPlace()).run(pstartup)

            def run(prog=pserver_prog, sc=sscope):
                fluid.Executor(fluid.CPUPlace()).run(
                    prog, feed={}, fetch_list=[], scope=sc)

            th = threading.Thread(target=run, daemon=True)
            th.start()
            server_threads.append(th)
        time.sleep(0.5)

        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(3)
        losses = []
        try:
            # one fixed batch with learnable labels (click = f(ids)):
            # repeated steps must drive the loss down
            feed = {"field_%d" % i:
                    rng.randint(0, 50, (16, 1)).astype(np.int64)
                    for i in range(4)}
            feed["click"] = (feed["field_0"] % 2).astype(np.float32)
            for _ in range(8):
                l, = exe.run(main, feed=feed, fetch_list=[loss])
                losses.append(float(np.asarray(l)))
        finally:
            for ep in eps:
                try:
                    cli = RPCClient(ep)
                    cli.shutdown_server()
                    cli.close()
                except OSError:
                    pass
            dist_ops.reset_clients()
        for th in server_threads:
            th.join(timeout=5)
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_sharded_table_two_trainers_match_single_double_batch():
    """TWO trainers against TWO pservers with a sharded table: the
    merged round (sum of both trainers' sparse + dense grads) must equal
    ONE trainer training on the concatenated batch with summed loss
    scaling — i.e. fan_in=2 sparse merging is exact."""
    import queue as _queue

    steps = 3
    batches = _batches(steps)
    # two trainers each see half of every batch
    halves = [[{k: v[:len(v) // 2] for k, v in b.items()}
               for b in batches],
              [{k: v[len(v) // 2:] for k, v in b.items()}
               for b in batches]]

    # reference: single trainer over the same HALF batch sizes but with
    # grads summed across the two halves — run trainer 0's stream and
    # trainer 1's stream against fresh servers with fan_in=2 below, and
    # compare against the local model trained on the FULL batch with
    # 0.5x learning rate scaling... simpler exact check: distributed
    # two-trainer losses must be finite and the final table equals a
    # LOCAL run applying the SUM of half-batch mean-gradients per step.
    import paddle_tpu.core.backward as _bwd

    def local_sum_of_halves():
        main, startup = fluid.Program(), fluid.Program()
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), fluid.scope_guard(scope):
            loss = _build_net(
                lambda: fluid.optimizer.SGD(learning_rate=0.1), False)
            # reference computes grads ONLY — strip the built-in sgd ops
            # (they would double-apply on top of the manual update below)
            gb = main.global_block()
            for op in [o for o in gb.ops if o.type == "sgd"]:
                gb.ops.remove(op)
            main._bump_version()
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            # emulate the pserver round: grad = sum over the two
            # trainers' half-batch mean grads; apply SGD manually
            params = [p.name for p in gb.all_parameters()
                      if p.trainable]
            for s in range(steps):
                gsums = {}
                for t in range(2):
                    outs = exe.run(
                        main, feed=halves[t][s],
                        fetch_list=[loss] + ["%s@GRAD" % p
                                             for p in params])
                    for p, gv in zip(params, outs[1:]):
                        gsums[p] = gsums.get(p, 0) + np.asarray(gv)
                for p in params:
                    cur = np.asarray(scope.find_var(p))
                    scope.set(p, cur - 0.1 * gsums[p])
            table = np.asarray(scope.find_var("dist_emb")).copy()
            fc = np.asarray(scope.find_var("dist_fc_w")).copy()
        return table, fc

    t_want, w_want = local_sum_of_halves()

    eps = _probe_ports(2)
    main, startup = fluid.Program(), fluid.Program()
    server_scopes, server_threads = [], []
    with fluid.program_guard(main, startup):
        loss = _build_net(
            lambda: fluid.optimizer.SGD(learning_rate=0.1), True)
        t = fluid.DistributeTranspiler(mode="pserver")
        t.transpile(trainer_id=0, program=main,
                    pservers=",".join(eps), trainers=2)
        for ep in eps:
            pprog = t.get_pserver_program(ep)
            pstart = t.get_startup_program(ep)
            sscope = fluid.Scope()
            with fluid.scope_guard(sscope):
                fluid.Executor(fluid.CPUPlace()).run(pstart)

            def run(p=pprog, s=sscope):
                fluid.Executor(fluid.CPUPlace()).run(
                    p, feed={}, fetch_list=[], scope=s)

            th = threading.Thread(target=run, daemon=True)
            th.start()
            server_scopes.append(sscope)
            server_threads.append(th)
        time.sleep(0.5)

    errs = _queue.Queue()

    # build each trainer's program SEQUENTIALLY (program_guard is a
    # global stack, not thread-safe); threads then only run steps —
    # each thread gets its own RPC connections (thread-local cache)
    trainers = []
    for tid in range(2):
        m2, s2 = fluid.Program(), fluid.Program()
        sc2 = fluid.Scope()
        with fluid.program_guard(m2, s2):
            l2 = _build_net(
                lambda: fluid.optimizer.SGD(learning_rate=0.1), True)
            t2 = fluid.DistributeTranspiler(mode="pserver")
            t2.transpile(trainer_id=tid, program=m2,
                         pservers=",".join(eps), trainers=2)
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(s2, scope=sc2)
        trainers.append((m2, sc2, exe2, l2))

    def trainer(tid):
        try:
            m2, sc2, exe2, l2 = trainers[tid]
            for s in range(steps):
                exe2.run(m2, feed=halves[tid][s], fetch_list=[l2],
                         scope=sc2)
        except BaseException as e:                  # surfaced below
            errs.put((tid, repr(e)))

    ths = [threading.Thread(target=trainer, args=(i,)) for i in range(2)]
    for th in ths:
        th.start()
    for th in ths:
        th.join(timeout=120)
    assert errs.empty(), list(errs.queue)

    for ep in eps:
        try:
            cli = RPCClient(ep)
            cli.shutdown_server()
            cli.close()
        except OSError:
            pass
    dist_ops.reset_clients()
    for th in server_threads:
        th.join(timeout=5)

    table = np.zeros((VOCAB, DIM), np.float32)
    for i, sscope in enumerate(server_scopes):
        shard = np.asarray(sscope.find_var("dist_emb"))
        for local in range(shard.shape[0]):
            g = local * 2 + i
            if g < VOCAB:
                table[g] = shard[local]
    np.testing.assert_allclose(table, t_want, rtol=1e-4, atol=1e-5)
