"""Incident forensics: fleet black-box capture + bundle assembly.

The detect half of the loop exists (signals burn-rate incidents with
offender trace ids); this module is the diagnose half. When an
incident transitions OPEN (``attach()`` installs ``capture()`` as the
Signals capture hook) — or on demand from the CLI — a coordinator fans
the ``DUMP`` verb out across the lease registry and assembles every
process's black box into one CRC-manifested bundle directory:

    <dir>/
      __manifest__.json            completeness marker + per-file CRCs
      incident.json                the FIRING transition (rule, window
                                   figures, offender trace ids)
      part-<role>-<pid>.json       one DUMP reply: metrics snapshot,
                                   non-default flags, recorder ring
                                   tail, role state (engine slots /
                                   queue counts / registry view)
      part-<role>-<pid>.spans.jsonl   the process's tail span ring +
                                   server ports, 'ev'-tagged exactly
                                   like a span log so trace.merge
                                   consumes it unchanged
      part-coordinator-<pid>...    the capturing process itself (its
                                   ring holds the client/router spans)
                                   plus the capture-time clock-offset
                                   samples that skew-correct the rest

Capture must never stall serving: each endpoint gets a bounded
deadline and the fan-out DROPS slow or dead processes (recorded in the
manifest as ``missing`` — who failed to answer is itself forensic
signal). ``verify()`` re-hashes every part against the manifest;
``render()`` (the ``monitor bundle`` CLI) draws the skew-corrected
cross-process span tree centered on the offender traces.
"""

import json
import os
import socket
import threading
import time

from . import metrics as _metrics

__all__ = ["capture", "verify", "render", "attach", "last_bundle",
           "BUNDLE_MANIFEST"]

_REG = _metrics.registry()

BUNDLES = _REG.counter(
    "ptpu_forensics_bundles_total",
    "incident bundles assembled (autonomous capture-on-FIRING + CLI "
    "--capture)")
DUMP_FAILURES = _REG.counter(
    "ptpu_forensics_dump_failures_total",
    "DUMP captures dropped by the per-process deadline or a dead "
    "endpoint — the bundle records them as missing", ("role",))

BUNDLE_MANIFEST = "__manifest__.json"
BUNDLE_FORMAT = "ptpu-forensics-1"

_LAST_BUNDLE = None
_LAST_LOCK = threading.Lock()


def last_bundle():
    """Path of the most recent bundle this process assembled, or None
    — the pointer the watch dashboard's incidents line shows."""
    with _LAST_LOCK:
        return _LAST_BUNDLE


def _set_last(path):
    global _LAST_BUNDLE
    with _LAST_LOCK:
        _LAST_BUNDLE = path


# -- capture ---------------------------------------------------------------

def _capture_one(role, ep, timeout, clock_probes=3):
    """One endpoint's black box: a few CLKS round trips (midpoint
    clock-offset samples — the merge needs an edge from the
    coordinator to every captured process) then one DUMP. Raises on
    any failure; the fan-out turns that into a ``missing`` entry."""
    from ..distributed.rpc import _send_msg, _recv_msg
    from ..trace import clock as _clock
    host, port = ep.rsplit(":", 1)
    s = socket.create_connection((host, int(port)), timeout=timeout)
    s.settimeout(timeout)
    try:
        clocks = []
        for _ in range(max(1, int(clock_probes))):
            t0 = time.time()
            _send_msg(s, "CLKS", "", b"")
            rop, _name, payload = _recv_msg(s)
            t3 = time.time()
            if rop != "OK":
                break
            server_t = float(json.loads(bytes(payload).decode())["t"])
            off, rtt = _clock.midpoint_offset(t0, server_t, t3)
            clocks.append({"ev": "clock", "ts": t3, "peer": ep,
                           "offset": off, "rtt": rtt,
                           "pid": os.getpid(), "proc": "forensics"})
        _send_msg(s, "DUMP", "", b"{}")
        rop, _name, payload = _recv_msg(s)
        if rop != "VAL":
            raise ConnectionError("DUMP reply %s from %s" % (rop, ep))
        part = json.loads(bytes(payload).decode())
        part["endpoint"] = ep
        part["discovered_role"] = role
        part["capture_clocks"] = clocks
        return part
    finally:
        try:
            s.close()
        except OSError:
            pass


def _discover(kv_endpoint, static, roles):
    """(role, endpoint) pairs: the collector's discovery (lease
    registry + statics), reused so capture sees exactly the fleet the
    dashboard sees."""
    from .collector import Collector, TELEMETRY_ROLE
    if roles is None:
        roles = ("ps", "replica", TELEMETRY_ROLE)
    c = Collector(kv_endpoint=kv_endpoint, roles=roles,
                  static=tuple(static or ()))
    try:
        return c._discover()
    finally:
        if c._kv is not None:
            c._kv.close()


def _local_part():
    """The coordinator's own black box — no RPC round trip (we ARE the
    process): tail span ring, recorder tail, metrics, flags. In an
    in-process fleet this part carries the client and router spans of
    the offender requests."""
    from . import runtime as _monrt
    part = {"role": "coordinator", "pid": os.getpid(),
            "t": time.time()}
    try:
        reg = _metrics.registry()
        part["incarnation"] = reg.incarnation
        part["uptime_s"] = reg.uptime_s()
        part["snapshot"] = reg.snapshot()
    except Exception:
        pass
    try:
        from .. import flags as _flags
        part["flags"] = _flags.overrides()
    except Exception:
        pass
    try:
        from ..trace import runtime as _trc
        part["spans"] = _trc.tail_dump()
    except Exception:
        pass
    rec = _monrt.recorder()
    if rec is not None:
        try:
            _cur, rows, lost = rec.events_since(None)
            part["events"] = rows[-1024:]
            part["events_lost"] = lost
            part["ring"] = rec.ring_id
        except Exception:
            pass
    return part


def _fan_out(targets, deadline_s):
    """DUMP every target concurrently with drop-if-slow semantics:
    each endpoint gets ``deadline_s``; a thread still running at the
    overall deadline is abandoned (daemon) and its target recorded as
    missing — a wedged replica must cost the bundle one part, not
    stall the capture (or the serving path behind it)."""
    parts, missing, lock = [], [], threading.Lock()
    done = set()

    def work(idx, role, ep):
        try:
            part = _capture_one(role, ep, timeout=deadline_s)
        except Exception as e:
            with lock:
                done.add(idx)
                missing.append({"role": role, "endpoint": ep,
                                "error": repr(e)})
            DUMP_FAILURES.inc(role=role)
            return
        with lock:
            done.add(idx)
            parts.append(part)

    threads = []
    for idx, (role, ep) in enumerate(targets):
        th = threading.Thread(target=work, args=(idx, role, ep),
                              daemon=True,
                              name="forensics-dump-%s" % ep)
        th.start()
        threads.append(th)
    deadline = time.monotonic() + deadline_s + 0.5
    for th in threads:
        th.join(max(0.0, deadline - time.monotonic()))
    with lock:
        for idx, (role, ep) in enumerate(targets):
            if idx not in done:
                done.add(idx)
                missing.append({"role": role, "endpoint": ep,
                                "error": "deadline exceeded (%.1fs)"
                                         % deadline_s})
                DUMP_FAILURES.inc(role=role)
        return list(parts), list(missing)


def _bundle_dir(base):
    if not base:
        from .. import flags as _flags
        base = _flags.get_flag("forensics_dir") or "forensics_bundles"
    name = "bundle-%d-%d" % (int(time.time() * 1000), os.getpid())
    path = os.path.join(base, name)
    os.makedirs(path, exist_ok=True)
    return path


def capture(incident=None, kv_endpoint=None, static=(), endpoints=None,
            roles=None, deadline_s=2.0, out_dir=None):
    """Assemble one incident bundle; returns its directory path.

    ``incident`` is a Signals FIRING transition dict (or None for an
    on-demand CLI capture). Targets come from ``endpoints`` ([(role,
    "host:port")]) when given, else lease-registry discovery via
    ``kv_endpoint`` + ``static``. Never raises past assembly errors a
    caller could do nothing about: a completely unreachable fleet
    still yields a bundle holding the coordinator part + the incident
    — partial forensics beat none."""
    from ..io import write_atomic_blob, write_json_atomic
    targets = list(endpoints) if endpoints is not None else \
        _discover(kv_endpoint, static, roles)
    parts, missing = _fan_out(targets, float(deadline_s)) \
        if targets else ([], [])
    parts.append(_local_part())
    path = _bundle_dir(out_dir)
    manifest = {"format": BUNDLE_FORMAT, "t": time.time(),
                "coordinator_pid": os.getpid(),
                "deadline_s": float(deadline_s),
                "parts": [], "missing": missing}
    if incident is not None:
        data = json.dumps(incident, default=repr).encode()
        manifest["incident_file"] = "incident.json"
        manifest["incident_crc32"] = write_atomic_blob(
            path, "incident.json", data)
        manifest["rule"] = incident.get("rule")
        manifest["offenders"] = [o.get("trace") for o in
                                 incident.get("offenders") or ()
                                 if o.get("trace")]
    used = set()
    for part in parts:
        spans = part.pop("spans", None)
        role = str(part.get("role", "proc"))
        pid = part.get("pid", 0)
        stem = "part-%s-%s" % (role.replace(os.sep, "_"), pid)
        # an in-process fleet shares one pid across roles: uniquify so
        # no part silently overwrites another's blob (the CRCs in the
        # manifest would then convict the survivor)
        n = 1
        while stem in used:
            n += 1
            stem = "part-%s-%s-%d" % (role.replace(os.sep, "_"),
                                      pid, n)
        used.add(stem)
        ent = {"file": stem + ".json", "role": role, "pid": pid,
               "endpoint": part.get("endpoint")}
        # capture-time clock samples ride in the SPANS file: they are
        # merge rows (coordinator pid -> endpoint edges), not state
        clocks = part.pop("capture_clocks", None) or []
        rows = list(clocks) + list(spans or [])
        ent["crc32"] = write_atomic_blob(
            path, ent["file"], json.dumps(part, default=repr).encode())
        if rows:
            blob = "\n".join(json.dumps(r, default=repr)
                             for r in rows).encode() + b"\n"
            ent["spans_file"] = stem + ".spans.jsonl"
            ent["spans_crc32"] = write_atomic_blob(
                path, ent["spans_file"], blob)
        manifest["parts"].append(ent)
    # the manifest lands LAST (atomic rename): its presence IS the
    # bundle's completeness marker, same contract as io checkpoints
    write_json_atomic(os.path.join(path, BUNDLE_MANIFEST), manifest)
    BUNDLES.inc()
    _set_last(path)
    return path


def attach(sig, **capture_kwargs):
    """Install autonomous capture-on-FIRING on a Signals evaluator:
    every incident OPEN transition assembles a bundle (offender traces
    are promoted by signals itself before the hook runs). Returns the
    hook so tests can call it directly."""

    def hook(tr):
        capture(incident=tr, **capture_kwargs)

    sig.capture_hook = hook
    return hook


def incidents_line(signals):
    """The one-line incidents summary the watch dashboards render
    under the alerts line: active incident count + rule names from the
    signals state, plus the most recent bundle this process assembled.
    Returns None when there is nothing to show (the frame stays
    byte-identical to pre-forensics output for quiet fleets)."""
    act = signals.active()
    bundle = last_bundle()
    if not act and bundle is None:
        return None
    if act:
        names = " ".join(sorted(act))
        line = "incident  %d active (%s)" % (len(act), names)
    else:
        line = "incident  none active"
    if bundle is not None:
        line += "   bundle %s" % bundle
    return line


# -- verify ----------------------------------------------------------------

def load_manifest(path):
    """The bundle manifest dict. Raises OSError/ValueError on a
    missing or unreadable manifest (CLI: usage error, exit 2)."""
    with open(os.path.join(path, BUNDLE_MANIFEST)) as f:
        m = json.load(f)
    if not isinstance(m, dict) or m.get("format") != BUNDLE_FORMAT:
        raise ValueError("not a forensics bundle (format %r)"
                         % (m.get("format") if isinstance(m, dict)
                            else None))
    return m


def verify(path, manifest=None):
    """Re-hash every manifested file. Returns a list of problem
    strings — empty means the bundle is intact."""
    import zlib
    if manifest is None:
        manifest = load_manifest(path)
    problems = []

    def check(fname, want):
        full = os.path.join(path, fname)
        try:
            with open(full, "rb") as f:
                data = f.read()
        except OSError as e:
            problems.append("%s: missing/unreadable (%s)" % (fname, e))
            return
        if zlib.crc32(data) != want:
            problems.append("%s: CRC mismatch (truncated or bit-"
                            "flipped write?)" % fname)

    if manifest.get("incident_file"):
        check(manifest["incident_file"], manifest["incident_crc32"])
    for ent in manifest.get("parts", ()):
        check(ent["file"], ent["crc32"])
        if ent.get("spans_file"):
            check(ent["spans_file"], ent["spans_crc32"])
    return problems


# -- render (the `monitor bundle` CLI body) --------------------------------

def _offender_traces(data, seeds):
    """Expand the offender trace-id set across the request-id join:
    the serving request span is a separate ROOT in the replica process
    (engine threads are unreachable from an ambient RPC stack), linked
    to the router/client spans by the ``rid`` attr. offender traces ->
    their rids -> every trace touching those rids."""
    seeds = {t for t in seeds if t}
    rids = set()
    for s in data["spans"]:
        if s.get("trace") in seeds:
            rid = (s.get("attrs") or {}).get("rid")
            if rid:
                rids.add(rid)
    traces = set(seeds)
    if rids:
        for s in data["spans"]:
            if (s.get("attrs") or {}).get("rid") in rids:
                traces.add(s.get("trace"))
    return traces, rids


def _render_tree(spans, offsets, procs, emit):
    """One skew-corrected span tree: children indented under parents,
    cross-process spans labeled with their lane."""
    from ..trace.merge import _corrected
    by_parent = {}
    by_id = {s["span"]: s for s in spans}
    roots = []
    for s in spans:
        p = s.get("parent")
        if p is not None and p in by_id:
            by_parent.setdefault(p, []).append(s)
        else:
            roots.append(s)
    base = min((_corrected(s, offsets) for s in spans), default=0.0)

    def walk(s, depth):
        t = _corrected(s, offsets) - base
        attrs = s.get("attrs") or {}
        extra = ""
        if attrs.get("error"):
            extra = "  ERROR %s" % attrs["error"]
        elif attrs.get("rid"):
            extra = "  rid=%s" % attrs["rid"]
        emit("    %s%-28s +%7.1fms %8.1fms  [%s]%s" % (
            "  " * depth, s["name"], t * 1000.0,
            float(s["dur"]) * 1000.0,
            procs.get(s["pid"], "pid%s" % s["pid"]), extra))
        for c in sorted(by_parent.get(s["span"], ()),
                        key=lambda c: _corrected(c, offsets)):
            walk(c, depth + 1)

    for r in sorted(roots, key=lambda s: _corrected(s, offsets)):
        walk(r, 0)


def render(path, out=None, lookback_s=600.0):
    """Verify + render a bundle to ``out`` (a line sink; default
    print). Returns an exit code on the analysis/slo convention:
    0 = rendered, bundle intact; 1 = CRC verification failed;
    the caller maps missing/unreadable bundles to 2."""
    from ..trace import merge as _merge
    emit = out if out is not None else print
    manifest = load_manifest(path)
    problems = verify(path, manifest)
    emit("forensics bundle %s" % path)
    emit("  captured %s  coordinator pid %s  deadline %.1fs" % (
        time.strftime("%Y-%m-%d %H:%M:%S",
                      time.localtime(manifest.get("t", 0))),
        manifest.get("coordinator_pid"), manifest.get("deadline_s", 0)))
    if problems:
        for p in problems:
            emit("  CORRUPT %s" % p)
        return 1
    emit("  manifest verified: %d part(s), %d missing, CRC ok" % (
        len(manifest.get("parts", ())),
        len(manifest.get("missing", ()))))
    for miss in manifest.get("missing", ()):
        emit("  MISSING %s %s: %s" % (miss.get("role"),
                                      miss.get("endpoint"),
                                      miss.get("error")))
    # -- incident summary
    incident, offender_ids = None, list(manifest.get("offenders") or ())
    if manifest.get("incident_file"):
        with open(os.path.join(path, manifest["incident_file"])) as f:
            incident = json.load(f)
        emit("incident: %s  severity=%s  state=%s  at %s" % (
            incident.get("rule"), incident.get("severity"),
            incident.get("state"),
            time.strftime("%H:%M:%S",
                          time.localtime(incident.get("ts", 0)))))
        figs = incident.get("figures") or {}
        if figs:
            # the burn-rate window that tripped, verbatim figures
            emit("  window: " + "  ".join(
                "%s=%s" % (k, _fig(v)) for k, v in sorted(figs.items())))
        for o in incident.get("offenders") or ():
            emit("  offender trace=%s proc=%s why=%s" % (
                o.get("trace"), o.get("proc"), o.get("why")))
    # -- per-process parts + metric deltas over the lookback
    span_files = []
    incident_ts = (incident or {}).get("ts") or manifest.get("t", 0)
    for ent in manifest.get("parts", ()):
        with open(os.path.join(path, ent["file"])) as f:
            part = json.load(f)
        if ent.get("spans_file"):
            span_files.append(os.path.join(path, ent["spans_file"]))
        errs = reqs = 0
        for e in part.get("events") or ():
            if e.get("ev") == "serving_request" and \
                    (e.get("ts") or 0) >= incident_ts - lookback_s:
                reqs += 1
                if e.get("error"):
                    errs += 1
        state = part.get("state") or {}
        emit("  part %-10s pid=%-7s %s%s" % (
            ent["role"], ent["pid"],
            "requests=%d errors=%d " % (reqs, errs)
            if reqs or errs else "",
            " ".join("%s=%s" % (k, _fig(state[k]))
                     for k in sorted(state)[:6])))
    # -- the offender-centered cross-process timeline
    if span_files:
        data = _merge.load_logs(span_files)
        offsets, ref, warnings = _merge.clock_offsets(data)
        for w in warnings:
            emit("  WARNING: %s" % w)
        traces, rids = _offender_traces(data, offender_ids)
        picked = [s for s in data["spans"] if s.get("trace") in traces]
        if picked:
            emit("offender timeline (%d spans, %d trace(s), rid %s; "
                 "skew-corrected to pid %s):" % (
                     len(picked), len(traces),
                     ",".join(sorted(rids)) or "-", ref))
            _render_tree(picked, offsets, data["procs"], emit)
        elif offender_ids:
            emit("offender traces %s: no spans captured (ring rotated "
                 "past the onset?)" % ",".join(offender_ids))
        else:
            emit("no offender traces named; bundle holds %d span(s) "
                 "across %d process(es)" % (len(data["spans"]),
                                            len(data["procs"])))
    return 0


def _fig(v):
    if isinstance(v, float):
        return "%.4g" % v
    return str(v)
