"""Flag / env bootstrap layer.

Reference parity: the gflags system (utils/Flags.h; fluid's
``__bootstrap__`` in python/paddle/fluid/__init__.py reads selected
FLAGS_* env vars at import). Here every runtime flag is registered in one
table with type, default, and docs; values come from ``PADDLE_TPU_*``
environment variables (gflags semantics for booleans: 0/false/off/no =
off) and can be read or overridden programmatically via get_flag/set_flag.

Registered flags:
  check_nan_inf   bool  per-op NaN/Inf guards in the compiled step
                        (FLAGS_check_nan_inf parity, executor.cc:27-94)
  lod_bucketing   bool  bucket flat LoD totals to powers of two so text
                        batches share compiled steps (SURVEY §7)
  segment_compile bool  jit the compute runs between host (IO) ops in
                        host-op programs instead of interpreting op-by-op
  debug_nans      bool  jax_debug_nans — XLA-level NaN tracer (heavier
                        than check_nan_inf; locates the primitive)
  data_home       str   dataset cache directory
  monitor*        —     paddle_tpu.monitor runtime telemetry knobs (arm
                        at import, flight-recorder path, stall watchdog,
                        console reporter, MFU peak/cost-model)
  faults*         —     paddle_tpu.resilience fault-injection plan
                        (JSON spec or @path) + decision seed
  trace*          —     paddle_tpu.trace cross-process distributed
                        tracing (sampling rate, span-log path, lane
                        label, clock-probe interval)
  rpc_retry*      —     transparent reconnect/retry of idempotent RPC
                        verbs (bounded backoff + total deadline)
  feed_plan_cache bool  cache _normalize_feeds plans + committed device
                        feed buffers across same-signature run() calls
  transform*      —     paddle_tpu.transform optimizing IR passes (arm
                        at the compile path, pass selection) + the
                        autoparallel planner's default device count
  serving*        —     paddle_tpu.serving continuous-batching engine
                        knobs (prefill chunk length, admission window,
                        fused decode megastep K, paged-KV layout /
                        block size / pool size / prefix cache,
                        speculative decode: on/off, draft length
                        gamma, drafter tier) and serving.fleet router
                        knobs (per-replica in-flight window, global
                        shed bound, stall-watchdog deadline)
  megastep_inflight int Executor.run_steps async dispatch window depth
                        (2 = double buffering)
  telemetry*      —     monitor.collector scrape-only TelemetryServer
                        (arm at import, port, membership KV endpoint
                        to self-register with for fleet discovery)
  slo_spec        str   default SLO spec JSON for python -m
                        paddle_tpu.slo and the live verdict line of
                        python -m paddle_tpu.monitor watch
  signals_spec    str   default spec for python -m paddle_tpu.monitor
                        alerts (burn-rate objectives + sustained-rule
                        overrides; falls back to slo_spec)
  trace_tail_*    —     tail-based trace retention (in-memory span
                        ring trace window; slow-root promotion
                        threshold in ms)
  forensics_dir   str   incident-bundle output directory for
                        monitor.forensics black-box DUMP captures

Distributed bootstrap envs (read by distributed.launch, not here):
  PADDLE_COORDINATOR, PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ID.
"""

import os

_TRUTHY_OFF = ("0", "false", "off", "no")


class _Flag:
    def __init__(self, name, type_, default, help_):
        self.name = name
        self.type = type_
        self.default = default
        self.help = help_
        self.env = "PADDLE_TPU_" + name.upper()
        self._override = None

    def value(self):
        if self._override is not None:
            return self._override
        raw = os.environ.get(self.env)
        if raw is None or not raw.strip():
            return self.default
        raw = raw.strip()
        if self.type is bool:
            return raw.lower() not in _TRUTHY_OFF
        return self.type(raw)


_FLAGS = {}


def _register(name, type_, default, help_):
    _FLAGS[name] = _Flag(name, type_, default, help_)


_register("check_nan_inf", bool, False,
          "scan every op output for NaN/Inf inside the compiled step")
_register("lod_bucketing", bool, True,
          "bucket flat LoD feed totals to the next power of two")
_register("segment_compile", bool, True,
          "jit-compile the compute runs between host (IO) ops instead of "
          "interpreting the whole program op-by-op")
_register("debug_nans", bool, False,
          "enable jax_debug_nans (XLA-level NaN localization)")
_register("profile_memory", bool, False,
          "record device live/peak bytes on every profiler event "
          "(FLAGS_benchmark memory-logging parity, operator.cc:576-578)")
_register("data_home", str,
          os.path.expanduser("~/.cache/paddle_tpu/dataset"),
          "dataset cache directory")
_register("gather_sharded_fetches", bool, False,
          "fetch-time all-gather of cross-process SHARDED values: every "
          "process receives the merged global array (the reference "
          "ParallelExecutor merged fetched tensors across devices, "
          "parallel_executor.cc:190-197). Default OFF: the gather "
          "crosses DCN on every fetch, so the default stays the loud "
          "NotImplementedError telling you to fetch replicated values")
_register("monitor", bool, False,
          "arm paddle_tpu.monitor at import: step/compile telemetry into "
          "the process-wide metrics registry (near-zero overhead; see "
          "monitor_log / monitor_stall_timeout for the recorder/watchdog)")
_register("monitor_log", str, "",
          "flight-recorder JSONL path (with the monitor flag on); empty "
          "= metrics only, no event log")
_register("monitor_stall_timeout", float, 0.0,
          "seconds without a completed step/compile before the monitor "
          "watchdog dumps all thread stacks + a metrics snapshot "
          "(0 = watchdog off)")
_register("monitor_report_interval", float, 0.0,
          "seconds between one-line monitor console reports to stderr "
          "(0 = no reporter thread)")
_register("monitor_peak_flops", float, 0.0,
          "device peak FLOP/s for the MFU gauge (0 = auto-detect by TPU "
          "device kind; stays unset on CPU, disabling the gauge)")
_register("monitor_sync_every", int, 1,
          "sync (block_until_ready) every Nth monitored step. 1 = every "
          "step: exact latency, but serializes JAX async dispatch — fine "
          "on CPU and for debugging. N>1: async TPU pipelines keep "
          "dispatch pipelining; the monitor syncs once per N steps and "
          "reports the window-average as that step's latency "
          "(intermediate steps log dispatch time, flagged synced=false, "
          "and are excluded from the latency histogram/MFU)")
_register("monitor_cost_model", bool, True,
          "price each compiled step with the paddle_tpu.analysis static "
          "cost model (one extra trace per COMPILE, nothing per step) so "
          "the monitor can derive MFU")
_register("faults", str, "",
          "arm a paddle_tpu.resilience fault-injection plan at import: "
          "a JSON spec, or @/path/to/plan.json (see resilience/faults.py "
          "for the spec schema). Empty = no injection, zero-cost hooks")
_register("faults_seed", int, 0,
          "decision seed for the armed fault plan — a fixed seed gives "
          "a reproducible chaos run")
_register("trace", str, "",
          "arm paddle_tpu.trace cross-process distributed tracing at "
          "import: '1'/'true' records every root span, a float in "
          "(0, 1] head-samples that fraction of roots "
          "(PADDLE_TPU_TRACE=0.01 for fleets). Span context propagates "
          "through RPC frames; arm the WHOLE fleet together. Empty/0 = "
          "off, zero-cost hooks (one is-None check per site)")
_register("trace_log", str, "",
          "span-log JSONL path ('{pid}' substitutes the process id — "
          "each process needs its own file). Empty = "
          "ptpu_trace_<pid>.jsonl in the cwd. Merge the fleet's logs: "
          "python -m paddle_tpu.trace merge *.jsonl -o timeline.json")
_register("trace_proc", str, "",
          "process label for the merged fleet-timeline lane (default: "
          "the executable basename) — e.g. trainer0, pserver1")
_register("trace_clock_interval", float, 15.0,
          "seconds between NTP-style clock-offset probes per peer "
          "(midpoint method over an idle RPC round trip; the merge CLI "
          "uses the min-RTT sample to skew-correct timestamps). <=0 "
          "probes at every opportunity")
_register("trace_tail_window", int, 256,
          "tail-based trace retention: number of recent traces the "
          "always-on in-memory span ring buffers per process (ALL "
          "spans, sampled-out ones included, grouped by trace id) so "
          "a retention decision made AFTER a trace ends — root error, "
          "root over trace_tail_slow_ms, or an incident naming the "
          "trace — can still promote the whole trace to the span log. "
          "0 disables the ring and restores pre-forensics behavior "
          "(sampled-out spans emit headerless frames)")
_register("trace_tail_slow_ms", float, 0.0,
          "tail-retention slow threshold: a ROOT span whose duration "
          "reaches this many milliseconds is retroactively promoted "
          "to the span log with reason 'slow' (derive it from the SLO "
          "latency objective). <=0 disables the slow rule; error and "
          "incident-offender promotion stay on")
_register("forensics_dir", str, "",
          "directory monitor.forensics writes incident bundles into "
          "(black-box DUMP captures assembled into a CRC-manifested "
          "bundle when a signals incident OPENs). Empty = "
          "forensics_bundles under the cwd")
_register("rpc_retry", bool, True,
          "run idempotent RPC verbs (GET/PRFT/PUT, tagged SEND/BARR, "
          "master GETT/DONE/FAIL/PING) under the resilience retry "
          "policy: transparent reconnect + bounded exponential backoff "
          "on socket errors instead of dying with the first broken "
          "connection")
_register("rpc_retry_deadline", float, 6.0,
          "total wall-clock budget (seconds) for one verb's retry loop "
          "— sized to ride out a pserver replacement (membership lease "
          "expiry + checkpoint recovery), after which the error "
          "propagates. The backoff schedule fills the whole budget "
          "(attempts are not the limiter)")
_register("feed_plan_cache", bool, True,
          "cache _normalize_feeds derivations per feed signature and "
          "reuse committed device feed buffers across Executor.run calls "
          "(the PERF.md round-5 in-process serving re-marshal fix); "
          "0 restores the per-call full normalization")
_register("serving_prefill_chunk", int, 16,
          "serving.Engine prompt-prefill chunk length: an admitted "
          "prompt is written into its slot's KV cache this many tokens "
          "per engine iteration, so one long prompt cannot stall the "
          "running decode batch")
_register("serving_admission_wait", float, 0.0,
          "serving.Engine wait-for-batch admission window (seconds): an "
          "IDLE engine holds admissions up to this long for the queue "
          "to fill to the slot count before starting a sparse batch. "
          "0 = greedy fill (admit at the next step boundary)")
_register("serving_megastep", int, 1,
          "serving.Engine decode iterations fused into ONE device "
          "dispatch (lax.scan over the slot step) when no admissions "
          "or prefills are pending — attacks the measured bs1 "
          "per-step dispatch floor (PERF.md round 5). Admissions and "
          "retirement bookkeeping land at megastep boundaries; output "
          "stays token-identical to the K=1 engine. 1 = one dispatch "
          "per decode step (the PR-5 behavior)")
_register("serving_paged", bool, True,
          "serving.Engine KV layout: paged block pool + per-slot "
          "block tables (the vLLM design — short requests stop "
          "reserving max_len worth of cache, shared prefixes share "
          "blocks). 0 restores the PR-5 dense [slots, ...] cache; "
          "greedy output is token-identical either way")
_register("serving_block_size", int, 16,
          "paged-KV block length (cache positions per block): the "
          "allocation granule, the prefix-cache match granule (only "
          "full-block prompt prefixes are cached/matched), and the "
          "COW copy unit")
_register("serving_kv_blocks", int, 0,
          "paged-KV pool size in blocks. 0 = auto: slots * "
          "ceil(max_len / block_size), dense-capacity parity — size "
          "it below that to trade concurrency headroom for memory "
          "(the engine preempts the lowest-priority request when the "
          "pool runs dry)")
_register("serving_block_kernel", bool, True,
          "block-native paged attention (ISSUE 20): walk each slot's "
          "allocated block chain with online softmax — compute and "
          "bandwidth scale with tokens held, not pool capacity "
          "(Pallas kernel on TPU, blocked lax fallback on CPU). 0 = "
          "the PR-10 dense-gather escape hatch; fp32 outputs are "
          "token-identical either way. Requires serving_paged")
_register("serving_kv_quant", str, "",
          "paged-KV pool quantization: '' (off, dense pool dtype), "
          "'int8' (symmetric per-(position,head)-vector scales stored "
          "beside the pool; ~0.4%/element error budget, serving "
          "outputs rtol-pinned at 2e-2), or 'fp8' (float8_e4m3fn, "
          "where the runtime provides it). Quantize on cache write, "
          "dequantize inside the kernel block loop; bytes_per_block "
          "and the autoparallel HBM filter price the smaller pool. "
          "Requires serving_paged + serving_block_kernel")
_register("serving_attn_unroll", int, 1,
          "block-kernel chain-walk group size: blocks gathered and "
          "scored per online-softmax update on the CPU/lax path "
          "(fewer, fatter iterations; the Pallas path grids over "
          "single blocks regardless). Numerics-neutral at any value")
_register("serving_prefix_cache", bool, True,
          "radix prefix cache over prompt blocks: an admission whose "
          "prompt shares a cached full-block prefix skips those "
          "prefill chunks entirely (refcounted chains, LRU eviction "
          "under pool pressure). Requires serving_paged")
_register("serving_speculative", bool, False,
          "serving.Engine speculative decode (ISSUE 13): a cheap "
          "drafter proposes up to serving_spec_gamma tokens per live "
          "slot and ONE paged-attention scoring dispatch verifies all "
          "of them — every dispatch emits 1..gamma+1 tokens, breaking "
          "the bs1 per-dispatch floor. Temp-0 output stays bitwise "
          "the non-speculative engine's (accept-longest-prefix "
          "against the model's own tokens); requires serving_paged")
_register("serving_spec_gamma", int, 4,
          "speculative draft length gamma: tokens proposed per live "
          "slot per iteration. A STATIC shape constant of the scoring "
          "program (one compile per gamma; Engine.warmup pre-pays "
          "it). 0 disables speculation outright — the engine runs "
          "the existing programs cost-for-cost")
_register("serving_spec_drafter", str, "ngram",
          "speculative drafter tier: 'ngram' (host-side prompt/n-gram "
          "lookup over the request's own token chain + the radix "
          "prefix cache's published chains — zero device cost) or "
          "'truncated' (a serving_spec_layers-deep pass over the same "
          "weights, one extra fused dispatch per drafted iteration)")
_register("serving_spec_ngram", int, 3,
          "longest suffix n-gram the ngram drafter matches (falls "
          "back to shorter suffixes down to serving_spec_ngram_min)")
_register("serving_spec_ngram_min", int, 2,
          "shortest suffix n-gram the ngram drafter accepts as "
          "evidence. 2 (default) skips weak single-token matches — "
          "measured: mostly-rejected drafts whose scoring dispatches "
          "cost more than they return; 3 drafts only on the "
          "strongest evidence (highest acceptance rate, fewest "
          "drafted iterations)")
_register("serving_spec_layers", int, 0,
          "transformer layers the 'truncated' drafter runs (0 = "
          "n_layer // 2). Draft quality only moves the acceptance "
          "rate, never the output")
_register("serving_fleet_window", int, 8,
          "serving.fleet Router per-replica in-flight window "
          "(backpressure): at most this many journaled requests are "
          "dispatched to one replica at a time; the rest queue "
          "router-side")
_register("serving_fleet_queue", int, 64,
          "serving.fleet Router global queue bound (load shedding): "
          "once this many requests await dispatch, submit() fast-fails "
          "with the typed Overloaded error, counted against the SLO "
          "error budget")
_register("serving_sparse_staleness_s", float, 5.0,
          "serving.sparse hot-ID cache bounded-staleness window "
          "(seconds): a cached embedding row older than this "
          "re-fetches from its pserver shard on next touch — the "
          "upper bound on how long an online update can stay "
          "invisible through the cache (an observed version bump or "
          "incarnation change invalidates sooner)")
_register("serving_sparse_cache_rows", int, 65536,
          "serving.sparse hot-ID cache capacity in ROWS (LRU): the "
          "per-process bound on cached embedding rows across tables")
_register("serving_scoring_batch", int, 8,
          "serving.sparse ScoringEngine batch capacity: requests "
          "scored per compiled dispatch (short batches pad to this "
          "shape, so the compiled program never re-traces)")
_register("serving_mirror_fraction", float, 0.25,
          "serving.fleet shadow mirroring: deterministic fraction of "
          "accepted decode requests duplicated to CANDIDATE replicas "
          "while a shadow mirror is armed (scored against the "
          "incumbent's result, never served, excluded from the "
          "incumbent's SLO histograms)")
_register("serving_canary_weight", float, 0.1,
          "serving.fleet canary split: deterministic fraction of "
          "accepted requests served FOR REAL by candidate replicas "
          "while a canary is armed (version stamped on row/span/"
          "lease; candidates at their window fall back to incumbents "
          "— the split never sheds)")
_register("serving_fleet_stall_timeout", float, 2.0,
          "serving.fleet Router response-deadline watchdog: a replica "
          "that answers no verb for this long (retry deadline "
          "included) is evicted from dispatch, its registry slot "
          "tombstoned for the supervisor, and its unfinished requests "
          "re-submitted to a survivor")
_register("megastep_inflight", int, 2,
          "Executor.run_steps async dispatch window: how many "
          "un-fetched megastep dispatches may be in flight before the "
          "next run_steps(return_numpy=False) call blocks on the "
          "oldest. 2 = double buffering (host feed of megastep N+1 "
          "overlaps device compute of megastep N); 1 restores "
          "serialized dispatch")
_register("telemetry", bool, False,
          "arm the scrape-only monitor.collector.TelemetryServer at "
          "import: any trainer/engine process becomes METR/HLTH "
          "scrapeable by a fleet collector even without hosting a "
          "pserver/master/replica dispatch loop")
_register("telemetry_port", int, 0,
          "TelemetryServer listen port (0 = ephemeral; the endpoint "
          "self-registers when telemetry_kv is set)")
_register("telemetry_kv", str, "",
          "membership KV endpoint (host:port) the armed "
          "TelemetryServer registers its endpoint with (role "
          "'telemetry', TTL lease) so collectors discover this "
          "process without configuration; empty = serve unregistered")
_register("telemetry_slots", int, 16,
          "how many 'telemetry' role slots the lease registry offers "
          "(register_endpoint desired count for flag-armed "
          "TelemetryServers)")
_register("signals_spec", str, "",
          "default SLO/signals spec JSON for python -m "
          "paddle_tpu.monitor alerts: error-budget objectives arm "
          "burn-rate rules, the spec's 'rules' object overrides the "
          "sustained-condition defaults (monitor/signals.py). Empty "
          "= fall back to slo_spec, then defaults-only rules")
_register("slo_spec", str, "",
          "default SLO spec JSON path: python -m paddle_tpu.slo uses "
          "it when no spec argument is given, and python -m "
          "paddle_tpu.monitor watch renders a live verdict line "
          "against it (see paddle_tpu/slo.py for the spec schema)")
_register("transform", bool, False,
          "arm paddle_tpu.transform at the executors' compile path: "
          "every compile-cache MISS runs the optimizing pass pipeline "
          "(see transform_passes) over the program and builds the "
          "transformed clone — the cache key stays the caller's "
          "program+version, and passes are semantics-preserving "
          "(bitwise-identical fetches, pinned in tests/test_transform)")
_register("transform_passes", str, "all",
          "which optimizing passes the armed transform (and the "
          "python -m paddle_tpu.transform CLI default) runs: 'all', "
          "'none', or a comma list from {constant_fold, cse, dead_op, "
          "fusion, bf16_cast} in application order ('all' excludes "
          "the opt-in, non-bitwise bf16_cast)")
_register("autoparallel_devices", int, 0,
          "default device count for the automatic parallelism planner "
          "(python -m paddle_tpu.transform --plan / "
          "transform.recommend); 0 = jax.device_count() at call time")
_register("autoparallel_calib", str, "",
          "path to a transform.calibrate calibration record "
          "(python -m paddle_tpu.transform --calibrate); when set, "
          "plan_cost prices candidates with the MEASURED per-chip "
          "matmul FLOP/s and ring-collective bandwidth instead of the "
          "documented placeholders. Empty / unreadable = placeholders "
          "(rankings stay ordinal, one stderr warning per bad path)")
_register("autoparallel_hbm_gb", float, 0.0,
          "per-chip HBM capacity (GB) the autoparallel planner "
          "filters against: candidates whose modeled per-chip bytes "
          "(param shard + optimizer state + paged-KV pool, "
          "transform.autoparallel.plan_hbm_bytes) exceed it are "
          "REJECTED, not ranked. 0 = no capacity filter")
_register("fuse_conv_bn", bool, False,
          "fuse 1x1-conv + train-BN batch stats into one Pallas matmul "
          "epilogue (ops/matmul_stats.py). Default OFF: measured SLOWER "
          "than XLA's composed path on ResNet-50 (PERF.md round-4 "
          "'conv+BN fusion probe'); kept as the committed evidence and "
          "an opt-in for other shapes")


def get_flag(name):
    return _FLAGS[name].value()


def set_flag(name, value):
    """Programmatic override (wins over the environment). Values coerce
    through the flag's type with the same gflags parsing env vars get, so
    set_flag('lod_bucketing', 'off') really turns it off."""
    f = _FLAGS[name]
    if value is not None and not isinstance(value, f.type):
        if f.type is bool:
            value = str(value).strip().lower() not in _TRUTHY_OFF
        else:
            value = f.type(value)
    f._override = value
    if name == "debug_nans":
        _apply_debug_nans()


def overrides():
    """{name: current value} of every flag whose value differs from
    its default (env var or set_flag) — the active-configuration stamp
    a forensics DUMP capture carries, so a bundle records how each
    process was actually configured at the incident."""
    out = {}
    for f in _FLAGS.values():
        v = f.value()
        if v != f.default:
            out[f.name] = v
    return out


def flags_help():
    return "\n".join(
        "%-16s %-5s default=%r env=%s\n    %s"
        % (f.name, f.type.__name__, f.default, f.env, f.help)
        for f in _FLAGS.values())


def _apply_debug_nans():
    import jax
    jax.config.update("jax_debug_nans", bool(get_flag("debug_nans")))


def __bootstrap__():
    """Read env-driven flags that must take effect at import (the
    reference's __bootstrap__ shape)."""
    if get_flag("debug_nans"):
        _apply_debug_nans()


__bootstrap__()
