"""Bounded-retry policy for the distributed clients.

``Policy`` is bounded exponential backoff with seeded jitter and a
total wall-clock deadline. RPCClient and MasterClient run their
IDEMPOTENT verbs through it (``retry=Policy(...)``): on a socket error
the client drops its connections, sleeps the backoff, reconnects —
optionally through an endpoint ``resolver``, so a REPLACEMENT pserver
(a new incarnation recovered from its checkpoint after a membership
lease expiry, possibly at a new port) is picked up transparently — and
re-issues the verb. Idempotency is what makes this safe:

  * GET / PRFT / PUT are idempotent by definition;
  * tagged SEND / BARR are exactly-once server-side (rpc.py replaces a
    retried (name, tag) send and dedups a counted barrier tag);
  * UNTAGGED SEND / BARR are NOT retried — a blind re-send would
    double-accumulate a gradient.

Non-socket errors (StaleIncarnationError, protocol assertions) always
propagate: they need the caller's semantics, not a blind retry.

Every retry/reconnect bumps a monitor counter and, when a flight
recorder is armed, writes a ``retry`` / ``reconnect`` event.
"""

import os
import random
import time

from ..monitor import runtime as _mon

__all__ = ["Policy", "default_policy", "RETRYABLE", "VERB_CLASSES"]

# TimeoutError covers socket.timeout (an alias since 3.10); both are
# OSError subclasses, listed for readers, matched as one family.
RETRYABLE = (ConnectionError, TimeoutError, OSError)

# The retry-idempotence contract, one entry per request verb — the
# machine-readable form of the rules the clients implement by hand
# (RPCClient._retrying call sites, MasterClient, ReplicaClient's
# journal dedup). `python -m paddle_tpu.analysis --runtime`
# (verb-conformance) checks every dispatch loop's verbs against this
# table, so a new verb MUST take a position on re-issue safety:
#
#   idempotent     blind re-issue after a lost reply is safe (reads,
#                  last-writer-wins puts, journal-deduped fleet verbs)
#   round_tag      safe ONLY when carrying a ROUND-format tag the
#                  server dedups (untagged SEND/BARR double-applies)
#   nonretryable   never re-issued blindly (CAS/CAD: a lost reply
#                  leaves compare-and-X outcomes ambiguous; CHNK:
#                  side-stream parts are re-sent by the commit SEND)
#   admin          connection/shutdown control, excluded from fault
#                  injection and retry alike
VERB_CLASSES = {
    # pserver (distributed/rpc.py)
    "SEND": "round_tag", "BARR": "round_tag",
    "PUT": "idempotent", "GET": "idempotent", "PRFT": "idempotent",
    "CHNK": "nonretryable",
    # master task queue (distributed/master.py)
    "GETT": "idempotent", "DONE": "idempotent", "FAIL": "idempotent",
    "PING": "idempotent",
    # membership KV (distributed/membership.py; PUT/GET shared above)
    "CAS": "nonretryable", "CAD": "nonretryable",
    "DEL": "idempotent", "LIST": "idempotent", "LEAS": "idempotent",
    # serving fleet (serving/fleet.py): exactly-once via the request
    # journal, so EVERY verb is idempotent by construction
    "SUBM": "idempotent", "POLL": "idempotent", "CANC": "idempotent",
    "STAT": "idempotent",
    # rollout controller (serving/rollout.py): VERD is a read of the
    # current delta-verdict state — safe to re-issue
    "VERD": "idempotent",
    # clock/telemetry/forensics reads served by every dispatcher +
    # shutdown (DUMP is a read-only snapshot: safe to re-issue)
    "CLKS": "idempotent", "METR": "idempotent", "HLTH": "idempotent",
    "DUMP": "idempotent",
    "EXIT": "admin",
}


class Policy:
    """Bounded exponential backoff + seeded jitter + total deadline.

    max_attempts:  total tries of the wrapped call (first one included)
    base_delay:    sleep before the first retry (seconds)
    multiplier:    backoff growth per retry
    max_delay:     per-sleep cap
    jitter:        each sleep is scaled by 1 + jitter*U[0,1)
    deadline:      total wall-clock budget; the next sleep must fit.
                   Note it bounds backoff SCHEDULING, not a single
                   in-flight attempt — the client's socket timeout is
                   what bounds a hung connect/recv.
    seed:          jitter RNG seed (deterministic chaos runs)
    """

    def __init__(self, max_attempts=6, base_delay=0.05, max_delay=2.0,
                 multiplier=2.0, jitter=0.25, deadline=30.0, seed=0):
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.deadline = float(deadline)
        self.seed = int(seed)

    def delays(self):
        """The deterministic backoff sequence (one sleep per retry)."""
        rng = random.Random(self.seed)
        d = self.base_delay
        for _ in range(max(0, self.max_attempts - 1)):
            yield min(d, self.max_delay) * (1.0 + self.jitter
                                            * rng.random())
            d *= self.multiplier

    def run(self, fn, what="rpc", retry_on=RETRYABLE, on_retry=None):
        """Call ``fn()`` with retries. ``on_retry(attempt, exc)`` runs
        before each backoff sleep (the clients drop their dead sockets
        there; reconnection happens inside the next ``fn()`` attempt so
        a refused reconnect counts as a failed attempt, not a crash).

        With paddle_tpu.trace armed and an ambient span open (the
        client's logical verb span), every try runs inside an
        ``<what>.attempt`` child span — a retried GET merges into ONE
        client span with N attempt children, failed attempts carrying
        their error and the reconnect/endpoint annotations from the
        client's _connect."""
        from ..trace import runtime as _trace
        t0 = time.monotonic()
        delays = self.delays()
        attempt = 0
        while True:
            trc = _trace._TRACER
            try:
                if trc is not None and trc.current_span() is not None:
                    with trc.span(what + ".attempt", attempt=attempt + 1):
                        return fn()
                return fn()
            except retry_on as exc:
                attempt += 1
                sleep_s = next(delays, None)
                if sleep_s is None or \
                        time.monotonic() - t0 + sleep_s > self.deadline:
                    raise
                _mon.on_retry(what, attempt, exc)
                _trace.annotate(retries=attempt)
                if on_retry is not None:
                    on_retry(attempt, exc)
                time.sleep(sleep_s)


def default_policy():
    """The flag-driven policy the executor's cached RPC clients use:
    ``rpc_retry`` (bool) gates it, ``rpc_retry_deadline`` bounds it.
    Returns None when retries are off.

    The deadline GOVERNS: max_attempts is set high enough that the
    backoff schedule always reaches the deadline (a handful of attempts
    would otherwise exhaust in ~2 s against a 6 s budget). The jitter
    seed derives from the pid so a fleet of trainers disconnected by
    the same pserver restart does NOT back off in lockstep — the
    deterministic-chaos tests pass their own seeded Policy instead."""
    from .. import flags
    try:
        if not flags.get_flag("rpc_retry"):
            return None
        deadline = float(flags.get_flag("rpc_retry_deadline"))
    except KeyError:
        return None
    return Policy(max_attempts=1000, deadline=deadline,
                  seed=os.getpid())
