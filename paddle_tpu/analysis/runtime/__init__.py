"""paddle_tpu.analysis.runtime — concurrency, protocol & catalog lint.

The runtime-code counterpart of the jaxpr analyzer: PR 1's rules lint
the *graph*, but the serving stack's dominant bug class lives in the
*runtime* code around it — locks held across socket I/O, lock-order
inversions, RPC verb tables drifting out of sync with the fault/retry
classification, and metric/flag catalogs drifting from the docs. This
subpackage walks the whole codebase with stdlib ``ast`` (no execution,
no new deps) and turns those hand-found review classes into exit-code
gates:

  RT01 lock-discipline      per-class lock-acquisition graph: cycles
                            (potential deadlock) + blocking calls
                            (socket send/recv/connect, sleeps, thread
                            joins, retry-policy runs) under a held lock
  RT02 verb-conformance     every RPC dispatch verb must be covered by
                            resilience.faults._DEFAULT_OPS, classified
                            in resilience.retry.VERB_CLASSES, and
                            served by a trace-header-aware loop
  RT03 catalog-consistency  every ptpu_* metric referenced anywhere in
                            the package or the README catalog must be
                            registered exactly once with one kind;
                            every flag read must be registered
  RT04 thread-shared-state  attributes of thread-spawning classes
                            mutated from >=2 methods with no lock in
                            scope (INFO heuristic)

API:   run_runtime(root=None) -> RuntimeReport
CLI:   python -m paddle_tpu.analysis --runtime [--json]
       (CI gate: exit 0 only when every finding at/above --fail-on is
       covered by a justified waiver in analysis/runtime/waivers.json)
"""

from .astscan import SourceIndex, SourceFile  # noqa: F401
from .engine import (  # noqa: F401
    Finding, RuntimeReport, RuntimeRule, register_runtime_rule,
    registered_runtime_rules, default_runtime_rules, run_rules,
    run_runtime, load_waivers, WaiverError, default_waivers_path)
from . import rules  # noqa: F401  (register the built-in rules)
