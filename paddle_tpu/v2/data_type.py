"""v2 data types (python/paddle/v2/data_type.py parity): declarative slot
descriptors consumed by paddle.v2.layer.data."""


class InputType:
    def __init__(self, dim, seq_type, dtype):
        self.dim = dim
        self.seq_type = seq_type   # 0 = no sequence, 1 = sequence
        self.dtype = dtype


def dense_vector(dim):
    return InputType(dim, 0, "float32")


def dense_vector_sequence(dim):
    return InputType(dim, 1, "float32")


def integer_value(value_range):
    return InputType(value_range, 0, "int64")


def integer_value_sequence(value_range):
    return InputType(value_range, 1, "int64")


def sparse_binary_vector(dim):
    # consumed as an id sequence on TPU (static-shape lowering)
    return InputType(dim, 1, "int64")
