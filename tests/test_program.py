"""Core IR tests: Program/Block/Operator/Variable construction, clone,
prune, serialization (reference analog: framework.py unit tests)."""

import numpy as np

import paddle_tpu as fluid


def test_program_build():
    prog = fluid.default_main_program()
    x = fluid.layers.data("x", [4])
    y = fluid.layers.fc(x, 3)
    assert y.shape == (-1, 3)
    ops = [op.type for op in prog.global_block().ops]
    assert "mul" in ops and "elementwise_add" in ops
    params = prog.global_block().all_parameters()
    assert len(params) == 2  # weight + bias
    w = [p for p in params if p.shape == (4, 3)]
    assert len(w) == 1


def test_program_clone_and_serialize():
    prog = fluid.default_main_program()
    x = fluid.layers.data("x", [4])
    y = fluid.layers.fc(x, 3, act="relu")
    clone = prog.clone()
    assert len(clone.global_block().ops) == len(prog.global_block().ops)
    # mutating the clone must not touch the original
    clone.global_block().append_op(type="mean", inputs={"X": [y.name]},
                                   outputs={"Out": ["m"]})
    assert len(clone.global_block().ops) == \
        len(prog.global_block().ops) + 1

    js = prog.to_json()
    rt = fluid.Program.from_json(js)
    assert [op.type for op in rt.global_block().ops] == \
        [op.type for op in prog.global_block().ops]
    assert set(rt.global_block().vars) == set(prog.global_block().vars)
    # parameters survive round-trip as parameters
    assert len(rt.global_block().all_parameters()) == 2


def test_clone_for_test_flips_dropout():
    prog = fluid.default_main_program()
    x = fluid.layers.data("x", [4])
    d = fluid.layers.dropout(x, 0.5)
    t = prog.clone(for_test=True)
    dropout_ops = [op for op in t.global_block().ops
                   if op.type == "dropout"]
    assert dropout_ops[0].attr("is_test") is True
    # original untouched
    assert not [op for op in prog.global_block().ops
                if op.type == "dropout"][0].attr("is_test", False)


def test_prune():
    prog = fluid.default_main_program()
    x = fluid.layers.data("x", [4])
    a = fluid.layers.fc(x, 3)
    b = fluid.layers.fc(x, 5)   # not needed for target a
    pruned = prog.prune([a])
    kept_ops = pruned.global_block().ops
    assert len(kept_ops) < len(prog.global_block().ops)
    out_names = {n for op in kept_ops for n in op.output_names}
    assert a.name in out_names
    assert b.name not in out_names


def test_variable_sugar_builds_ops():
    prog = fluid.default_main_program()
    x = fluid.layers.data("x", [4])
    y = fluid.layers.data("y", [4])
    z = x + y
    w = z * 2.0
    ops = [op.type for op in prog.global_block().ops]
    assert "elementwise_add" in ops
    assert "scale" in ops


def test_scope():
    s = fluid.Scope()
    s.set("a", np.ones(3))
    kid = s.new_scope()
    assert kid.has_var("a")
    kid.set("b", np.zeros(2))
    assert not s.has_var("b")
    assert np.allclose(kid.find_var("a"), 1.0)
