"""Ablation probe: where does the ResNet-50 device step time go?
Times train-step variants back-to-back (single sync per window)."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models import resnet

PEAK_BF16 = 197e12
FLOPS_PER_IMG_TRAIN = 3 * 4.1e9
FLOPS_PER_IMG_FWD = 4.1e9


def build_and_time(label, bs, amp=True, train=True, opt="momentum",
                   iters=8):
    fluid.amp.enable_amp(amp)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        sys.path.insert(0, "benchmarks")
        from common import synthetic_feeds
        synth = synthetic_feeds({
            "data": ((bs, 3, 224, 224), "float32", 1.0),
            "label": ((bs, 1), "int64", 1000)})
        image, lab = synth["data"], synth["label"]
        pred = resnet.resnet_imagenet(image, 50, 1000)
        cost = fluid.layers.cross_entropy(pred, lab)
        avg_cost = fluid.layers.mean(cost)
        if train:
            if opt == "momentum":
                fluid.optimizer.Momentum(learning_rate=0.01,
                                         momentum=0.9).minimize(avg_cost)
            else:
                fluid.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        exe.run(feed={}, fetch_list=[avg_cost])
        (entry,) = [v for k, v in exe._cache.items() if k[0] is main]
        persistable = [v.name for v in main.global_block().vars.values()
                       if v.persistable]
        state = {n: scope.find_var(n) for n in persistable
                 if scope.find_var(n) is not None}
        key = jax.random.key(0)
        fetches, state = entry(state, {}, key)
        jax.block_until_ready(state)
        t0 = time.perf_counter()
        for _ in range(iters):
            fetches, state = entry(state, {}, key)
        jax.block_until_ready(state)
        dt = (time.perf_counter() - t0) / iters
    flops = FLOPS_PER_IMG_TRAIN if train else FLOPS_PER_IMG_FWD
    ips = bs / dt
    print("%-32s bs=%4d  %7.2f ms  %8.1f img/s  MFU=%5.1f%%"
          % (label, bs, dt * 1e3, ips, ips * flops / PEAK_BF16 * 100),
          flush=True)


if __name__ == "__main__":
    bs = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    build_and_time("train bf16 momentum", bs)
    build_and_time("train fp32 momentum", bs, amp=False)
    build_and_time("train bf16 sgd", bs, opt="sgd")
    build_and_time("fwd-only bf16", bs, train=False)
