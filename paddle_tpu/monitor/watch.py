"""Live terminal dashboard: tail a flight-recorder JSONL and render a
refreshing one-screen view of the run — the operator's glass for a
serving engine (tokens/s, occupancy, queue depth, rolling TTFT/TPOT
percentiles) and for training (step p50/p95, tokens/s, MFU), with
stall / NaN / truncation indicators and an optional live SLO verdict.

    python -m paddle_tpu.monitor watch run.jsonl
    python -m paddle_tpu.monitor watch run.jsonl --slo slo.json
    python -m paddle_tpu.monitor watch run.jsonl --once   # one frame
    python -m paddle_tpu.monitor watch rep0.jsonl rep1.jsonl ...
                       # serving fleet: one log per replica, the
                       # dashboard (and --slo verdict) covers the union
    python -m paddle_tpu.monitor watch --fleet <kv-endpoint>
                       # LIVE fleet scrape: discover every process
                       # from the membership lease registry, scrape
                       # metrics + recorder deltas over RPC (METR),
                       # and render the merged dashboard — no files

The tail is incremental (only new bytes are parsed per refresh) and
tolerant: a torn trailing line — the writer is LIVE — is retried on
the next refresh, never fatal. Rolling figures cover the last
``--window`` rows of each kind; totals (steps, requests, stalls) cover
the whole log. Multi-log mode prints a per-log staleness line —
seconds since each file's last row — so a dead replica's SILENCE is
visible instead of silently aging out of the rolling window.
"""

import collections
import json
import sys
import time

from .recorder import percentile_sorted as _pct

__all__ = ["watch", "watch_fleet", "WatchState", "render_frame",
           "staleness_lines", "fleet_lines", "rollout_line"]


class _Tail:
    """Incremental reader: each poll() returns the complete lines that
    arrived since the last poll, holding a torn trailing fragment back
    for the next round. Opens lazily — a live tail may be started
    BEFORE the run creates its log; poll() returns None until the file
    exists."""

    def __init__(self, path):
        self.path = path
        self._f = None
        self._buf = ""

    def poll(self):
        if self._f is None:
            try:
                self._f = open(self.path, "r")
            except (FileNotFoundError, PermissionError):
                return None             # not created yet: retry later
        chunk = self._f.read()
        if not chunk:
            return []
        self._buf += chunk
        lines = self._buf.split("\n")
        self._buf = lines.pop()         # "" on a complete final line
        return [ln for ln in lines if ln.strip()]

    def close(self):
        if self._f is not None:
            self._f.close()


class WatchState:
    """Rolling aggregation over flight-recorder rows."""

    def __init__(self, window=256):
        self.window = int(window)
        self.serving_steps = collections.deque(maxlen=self.window)
        self.requests = collections.deque(maxlen=self.window)
        self.train_steps = collections.deque(maxlen=self.window)
        # rolling RAW-event window per source (one per log file /
        # scraped process): the goodput ledger must attribute each
        # process's own wall clock, never a union timeline, and needs
        # every timestamped row kind (steps, compiles, stalls,
        # markers), not just the serving deques above. LRU-bounded:
        # under supervisor respawn churn every new replica endpoint is
        # a fresh source key, and a dashboard must not grow (or keep
        # verdict-voting dead processes' last windows) forever.
        self.goodput_events = collections.OrderedDict()
        self.max_sources = 64
        self.events = 0
        self.skipped = 0
        self.total_serving_steps = 0
        self.total_requests = 0
        self.total_errors = 0
        self.total_preemptions = 0
        self.total_train_steps = 0
        self.stalls = 0
        self.nan_trips = 0
        self.truncated = False
        self.platform = None
        self.last_ts = None
        # elastic-fleet rows (serving.autoscale, ISSUE 18): the newest
        # scale_event / roll row feed the dashboard's autoscale line
        self.last_scale_event = None
        self.last_roll = None
        # canary-rollout rows (serving.rollout, ISSUE 19): newest
        # phase-transition row + per-phase delta verdicts feed the
        # rollout status line — same recorder rows the collector
        # already ships, no parallel machinery
        self.last_rollout = None
        self.verdicts = {}         # phase -> newest verdict row

    def feed_line(self, line, source=""):
        e = self.parse_line(line)
        if e is not None:
            self.feed_event(e, source=source)

    def parse_line(self, line):
        """One JSONL line -> event dict, or None (counted skipped)."""
        try:
            e = json.loads(line)
        except json.JSONDecodeError:
            self.skipped += 1
            return None
        if not isinstance(e, dict) or "ev" not in e:
            self.skipped += 1
            return None
        return e

    def feed_event(self, e, source=""):
        self.events += 1
        if e.get("ts") is not None:
            self.last_ts = e["ts"]
            key = source or ""
            dq = self.goodput_events.get(key)
            if dq is None:
                dq = self.goodput_events[key] = collections.deque(
                    maxlen=self.window)
            dq.append(e)
            self.goodput_events.move_to_end(key)
            while len(self.goodput_events) > self.max_sources:
                self.goodput_events.popitem(last=False)
        ev = e["ev"]
        if ev == "serving_step":
            # a fused megastep row advances k logical steps (dt stays
            # per-logical-step) — weight so totals are K-comparable
            self.total_serving_steps += int(e.get("k") or 1)
            self.total_preemptions += int(e.get("preempted") or 0)
            self.serving_steps.append(e)
        elif ev == "serving_request":
            self.total_requests += 1
            if e.get("error"):
                self.total_errors += 1
            self.requests.append(e)
        elif ev == "step":
            self.total_train_steps += int(e.get("k") or 1)
            self.train_steps.append(e)
        elif ev == "stall":
            self.stalls += 1
        elif ev == "nan_guard":
            self.nan_trips += 1
        elif ev == "truncated":
            self.truncated = True
        elif ev == "devices":
            self.platform = e.get("platform")
        elif ev == "scale_event":
            self.last_scale_event = e
        elif ev == "roll":
            self.last_roll = e
        elif ev == "rollout":
            self.last_rollout = e
        elif ev == "verdict":
            self.verdicts[e.get("phase") or "?"] = e

    def goodput_rollup(self):
        """Per-SOURCE rolling ledgers rolled up per process — NEVER a
        union timeline (two replicas' concurrent productive intervals
        would collapse into one). The ONE rollup idiom shared by the
        SLO samples below, the file-mode watch loop, and the fleet
        loop. None when no timestamped rows have arrived."""
        if not self.goodput_events:
            return None
        from . import goodput as _goodput
        return _goodput.rollup(
            _goodput.ledger_from_events(evs)
            for evs in self.goodput_events.values())

    def request_samples(self):
        """SLO-engine-shaped samples over the rolling request window
        (what --slo evaluates live) — delegates to the slo module's
        one rows->samples extraction. goodput comes from
        ``goodput_rollup`` (the request/serving deques alone would
        misattribute a training log and collapse a fleet's concurrent
        timelines)."""
        import itertools
        from .. import slo as _slo
        out = _slo.samples_from_events(
            itertools.chain(self.requests, self.serving_steps),
            source="watch window", compute_goodput=False)
        out["goodput"] = self.goodput_rollup()
        return out


def _ms(v):
    return "n/a" if v is None else "%.1fms" % (1000.0 * v)


def _p(vals, q):
    return _pct(sorted(vals), q) if vals else None


def staleness_lines(last_ts, now=None, stale_after=5.0):
    """Per-log staleness indicator for multi-log mode: one line per
    file with seconds since ITS last row, so a dead replica's silence
    is visible instead of quietly aging out of the rolling window.
    ``last_ts``: {path: newest row ts or None}. With ``now`` (live
    loop) ages are absolute; without it (--once, deterministic) they
    are relative to the newest row across all logs."""
    if len(last_ts) < 2:
        return []
    base = now
    if base is None:
        known = [t for t in last_ts.values() if t is not None]
        if not known:
            return []
        base = max(known)
    out = []
    for path in sorted(last_ts):
        t = last_ts[path]
        if t is None:
            out.append("  %-40s no rows yet" % path)
            continue
        age = max(0.0, base - t)
        flag = "   [STALE]" if age >= stale_after else ""
        out.append("  %-40s last row %5.1fs ago%s" % (path, age, flag))
    return ["logs"] + out


def _fleet_counter(snap, name):
    """Summed counter value, or None when the metric is ABSENT — a
    present-but-zero counter must not read as missing (the requests
    line falls back to admissions only when no router counted at
    all)."""
    ent = snap.get(name) or {}
    if ent.get("kind") != "counter":
        return None
    return sum(ent.get("series", {}).values())


def _fleet_gauge_series(snap, name):
    """Gauge series dict (label key -> value), or None when the metric
    is ABSENT — same absent-vs-zero discipline as _fleet_counter (the
    autoscale line only renders when an autoscaler actually exports)."""
    ent = snap.get(name) or {}
    if ent.get("kind") != "gauge":
        return None
    return ent.get("series") or {}


def fleet_lines(fleet_snap, now=None, state=None):
    """Fleet header for the scraped dashboard: one line per endpoint
    (role, liveness, uptime, scrape staleness) plus the merged fleet
    counters — the collector's exact-sum view. ``state`` (a WatchState,
    optional) contributes the newest scale_event / roll recorder rows
    to the autoscale line."""
    from .metrics import META_KEY
    meta = fleet_snap.get(META_KEY) or {}
    eps = meta.get("endpoints") or []
    lines = ["fleet     %d process(es), %d endpoint(s), %d scrape(s)%s"
             % (meta.get("processes", 0), len(eps),
                meta.get("scrapes", 0),
                "   [%d event(s) LOST to ring overflow]"
                % meta["events_lost"] if meta.get("events_lost")
                else "")]
    for ep in eps:
        status = "up" if ep.get("ok") else "DOWN"
        up = ep.get("uptime_s")
        age = ep.get("age_s")
        lines.append(
            "  %-8s %-22s %-4s uptime %-8s scraped %s"
            % (ep.get("role", "?"), ep.get("endpoint", "?"), status,
               "n/a" if up is None else "%.0fs" % up,
               "n/a" if age is None else "%.1fs ago" % age))
    steps = _fleet_counter(fleet_snap, "ptpu_steps_total")
    tokens = _fleet_counter(fleet_snap, "ptpu_serving_tokens_total")
    reqs = _fleet_counter(fleet_snap, "ptpu_fleet_requests_total")
    if reqs is None:          # no router in the fleet: engine-level
        reqs = _fleet_counter(fleet_snap,
                              "ptpu_serving_admissions_total")
    errs = _fleet_counter(fleet_snap,
                          "ptpu_serving_request_failures_total")
    rounds = _fleet_counter(fleet_snap, "ptpu_ps_rounds_total")
    lines.append(
        "  totals   steps %d   serving tokens %d   requests %d   "
        "errors %d   ps rounds %d" % (steps or 0, tokens or 0,
                                      reqs or 0, errs or 0,
                                      rounds or 0))
    sp_h = _fleet_counter(fleet_snap, "ptpu_sparse_cache_hits_total")
    sp_m = _fleet_counter(fleet_snap, "ptpu_sparse_cache_misses_total")
    if sp_h is not None or sp_m is not None:
        # sparse serving tier present (serving.sparse): the merged
        # hot-ID cache view — exact sums across every scraped process
        sp_h, sp_m = sp_h or 0, sp_m or 0
        sp_s = _fleet_counter(
            fleet_snap, "ptpu_sparse_cache_stale_total") or 0
        sp_r = _fleet_counter(
            fleet_snap, "ptpu_sparse_prefetch_rows_total") or 0
        rate = "n/a" if sp_h + sp_m == 0 \
            else "%.0f%%" % (100.0 * sp_h / (sp_h + sp_m))
        lines.append(
            "  sparse   cache hits %d misses %d stale %d (hit rate "
            "%s)   prefetch rows %d" % (sp_h, sp_m, sp_s, rate, sp_r))
    spd = _fleet_counter(fleet_snap, "ptpu_spec_drafted_tokens_total")
    spa = _fleet_counter(fleet_snap,
                         "ptpu_spec_accepted_tokens_total")
    spn = _fleet_counter(fleet_snap, "ptpu_spec_dispatches_total")
    if spn:
        # speculative tier present (ISSUE 13): merged accept rate over
        # every scraped engine — exact counter sums, like the sparse
        # line above
        spd, spa = spd or 0, spa or 0
        rate = "n/a" if not spd else "%.0f%%" % (100.0 * spa / spd)
        lines.append(
            "  spec     drafted %d accepted %d (accept rate %s)   "
            "dispatches %d" % (spd, spa, rate, spn))
    des = _fleet_gauge_series(fleet_snap, "ptpu_fleet_desired_replicas")
    if des:
        # elastic fleet present (serving.autoscale, ISSUE 18): live vs
        # desired replica count, per-version mix (the roll's
        # convergence renders as this mix shifting to one version),
        # and the scale/drain/roll totals the collector merges
        # incarnation-correctly like every other counter
        desired = int(max(des.values()))
        live_g = _fleet_gauge_series(fleet_snap,
                                     "ptpu_fleet_replicas") or {}
        live = "%d" % int(max(live_g.values())) if live_g else "?"
        mix_g = _fleet_gauge_series(
            fleet_snap, "ptpu_fleet_version_replicas") or {}
        mix = " ".join("%s:%d" % (k, int(v))
                       for k, v in sorted(mix_g.items())
                       if int(v)) or "n/a"
        scl = _fleet_counter(fleet_snap,
                             "ptpu_fleet_scale_events_total") or 0
        drn = _fleet_counter(fleet_snap, "ptpu_fleet_drains_total") or 0
        rol = _fleet_counter(fleet_snap, "ptpu_fleet_rolls_total") or 0
        line = ("  autoscale replicas %s/%d   versions %s   "
                "scale events %d   drains %d   rolls %d"
                % (live, desired, mix, scl, drn, rol))
        last = getattr(state, "last_scale_event", None)
        if last is not None:
            line += "   last %s->%s (%s)" % (
                last.get("direction", "?"), last.get("desired", "?"),
                last.get("reason", "?"))
        lines.append(line)
        lr = getattr(state, "last_roll", None)
        if lr is not None:
            dt = lr.get("convergence_s")
            lines.append(
                "  roll     %s -> %s   %s   replaced %d   shed %d%s"
                % (lr.get("from_version", "?"),
                   lr.get("to_version", "?"),
                   "ABORTED: %s" % lr.get("reason")
                   if lr.get("aborted") else "converged",
                   int(lr.get("replaced") or 0),
                   int(lr.get("shed_during") or 0),
                   "" if dt is None else "   %.1fs" % dt))
    return lines


def rollout_line(state):
    """The canary-rollout status line (ISSUE 19): live phase, version
    mix, per-phase delta verdicts, and — once promoted — the
    version-convergence time. Rendered from the newest ``rollout`` /
    ``verdict`` recorder rows (file mode tails them, fleet mode ships
    them through the collector — one source either way). None while
    no rollout has ever run (quiet fleets keep byte-identical
    frames)."""
    ro = state.last_rollout
    if ro is None:
        return None
    line = "  rollout  %s   phase %s" % (ro.get("version", "?"),
                                         ro.get("phase", "?"))
    if state.verdicts:
        vs = " ".join(
            "%s:%s" % (p, v.get("verdict", "?"))
            for p, v in sorted(state.verdicts.items()))
        line += "   verdicts %s" % vs
    mix = ro.get("version_mix")
    if mix:
        line += "   versions %s" % " ".join(
            "%s:%d" % (k, int(n)) for k, n in sorted(mix.items())
            if int(n))
    if ro.get("detail"):
        line += "   (%s)" % ro["detail"]
    if ro.get("convergence_s") is not None:
        line += "   convergence %.1fs" % float(ro["convergence_s"])
    return line


def render_frame(state, path, slo_verdict=None, now=None,
                 staleness=None, fleet=None, alerts_line=None,
                 incidents_line=None):
    """One frame of the dashboard as a string (the ``--once`` / test
    surface; the live loop wraps it in an ANSI clear). ``staleness``:
    {path: last row ts} for the multi-log per-file indicator;
    ``fleet``: a collector fleet snapshot for the scraped-dashboard
    header; ``alerts_line``: the signals evaluator's ACTIVE ALERTS
    summary (monitor/signals.py — file mode and --fleet render the
    same line from the same evaluation shape); ``incidents_line``:
    the forensics incidents summary (active incident names + most
    recent bundle path, monitor/forensics.py)."""
    lines = ["paddle_tpu monitor watch — %s   %d events (%s)"
             % (path, state.events, state.platform or "?")]
    if state.last_ts is not None and now is not None:
        age = max(0.0, now - state.last_ts)
        lines[0] += "   last event %.1fs ago" % age
    if fleet is not None:
        lines.extend(fleet_lines(fleet, now=now, state=state))
    ro_line = rollout_line(state)
    if ro_line is not None:
        lines.append(ro_line)
    if staleness:
        lines.extend(staleness_lines(staleness, now=now))

    if state.serving_steps:
        dts = [s["dt"] for s in state.serving_steps
               if s.get("dt") is not None]
        last = state.serving_steps[-1]
        occ = (last["active"] / last["slots"]) if last.get("slots") \
            else 0.0
        tps = None
        ts = [s["ts"] for s in state.serving_steps
              if s.get("ts") is not None]
        if len(ts) >= 2 and ts[-1] > ts[0]:
            tok = sum(s.get("emitted") or 0
                      for s in state.serving_steps)
            tps = tok / (ts[-1] - ts[0])
        lines.append(
            "serving   steps %-7d tokens/s %-8s occupancy %-5.2f "
            "queue %-4d step p50 %s p95 %s"
            % (state.total_serving_steps,
               "n/a" if tps is None else "%.0f" % tps, occ,
               last.get("queue_depth", 0),
               _ms(_p(dts, 0.50)), _ms(_p(dts, 0.95))))
    kv_last = {}
    for s in state.serving_steps:
        if s.get("kv_used_blocks") is not None:
            # LAST row PER ENGINE: a fleet writes one log per replica,
            # and reading only the globally-last row would render one
            # arbitrary replica's pool as the fleet's (the
            # single-replica-flatters-the-fleet distortion the PR-8
            # multi-log union exists to avoid)
            kv_last[s.get("engine") or "engine"] = s
    if kv_last:
        # occupancy sums the per-engine gauges; hit rate sums the
        # cumulative counters each engine's rows carry (last-row
        # arithmetic per engine, never a window sum)
        rows = list(kv_last.values())
        used = sum(r["kv_used_blocks"] for r in rows)
        total = sum(r["kv_total_blocks"] for r in rows)
        h = sum(r.get("prefix_hits") or 0 for r in rows)
        m = sum(r.get("prefix_misses") or 0 for r in rows)
        rate = "n/a" if h + m == 0 else "%.0f%%" % (100.0 * h / (h + m))
        # real HBM, not block counts (ISSUE 20): engines stamp
        # quantization-aware byte figures, so an int8 pool's line
        # shows its actual (smaller) footprint
        bu = sum(r.get("kv_bytes_used") or 0 for r in rows)
        bt = sum(r.get("kv_bytes_total") or 0 for r in rows)
        hbm = "" if not bt else "   hbm %.1f/%.1f MiB" % (
            bu / 2**20, bt / 2**20)
        lines.append(
            "kv        blocks %d/%d (%.0f%%)%s   prefix hits %d "
            "misses %d (hit rate %s)   preemptions %d"
            % (used, total, 100.0 * used / total if total else 0.0,
               hbm, h, m, rate, state.total_preemptions))
    spec_last = {}
    for s in state.serving_steps:
        if s.get("spec_dispatches") is not None:
            # speculative-decode counters are CUMULATIVE per engine
            # row (ISSUE 13) — last row per engine, same discipline
            # as the kv line above
            spec_last[s.get("engine") or "engine"] = s
    if spec_last:
        rows = list(spec_last.values())
        dr = sum(r.get("spec_drafted") or 0 for r in rows)
        ac = sum(r.get("spec_accepted") or 0 for r in rows)
        em = sum(r.get("spec_emitted") or 0 for r in rows)
        di = sum(r.get("spec_dispatches") or 0 for r in rows)
        rate = "n/a" if not dr else "%.0f%%" % (100.0 * ac / dr)
        lines.append(
            "spec      drafted %d accepted %d (accept rate %s)   "
            "dispatches %d (%s tok/dispatch)"
            % (dr, ac, rate, di,
               "n/a" if not di else "%.2f" % (em / di)))
    sparse_last = {}
    for s in state.serving_steps:
        if s.get("cache_hits") is not None:
            # hot-ID cache counters are CUMULATIVE per engine row
            # (serving.sparse scoring engines) — last row per engine,
            # same discipline as the kv line above
            sparse_last[s.get("engine") or "engine"] = s
    if sparse_last:
        rows = list(sparse_last.values())
        h = sum(r.get("cache_hits") or 0 for r in rows)
        m = sum(r.get("cache_misses") or 0 for r in rows)
        st = sum(r.get("cache_stale") or 0 for r in rows)
        ev = sum(r.get("cache_evictions") or 0 for r in rows)
        rate = "n/a" if h + m == 0 else "%.0f%%" % (100.0 * h / (h + m))
        lines.append(
            "sparse    cache hits %d misses %d stale %d evictions %d "
            "(hit rate %s)" % (h, m, st, ev, rate))
    if state.requests:
        # failed rows are error-budget-only (same policy as the SLO
        # engine — this line and the verdict line below must agree)
        ok = [r for r in state.requests if not r.get("error")]
        ttft = [r["ttft"] for r in ok if r.get("ttft") is not None]
        tpot = [r["tpot"] for r in ok if r.get("tpot") is not None]
        qw = [r["queue_wait"] for r in ok
              if r.get("queue_wait") is not None]
        lines.append(
            "requests  n %-9d TTFT p50 %s p95 %s   TPOT p50 %s "
            "p95 %s   queue_wait p95 %s"
            % (state.total_requests,
               _ms(_p(ttft, 0.50)), _ms(_p(ttft, 0.95)),
               _ms(_p(tpot, 0.50)), _ms(_p(tpot, 0.95)),
               _ms(_p(qw, 0.95))))
    if state.train_steps:
        dts = [s["dt"] for s in state.train_steps
               if s.get("dt") is not None and s.get("synced", True)]
        last = state.train_steps[-1]
        extra = ""
        if last.get("tokens_per_sec"):
            extra += "   tok/s %.0f" % last["tokens_per_sec"]
        if last.get("mfu"):
            extra += "   mfu %.1f%%" % (100 * last["mfu"])
        lines.append("train     steps %-7d p50 %s p95 %s%s"
                     % (state.total_train_steps, _ms(_p(dts, 0.50)),
                        _ms(_p(dts, 0.95)), extra))
    health = "health    stalls %d   nan trips %d   errors %d" % (
        state.stalls, state.nan_trips, state.total_errors)
    if state.truncated:
        health += "   [LOG TRUNCATED AT CAP]"
    if state.skipped:
        # complete-but-unparseable lines: permanently skipped (a TORN
        # trailing line never reaches here — _Tail holds it back and
        # retries it next refresh)
        health += "   (%d corrupt line(s) skipped)" % state.skipped
    lines.append(health)
    if alerts_line is not None:
        lines.append(alerts_line)
    if incidents_line is not None:
        lines.append(incidents_line)
    if slo_verdict is not None:
        status = " ".join(
            "%s %s%s" % ("PASS" if r["pass"] else "FAIL",
                         r["metric"] + (" burn" if r.get("burn")
                                        else ""),
                         ("=" + _ms(r["measured"]))
                         if r["measured"] is not None
                         and not r.get("burn")
                         and r["metric"] not in ("error_rate",
                                                 "goodput_fraction")
                         else "")
            for r in slo_verdict["objectives"])
        lines.append("slo       %s   %s"
                     % ("PASS" if slo_verdict["pass"] else "FAIL",
                        status))
    return "\n".join(lines)


def watch(path, interval=2.0, window=256, once=False, out=None,
          slo_spec=None, max_frames=None):
    """Tail ``path`` — one flight-recorder log, or a LIST of them (a
    serving fleet writes one per replica; the dashboard and the live
    SLO verdict aggregate the union) — and render the dashboard every
    ``interval`` seconds until interrupted. ``once`` reads what is
    there now, renders ONE frame without clearing the screen, and
    returns it (tests and scripts). ``slo_spec`` (path/dict) adds a
    live verdict line evaluated over the rolling request window.
    ``max_frames`` bounds the live loop (None = until Ctrl-C)."""
    paths = [path] if isinstance(path, str) else list(path)
    label = ", ".join(paths)
    if out is None:
        out = sys.stdout
    spec = None
    if slo_spec:
        from .. import slo as _slo
        spec = _slo.load_spec(slo_spec)
    # the ACTIVE ALERTS line (ISSUE 14): a local signals evaluation
    # over the tailed rows — single-process runs get alerting without
    # a collector. Burn rules arm when the spec carries error-budget
    # objectives; the sustained-condition defaults always arm.
    from . import signals as _signals
    sig = _signals.Signals(spec=spec)
    state = WatchState(window=window)
    tails = [_Tail(p) for p in paths]
    last_ts = {p: None for p in paths}   # per-log staleness indicator
    frames = 0
    try:
        while True:
            polls = [t.poll() for t in tails]
            if all(p is None for p in polls):   # no log created yet
                if once:
                    out.write("watch: %s does not exist (yet)\n"
                              % label)
                    return None
                out.write("\x1b[2J\x1b[Hwatch: waiting for %s ...\n"
                          % label)
                out.flush()
                time.sleep(interval)
                continue
            # merge this poll round's rows ACROSS logs by timestamp
            # before feeding the rolling window: fed file-by-file, the
            # last log's rows would evict every other replica's from
            # the window — exactly the single-replica view a fleet
            # dashboard exists to avoid. Stable sort keeps each file's
            # own order for ts-less rows.
            events = []
            for t, lines in zip(tails, polls):
                for line in lines or ():
                    e = state.parse_line(line)
                    if e is not None:
                        events.append((e, t.path))
                        if e.get("ts") is not None:
                            last_ts[t.path] = max(
                                last_ts[t.path] or 0.0, e["ts"])
            events.sort(key=lambda pair: (pair[0].get("ts") is None,
                                          pair[0].get("ts") or 0.0))
            for e, src in events:
                state.feed_event(e, source=src)
            verdict = None
            if spec is not None:
                from .. import slo as _slo
                verdict = _slo.evaluate(spec, state.request_samples())
            led = state.goodput_rollup()
            if led is not None and led["goodput_fraction"] is not None:
                # the per-source rollup feeds the goodput_fraction
                # rule — spec or no spec, the alerts line gets it
                sig.feed_sample("goodput_fraction",
                                led["goodput_fraction"],
                                now=state.last_ts)
            if once:
                # deterministic offline evaluation on the log's own
                # clock: rows grouped into 1 s rounds, so alerts the
                # history SHOULD have fired are active in the frame
                sig.replay([e for e, _ in events])
            else:
                sig.feed_events([e for e, _ in events])
                sig.evaluate(now=time.time())
            from . import forensics as _forensics
            frame = render_frame(state, label, slo_verdict=verdict,
                                 now=None if once else time.time(),
                                 staleness=last_ts
                                 if len(paths) > 1 else None,
                                 alerts_line=_signals
                                 .active_alerts_line(sig),
                                 incidents_line=_forensics
                                 .incidents_line(sig))
            if once:
                out.write(frame + "\n")
                return frame
            out.write("\x1b[2J\x1b[H" + frame + "\n")
            out.flush()
            frames += 1
            if max_frames is not None and frames >= max_frames:
                return frame
            time.sleep(interval)
    except KeyboardInterrupt:
        return None
    finally:
        for t in tails:
            t.close()


def watch_fleet(kv_endpoint=None, static=(), interval=2.0, window=256,
                once=False, out=None, slo_spec=None, max_frames=None,
                collector=None):
    """The LIVE scraped dashboard (``watch --fleet``): a
    ``monitor.collector.Collector`` discovers the fleet from the
    membership lease registry (plus ``static`` (role, endpoint)
    pairs), scrapes every process's registry + flight-recorder delta
    over RPC each ``interval``, and renders the merged frame —
    replacing the PR-8 pattern of tailing one JSONL per replica.
    The SLO verdict line evaluates ``slo_spec`` against the rolling
    scraped request rows when any process streams recorder events,
    falling back to the merged fleet METRICS snapshot (approximate,
    bucket-interpolated) when none does — one spec gates the whole
    fleet either way."""
    from .collector import Collector
    if out is None:
        out = sys.stdout
    spec = None
    if slo_spec:
        from .. import slo as _slo
        spec = _slo.load_spec(slo_spec)
    col = collector if collector is not None else Collector(
        kv_endpoint=kv_endpoint, static=static)
    own_col = collector is None
    from . import signals as _signals
    sig = _signals.Signals(spec=spec)
    state = WatchState(window=window)
    label = kv_endpoint or ", ".join(ep for _, ep in static) \
        or "scrape"
    frames = 0
    try:
        while True:
            round_events = col.scrape_once()
            for e in round_events:
                # scraped rows carry proc = "role@endpoint": the
                # per-process key the rolling goodput rollup needs
                state.feed_event(e, source=e.get("proc") or "")
            snap = col.fleet_snapshot()
            # signals round: the merged snapshot feeds the counter
            # series (incarnation-aware), the scraped rows feed
            # samples + offender correlation, then one evaluation
            sig.feed_snapshot(snap)
            sig.feed_events(round_events)
            verdict = None
            if spec is not None:
                from .. import slo as _slo
                samples = state.request_samples()
                if not any(samples.get(k) for k in
                           ("ttft", "tpot", "queue_wait",
                            "step_latency")):
                    # no per-request rows scraped: latency objectives
                    # fall back to the merged fleet histograms — but
                    # the row-derived goodput ledger (training fleets
                    # stream step rows without serving rows) must
                    # survive the swap
                    fallback = _slo.samples_from_metrics(snap)
                    fallback["goodput"] = samples.get("goodput")
                    samples = fallback
                verdict = _slo.evaluate(spec, samples)
            led = state.goodput_rollup()
            if led is not None and led["goodput_fraction"] is not None:
                # same per-source rollup discipline as file mode —
                # the goodput rule is armed with or without a spec
                sig.feed_sample("goodput_fraction",
                                led["goodput_fraction"])
            sig.evaluate()
            from . import forensics as _forensics
            frame = render_frame(state, "fleet %s" % label,
                                 slo_verdict=verdict,
                                 now=None if once else time.time(),
                                 fleet=snap,
                                 alerts_line=_signals
                                 .active_alerts_line(sig),
                                 incidents_line=_forensics
                                 .incidents_line(sig))
            if once:
                from .metrics import META_KEY
                eps = (snap.get(META_KEY) or {}).get("endpoints") or []
                if not any(e.get("ok") for e in eps):
                    # exit-code parity with file mode (--once on a
                    # missing log returns None -> exit 1): a fleet
                    # where NOTHING answered must not read as healthy
                    out.write(frame + "\nwatch: no endpoint "
                              "answered the scrape\n")
                    return None
                out.write(frame + "\n")
                return frame
            out.write("\x1b[2J\x1b[H" + frame + "\n")
            out.flush()
            frames += 1
            if max_frames is not None and frames >= max_frames:
                return frame
            time.sleep(interval)
    except KeyboardInterrupt:
        return None
    finally:
        if own_col:
            col.close()
