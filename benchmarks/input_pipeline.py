"""Input-pipeline sustain benchmark: can the host feed the chip?

Measures the full data plane — recordio files on disk → reader.open_files
(threaded multi-file scan + decode) → paddle.batch → DataFeeder (sample
tuples → feed arrays) → DeviceLoader (prefetch thread, host→device
transfer) — as sustained ResNet-shaped images/sec, against the measured
~2500 img/s TPU training rate (BENCH resnet line). Reference parity:
the double-buffer reader chain (operators/reader/
create_double_buffer_reader_op.cc:34 + open_files_op.cc).

Stages reported separately so a gap is attributable:
  raw      open_files scan+decode only
  feeder   + batch + DataFeeder
  device   + DeviceLoader host->device transfer (the full path)
"""

import os
import tempfile
import time

import numpy as np

from common import parse_args  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import reader as reader_mod  # noqa: E402
from paddle_tpu.reader.device_loader import DeviceLoader  # noqa: E402


def _write_files(tmpdir, n_files, per_file, shape, dtype, compressor):
    """recordio files of (image CHW, label i64) samples."""
    from paddle_tpu import recordio
    comp = recordio.COMPRESSOR_NONE if compressor == "none" \
        else recordio.COMPRESSOR_DEFLATE
    paths = []
    rng = np.random.RandomState(0)
    for f in range(n_files):
        p = os.path.join(tmpdir, "part-%03d.recordio" % f)

        def creator(f=f):
            for i in range(per_file):
                img = rng.rand(*shape).astype(np.float32)
                if dtype == "uint8":
                    img = (img * 255).astype(np.uint8)
                yield (img, np.int64(i % 1000))
        recordio.convert_reader_to_recordio_file(p, creator,
                                                 compressor=comp)
        paths.append(p)
    return paths


def _drain(it, n_items_fn):
    t0 = time.perf_counter()
    n = 0
    for item in it:
        n += n_items_fn(item)
    dt = time.perf_counter() - t0
    return n / dt, n


def main():
    args = parse_args(
        "input_pipeline", batch_size=64, iterations=0,
        extra=lambda p: (
            p.add_argument("--n_files", type=int, default=8),
            p.add_argument("--per_file", type=int, default=256),
            p.add_argument("--image_size", type=int, default=224),
            p.add_argument("--thread_num", type=int, default=4),
            p.add_argument("--sample_dtype", type=str,
                           default="float32",
                           choices=["float32", "uint8"]),
            p.add_argument("--compressor", type=str, default="deflate",
                           choices=["deflate", "none"]),
            p.add_argument("--target_rate", type=float, default=2500.0)))
    shape = (3, args.image_size, args.image_size)
    tmpdir = tempfile.mkdtemp(prefix="ipbench_")
    paths = _write_files(tmpdir, args.n_files, args.per_file, shape,
                         args.sample_dtype, args.compressor)
    total = args.n_files * args.per_file

    def open_all():
        return reader_mod.open_files(paths, thread_num=args.thread_num,
                                     buffer_size=128)

    # stage 1: raw scan+decode
    raw_ips, n = _drain(open_all()(), lambda s: 1)
    assert n == total, (n, total)

    # stage 2: + batch + DataFeeder
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        # uint8 samples stay uint8 through feed + transfer (cast to f32
        # on DEVICE in a real step) — 4x less tunnel traffic
        img = fluid.layers.data("image", list(shape),
                                dtype=args.sample_dtype)
        lbl = fluid.layers.data("label", [1], dtype="int64")
        feeder = fluid.DataFeeder([img, lbl], program=main_p)
    batched = reader_mod.batch(open_all(), args.batch_size)

    def feed_iter(src):
        for samples in src():
            yield feeder.feed(samples)

    feeder_ips, _ = _drain(feed_iter(lambda: batched()),
                           lambda d: d["image"].shape[0])

    # stage 3: + DeviceLoader prefetch + host->device transfer (full
    # path). device_put ENQUEUES asynchronously, so the clock must run
    # until the last transfer COMPLETES (a one-element fetch of the
    # final batch orders the timeline) — counting enqueues would
    # overstate the tunnel's few-MB/s upload path several-fold.
    batched2 = reader_mod.batch(open_all(), args.batch_size)
    loader = DeviceLoader(feed_iter(lambda: batched2()), capacity=2)
    t0 = time.perf_counter()
    n_img, last = 0, None
    for d in loader:
        n_img += d["image"].shape[0]
        last = d["image"]
    if last is not None:
        np.asarray(last.ravel()[:1])
    device_ips = n_img / (time.perf_counter() - t0)

    print("input_pipeline: raw %.0f img/s | +feeder %.0f img/s | "
          "+device %.0f img/s (target: sustain %.0f img/s)"
          % (raw_ips, feeder_ips, device_ips, args.target_rate))
    verdict = "SUSTAINS" if device_ips >= args.target_rate else "GAP"
    print("=> %s: full-path %.0f img/s vs %.0f img/s train rate (%.1fx)"
          % (verdict, device_ips, args.target_rate,
             device_ips / args.target_rate))
    return device_ips


if __name__ == "__main__":
    main()
