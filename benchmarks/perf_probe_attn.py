"""Standalone flash-attention kernel probe (round-4 directive #2).

The transformer ablation (perf_probe_transformer.py) attributes ~46% of
the 8L/d1024 step to attention whose FLOP share is 13% — the kernel runs
at ~12% MFU while FFN matmuls hit 61%. This probe times fwd+bwd of one
attention call at the bench shape across kernel variants to pick the fix.

Sync protocol: device->host scalar fetch per window (axon tunnel).
"""

import functools
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def time_fn(name, fn, *args, iters=20, windows=5):
    f = jax.jit(fn)
    r = f(*args)
    float(jnp.sum(r[0] if isinstance(r, tuple) else r))
    times = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(iters):
            r = f(*args)
        float(jnp.sum(r[0] if isinstance(r, tuple) else r))
        times.append((time.perf_counter() - t0) / iters)
    times.sort()
    med = times[len(times) // 2]
    print("%-34s %8.3f ms  (best %.3f worst %.3f)"
          % (name, med * 1000, times[0] * 1000, times[-1] * 1000),
          flush=True)
    return med


def main():
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("--b", type=int, default=8)
    p.add_argument("--h", type=int, default=8)
    p.add_argument("--t", type=int, default=1024)
    p.add_argument("--d", type=int, default=128)
    args = p.parse_args()
    B, H, T, D = args.b, args.h, args.t, args.d

    from paddle_tpu.ops import flash_attention as FA

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, T, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, H, T, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, H, T, D), jnp.bfloat16)
    dy = jnp.asarray(rng.randn(B, H, T, D), jnp.bfloat16)

    # CHAIN = stacked attention calls inside ONE jit: a single call is
    # below the tunnel dispatch floor (~6 ms), which would swamp the
    # kernel; the chain mirrors the model's 8 layers
    CHAIN = 8
    # causal attention FLOPs (block-skipped ideal): fwd 2 matmuls, bwd 5
    full_fwd = 2 * 2 * B * H * T * T * D
    causal_fwd = full_fwd / 2 * CHAIN
    causal_tot = causal_fwd * 3.5          # fwd + bwd(2.5x)
    print("shape [%d,%d,%d,%d] x%d chained: causal fwd+bwd useful "
          "FLOPs %.1f GF" % (B, H, T, D, CHAIN, causal_tot / 1e9),
          flush=True)

    def fwdbwd(attn_fn):
        def loss(q, k, v):
            c = q
            for _ in range(CHAIN):
                # re-project c through a cheap elementwise twist so XLA
                # cannot CSE the chained calls
                c = attn_fn(c, k, v) + 1e-6 * c
            return jnp.sum(c.astype(jnp.float32) * dy.astype(jnp.float32))

        def run(q, k, v):
            l, g = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
            return l
        return run

    def report(name, med):
        print("   -> %s: %.1f TF/s = %.1f%% MFU (causal-useful)"
              % (name, causal_tot / med / 1e12,
                 causal_tot / med / 197e12 * 100), flush=True)

    variants = [
        ("ours 256x256 (current)", functools.partial(
            FA.flash_attention, causal=True, force="pallas")),
        ("ours 512x512", functools.partial(
            FA.flash_attention, causal=True, force="pallas",
            block_q=512, block_k=512)),
        ("ours 1024x1024", functools.partial(
            FA.flash_attention, causal=True, force="pallas",
            block_q=1024, block_k=1024)),
        ("dense XLA", functools.partial(
            FA.flash_attention, causal=True, force="dense")),
    ]
    for name, fn in variants:
        try:
            med = time_fn(name, fwdbwd(fn), q, k, v)
            report(name, med)
        except Exception as e:
            print("%s FAILED: %s" % (name, str(e)[:200]), flush=True)

    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as jax_fa, BlockSizes)

        def bundled(q, k, v):
            return jax_fa(q, k, v, causal=True,
                          sm_scale=float(D) ** -0.5)
        med = time_fn("jax bundled flash", fwdbwd(bundled), q, k, v)
        report("jax bundled", med)
    except Exception as e:
        print("jax bundled FAILED: %s" % str(e)[:200], flush=True)

    # fwd-only splits for the winner diagnosis
    def fwd_chain(attn_fn):
        def run(q, k, v):
            c = q
            for _ in range(CHAIN):
                c = attn_fn(c, k, v) + 1e-6 * c
            return jnp.sum(c)
        return run

    for name, fn in [
            ("fwd-only ours 256", functools.partial(
                FA.flash_attention, causal=True, force="pallas")),
            ("fwd-only dense", functools.partial(
                FA.flash_attention, causal=True, force="dense"))]:
        med = time_fn(name, fwd_chain(fn), q, k, v)
        print("   -> fwd: %.1f TF/s (causal-useful %.1f GF)"
              % (causal_fwd / med / 1e12, causal_fwd / 1e9), flush=True)


if __name__ == "__main__":
    main()
