"""SPMD parallel tests on the 8-virtual-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8): dp ParallelExecutor parity with the
single-device Executor, tp sharding hints, ring/ulysses attention vs dense
reference."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu import parallel


def _mlp_with_loss():
    x = fluid.layers.data("x", [16])
    label = fluid.layers.data("label", [1], dtype="int64")
    h = fluid.layers.fc(x, 32, act="relu")
    pred = fluid.layers.fc(h, 4, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    return loss


def test_parallel_executor_matches_single_device():
    loss = _mlp_with_loss()
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(0)
    xv = rng.rand(16, 16).astype(np.float32)
    yv = rng.randint(0, 4, (16, 1)).astype(np.int64)

    # snapshot initial params, run single-device baseline
    scope = fluid.global_scope()
    names = [p.name for p in fluid.default_main_program().all_parameters()]
    init = {n: np.asarray(scope.find_var(n)).copy() for n in names}
    single = [float(np.asarray(exe.run(feed={"x": xv, "label": yv},
                                       fetch_list=[loss])[0]))
              for _ in range(3)]

    # restore, run the same steps under an 8-way dp mesh
    for n, v in init.items():
        scope.set(n, v)
    mesh = parallel.make_mesh({"dp": 8})
    pexe = fluid.ParallelExecutor(loss_name=loss.name, mesh=mesh)
    assert pexe.device_count == 8
    par = [float(np.asarray(pexe.run([loss],
                                     feed={"x": xv, "label": yv})[0]))
           for _ in range(3)]
    np.testing.assert_allclose(single, par, rtol=1e-5, atol=1e-6)


def test_parallel_executor_rejects_indivisible_batch():
    loss = _mlp_with_loss()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    mesh = parallel.make_mesh({"dp": 8})
    pexe = fluid.ParallelExecutor(mesh=mesh)
    with pytest.raises(ValueError, match="divisible"):
        pexe.run([loss], feed={"x": np.ones((6, 16), np.float32),
                               "label": np.zeros((6, 1), np.int64)})


def test_tensor_parallel_sharding_hint():
    x = fluid.layers.data("x", [32])
    w_attr = fluid.ParamAttr(name="tp_w")
    h = fluid.layers.fc(x, 64, param_attr=w_attr, bias_attr=False)
    out = fluid.layers.reduce_sum(h)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    parallel.shard("tp_w", None, "tp")
    mesh = parallel.make_mesh({"dp": 2, "tp": 4})
    pexe = fluid.ParallelExecutor(mesh=mesh)
    xv = np.random.RandomState(1).rand(8, 32).astype(np.float32)
    got, = pexe.run([out], feed={"x": xv})
    w = np.asarray(fluid.global_scope().find_var("tp_w"))
    np.testing.assert_allclose(float(np.asarray(got)), (xv @ w).sum(),
                               rtol=1e-4)
    # the committed state must actually be laid out tp-sharded
    wv = fluid.global_scope().find_var("tp_w")
    assert isinstance(wv, jax.Array)
    spec = wv.sharding.spec
    assert tuple(spec) in ((None, "tp"), ("tp",)) or "tp" in str(spec)


def _dense_attention(q, k, v, causal=False):
    scale = q.shape[-1] ** -0.5
    s = np.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        t = s.shape[-1]
        mask = np.tril(np.ones((t, t), bool))
        s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    mesh = parallel.make_mesh({"sp": 8})
    rng = np.random.RandomState(0)
    b, h, t, d = 2, 4, 64, 16
    q = rng.randn(b, h, t, d).astype(np.float32)
    k = rng.randn(b, h, t, d).astype(np.float32)
    v = rng.randn(b, h, t, d).astype(np.float32)
    with mesh:
        got = np.asarray(parallel.ring_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh,
            axis_name="sp", causal=causal))
    want = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(causal):
    from paddle_tpu.parallel.ring import ulysses_attention
    mesh = parallel.make_mesh({"sp": 8})
    rng = np.random.RandomState(1)
    b, h, t, d = 2, 8, 32, 8
    q = rng.randn(b, h, t, d).astype(np.float32)
    k = rng.randn(b, h, t, d).astype(np.float32)
    v = rng.randn(b, h, t, d).astype(np.float32)
    with mesh:
        got = np.asarray(ulysses_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh,
            axis_name="sp", causal=causal))
    want = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ring_attention_differentiable():
    mesh = parallel.make_mesh({"sp": 4})
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(1, 2, 16, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 2, 16, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 2, 16, 8).astype(np.float32))

    def loss_fn(q, k, v):
        with mesh:
            return jnp.sum(parallel.ring_attention(q, k, v, mesh,
                                                   axis_name="sp") ** 2)

    g = jax.grad(loss_fn)(q, k, v)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).max()) > 0


def test_collective_ops_identity_outside_mesh():
    x = fluid.layers.data("x", [4])
    blk = fluid.default_main_program().current_block()
    out = blk.create_var(name="ar_out", dtype="float32")
    blk.append_op(type="c_allreduce_sum", inputs={"X": [x]},
                  outputs={"Out": [out]})
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((2, 4), np.float32)
    got, = exe.run(feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(got, xv)
