"""Optimizer ops.

Reference parity: operators/{sgd,momentum,adagrad,adam,adamax,decayed_adagrad,
adadelta,rmsprop,ftrl,proximal_gd,proximal_adagrad}_op.cc.

The reference updates Param in-place in the scope. Here each op writes the
updated value back into the env under the *input* var's name (as well as the
declared output slot), so the Executor's state threading commits it — with
buffer donation this compiles to a true in-place update on device.
"""

import jax.numpy as jnp

from ..core.registry import register
from ..core.selected_rows import SelectedRows


def _upd(ctx, op, slot_in, slot_out, value):
    names = op.input(slot_in)
    if names:
        ctx.env[names[0]] = value
    out = op.output(slot_out)
    if out:
        ctx.env[out[0]] = value


def _sparse(g):
    """(rows, values) for a SelectedRows grad, else None — optimizer ops
    with a SelectedRows grad apply SPARSE row updates (sgd_op.cc /
    adam_op lazy-mode SelectedRows branches): only touched rows of the
    param (and moments) change — the pserver-side sharded-embedding
    update path."""
    if isinstance(g, SelectedRows):
        rows = jnp.asarray(g.rows).reshape(-1)
        vals = jnp.asarray(g.value).reshape((rows.shape[0], -1))
        return rows, vals
    return None


@register("sgd")
def _sgd(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad")
    lr = ctx.in1(op, "LearningRate")
    sp = _sparse(g)
    if sp is not None:
        rows, vals = sp
        p_new = jnp.asarray(p).at[rows].add(
            (-lr * vals).reshape((rows.shape[0],) + p.shape[1:]))
        _upd(ctx, op, "Param", "ParamOut", p_new)
        return
    _upd(ctx, op, "Param", "ParamOut", p - lr * g)


@register("momentum")
def _momentum(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad")
    v = ctx.in1(op, "Velocity")
    lr = ctx.in1(op, "LearningRate")
    mu = op.attr("mu", 0.9)
    v_new = mu * v + g
    if op.attr("use_nesterov", False):
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    _upd(ctx, op, "Velocity", "VelocityOut", v_new)
    _upd(ctx, op, "Param", "ParamOut", p_new)


@register("adagrad")
def _adagrad(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad")
    m = ctx.in1(op, "Moment")
    lr = ctx.in1(op, "LearningRate")
    eps = op.attr("epsilon", 1e-6)
    m_new = m + g * g
    p_new = p - lr * g / (jnp.sqrt(m_new) + eps)
    _upd(ctx, op, "Moment", "MomentOut", m_new)
    _upd(ctx, op, "Param", "ParamOut", p_new)


@register("adam")
def _adam(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad")
    m1 = ctx.in1(op, "Moment1")
    m2 = ctx.in1(op, "Moment2")
    lr = ctx.in1(op, "LearningRate")
    b1p = ctx.in1(op, "Beta1Pow")
    b2p = ctx.in1(op, "Beta2Pow")
    b1 = op.attr("beta1", 0.9)
    b2 = op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-8)
    sp = _sparse(g)
    if sp is not None:
        # lazy Adam (adam_op SelectedRows branch): moments and param
        # update only on the touched rows; untouched rows keep state
        rows, vals = sp
        tail = p.shape[1:]
        gv = vals.reshape((rows.shape[0],) + tail)
        p = jnp.asarray(p)
        m1 = jnp.asarray(m1)
        m2 = jnp.asarray(m2)
        m1r = b1 * m1[rows] + (1 - b1) * gv
        m2r = b2 * m2[rows] + (1 - b2) * gv * gv
        lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
        p_new = p.at[rows].add(-lr_t * m1r / (jnp.sqrt(m2r) + eps))
        _upd(ctx, op, "Moment1", "Moment1Out", m1.at[rows].set(m1r))
        _upd(ctx, op, "Moment2", "Moment2Out", m2.at[rows].set(m2r))
        _upd(ctx, op, "Param", "ParamOut", p_new)
        if op.attr("update_beta_pow", False):
            _upd(ctx, op, "Beta1Pow", "Beta1PowOut", b1p * b1)
            _upd(ctx, op, "Beta2Pow", "Beta2PowOut", b2p * b2)
        return
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * g * g
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    p_new = p - lr_t * m1n / (jnp.sqrt(m2n) + eps)
    _upd(ctx, op, "Moment1", "Moment1Out", m1n)
    _upd(ctx, op, "Moment2", "Moment2Out", m2n)
    _upd(ctx, op, "Param", "ParamOut", p_new)
    # Beta pow accumulators updated by the caller-side scale op in the
    # reference (optimizer.py); here we advance them with the op itself when
    # this op is the designated "last" one (attr set by Optimizer).
    if op.attr("update_beta_pow", False):
        _upd(ctx, op, "Beta1Pow", "Beta1PowOut", b1p * b1)
        _upd(ctx, op, "Beta2Pow", "Beta2PowOut", b2p * b2)


@register("adamax")
def _adamax(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad")
    m = ctx.in1(op, "Moment")
    inf_norm = ctx.in1(op, "InfNorm")
    lr = ctx.in1(op, "LearningRate")
    b1p = ctx.in1(op, "Beta1Pow")
    b1 = op.attr("beta1", 0.9)
    b2 = op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-8)
    m_new = b1 * m + (1 - b1) * g
    inf_new = jnp.maximum(b2 * inf_norm, jnp.abs(g) + eps)
    p_new = p - (lr / (1 - b1p)) * m_new / inf_new
    _upd(ctx, op, "Moment", "MomentOut", m_new)
    _upd(ctx, op, "InfNorm", "InfNormOut", inf_new)
    _upd(ctx, op, "Param", "ParamOut", p_new)
    if op.attr("update_beta_pow", False):
        _upd(ctx, op, "Beta1Pow", "Beta1PowOut", b1p * b1)


@register("decayed_adagrad")
def _decayed_adagrad(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad")
    m = ctx.in1(op, "Moment")
    lr = ctx.in1(op, "LearningRate")
    decay = op.attr("decay", 0.95)
    eps = op.attr("epsilon", 1e-6)
    m_new = decay * m + (1 - decay) * g * g
    p_new = p - lr * g / (jnp.sqrt(m_new) + eps)
    _upd(ctx, op, "Moment", "MomentOut", m_new)
    _upd(ctx, op, "Param", "ParamOut", p_new)


@register("adadelta")
def _adadelta(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad")
    avg_sq_grad = ctx.in1(op, "AvgSquaredGrad")
    avg_sq_upd = ctx.in1(op, "AvgSquaredUpdate")
    rho = op.attr("rho", 0.95)
    eps = op.attr("epsilon", 1e-6)
    asg = rho * avg_sq_grad + (1 - rho) * g * g
    upd = -jnp.sqrt((avg_sq_upd + eps) / (asg + eps)) * g
    asu = rho * avg_sq_upd + (1 - rho) * upd * upd
    _upd(ctx, op, "AvgSquaredGrad", "AvgSquaredGradOut", asg)
    _upd(ctx, op, "AvgSquaredUpdate", "AvgSquaredUpdateOut", asu)
    _upd(ctx, op, "Param", "ParamOut", p + upd)


@register("rmsprop")
def _rmsprop(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad")
    ms = ctx.in1(op, "MeanSquare")
    mom = ctx.in1(op, "Moment")
    lr = ctx.in1(op, "LearningRate")
    rho = op.attr("decay", 0.9)
    eps = op.attr("epsilon", 1e-10)
    momentum = op.attr("momentum", 0.0)
    ms_new = rho * ms + (1 - rho) * g * g
    mom_new = momentum * mom + lr * g / jnp.sqrt(ms_new + eps)
    _upd(ctx, op, "MeanSquare", "MeanSquareOut", ms_new)
    _upd(ctx, op, "Moment", "MomentOut", mom_new)
    _upd(ctx, op, "Param", "ParamOut", p - mom_new)


@register("ftrl")
def _ftrl(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad")
    sq = ctx.in1(op, "SquaredAccumulator")
    lin = ctx.in1(op, "LinearAccumulator")
    lr = ctx.in1(op, "LearningRate")
    l1 = op.attr("l1", 0.0)
    l2 = op.attr("l2", 0.0)
    power = op.attr("lr_power", -0.5)
    sq_new = sq + g * g
    sigma = (jnp.power(sq_new, -power) - jnp.power(sq, -power)) / lr
    lin_new = lin + g - sigma * p
    x = l1 * jnp.sign(lin_new) - lin_new
    y = jnp.power(sq_new, -power) / lr + 2 * l2
    p_new = jnp.where(jnp.abs(lin_new) > l1, x / y, jnp.zeros_like(p))
    _upd(ctx, op, "SquaredAccumulator", "SquaredAccumOut", sq_new)
    _upd(ctx, op, "LinearAccumulator", "LinearAccumOut", lin_new)
    _upd(ctx, op, "Param", "ParamOut", p_new)


@register("proximal_gd")
def _proximal_gd(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad")
    lr = ctx.in1(op, "LearningRate")
    l1 = op.attr("l1", 0.0)
    l2 = op.attr("l2", 0.0)
    prox = p - lr * g
    p_new = jnp.sign(prox) * jnp.maximum(
        jnp.abs(prox) - lr * l1, 0.0) / (1.0 + lr * l2)
    _upd(ctx, op, "Param", "ParamOut", p_new)


@register("proximal_adagrad")
def _proximal_adagrad(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad")
    m = ctx.in1(op, "Moment")
    lr = ctx.in1(op, "LearningRate")
    l1 = op.attr("l1", 0.0)
    l2 = op.attr("l2", 0.0)
    m_new = m + g * g
    lr_t = lr / jnp.sqrt(m_new)
    prox = p - lr_t * g
    p_new = jnp.sign(prox) * jnp.maximum(
        jnp.abs(prox) - lr_t * l1, 0.0) / (1.0 + lr_t * l2)
    _upd(ctx, op, "Moment", "MomentOut", m_new)
    _upd(ctx, op, "Param", "ParamOut", p_new)


@register("average_accumulates")
def _average_accumulates(ctx, op):
    """ModelAverage support (operators/average_accumulates_op.cc) —
    maintains windowed sums of parameter values."""
    p = ctx.in1(op, "param")
    sum1 = ctx.in1(op, "in_sum_1")
    sum2 = ctx.in1(op, "in_sum_2")
    sum3 = ctx.in1(op, "in_sum_3")
    num_acc = ctx.in1(op, "in_num_accumulates")
    old_num = ctx.in1(op, "in_old_num_accumulates")
    num_upd = ctx.in1(op, "in_num_updates")
    avg_window = op.attr("average_window", 0.0)
    max_avg_win = op.attr("max_average_window", 10000)
    min_avg_win = op.attr("min_average_window", 10000)

    # reference semantics (average_accumulates_op.h): accumulate param into
    # sum1 each step; when the window is reached, fold everything into sum3
    # and reset sum1/sum2 — apply() divides (s1+s2+s3)/(num+old_num).
    num_upd_n = num_upd + 1
    num_acc_n = num_acc + 1
    sum1_acc = sum1 + p
    window = jnp.minimum(
        jnp.maximum(min_avg_win, avg_window * num_upd_n.astype(jnp.float32)),
        max_avg_win).astype(jnp.float32)
    roll = num_acc_n.astype(jnp.float32) >= window
    sum1_n = jnp.where(roll, jnp.zeros_like(sum1), sum1_acc)
    sum2_n = jnp.where(roll, jnp.zeros_like(sum2), sum2)
    sum3_n = jnp.where(roll, sum1_acc + sum2, sum3)
    old_num_n = jnp.where(roll, num_acc_n, old_num)
    num_acc_n = jnp.where(roll, jnp.zeros_like(num_acc_n), num_acc_n)

    _upd(ctx, op, "in_sum_1", "out_sum_1", sum1_n)
    _upd(ctx, op, "in_sum_2", "out_sum_2", sum2_n)
    _upd(ctx, op, "in_sum_3", "out_sum_3", sum3_n)
    _upd(ctx, op, "in_num_accumulates", "out_num_accumulates", num_acc_n)
    _upd(ctx, op, "in_old_num_accumulates", "out_old_num_accumulates",
         old_num_n)
    _upd(ctx, op, "in_num_updates", "out_num_updates", num_upd_n)
