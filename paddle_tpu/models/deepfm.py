"""DeepFM CTR model — the sparse-embedding workload SURVEY §7 M5 names
(the reference serves it with the distributed lookup table:
distribute_transpiler.py:201-255, lookup_table_op.cc `is_distributed`).

Architecture (DeepFM): per-field sparse id embeddings feed BOTH a
factorization machine (first-order weights + pairwise second-order
interactions via the sum-square/square-sum identity) and a DNN over the
concatenated embeddings; logits add. Sparse gradients flow through the
lookup_table `is_sparse` path, and under the DistributeTranspiler the
same table splits across pservers with prefetch.
"""

import paddle_tpu as fluid
from paddle_tpu import layers


def deepfm(field_inputs, vocab_size, embed_dim=8, dnn_dims=(32, 32),
           is_sparse=True, is_distributed=False):
    """field_inputs: list of [B, 1] int64 Variables (one id per field).
    Returns (prob [B, 1], logit [B, 1])."""
    num_fields = len(field_inputs)

    # first-order term: a 1-wide embedding per id
    first = [layers.embedding(
        x, size=[vocab_size, 1], is_sparse=is_sparse,
        is_distributed=is_distributed,
        param_attr=fluid.ParamAttr(name="fm_first_w"))
        for x in field_inputs]
    y_first = layers.sums([layers.reshape(f, [-1, 1]) for f in first])

    # second-order term over shared k-dim embeddings:
    # 0.5 * sum_k[(sum_f v_fk)^2 - sum_f v_fk^2]
    embeds = [layers.embedding(
        x, size=[vocab_size, embed_dim], is_sparse=is_sparse,
        is_distributed=is_distributed,
        param_attr=fluid.ParamAttr(name="fm_second_w"))
        for x in field_inputs]
    embeds2d = [layers.reshape(e, [-1, embed_dim]) for e in embeds]
    sum_v = layers.sums(embeds2d)
    sum_sq = fluid.layers.elementwise_mul(sum_v, sum_v)
    sq_sum = layers.sums(
        [fluid.layers.elementwise_mul(e, e) for e in embeds2d])
    second = fluid.layers.scale(
        fluid.layers.elementwise_sub(sum_sq, sq_sum), scale=0.5)
    y_second = fluid.layers.reduce_sum(second, dim=[1], keep_dim=True)

    # deep component over the concatenated field embeddings
    deep = layers.concat(embeds2d, axis=1)      # [B, F*k]
    for width in dnn_dims:
        deep = layers.fc(deep, width, act="relu")
    y_deep = layers.fc(deep, 1)

    logit = fluid.layers.elementwise_add(
        fluid.layers.elementwise_add(y_first, y_second), y_deep)
    prob = fluid.layers.sigmoid(logit)
    return prob, logit


def build_train_net(num_fields=8, vocab_size=1000, embed_dim=8,
                    learning_rate=1e-2, is_sparse=True):
    """CTR training net: per-field ids + 0/1 click label -> log loss."""
    fields = [layers.data("field_%d" % i, [1], dtype="int64")
              for i in range(num_fields)]
    label = layers.data("click", [1])
    prob, logit = deepfm(fields, vocab_size, embed_dim,
                         is_sparse=is_sparse)
    loss = fluid.layers.mean(
        fluid.layers.sigmoid_cross_entropy_with_logits(logit, label))
    fluid.optimizer.Adam(learning_rate=learning_rate).minimize(loss)
    return fields, label, prob, loss


def zoo_spec():
    """(build_fn, feed_fn): DeepFM CTR Adam train step."""
    import numpy as np
    num_fields, vocab = 8, 1000

    def build():
        _, _, prob, loss = build_train_net(num_fields=num_fields,
                                           vocab_size=vocab)
        return loss, prob

    def feeds(rng):
        f = {"field_%d" % i: rng.randint(0, vocab, (8, 1))
             .astype(np.int64) for i in range(num_fields)}
        f["click"] = rng.randint(0, 2, (8, 1)).astype(np.float32)
        return f

    return build, feeds


def analysis_entry():
    """Static-analyzer entry: DeepFM CTR Adam train step (sparse
    embedding lookups + FM interactions)."""
    from .harness import program_entry
    return program_entry(*zoo_spec())

