"""Continuous-batching serving benchmark: Engine vs sequential decode.

The ISSUE-5 acceptance protocol, runnable anywhere (fast CPU mode is the
tier-1 smoke): a mixed-length request set (random prompts, 16-128 new
tokens) is decoded twice — once per-request sequentially (the jitted
single-token bs1 loop, PERF.md's measured serving shape) and once
through ``serving.Engine`` with ``--slots`` decode slots. Reports
aggregate tokens/s for both, the speedup, slot occupancy, the
request-level SLO percentiles (TTFT/TPOT p50/p95, queue_wait p95 —
from the Request handles' lifecycle attribution), and verifies the
engine output is TOKEN-IDENTICAL to the sequential baseline.
Prints one JSON line; ``main()`` returns the dict (bench.py stamps it).
"""

import json
import sys
import time

import numpy as np

from common import parse_args, get_place  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.models import transformer as T  # noqa: E402
from paddle_tpu.models.transformer_infer import TransformerLMInfer  # noqa: E402
from paddle_tpu import serving  # noqa: E402
from paddle_tpu.monitor import runtime as monrt  # noqa: E402
from paddle_tpu.monitor.recorder import percentile_sorted  # noqa: E402


def build_requests(rng, n, vocab, max_prompt, min_new, max_new):
    """Mixed-length workload: random prompt prefixes + new-token budgets."""
    reqs = []
    for _ in range(n):
        plen = int(rng.randint(1, max_prompt + 1))
        prompt = [1] + rng.randint(3, vocab, plen - 1).tolist()
        reqs.append((prompt, int(rng.randint(min_new, max_new + 1))))
    return reqs


def _prefix_share_ab(args, infer, eng):
    """Shared-system-prompt A/B (ISSUE 10): a request set that all
    opens with the same ``--prefix_share``-token system prompt,
    decoded through the PAGED+prefix engine (``eng``, cache warm after
    the first window) and a fresh PR-5 DENSE engine, in interleaved
    windows (round-5 protocol). Stamps tokens/s both arms, the
    prefill chunks each arm actually executed (the measured
    prefill-compute saving), the paged arm's prefix hit rate, and
    token identity of both arms against the sequential baseline."""
    import statistics
    rng = np.random.RandomState(args.seed + 1)
    n = max(8, min(args.requests, 16))
    tail_max = max(2, min(6, args.max_prompt))
    headroom = args.max_len - args.prefix_share - tail_max
    if headroom < 2:
        # reject the flag combination up front — letting it through
        # would abort the whole bench inside Engine.submit's max_len
        # bound mid-measurement
        raise SystemExit(
            "--prefix_share %d leaves no decode headroom at "
            "--max_len %d (need prefix + %d-token tail + >=2 new "
            "tokens)" % (args.prefix_share, args.max_len, tail_max))
    sysp = [1] + rng.randint(3, args.vocab,
                             args.prefix_share - 1).tolist()
    new_cap = min(args.max_new, headroom)
    new_min = min(args.min_new, new_cap)
    psreqs = []
    for _ in range(n):
        tail = rng.randint(
            3, args.vocab, int(rng.randint(1, tail_max + 1))).tolist()
        psreqs.append((sysp + tail,
                       int(rng.randint(new_min, new_cap + 1))))
    seq_ps = serving.sequential_generate(infer, psreqs)
    total = sum(len(t) for t, _ in seq_ps)
    dense = serving.Engine(infer, slots=args.slots,
                           prefill_chunk=args.prefill_chunk,
                           paged=False, name="engine-dense")

    def run_set(engine):
        t0 = time.perf_counter()
        handles = [engine.submit(p, m) for p, m in psreqs]
        res = [h.result() for h in handles]
        return time.perf_counter() - t0, res

    run_set(dense), run_set(eng)        # warm compiles + prefix cache
    h0, m0 = eng.stats["prefix_hits"], eng.stats["prefix_misses"]
    cp0, cd0 = eng.stats["prefill_chunks"], dense.stats["prefill_chunks"]
    wins = 1 if args.fast else 3
    da, dp, identical = [], [], True
    for _ in range(wins):               # interleaved A/B
        dt, res = run_set(dense)
        da.append(dt)
        identical = identical and all(
            st == rt for (st, _), (rt, _) in zip(seq_ps, res))
        dt, res = run_set(eng)
        dp.append(dt)
        identical = identical and all(
            st == rt for (st, _), (rt, _) in zip(seq_ps, res))
    dense_chunks = dense.stats["prefill_chunks"] - cd0
    paged_chunks = eng.stats["prefill_chunks"] - cp0
    hits = eng.stats["prefix_hits"] - h0
    miss = eng.stats["prefix_misses"] - m0
    dense.close()
    md, mp = statistics.median(da), statistics.median(dp)
    out = {
        "prefix_share": args.prefix_share,
        "prefix_requests": n,
        "prefix_windows": wins,
        "prefix_dense_tok_s": round(total * 1.0 / md, 1),
        "prefix_paged_tok_s": round(total * 1.0 / mp, 1),
        "prefix_speedup": round(md / mp, 2),
        "prefix_chunks_dense": dense_chunks,
        "prefix_chunks_paged": paged_chunks,
        "prefix_hit_rate": round(hits / (hits + miss), 3)
        if hits + miss else None,
        "prefix_identical": bool(identical),
    }
    print("prefix-share A/B (%d-token system prompt, %d reqs): paged "
          "%.0f vs dense %.0f tok/s (%.2fx), chunks %d vs %d, hit "
          "rate %s, identical=%s"
          % (args.prefix_share, n, total / mp, total / md, md / mp,
             paged_chunks, dense_chunks, out["prefix_hit_rate"],
             identical), file=sys.stderr)
    return out


def _speculative_ab(args, infer):
    """Speculative-decode A/B (ISSUE 13): the same request sets
    decoded through a speculative engine (γ drafts per slot verified
    in one scoring dispatch) and a plain engine, in interleaved
    windows, over the TWO regimes where acceptance rates diverge —
    a shared-prefix set (every request opens with the same system
    prompt; the radix cache's published chains feed the drafter) and
    a "natural-text" set (the mixed random prompts of the main
    protocol, where only each request's own chain drafts). Stamps
    tok/s both arms + speedup + accept rate + accepted tokens per
    scoring dispatch per set, token identity against the sequential
    baseline, and the bs1 dispatch-floor A/B the ISSUE acceptance
    gates (ONE long request — the shape PERF.md round 5 pinned at the
    dispatch floor and megastep attacked with K; speculation attacks
    it with >1 verified tokens per dispatch)."""
    import statistics
    g = args.speculative
    rng = np.random.RandomState(args.seed + 2)
    n = max(6, min(args.requests, 12))
    new_cap = min(args.max_new, 64)

    # natural-text regime: mixed random prompts (self-chain drafting
    # only). shared-prefix regime: one system prompt + short tails
    # (cross-request drafting through the prefix cache's chains)
    nat = build_requests(rng, n, args.vocab, args.max_prompt,
                         min(args.min_new, new_cap), new_cap)
    sysp = [1] + rng.randint(3, args.vocab, 23).tolist()
    shared = []
    for _ in range(n):
        tail = rng.randint(3, args.vocab,
                           int(rng.randint(1, 5))).tolist()
        shared.append((sysp + tail, new_cap))

    out = {"spec_gamma": g}
    wins = 1 if args.fast else 3
    for tag, reqs in (("natural", nat), ("shared", shared)):
        seq = serving.sequential_generate(infer, reqs)
        total = sum(len(t) for t, _ in seq)
        base = serving.Engine(infer, slots=args.slots,
                              prefill_chunk=args.prefill_chunk,
                              name="eng-base-" + tag).warmup()
        spec = serving.Engine(infer, slots=args.slots,
                              prefill_chunk=args.prefill_chunk,
                              speculative=True, spec_gamma=g,
                              name="eng-spec-" + tag).warmup()

        def run_set(engine):
            t0 = time.perf_counter()
            hs = [engine.submit(p, m) for p, m in reqs]
            res = [h.result() for h in hs]
            return time.perf_counter() - t0, res

        run_set(base), run_set(spec)    # warm compiles/prefix cache
        d0 = spec.stats["spec_dispatches"]
        e0 = spec.stats["spec_emitted"]
        dr0 = spec.stats["spec_drafted"]
        ac0 = spec.stats["spec_accepted"]
        ba, sa, identical = [], [], True
        for _ in range(wins):           # interleaved A/B
            dt, res = run_set(base)
            ba.append(dt)
            identical = identical and all(
                st == rt for (st, _), (rt, _) in zip(seq, res))
            dt, res = run_set(spec)
            sa.append(dt)
            identical = identical and all(
                st == rt for (st, _), (rt, _) in zip(seq, res))
        disp = spec.stats["spec_dispatches"] - d0
        emitted = spec.stats["spec_emitted"] - e0
        drafted = spec.stats["spec_drafted"] - dr0
        accepted = spec.stats["spec_accepted"] - ac0
        mb, ms = statistics.median(ba), statistics.median(sa)
        spread = (100.0 * (max(sa) - min(sa)) / ms) if ms else 0.0
        out["spec_%s_base_tok_s" % tag] = round(total / mb, 1)
        out["spec_%s_tok_s" % tag] = round(total / ms, 1)
        out["spec_%s_speedup" % tag] = round(mb / ms, 2)
        out["spec_%s_spread_pct" % tag] = round(spread, 1)
        out["spec_%s_accept_rate" % tag] = round(
            accepted / drafted, 3) if drafted else None
        out["spec_%s_tokens_per_dispatch" % tag] = round(
            emitted / disp, 2) if disp else None
        out["spec_identical"] = bool(
            out.get("spec_identical", True) and identical)
        print("spec A/B (%s, γ=%d): spec %.0f vs base %.0f tok/s "
              "(%.2fx), accept %s, %s tok/scoring-dispatch, "
              "identical=%s"
              % (tag, g, total / ms, total / mb, mb / ms,
                 out["spec_%s_accept_rate" % tag],
                 out["spec_%s_tokens_per_dispatch" % tag], identical),
              file=sys.stderr)
        base.close()
        spec.close()
    out.update(_spec_bs1_floor(args))
    return out


def _spec_bs1_floor(args):
    """The bs1 dispatch-floor probe (the ISSUE-13 acceptance figure):
    ONE request through a DISPATCH-BOUND model — 2L/2H/d32, the
    megastep-probe shape class, where per-step compute is small next
    to the per-dispatch tax (the regime PERF.md round 5 pinned at
    0.34 ms/token on chip, where speculative decode's economics live)
    — with a predictable (cyclic) continuation, the boilerplate/
    template regime prompt-lookup drafting targets. The plain engine
    pays one dispatch per token; the speculative engine pays one
    scoring dispatch per 1..γ+1 VERIFIED tokens. Stamps the verified
    tokens-per-dispatch multiplication (the figure a chip converts to
    wall time one-for-one at the dispatch floor) and the measured
    CPU wall A/B — honest caveat: on THIS container the γ+1-position
    scoring compute is NOT free (CPU compute scales with γ while the
    dispatch tax does not), so the wall ratio here understates the
    chip win exactly as the megastep mixed-set ~1x did (PERF.md
    round 6); the chip round gates the wall figure."""
    import statistics
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as T
    from paddle_tpu.models.transformer_infer import TransformerLMInfer
    from paddle_tpu.serving.spec import NgramDrafter

    g = args.speculative
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        T.transformer_lm(vocab_size=64, max_len=96, n_layer=2,
                         n_head=2, d_model=32, d_inner=64)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        lm = TransformerLMInfer(main, scope, 2, 2, 32, 96, end_id=64)

    # pick the most n-gram-predictable continuation from a few seeded
    # candidates: the probe measures the floor in drafting's FAVORABLE
    # regime (predictable text), with the regime A/B above carrying
    # the unfavorable one
    dr = NgramDrafter(max_n=3, min_n=3)
    rng = np.random.RandomState(args.seed)
    best, best_score = None, -1.0
    for _ in range(4 if args.fast else 12):
        p = [1] + rng.randint(3, 64,
                              int(rng.randint(3, 10))).tolist()
        [(toks, _)] = serving.sequential_generate(lm, [(p, 80)])
        chain, hit, tot, i = list(p), 0, 0, 0
        while i < len(toks):
            prop = dr.propose(chain, g)
            adv = 1
            if prop:
                k = 0
                while k < len(prop) and i + k < len(toks) \
                        and prop[k] == toks[i + k]:
                    k += 1
                hit += k
                tot += len(prop)
                adv = k + 1
            chain.extend(toks[i:i + adv])
            i += adv
        score = hit / max(1, tot)
        if score > best_score:
            best, best_score = p, score
    req = (best, 80)
    [(ref, _)] = serving.sequential_generate(lm, [req])

    base = serving.Engine(lm, slots=2, prefill_chunk=8,
                          name="bs1-base").warmup()
    spec = serving.Engine(lm, slots=2, prefill_chunk=8,
                          speculative=True, spec_gamma=g,
                          name="bs1-spec").warmup()
    spec._drafter = dr      # strongest-evidence drafting (min_n 3)

    def rnd(engine):
        t0 = time.perf_counter()
        toks, _ = engine.submit(*req).result()
        assert toks == ref, "bs1 probe diverged from baseline"
        return len(toks) / (time.perf_counter() - t0)

    rnd(base), rnd(spec)
    d0 = spec.stats["spec_dispatches"]
    e0 = spec.stats["spec_emitted"]
    t0 = spec.stats["tokens"]
    s0 = spec.stats["decode_steps"]
    a, b = [], []
    for _ in range(3 if args.fast else 7):
        a.append(rnd(base))
        b.append(rnd(spec))
    k1, ks = statistics.median(a), statistics.median(b)
    disp = spec.stats["spec_dispatches"] - d0
    emitted = spec.stats["spec_emitted"] - e0
    toks_all = spec.stats["tokens"] - t0
    steps_all = max(1, spec.stats["decode_steps"] - s0)
    out = {
        "spec_bs1_base_tok_s": round(k1, 1),
        "spec_bs1_tok_s": round(ks, 1),
        "spec_bs1_speedup": round(ks / k1, 2),
        "spec_bs1_spread_pct": round(
            100.0 * (max(b) - min(b)) / ks, 1) if ks else 0.0,
        # the SLO-visible figure: VERIFIED tokens per scoring
        # dispatch at the bs1 floor (per-slot by construction — one
        # request), plus the all-dispatch view (scoring + draftless
        # fallback steps) — the dispatch-count multiplication a chip
        # converts to wall time at the dispatch floor
        "accepted_tokens_per_dispatch": round(emitted / disp, 2)
        if disp else None,
        "spec_bs1_tokens_per_decode_dispatch": round(
            toks_all / steps_all, 2),
        "spec_bs1_predictability": round(best_score, 2),
    }
    print("spec bs1 floor (dispatch-bound shape): base %.0f vs spec "
          "%.0f tok/s (%.2fx wall on CPU), %s verified "
          "tok/scoring-dispatch, %.2f tok/decode-dispatch overall"
          % (k1, ks, ks / k1, out["accepted_tokens_per_dispatch"],
             toks_all / steps_all), file=sys.stderr)
    base.close()
    spec.close()
    return out


def _block_kernel_ab(args):
    """Block-kernel vs gather-path A/B (ISSUE 20): the paged decode
    step at a FIXED context (tokens actually held) across two pool
    capacities (max_len 4x apart). The gather path materializes the
    dense ``[.., max_len, ..]`` axis, so its step time grows with
    capacity at fixed context; the block kernel walks only the
    allocated chain, so its step time tracks tokens held. Measures
    the jitted ``_step_logits_paged`` directly (both arms share one
    dispatch shape — no engine-loop noise), interleaved rounds,
    medians. Stamps per-arm step ms at both capacities, the
    large-capacity speedup, and the capacity-scaling ratio
    (gather-growth / block-growth — the flatness figure the
    acceptance criterion gates, >1 = the block kernel is flatter).
    The int8-quantized arm is stamped separately at the large
    capacity."""
    import statistics
    import jax
    import jax.numpy as jnp

    bs = 16
    held = 48                           # tokens held, both capacities
    cap_small, cap_large = 64, 256
    slots = args.slots
    rng = np.random.RandomState(args.seed + 3)

    def build(cap):
        main, startup = fluid.Program(), fluid.Program()
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope):
            T.transformer_lm(vocab_size=args.vocab, max_len=cap,
                             n_layer=args.n_layer, n_head=args.n_head,
                             d_model=args.d_model,
                             d_inner=args.d_model * 4)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            return TransformerLMInfer(
                main, scope, args.n_layer, args.n_head, args.d_model,
                cap, end_id=args.vocab)

    def arm(infer, cap, block_kernel, kv_quant=None):
        """One jitted step closure at one capacity: every slot holds
        ``held`` tokens of KV in its own block chain. The state is
        DONATED and threaded exactly like the engine's step — without
        donation XLA copies the whole pool every call and the copy
        (proportional to capacity) drowns the attention delta the
        probe exists to measure."""
        nbs = cap // bs
        state = [infer._init_paged_state(slots * nbs, bs,
                                         kv_quant=kv_quant)]
        btab = jnp.arange(slots * nbs,
                          dtype=jnp.int32).reshape(slots, nbs)
        pos = jnp.full((slots,), held, jnp.int32)
        tok = jnp.asarray(rng.randint(3, args.vocab, slots),
                          jnp.int32)
        fn = jax.jit(lambda t, s, p, b: infer._step_logits_paged(
            t, s, p, b, block_kernel=block_kernel),
            donate_argnums=(1,))

        def step():
            logits, state[0] = fn(tok, state[0], pos, btab)
            logits.block_until_ready()
        step()                          # compile outside the clock
        return step

    inf_s, inf_l = build(cap_small), build(cap_large)
    arms = {
        "gather_small": arm(inf_s, cap_small, False),
        "block_small": arm(inf_s, cap_small, True),
        "gather_large": arm(inf_l, cap_large, False),
        "block_large": arm(inf_l, cap_large, True),
        "quant_large": arm(inf_l, cap_large, True, kv_quant="int8"),
    }
    reps, rounds = (6, 3) if args.fast else (10, 5)
    times = {k: [] for k in arms}
    for _ in range(rounds):             # interleaved A/B rounds
        for name, step in arms.items():
            t0 = time.perf_counter()
            for _ in range(reps):
                step()
            times[name].append((time.perf_counter() - t0) / reps)
    ms = {k: 1000.0 * statistics.median(v) for k, v in times.items()}
    bl = times["block_large"]
    spread = (100.0 * (max(bl) - min(bl)) * 1000.0
              / ms["block_large"]) if ms["block_large"] else 0.0
    gather_growth = ms["gather_large"] / ms["gather_small"]
    block_growth = ms["block_large"] / ms["block_small"]
    out = {
        "block_probe_tokens_held": held,
        "block_probe_capacities": [cap_small, cap_large],
        "block_step_ms_small": round(ms["block_small"], 3),
        "block_step_ms_large": round(ms["block_large"], 3),
        "gather_step_ms_small": round(ms["gather_small"], 3),
        "gather_step_ms_large": round(ms["gather_large"], 3),
        "block_quant_step_ms_large": round(ms["quant_large"], 3),
        "block_kernel_speedup": round(
            ms["gather_large"] / ms["block_large"], 2),
        "block_kernel_quant_speedup": round(
            ms["gather_large"] / ms["quant_large"], 2),
        # flatness: how much faster the gather arm grows with
        # capacity than the block arm does (>1 = block is flatter)
        "block_kernel_scale_ratio": round(
            gather_growth / block_growth, 2),
        "block_kernel_spread_pct": round(spread, 1),
    }
    print("block-kernel A/B (%d tokens held, capacity %d->%d): "
          "block %.2f->%.2f ms vs gather %.2f->%.2f ms "
          "(%.2fx at large, scale ratio %.2f, quant %.2f ms)"
          % (held, cap_small, cap_large, ms["block_small"],
             ms["block_large"], ms["gather_small"],
             ms["gather_large"], out["block_kernel_speedup"],
             out["block_kernel_scale_ratio"], ms["quant_large"]),
          file=sys.stderr)
    return out


def main():
    args = parse_args(
        "serving_bench", batch_size=0, iterations=1, skip=0,
        extra=lambda p: (
            p.add_argument("--slots", type=int, default=4),
            p.add_argument("--n_layer", type=int, default=2),
            p.add_argument("--n_head", type=int, default=4),
            p.add_argument("--d_model", type=int, default=128),
            p.add_argument("--vocab", type=int, default=512),
            p.add_argument("--max_len", type=int, default=160),
            p.add_argument("--requests", type=int, default=12),
            p.add_argument("--max_prompt", type=int, default=16),
            p.add_argument("--min_new", type=int, default=16),
            p.add_argument("--max_new", type=int, default=128),
            p.add_argument("--prefill_chunk", type=int, default=8),
            p.add_argument("--seed", type=int, default=7),
            p.add_argument("--megastep", type=int, default=0,
                           help="also measure a fused-K megastep "
                                "engine pass (ISSUE 7): K decode "
                                "iterations per dispatch, stamped as "
                                "megastep_* fields (0 = skip)"),
            p.add_argument("--prefix_share", type=int, default=0,
                           help="also measure a shared-system-prompt "
                                "A/B (ISSUE 10): every request opens "
                                "with the same N-token prefix; "
                                "interleaved windows of the paged+"
                                "prefix engine vs the PR-5 dense "
                                "layout, stamped as prefix_* fields "
                                "(0 = skip)"),
            p.add_argument("--speculative", type=int, default=0,
                           help="also measure a speculative-decode "
                                "A/B (ISSUE 13) with this draft "
                                "length γ: spec vs plain engine on a "
                                "shared-prefix AND a natural-text "
                                "set + the bs1 dispatch-floor probe, "
                                "stamped as spec_* fields (0 = "
                                "skip)"),
            p.add_argument("--block_probe", action="store_true",
                           help="also measure the block-kernel vs "
                                "gather-path A/B (ISSUE 20): paged "
                                "decode step time at fixed tokens "
                                "held across two pool capacities, "
                                "stamped as block_* fields (the "
                                "quantized arm separately)"),
            p.add_argument("--fast", action="store_true",
                           help="tier-1 CPU smoke: smaller request set")))
    import jax

    restore_dev = None
    if args.device == "CPU":
        # the engine loop runs on a background thread, so a scoped
        # jax.default_device() (thread-local) cannot pin it — set the
        # process default and restore after
        restore_dev = jax.config.jax_default_device
        jax.config.update("jax_default_device", jax.devices("cpu")[0])
    try:
        return _run_bench(args)
    finally:
        if args.device == "CPU":
            jax.config.update("jax_default_device", restore_dev)


def _run_bench(args):
    if args.fast:
        args.requests = min(args.requests, 10)
        args.max_new = min(args.max_new, 96)
    T.transformer_lm(
        vocab_size=args.vocab, max_len=args.max_len,
        n_layer=args.n_layer, n_head=args.n_head, d_model=args.d_model,
        d_inner=args.d_model * 4)
    exe = fluid.Executor(get_place(args))
    exe.run(fluid.default_startup_program())
    # end_id past the vocab: the randomly-initialized model would
    # otherwise greedy-emit EOS within a few tokens, collapsing the
    # mixed 16-128-token budgets this protocol is about. Slots still
    # retire at max_new, so admission/retirement churn stays real.
    infer = TransformerLMInfer(
        fluid.default_main_program(), fluid.global_scope(),
        args.n_layer, args.n_head, args.d_model, args.max_len,
        end_id=args.vocab)

    rng = np.random.RandomState(args.seed)
    reqs = build_requests(rng, args.requests, args.vocab,
                          args.max_prompt, args.min_new, args.max_new)

    # warm both compiled paths before timing
    warm = [([1, 4, 5], 4)]
    serving.sequential_generate(infer, warm)
    eng = serving.Engine(infer, slots=args.slots,
                         prefill_chunk=args.prefill_chunk)
    eng.generate_many([p for p, _ in warm], [m for _, m in warm])
    for k in eng.stats:
        eng.stats[k] = 0

    t0 = time.perf_counter()
    seq_out = serving.sequential_generate(infer, reqs)
    seq_dt = time.perf_counter() - t0
    total = sum(len(t) for t, _ in seq_out)

    t0 = time.perf_counter()
    # submit + drain by hand (not generate_many): the Request handles
    # carry the lifecycle attribution the SLO stamp below reads
    handles = [eng.submit(p, m) for p, m in reqs]
    eng_out = [h.result() for h in handles]
    eng_dt = time.perf_counter() - t0
    occupancy = eng.occupancy()

    identical = all(st == et for (st, _), (et, _) in zip(seq_out, eng_out))
    seq_tps = total / seq_dt
    eng_tps = total / eng_dt
    out = {
        "metric": "serving_engine_tokens_per_sec",
        "value": round(eng_tps, 1),
        "sequential_tokens_per_sec": round(seq_tps, 1),
        "speedup": round(eng_tps / seq_tps, 2),
        "identical": bool(identical),
        "slots": args.slots,
        "occupancy": round(occupancy, 3),
        "requests": len(reqs),
        "tokens": total,
        # monitor gauges the engine exported during the run
        "slot_occupancy_gauge": monrt.SERVING_SLOT_OCCUPANCY.value(),
        "served_tokens_total": monrt.SERVING_TOKENS.value(),
    }

    def _pct_ms(vals, q):
        vals = sorted(v for v in vals if v is not None)
        v = percentile_sorted(vals, q)
        return None if v is None else round(1000.0 * v, 3)

    if args.megastep > 1:
        # fused-K pass (ISSUE 7): same request set through an engine
        # that scans K decode iterations per dispatch when idle of
        # admissions/prefills — token identity verified against the
        # same sequential baseline, throughput stamped alongside.
        # warmup() compiles BOTH dispatch paths up front: a K>1 engine
        # otherwise meets the single-step path for the first time on a
        # mid-flight admission and eats an XLA compile mid-measurement
        eng2 = serving.Engine(infer, slots=args.slots,
                              prefill_chunk=args.prefill_chunk,
                              megastep=args.megastep,
                              name="engine-mega").warmup()
        eng2.generate_many([p for p, _ in warm], [m for _, m in warm])
        t0 = time.perf_counter()
        h2 = [eng2.submit(p, m) for p, m in reqs]
        mega_out = [h.result() for h in h2]
        mega_dt = time.perf_counter() - t0
        mega_tps = total / mega_dt
        out["megastep_k"] = args.megastep
        out["megastep_tokens_per_sec"] = round(mega_tps, 1)
        out["megastep_vs_engine"] = round(mega_tps / eng_tps, 2)
        out["megastep_identical"] = bool(all(
            st == et for (st, _), (et, _) in zip(seq_out, mega_out)))
        out["megastep_dispatches"] = eng2.stats["megastep_dispatches"]
        print("serving megastep K=%d: %.0f tok/s (%.2fx engine, "
              "identical=%s, %d fused dispatches)"
              % (args.megastep, mega_tps, mega_tps / eng_tps,
                 out["megastep_identical"],
                 out["megastep_dispatches"]), file=sys.stderr)
        # bs1 dispatch-floor probe — the shape PERF.md round 5 pinned
        # at 0.34 ms/token: ONE long request, so after prefill every
        # iteration is pure decode. The K=1 engine pays one host
        # dispatch per token; the fused engine pays one per K tokens.
        # Interleaved A/B medians over 5 rounds.
        import statistics
        bs1_new = min(args.max_new, infer.max_len - 4)
        bs1 = ([1, 4, 5], bs1_new)

        def bs1_round(engine):
            t0 = time.perf_counter()
            toks, _ = engine.submit(*bs1).result()
            return len(toks) / (time.perf_counter() - t0)

        bs1_round(eng), bs1_round(eng2)        # warm prefill shapes
        a, b = [], []
        for _ in range(5):
            a.append(bs1_round(eng))
            b.append(bs1_round(eng2))
        k1, k8 = statistics.median(a), statistics.median(b)
        out["megastep_bs1_k1_tok_s"] = round(k1, 1)
        out["megastep_bs1_tok_s"] = round(k8, 1)
        out["megastep_bs1_speedup"] = round(k8 / k1, 2)
        print("serving megastep bs1 floor: K=1 %.0f vs K=%d %.0f "
              "tok/s (%.2fx)" % (k1, args.megastep, k8, k8 / k1),
              file=sys.stderr)
        eng2.close()

    if args.prefix_share > 0 and eng._paged:
        out.update(_prefix_share_ab(args, infer, eng))

    if args.speculative > 0 and eng._paged:
        out.update(_speculative_ab(args, infer))

    if args.block_probe and eng._paged:
        out.update(_block_kernel_ab(args))

    if eng._paged:
        # pool stats of the main pass (the paged engine's whole run)
        out["kv_pool_blocks"] = eng._pool.num_blocks
        out["kv_peak_blocks"] = eng.stats["kv_peak_blocks"]
        out["kv_peak_occupancy"] = round(
            eng.stats["kv_peak_blocks"] / eng._pool.num_blocks, 3)
        out["preemptions"] = eng.stats["preemptions"]
    eng.close()

    ttft = [h.ttft for h in handles]
    tpot = [h.tpot for h in handles]
    qw = [h.queue_wait for h in handles]
    # the request-level SLO figures (ISSUE 6): what a latency gate
    # would bound on this host class
    out["ttft_p50_ms"] = _pct_ms(ttft, 0.50)
    out["ttft_p95_ms"] = _pct_ms(ttft, 0.95)
    out["tpot_p50_ms"] = _pct_ms(tpot, 0.50)
    out["tpot_p95_ms"] = _pct_ms(tpot, 0.95)
    out["queue_wait_p95_ms"] = _pct_ms(qw, 0.95)
    # progress line on stderr; the stdout JSON stays the __main__ CLI's
    # (bench.py embeds the dict in ITS one JSON line instead)
    print("serving: engine %.0f tok/s vs sequential %.0f (%.2fx, "
          "occupancy %.2f, identical=%s)"
          % (eng_tps, seq_tps, eng_tps / seq_tps, occupancy, identical),
          file=sys.stderr)
    return out


if __name__ == "__main__":
    print(json.dumps(main()))
