"""paddle_tpu.serving.sparse — sharded-embedding recsys serving
(ISSUE 12, ROADMAP direction 3).

The inference composition over live pservers: the distributed lookup
table (row-sharded embeddings + server-side lazy sparse optimizers,
trained since the seed) finally SERVED —

  * ``cache``   — ``HotIDCache`` (per-process LRU, bounded staleness,
    version/incarnation invalidation) + ``SparseClient`` (batched,
    deduplicated PRFT prefetch against the shards, retry policy +
    membership resolver-following, measured miss-path cost),
  * ``scoring`` — ``ScoringEngine``: the serving Engine's
    iteration-level scheduling generalized to heterogeneous feature
    batches; ONE compiled fixed-shape scoring dispatch per iteration,
    request latency flowing into the existing TTFT-analogue
    histograms / SLO specs / flight recorder / trace spans; the PR-8
    fleet Router serves it unchanged (scores ride the decode result
    wire),
  * ``online``  — ``OnlineTrainer`` (sparse grad pushes landing while
    serving reads, exactly-once round tags) + ``measure_staleness``
    (the read-your-writes probe behind the SLO ``staleness_s``
    objective).

See README "Recsys serving" for the topology and the staleness
contract.
"""

from .cache import HotIDCache, SparseClient
from .online import OnlineTrainer, measure_staleness
from .scoring import ScoringEngine, ScoringRequest

__all__ = ["HotIDCache", "SparseClient", "ScoringEngine",
           "ScoringRequest", "OnlineTrainer", "measure_staleness"]
