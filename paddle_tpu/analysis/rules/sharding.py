"""R003 sharding / transfer audit.

Walks shard_map and collective eqns (the lowering targets of
paddle_tpu/parallel/: psum from the Megatron tp hints, all_gather from
c_allgather, all_to_all from the MoE dispatch) and flags the patterns
that silently eat ICI/HBM bandwidth: large fully-replicated operands
entering a shard_map, implicit all-gathers, and host<->device transfers
inside the step.
"""

from ..diagnostics import Diagnostic, WARNING, INFO
from ..engine import Rule, register_rule, aval_nbytes
from ..cost import fmt_bytes

_COLLECTIVES = {"psum", "all_gather", "all_to_all", "ppermute",
                "psum_scatter", "pmax", "pmin", "all_gather_invariant"}


def _axis_names(eqn):
    ax = eqn.params.get("axes") or eqn.params.get("axis_name") or ()
    if isinstance(ax, (tuple, list)):
        return ",".join(str(x) for x in ax)
    return str(ax)


@register_rule
class ShardingTransferRule(Rule):
    name = "sharding-transfer"
    id = "R003"
    doc = ("replicated large shard_map operands, implicit all-gathers, "
           "host<->device transfers, collective roll-up")

    def __init__(self, replicated_min_bytes=1 << 20,
                 gather_warn_bytes=1 << 20):
        self.replicated_min_bytes = replicated_min_bytes
        self.gather_warn_bytes = gather_warn_bytes

    def check(self, a):
        n_coll = 0
        coll_bytes = 0.0
        for view, eqn in a.iter_eqns():
            prim = eqn.primitive.name
            if prim == "device_put":
                src = eqn.invars[0] if eqn.invars else None
                # placement of a trace-time constant (assign_value /
                # prior tables) happens once at compile, not per step
                if src is None or not hasattr(src, "aval") \
                        or src in view.jaxpr.constvars:
                    continue
                yield Diagnostic(
                    self.name, WARNING,
                    "device_put inside the traced step — a host<->"
                    "device transfer (or forced placement) on the hot "
                    "path",
                    path=view.eqn_path(eqn),
                    hint="move placement outside the step; let the "
                         "executor's donated state carry buffers")
                continue
            if prim == "shard_map":
                in_names = eqn.params.get("in_names") or ()
                for var, names in zip(eqn.invars, in_names):
                    aval = getattr(var, "aval", None)
                    if aval is None:
                        continue
                    nb = aval_nbytes(aval)
                    if not names and nb >= self.replicated_min_bytes:
                        yield Diagnostic(
                            self.name, WARNING,
                            "fully-replicated operand (%s, %s) enters "
                            "shard_map over mesh %s — every device "
                            "holds a full copy"
                            % (list(aval.shape), fmt_bytes(nb),
                               getattr(eqn.params.get("mesh"),
                                       "shape", "?")),
                            path=view.eqn_path(eqn),
                            hint="shard the param dim over a mesh "
                                 "axis (parallel.shard hint) or mark "
                                 "it intentionally replicated")
                continue
            if prim in _COLLECTIVES:
                n_coll += 1
                out_nb = sum(aval_nbytes(v.aval) for v in eqn.outvars
                             if hasattr(v, "aval")) * view.weight
                coll_bytes += out_nb
                if prim == "all_gather":
                    sev = WARNING if out_nb >= self.gather_warn_bytes \
                        else INFO
                    yield Diagnostic(
                        self.name, sev,
                        "all_gather over axis %s materializes %s per "
                        "device" % (_axis_names(eqn),
                                    fmt_bytes(out_nb)),
                        path=view.eqn_path(eqn),
                        hint="prefer keeping the value sharded "
                             "(psum_scatter / ring schedules) if the "
                             "consumer can work on shards")
                elif prim in ("all_to_all", "ppermute"):
                    yield Diagnostic(
                        self.name, INFO,
                        "%s over axis %s moves %s"
                        % (prim, _axis_names(eqn), fmt_bytes(out_nb)),
                        path=view.eqn_path(eqn))
        if n_coll:
            yield Diagnostic(
                self.name, INFO,
                "collective roll-up: %d collective eqn(s), ~%s of "
                "outputs crossing the mesh per step"
                % (n_coll, fmt_bytes(coll_bytes)))
