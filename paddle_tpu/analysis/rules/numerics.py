"""R004 numerical-risk lint.

Pattern checks for the classic TPU-training footguns: log/div/rsqrt
reached by values that can hit zero with no epsilon/clamp guard, and
softmax/logsumexp built without max-subtraction (exp overflow). The
"guard" whitelist mirrors the idioms the shipped ops actually use —
log(clip(x, eps)) in cross_entropy, log(p + eps) in sigmoid CE,
rsqrt(var + eps) in layer/batch norm, and the jax.nn softmax chain
(sub of a stop_gradient'ed reduce_max before exp).
"""

from ..diagnostics import Diagnostic, WARNING
from ..engine import Rule, register_rule, Literal

# producers that bound their output away from the singular point.
# NOT sqrt/abs: they preserve zero, so dividing by them is as risky as
# dividing by their operand.
_GUARDS = {"add", "max", "clamp", "log1p", "xlogy", "exp", "logistic",
           "integer_pow", "rsqrt",
           # select(cond, fallback, x) IS the guard idiom (masked
           # softmax denominators, where-protected divisions)
           "select_n"}


def _is_shifted_exp_sum(a, view, var):
    """True if ``var`` is reduce_sum(exp(x - max(x))) — the logsumexp /
    softmax normalizer, which is >= 1 by construction."""
    view, eqn = a.resolve_producer(view, var)
    if eqn is None or eqn.primitive.name != "reduce_sum":
        return False
    view2, exp_eqn = a.resolve_producer(view, eqn.invars[0])
    if exp_eqn is None or exp_eqn.primitive.name != "exp":
        return False
    _, sub_eqn = a.resolve_producer(view2, exp_eqn.invars[0])
    return sub_eqn is not None and sub_eqn.primitive.name == "sub"


def _guarded(a, view, var, _depth=0):
    """Heuristic: the value's real producer bounds it away from 0/inf
    (x + eps, max(x, c), clamp, exp, select-fallbacks ...), or it is a
    literal/const/plain input (assumed owned by the caller)."""
    if isinstance(var, Literal):
        return True
    rview, eqn = a.resolve_producer(view, var)
    if eqn is None:
        return True     # program input or constant — caller's contract
    prim = eqn.primitive.name
    if prim in _GUARDS:
        return True
    if prim in ("sqrt", "abs") and _depth < 8:
        # zero-preserving: sqrt(x)/|x| is safe exactly when x is —
        # sqrt(var + eps) (the batch_norm denominator) passes, a bare
        # sqrt(var) does not
        return _guarded(a, rview, eqn.invars[0], _depth + 1)
    if prim == "sub" and isinstance(eqn.invars[0], Literal):
        # c - x with a literal c: the Adam/LAMB bias-correction shape
        # (1 - beta^t), bounded away from 0 for every real step count
        return True
    return _is_shifted_exp_sum(a, view, var)


@register_rule
class NumericalRiskRule(Rule):
    name = "numerical-risk"
    id = "R004"
    doc = ("log/div/rsqrt without epsilon or clamp guards; softmax/"
           "logsumexp built without max-subtraction")

    def check(self, a):
        for view, eqn in a.iter_eqns():
            prim = eqn.primitive.name
            if prim == "log":
                if not _guarded(a, view, eqn.invars[0]):
                    yield Diagnostic(
                        self.name, WARNING,
                        "log of an unguarded computed value — "
                        "log(0) = -inf poisons the loss and every "
                        "gradient behind it",
                        path=view.eqn_path(eqn),
                        hint="log(clip(x, eps)) or log(x + eps) "
                             "(ops/loss.py idiom)")
            elif prim == "div":
                if not _guarded(a, view, eqn.invars[1]):
                    yield Diagnostic(
                        self.name, WARNING,
                        "division by an unguarded computed value — "
                        "a zero denominator (empty mask, dead batch) "
                        "yields inf/nan",
                        path=view.eqn_path(eqn),
                        hint="divide by maximum(x, eps) or add eps")
            elif prim == "rsqrt":
                if not _guarded(a, view, eqn.invars[0]):
                    yield Diagnostic(
                        self.name, WARNING,
                        "rsqrt of an unguarded computed value — "
                        "rsqrt(0) = inf (variance of a constant "
                        "feature does this)",
                        path=view.eqn_path(eqn),
                        hint="rsqrt(var + eps), the layer_norm idiom")
            elif prim == "exp":
                # exp feeding a sum (softmax/logsumexp normalizer)
                # must be max-shifted or large logits overflow
                users = view.consumers.get(eqn.outvars[0], [])
                if not any(u.primitive.name == "reduce_sum"
                           for u in users):
                    continue
                _sv, shift = a.resolve_producer(view, eqn.invars[0])
                if shift is None or shift.primitive.name != "sub":
                    yield Diagnostic(
                        self.name, WARNING,
                        "softmax/logsumexp normalizer without max-"
                        "subtraction — exp of raw scores overflows "
                        "past ~88 (f32) / ~127 (bf16 exponent ok but "
                        "f32 sum still saturates)",
                        path=view.eqn_path(eqn),
                        hint="subtract stop_gradient(max(x)) before "
                             "exp (jax.nn.softmax does this)")
