"""Convolution / pooling layer functions.

Reference parity: python/paddle/fluid/layers/nn.py conv2d/conv3d/pool2d/
pool3d/conv2d_transpose/conv3d_transpose/roi_pool/row_conv/spp/im2sequence.
"""

import numpy as np

from .layer_helper import LayerHelper
from ..initializer import Normal


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return [int(x) for x in v]
    return [int(v)] * n


def _conv_out_dim(size, k, p, s, d=1):
    if size is None or int(size) < 0:
        return -1
    return (int(size) + 2 * p - (d * (k - 1) + 1)) // s + 1


def _conv_nd_layer(nd, op_type, input, num_filters, filter_size, stride,
                   padding, dilation, groups, param_attr, bias_attr, act,
                   name):
    helper = LayerHelper(op_type, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    groups = groups or 1
    num_channels = input.shape[1]
    filter_size = _pair(filter_size, nd)
    stride = _pair(stride, nd)
    padding = _pair(padding, nd)
    dilation = _pair(dilation, nd)
    filter_shape = [num_filters, num_channels // groups] + filter_size

    fan_in = (num_channels // groups) * int(np.prod(filter_size))
    std = (2.0 / fan_in) ** 0.5
    filter_param = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=Normal(0.0, std))

    out_shape = (input.shape[0], num_filters) + tuple(
        _conv_out_dim(input.shape[2 + i], filter_size[i], padding[i],
                      stride[i], dilation[i]) for i in range(nd))
    pre_bias = helper.create_variable_for_type_inference(dtype,
                                                         shape=out_shape)
    helper.append_op(
        type=op_type,
        inputs={"Input": [input], "Filter": [filter_param]},
        outputs={"Output": [pre_bias]},
        attrs={"strides": stride, "paddings": padding, "dilations": dilation,
               "groups": groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None):
    return _conv_nd_layer(2, "conv2d", input, num_filters, filter_size,
                          stride, padding, dilation, groups, param_attr,
                          bias_attr, act, name)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None):
    return _conv_nd_layer(3, "conv3d", input, num_filters, filter_size,
                          stride, padding, dilation, groups, param_attr,
                          bias_attr, act, name)


def _conv_transpose(nd, op_type, input, num_filters, output_size=None,
                    filter_size=None, padding=0, stride=1, dilation=1,
                    groups=None, param_attr=None, bias_attr=None,
                    use_cudnn=True, act=None, name=None):
    helper = LayerHelper(op_type, param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    dtype = input.dtype
    groups = groups or 1
    num_channels = input.shape[1]
    padding = _pair(padding, nd)
    stride = _pair(stride, nd)
    dilation = _pair(dilation, nd)
    if filter_size is None:
        if output_size is None:
            raise ValueError("output_size must be set when filter_size is None")
        output_size = _pair(output_size, nd)
        filter_size = [
            (output_size[i] - (input.shape[2 + i] - 1) * stride[i]
             + 2 * padding[i] - 1) // dilation[i] + 1 for i in range(nd)]
    else:
        filter_size = _pair(filter_size, nd)
    filter_shape = [num_channels, num_filters // groups] + filter_size
    img_filter = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype)
    if output_size is not None:
        out_sp = tuple(_pair(output_size, nd))
    else:
        out_sp = tuple(
            -1 if input.shape[2 + i] in (None, -1) else
            (input.shape[2 + i] - 1) * stride[i] - 2 * padding[i]
            + dilation[i] * (filter_size[i] - 1) + 1 for i in range(nd))
    pre_bias = helper.create_variable_for_type_inference(
        dtype, shape=(input.shape[0], num_filters) + out_sp)
    attrs = {"strides": stride, "paddings": padding, "dilations": dilation,
             "groups": groups}
    if output_size is not None:
        attrs["output_size"] = _pair(output_size, nd)
    helper.append_op(
        type=op_type,
        inputs={"Input": [input], "Filter": [img_filter]},
        outputs={"Output": [pre_bias]}, attrs=attrs)
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    return _conv_transpose(2, "conv2d_transpose", input, num_filters,
                           output_size, filter_size, padding, stride,
                           dilation, groups, param_attr, bias_attr,
                           use_cudnn, act, name)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    return _conv_transpose(3, "conv3d_transpose", input, num_filters,
                           output_size, filter_size, padding, stride,
                           dilation, groups, param_attr, bias_attr,
                           use_cudnn, act, name)


def _pool(nd, op_type, input, pool_size, pool_type, pool_stride, pool_padding,
          global_pooling, use_cudnn, ceil_mode, name, exclusive=True):
    if pool_type not in ("max", "avg"):
        raise ValueError("pool_type must be 'max' or 'avg', got %r" % pool_type)
    helper = LayerHelper(op_type, name=name)
    pool_size = _pair(pool_size, nd)
    pool_stride = _pair(pool_stride or pool_size, nd)
    pool_padding = _pair(pool_padding, nd)

    def odim(i):
        s = input.shape[2 + i]
        if s in (None, -1):
            return -1
        if global_pooling:
            return 1
        span = s + 2 * pool_padding[i] - pool_size[i]
        if ceil_mode:
            return -(-span // pool_stride[i]) + 1
        return span // pool_stride[i] + 1

    out_shape = (input.shape[0], input.shape[1]) + tuple(
        odim(i) for i in range(nd))
    out = helper.create_variable_for_type_inference(input.dtype,
                                                    shape=out_shape)
    helper.append_op(
        type=op_type, inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": pool_size,
               "global_pooling": global_pooling, "strides": pool_stride,
               "paddings": pool_padding, "ceil_mode": ceil_mode,
               "exclusive": exclusive})
    return out


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True):
    if pool_size == -1:
        global_pooling = True
        pool_size = 1
    return _pool(2, "pool2d", input, pool_size, pool_type, pool_stride,
                 pool_padding, global_pooling, use_cudnn, ceil_mode, name,
                 exclusive)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True):
    if pool_size == -1:
        global_pooling = True
        pool_size = 1
    return _pool(3, "pool3d", input, pool_size, pool_type, pool_stride,
                 pool_padding, global_pooling, use_cudnn, ceil_mode, name,
                 exclusive)


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0):
    helper = LayerHelper("roi_pool")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="roi_pool", inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out]},
        attrs={"pooled_height": pooled_height, "pooled_width": pooled_width,
               "spatial_scale": spatial_scale})
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper("row_conv", param_attr=param_attr, act=act)
    filter_shape = [future_context_size + 1, input.shape[-1]]
    filter_param = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype,
                                                    shape=input.shape)
    helper.append_op(
        type="row_conv", inputs={"X": [input], "Filter": [filter_param]},
        outputs={"Out": [out]})
    return helper.append_activation(out)


def spp(input, pyramid_height=1, pool_type="max", name=None):
    helper = LayerHelper("spp", name=name)
    c = input.shape[1]
    width = c * sum(4 ** lvl for lvl in range(pyramid_height))
    out = helper.create_variable_for_type_inference(
        input.dtype, shape=(input.shape[0], width))
    helper.append_op(
        type="spp", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pyramid_height": pyramid_height, "pooling_type": pool_type})
    return out
