"""v2 SGD trainer (python/paddle/v2/trainer.py:37,137 parity): combines a
cost layer, a Parameters dict and an optimizer into the classic
`trainer.train(reader, num_passes, event_handler)` event loop over the
fluid executor."""

import numpy as np

from ..core.executor import Executor
from ..core.places import CPUPlace
from ..core.scope import scope_guard
from ..data_feeder import DataFeeder
from . import event as v2_event
from .optimizer import Optimizer as V2Optimizer
from .parameters import Parameters


def default_event_handler(event):
    pass


class SGD:
    """v2 trainer. `cost` is a fluid Variable (built via paddle.v2.layer or
    fluid.layers), `parameters` a v2 Parameters, `update_equation` a v2
    optimizer."""

    def __init__(self, cost, parameters, update_equation, extra_layers=None,
                 is_local=True, place=None):
        if not isinstance(parameters, Parameters):
            raise TypeError("parameters should be a paddle.v2 Parameters")
        if not isinstance(update_equation, V2Optimizer):
            raise TypeError("update equation parameter must be a "
                            "paddle.v2.optimizer.Optimizer")
        self.__cost__ = cost
        self.__parameters__ = parameters
        self.__program__ = cost.block.program
        # clone BEFORE optimizer ops are appended: the test-time program
        # computes the cost without updating parameters
        self.__test_program__ = self.__program__.clone()
        from ..core.program import program_guard, default_startup_program
        with program_guard(self.__program__):
            update_equation._make().minimize(cost)
        self.__startup__ = default_startup_program()
        self.__exe__ = Executor(place or CPUPlace())
        self.__started__ = False
        # feed order = data layers in creation order (v2 feeding maps
        # reader columns onto these names)
        self.__data_vars__ = [
            v for v in self.__program__.global_block().vars.values()
            if getattr(v, "is_data", False)]

    # ------------------------------------------------------------------
    def __ensure_startup__(self):
        if not self.__started__:
            with scope_guard(self.__parameters__._scope):
                self.__exe__.run(self.__startup__)
            self.__started__ = True

    def __feeder__(self, feeding):
        data_vars = self.__data_vars__
        if feeding:
            order = sorted(feeding, key=lambda n: feeding[n])
            by_name = {v.name: v for v in data_vars}
            data_vars = [by_name[n] for n in order]
        return DataFeeder(data_vars, self.__exe__.place,
                          program=self.__program__)

    def train(self, reader, num_passes=1, event_handler=None, feeding=None):
        """The reference event loop (v2/trainer.py:137): BeginPass →
        (BeginIteration → step → EndIteration)* → EndPass per pass."""
        event_handler = event_handler or default_event_handler
        self.__ensure_startup__()
        feeder = self.__feeder__(feeding)
        for pass_id in range(num_passes):
            event_handler(v2_event.BeginPass(pass_id))
            costs = []
            for batch_id, data_batch in enumerate(reader()):
                event_handler(v2_event.BeginIteration(pass_id, batch_id))
                feed = feeder.feed(data_batch)
                with scope_guard(self.__parameters__._scope):
                    cost_v, = self.__exe__.run(
                        self.__program__, feed=feed,
                        fetch_list=[self.__cost__])
                cost_v = float(np.asarray(cost_v).ravel()[0])
                costs.append(cost_v)
                event_handler(v2_event.EndIteration(
                    pass_id, batch_id, cost_v))
            event_handler(v2_event.EndPass(
                pass_id, cost=float(np.mean(costs)) if costs else None))

    def test(self, reader, feeding=None):
        """Evaluate the cost WITHOUT updating parameters (reference
        trainer.test): runs the pre-minimize clone of the program."""
        self.__ensure_startup__()
        feeder = self.__feeder__(feeding)
        costs = []
        for data_batch in reader():
            feed = feeder.feed(data_batch)
            with scope_guard(self.__parameters__._scope):
                cost_v, = self.__exe__.run(
                    self.__test_program__, feed=feed,
                    fetch_list=[self.__cost__.name])
            costs.append(float(np.asarray(cost_v).ravel()[0]))
        return v2_event.TestResult(
            cost=float(np.mean(costs)) if costs else None)

    def save_parameter_to_tar(self, f):
        self.__parameters__.to_tar(f)
