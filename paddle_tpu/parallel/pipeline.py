"""Pipeline parallelism over the ``pp`` mesh axis (GPipe + interleaved).

Beyond the 2018 reference (SURVEY.md §2.7: PP absent; the closest legacy
analog is ParallelNeuralNetwork's static layer placement). TPU-native
design: stage parameters are STACKED on a leading [S, ...] axis sharded on
``pp`` — every device runs the same stage function on its own parameter
shard, and activations ride the ICI ring via ``ppermute``. One jitted
computation, S + M - 1 ticks for M microbatches (the classic GPipe bubble),
differentiable end-to-end (grads flow through ppermute).

Schedules:
  * ``gpipe`` — all M microbatches stream through the S stages;
    bubble fraction (S-1)/(S+M-1) per direction. Reverse-mode AD turns
    the tick loop into the mirrored backward pipeline, so the memory
    profile already matches 1F1B-with-flush (PipeDream-flush): both
    schedules have the SAME bubble; 1F1B's classic win is activation
    memory, which here is had with ``recompute`` on the stage body.
  * ``gpipe_interleaved`` — Megatron-style virtual stages: each device
    holds V non-contiguous layer CHUNKS (device d owns global chunks
    {d, d+S, ...}), a microbatch makes V laps around the ring, and the
    pipeline fill shrinks to (S-1) CHUNK times — bubble cut by V:
    time = M·t_stage + (S-1)·t_stage/V  vs  (M+S-1)·t_stage.
    This is the schedule that beats GPipe at small M (the interleaved
    1F1B regime); it requires M <= S so at most one microbatch is in
    flight per device per tick (the single-register SPMD carry).

Composition with tensor parallelism: ``param_specs`` lets the stacked
params carry extra mesh axes (e.g. Megatron col/row sharding on ``tp``);
the stage_fn then runs INSIDE shard_map over both axes and issues its own
``lax.psum`` over tp — see ops/parallel_ops._decoder_layer_apply_tp.

Output handling: only the LAST stage produces real outputs, so the result
leaves the shard_map with its leading axis sharded on ``pp`` and the
caller slices stage S-1 — a single sliced transfer sized like the output,
instead of an S-redundant psum of the whole buffer. Heterogeneous stages
(per-stage parameter SHAPES) are supported by passing a list of per-stage
param pytrees: those are replicated to every device and selected by
``lax.switch`` on the stage index — functional, at the memory cost of
holding all stages' params per device; the stacked form is the scalable
path.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ._shard_map import shard_map


def _run_ticks(apply, xs, s_idx, n_stage, axis_name, with_aux=False):
    """The GPipe tick loop for one shard. apply: x -> stage output for
    THIS stage (-> (out, aux) when with_aux). xs [M, mb, ...]
    microbatches (replicated or dp-sharded). Returns [1, M, mb, ...]
    final-stage outputs (zeros on other shards) — plus, with_aux, this
    stage's aux sum over LIVE ticks / M (bubble ticks run on garbage
    and must not pollute the aux loss). The buffer is allocated per
    shard (SPMD executes one program), but only the last stage ever
    writes it."""
    m = xs.shape[0]

    def tick(t, carry):
        state_in, outputs, aux_sum = carry
        mb_idx = jnp.clip(t, 0, m - 1)
        inject = jnp.where(t < m, xs[mb_idx], jnp.zeros_like(xs[0]))
        inp = jnp.where(s_idx == 0, inject, state_in)
        if with_aux:
            out, aux = apply(inp)
            # stage s runs microbatch t - s at tick t
            live = jnp.logical_and(t - s_idx >= 0, t - s_idx < m)
            aux_sum = aux_sum + jnp.where(live, aux, 0.0)
        else:
            out = apply(inp)
        out_mb = t - (n_stage - 1)
        write = jnp.logical_and(s_idx == n_stage - 1, out_mb >= 0)
        upd = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(write, out, outputs[jnp.clip(out_mb, 0, m - 1)]),
            jnp.clip(out_mb, 0, m - 1), 0)
        outputs = jnp.where(write, upd, outputs)
        state_next = lax.ppermute(
            out, axis_name,
            [(j, (j + 1) % n_stage) for j in range(n_stage)])
        return state_next, outputs, aux_sum

    state0 = jnp.zeros_like(xs[0])
    outputs0 = jnp.zeros_like(xs)
    # the aux accumulator carries as shape [1], NOT a scalar: jax
    # 0.4.37's shard_map partial-eval only promotes NON-forwarded scalar
    # residuals, so a scalar loop-carry tangent crossing the shard_map
    # boundary gets paired with a rank-referencing spec in the transpose
    # and raises _SpecError under value_and_grad (the pp x ep failure)
    _, outputs, aux_sum = lax.fori_loop(
        0, n_stage + m - 1, tick,
        (state0, outputs0, jnp.zeros((1,), jnp.float32)))
    # leading singleton axis: the caller's out_spec shards it on pp, so
    # the global result is [S, M, mb, ...] and slicing [-1] pulls ONLY
    # the last stage's buffer — no collective inside the loop or after
    if with_aux:
        return outputs[None], aux_sum / m
    return outputs[None]


def _run_ticks_interleaved(apply, xs, s_idx, n_stage, axis_name,
                           n_chunks, with_aux=False):
    """Virtual-stage tick loop for one shard. apply: (chunk_idx, x) ->
    chunk output for THIS device's local chunk `chunk_idx`. Microbatch i
    is injected at tick i and makes V laps: at hop h (one hop per tick)
    it sits on device h % S running global chunk h. With M <= S no two
    microbatches ever share a device, so the carry stays one state
    register. Total ticks: M - 1 + V*S."""
    m = xs.shape[0]
    total = n_chunks * n_stage

    def tick(t, carry):
        state_in, outputs, aux_sum = carry
        # the unique hop index on THIS device at tick t: the largest
        # h <= t with h ≡ s_idx (mod S); the microbatch holding it is
        # mb = t - h (live iff mb < M and h < total)
        h = t - ((t - s_idx) % n_stage)
        mb = t - h
        # h >= 0 matters: during pipeline FILL a device's congruent hop
        # is negative (idle tick) — without the bound the aux of the
        # garbage apply() would be counted (output writes were always
        # safe: they additionally require h == total-1)
        live = (h >= 0) & (h < total) & (mb < m)
        inject = jnp.where(h == 0, xs[jnp.clip(mb, 0, m - 1)], state_in)
        chunk = jnp.clip(h // n_stage, 0, n_chunks - 1)
        if with_aux:
            out, aux = apply(chunk, inject)
            aux_sum = aux_sum + jnp.where(live, aux, 0.0)
        else:
            out = apply(chunk, inject)
        write = jnp.logical_and(live, h == total - 1)
        mb_c = jnp.clip(mb, 0, m - 1)
        upd = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, out, outputs[mb_c]), mb_c, 0)
        outputs = jnp.where(write, upd, outputs)
        state_next = lax.ppermute(
            out, axis_name,
            [(j, (j + 1) % n_stage) for j in range(n_stage)])
        return state_next, outputs, aux_sum

    state0 = jnp.zeros_like(xs[0])
    outputs0 = jnp.zeros_like(xs)
    # [1]-shaped aux carry — see _run_ticks for the shard_map
    # scalar-residual rationale
    _, outputs, aux_sum = lax.fori_loop(
        0, m - 1 + total, tick,
        (state0, outputs0, jnp.zeros((1,), jnp.float32)))
    if with_aux:
        return outputs[None], aux_sum / m
    return outputs[None]


def _aux_reduce(aux, axis_name, aux_mean_axes):
    """Stage aux sums add over pp (total over layers); members along the
    token-splitting axes (dp/ep/sp) hold DIFFERENT token groups, so their
    auxes average — matching a dense fallback that means over groups.
    (tp members compute identical values; the pmean is a no-op there.)"""
    aux = lax.psum(aux, axis_name)
    for ax in aux_mean_axes or ():
        aux = lax.pmean(aux, ax)
    return aux


def _gpipe_sharded(params, xs, stage_fn, axis_name, with_aux=False,
                   aux_mean_axes=()):
    """Stacked (homogeneous) path: params leaves arrive [1, ...] — this
    shard's slice of the [S, ...] stack."""
    s_idx = lax.axis_index(axis_name)
    n_stage = lax.psum(1, axis_name)
    local_params = jax.tree_util.tree_map(lambda p: p[0], params)
    res = _run_ticks(lambda x: stage_fn(local_params, x), xs, s_idx,
                     n_stage, axis_name, with_aux=with_aux)
    if with_aux:
        out, aux = res
        return out, _aux_reduce(aux, axis_name, aux_mean_axes)
    return res


def _interleaved_sharded(params, xs, stage_fn, axis_name, n_chunks,
                         with_aux=False, aux_mean_axes=()):
    """Interleaved path: params leaves arrive [1, V, ...] — this shard's
    V chunk slices. stage_fn(chunk_params, x) runs ONE chunk."""
    s_idx = lax.axis_index(axis_name)
    n_stage = lax.psum(1, axis_name)
    local = jax.tree_util.tree_map(lambda p: p[0], params)

    def apply(chunk, x):
        cp = jax.tree_util.tree_map(
            lambda p: lax.dynamic_index_in_dim(p, chunk, 0,
                                               keepdims=False), local)
        return stage_fn(cp, x)

    res = _run_ticks_interleaved(apply, xs, s_idx, n_stage, axis_name,
                                 n_chunks, with_aux=with_aux)
    if with_aux:
        out, aux = res
        return out, _aux_reduce(aux, axis_name, aux_mean_axes)
    return res


def _gpipe_hetero(params_seq, xs, stage_fn, axis_name):
    """Heterogeneous path: params_seq is a tuple of per-stage pytrees
    (arbitrary, differing shapes), replicated; lax.switch picks this
    stage's branch."""
    s_idx = lax.axis_index(axis_name)
    n_stage = lax.psum(1, axis_name)
    branches = [functools.partial(stage_fn, p) for p in params_seq]
    return _run_ticks(lambda x: lax.switch(s_idx, branches, x), xs, s_idx,
                      n_stage, axis_name)


def gpipe(stage_fn, stacked_params, microbatches, mesh, axis_name="pp",
          batch_axis=None, param_specs=None, seq_axis=None,
          with_aux=False):
    """Run ``stage_fn(params_i, x)`` as an S-stage pipeline.

    stacked_params: EITHER a pytree whose leaves have leading dim S
                    (= mesh[axis]) — sharded on ``axis_name``, the
                    scalable form — OR a list/tuple of S per-stage
                    pytrees with arbitrary per-stage shapes (replicated
                    to every device, selected by stage index).
    microbatches:   [M, mb, T, ...] array of M microbatches.
    batch_axis:     mesh axis the mb dim is data-sharded on (e.g. "dp"),
                    None if replicated.
    param_specs:    optional pytree of PartitionSpecs for the NON-leading
                    dims of the stacked params (tensor-parallel
                    composition: Megatron col/row shards on "tp"; the
                    leading ``axis_name`` entry is prepended here). The
                    stage_fn then runs inside shard_map over both axes
                    and must psum its partial sums over the tp axis.
    seq_axis:       mesh axis the T (dim-2) activation dim is sharded on
                    (sequence-parallel composition: the stage_fn must
                    run ring/Ulysses attention over that axis).
    with_aux:       stage_fn returns (out, aux_scalar) — e.g. the MoE
                    load-balancing loss (pp x ep). Live-tick aux sums
                    psum over pp and pmean over the token-splitting
                    axes; gpipe then returns (outputs, aux). batch_axis
                    may be a TUPLE of axes (the dp x ep token split).
    Returns [M, mb, ...] outputs of the final stage (with_aux: a tuple).
    """
    s = mesh.shape[axis_name]
    xspec = P(None, batch_axis, seq_axis)
    out_spec = P(axis_name, None, batch_axis, seq_axis)
    aux_axes = tuple(a for a in jax.tree_util.tree_leaves(
        (batch_axis, seq_axis)) if a) if with_aux else ()
    out_specs = (out_spec, P()) if with_aux else out_spec

    if isinstance(stacked_params, (list, tuple)):
        if with_aux:
            raise NotImplementedError(
                "with_aux is not supported on the heterogeneous "
                "per-stage-params path")
        if len(stacked_params) != s:
            raise ValueError(
                "per-stage params list has %d entries != %d pipeline "
                "stages" % (len(stacked_params), s))
        params_seq = tuple(stacked_params)
        pspec = jax.tree_util.tree_map(lambda _: P(), params_seq)
        fn = shard_map(
            functools.partial(_gpipe_hetero, stage_fn=stage_fn,
                              axis_name=axis_name),
            mesh=mesh, in_specs=(pspec, xspec), out_specs=out_spec,
            check_vma=False)
        return fn(params_seq, microbatches)[-1]

    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != s:
            raise ValueError(
                "stacked_params leading dim %d != %d pipeline stages"
                % (leaf.shape[0], s))
    if param_specs is None:
        pspec = jax.tree_util.tree_map(lambda _: P(axis_name),
                                       stacked_params)
    else:
        pspec = jax.tree_util.tree_map(
            lambda sp: P(axis_name, *sp), param_specs,
            is_leaf=lambda x: isinstance(x, (P, tuple)))
    fn = shard_map(
        functools.partial(_gpipe_sharded, stage_fn=stage_fn,
                          axis_name=axis_name, with_aux=with_aux,
                          aux_mean_axes=aux_axes),
        mesh=mesh, in_specs=(pspec, xspec), out_specs=out_specs,
        check_vma=False)
    res = fn(stacked_params, microbatches)
    if with_aux:
        # aux crosses the shard_map as [1] (scalar-residual workaround
        # in _run_ticks); hand the caller the scalar it expects
        return res[0][-1], res[1].reshape(())
    return res[-1]


def gpipe_interleaved(stage_fn, stacked_params, microbatches, mesh,
                      n_chunks, axis_name="pp", batch_axis=None,
                      param_specs=None, seq_axis=None, with_aux=False):
    """Interleaved virtual-stage pipeline (Megatron 1F1B-interleaved
    regime): device d holds the V = n_chunks chunk param slices
    {d, d+S, ...}; bubble = (S-1)/V chunk-times instead of (S-1)
    stage-times — the schedule that beats GPipe at small M.

    stacked_params: pytree with leaves [S, V, per_chunk, ...] — leading
                    dim sharded on ``axis_name``, dim 1 the local chunk
                    index (see ops/parallel_ops for the [L,...] →
                    [S, V, ...] interleave reshape).
    microbatches:   [M, mb, ...], M <= S (single in-flight microbatch
                    per device per tick).
    stage_fn(chunk_params, x) runs ONE chunk (per_chunk layers).
    """
    s = mesh.shape[axis_name]
    m = microbatches.shape[0]
    if m > s:
        raise ValueError(
            "interleaved schedule needs microbatches M=%d <= S=%d "
            "pipeline stages (use gpipe for the large-M regime)" % (m, s))
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != s or leaf.shape[1] != n_chunks:
            raise ValueError(
                "interleaved stacked_params leaves must be "
                "[S=%d, V=%d, ...]; got %s" % (s, n_chunks, leaf.shape))
    xspec = P(None, batch_axis, seq_axis)
    out_spec = P(axis_name, None, batch_axis, seq_axis)
    aux_axes = tuple(a for a in jax.tree_util.tree_leaves(
        (batch_axis, seq_axis)) if a) if with_aux else ()
    out_specs = (out_spec, P()) if with_aux else out_spec
    if param_specs is None:
        pspec = jax.tree_util.tree_map(lambda _: P(axis_name, None),
                                       stacked_params)
    else:
        pspec = jax.tree_util.tree_map(
            lambda sp: P(axis_name, None, *sp), param_specs,
            is_leaf=lambda x: isinstance(x, (P, tuple)))
    fn = shard_map(
        functools.partial(_interleaved_sharded, stage_fn=stage_fn,
                          axis_name=axis_name, n_chunks=n_chunks,
                          with_aux=with_aux, aux_mean_axes=aux_axes),
        mesh=mesh, in_specs=(pspec, xspec), out_specs=out_specs,
        check_vma=False)
    res = fn(stacked_params, microbatches)
    if with_aux:
        return res[0][-1], res[1].reshape(())
    return res[-1]
