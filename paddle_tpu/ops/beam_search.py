"""Beam search ops, TPU-first.

Reference parity: operators/beam_search_op.cc:1 (per-step top-k selection
with end-of-sentence pruning) and beam_search_decode_op.cc:1 (backtracking
the beam tree into finished sentences).

The reference walks variable-length LoD levels with host loops and builds a
pointer tree (BeamNode) for decoding. Neither maps to the MXU/XLA model, so
the design here is dense and static-shaped:

* every source sentence always owns exactly ``beam_size`` rows — dead beams
  (those that already emitted ``end_id``) stay in the tensor, are masked to
  -inf so they never win, and re-emit ``end_id`` with a frozen score;
* selection is one ``lax.top_k`` over the flattened ``beam_size * K``
  candidate table per source — no data-dependent shapes;
* decoding is a reverse ``lax.scan`` over the recorded parent pointers
  (the functional equivalent of the BeamNode backtrack), producing padded
  ``[batch, beam, max_len]`` sentences.

This is the same dense formulation the step-level op AND the whole-loop
functional decoder (models/decoding.py) share, so a Program built from
layers.beam_search and a jitted scan decode select identical beams.
"""

import jax.numpy as jnp
from jax import lax

from ..core.registry import register

NEG_INF = -1e9


def beam_search_step(pre_ids, pre_scores, scores, beam_size, end_id,
                     first_step=False):
    """One dense beam-search step.

    Args:
      pre_ids:    [B*W] int32 — token chosen at the previous step.
      pre_scores: [B*W] f32 — accumulated log-prob per beam.
      scores:     [B*W, V] f32 — *local* log-probs for the next token
                  (log softmax of the decoder output).
      beam_size:  W.
      end_id:     EOS token id.
      first_step: if True, only beam 0 of each source is live (all beams
                  hold identical state at t=0, so without this every source
                  would select W copies of the same token).

    Returns (selected_ids [B*W] i32, selected_scores [B*W] f32,
             parent_idx [B*W] i32 — index into the previous step's B*W rows).
    """
    bw, vocab = scores.shape
    batch = bw // beam_size
    finished = (pre_ids == end_id)

    # accumulated candidate table: alive beams extend by every token;
    # finished beams contribute exactly one frozen candidate at end_id.
    acc = pre_scores[:, None] + scores                 # [B*W, V]
    acc = jnp.where(finished[:, None], NEG_INF, acc)
    frozen = jnp.full((bw, vocab), NEG_INF, acc.dtype)
    frozen = frozen.at[:, end_id].set(
        jnp.where(finished, pre_scores, NEG_INF))
    cand = jnp.maximum(acc, frozen)                    # [B*W, V]

    if first_step:
        beam_pos = jnp.arange(bw) % beam_size
        cand = jnp.where((beam_pos > 0)[:, None], NEG_INF, cand)

    flat = cand.reshape(batch, beam_size * vocab)
    top_scores, top_idx = lax.top_k(flat, beam_size)   # [B, W]
    parent_in_src = top_idx // vocab                   # [B, W] ∈ [0, W)
    token = top_idx % vocab
    src_base = jnp.arange(batch)[:, None] * beam_size
    parent_idx = (src_base + parent_in_src).reshape(-1)
    return (token.reshape(-1).astype(jnp.int32),
            top_scores.reshape(-1),
            parent_idx.astype(jnp.int32))


def beam_search_decode(step_ids, step_parents, final_scores, beam_size,
                       end_id):
    """Backtrack recorded steps into sentences.

    Args:
      step_ids:     [T, B*W] i32 — selected token per step.
      step_parents: [T, B*W] i32 — parent row per step.
      final_scores: [B*W] f32 — accumulated score of each final beam.
      beam_size, end_id: as above.

    Returns (sentences [B, W, T] i32 padded with end_id after EOS,
             scores [B, W] f32).
    """
    T, bw = step_ids.shape
    batch = bw // beam_size

    def back(carry, xs):
        row = carry                       # [B*W] current row per final beam
        ids_t, par_t = xs                 # each [B*W]
        tok = ids_t[row]
        prev = par_t[row]
        return prev, tok

    rows0 = jnp.arange(bw, dtype=jnp.int32)
    _, toks_rev = lax.scan(back, rows0, (step_ids[::-1], step_parents[::-1]))
    sentences = toks_rev[::-1].T          # [B*W, T]

    # pad everything after the first end_id with end_id
    seen_end = jnp.cumsum((sentences == end_id).astype(jnp.int32), axis=1)
    after_end = jnp.concatenate(
        [jnp.zeros((bw, 1), jnp.int32), seen_end[:, :-1]], axis=1) > 0
    sentences = jnp.where(after_end, end_id, sentences)
    return (sentences.reshape(batch, beam_size, T),
            final_scores.reshape(batch, beam_size))


# --------------------------------------------------------------------------
# Program-IR op lowerings
# --------------------------------------------------------------------------

@register("beam_search")
def _beam_search(ctx, op):
    """Dense per-step op (beam_search_op.cc). Inputs pre_ids [B*W,1],
    pre_scores [B*W,1], scores [B*W,V]; attrs beam_size, end_id,
    is_first_step. The `ids` slot of the reference (pre-selected candidate
    ids) is unnecessary in the dense form — scores covers the full vocab."""
    pre_ids = ctx.in1(op, "pre_ids").reshape(-1)
    pre_scores = ctx.in1(op, "pre_scores").reshape(-1).astype(jnp.float32)
    scores = ctx.in1(op, "scores")
    sel, sc, par = beam_search_step(
        pre_ids, pre_scores, scores,
        int(op.attr("beam_size", 4)), int(op.attr("end_id", 0)),
        bool(op.attr("is_first_step", False)))
    ctx.set_out(op, "selected_ids", sel[:, None])
    ctx.set_out(op, "selected_scores", sc[:, None])
    ctx.set_out(op, "parent_idx", par)


@register("beam_search_decode")
def _beam_search_decode(ctx, op):
    """Backtracking decode (beam_search_decode_op.cc). Inputs Ids / Parents
    as LoDTensorArrays (lists of [B*W,1] per step) or stacked [T,B*W]
    tensors, Scores [B*W,1] accumulated; outputs SentenceIds [B,W,T],
    SentenceScores [B,W]."""
    ids = ctx.in1(op, "Ids")
    parents = ctx.in1(op, "Parents")
    scores = ctx.in1(op, "Scores")
    if isinstance(ids, list):
        ids = jnp.stack([jnp.asarray(a).reshape(-1) for a in ids])
    else:
        ids = jnp.asarray(ids).reshape(ids.shape[0], -1)
    if isinstance(parents, list):
        parents = jnp.stack([jnp.asarray(a).reshape(-1) for a in parents])
    else:
        parents = jnp.asarray(parents).reshape(parents.shape[0], -1)
    sent, sc = beam_search_decode(
        ids.astype(jnp.int32), parents.astype(jnp.int32),
        jnp.asarray(scores).reshape(-1).astype(jnp.float32),
        int(op.attr("beam_size", 4)), int(op.attr("end_id", 0)))
    ctx.set_out(op, "SentenceIds", sent)
    ctx.set_out(op, "SentenceScores", sc)
