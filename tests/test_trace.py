"""paddle_tpu.trace: cross-process distributed tracing.

Covers the ISSUE-4 acceptance surface: SpanContext inject/extract
through a LIVE loopback RPC pair, old-frame (headerless) compatibility
in both directions, the NTP-midpoint clock-offset estimator on
synthetic skew, a merge-CLI golden fixture where nesting only holds
AFTER skew correction, retry attempts as children of one logical client
span, executor root spans + monitor trace-id stamping, the satellite
CLI/profiler behaviors, and the tier-1 smoke: a zoo-MLP trainer against
a live master+pserver in a SECOND real process, each writing its own
span log, merged into one Perfetto timeline where the server GET span
nests inside its client span.
"""

import itertools
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor, trace
from paddle_tpu.distributed import rpc
from paddle_tpu.distributed.master import MasterClient
from paddle_tpu.distributed.rpc import RPCClient, VariableServer
from paddle_tpu.models.mlp import mlp
from paddle_tpu.resilience import Policy, faults
from paddle_tpu.trace import clock as tclock
from paddle_tpu.trace import merge as tmerge
from paddle_tpu.trace import runtime as trt
from paddle_tpu.trace.__main__ import main as trace_cli


@pytest.fixture(autouse=True)
def _trace_teardown():
    yield
    trace.disable()
    faults.disarm()
    monitor.disable()


def _spans(log):
    rows = [json.loads(line) for line in open(log)]
    return [r for r in rows if r.get("ev") == "span"]


# -- wire format -----------------------------------------------------------

def test_wire_header_roundtrip_and_headerless(tmp_path):
    a, b = socket.socketpair()
    try:
        # old (headerless) frame parses with and without want_ctx
        rpc._send_msg(a, "GET", "w")
        op, name, payload, ctx = rpc._recv_msg(b, want_ctx=True)
        assert (op, name, ctx) == ("GET", "w", None)

        trace.enable(log_path=str(tmp_path / "t.jsonl"))
        # armed + ambient sampled span -> context block round-trips
        with trace.span("root"):
            sent = trace.current_span().ctx.span_id
            rpc._send_msg(a, "GET", "w")
        op, name, payload, ctx = rpc._recv_msg(b, want_ctx=True)
        assert (op, name) == ("GET", "w") and ctx is not None
        sc = trace.extract(ctx)
        assert sc is not None and sc.span_id == sent and sc.sampled

        # a receiver NOT asking for context still consumes the block
        # (the reply direction / a tracing-disarmed process)
        with trace.span("root2"):
            rpc._send_msg(a, "OK", "", b"payload")
        assert rpc._recv_msg(b) == ("OK", "", bytearray(b"payload"))

        # armed but NO ambient span -> byte-identical old frames
        rpc._send_msg(a, "GET", "w")
        raw = rpc._recv_exact(b, 12)
        assert bytes(raw[:4]) == b"GET "
        rpc._recv_exact(b, 1)                       # drain the name

        # sampled-out root with the tail ring armed (the default) ->
        # the context block still travels, flagged sampled=0, so a
        # downstream retention promotion can recover the whole trace
        trace.enable(log_path=str(tmp_path / "t2.jsonl"),
                     sample_rate=1e-12)
        with trace.span("root3"):
            tid = trace.active_trace_id()
            rpc._send_msg(a, "GET", "w")
        op, name, payload, ctx = rpc._recv_msg(b, want_ctx=True)
        assert (op, name) == ("GET", "w") and ctx is not None
        sc = trace.extract(ctx)
        assert sc is not None and sc.trace_id == tid and not sc.sampled

        # sampled-out root with the ring OFF -> headerless, exactly
        # the historical frames (old peers stay safe at any sampling
        # rate when tail retention is disabled)
        trace.enable(log_path=str(tmp_path / "t3.jsonl"),
                     sample_rate=1e-12, tail_window=0)
        with trace.span("root4"):
            rpc._send_msg(a, "GET", "w")
        raw = rpc._recv_exact(b, 12)
        assert bytes(raw[:4]) == b"GET "
        rpc._recv_exact(b, 1)
    finally:
        a.close()
        b.close()


def test_extract_never_raises():
    assert trace.extract(None) is None
    assert trace.extract(b"garbage") is None
    assert trace.extract(b"\xff\xfe:oops") is None
    assert trace.extract(b"::0") is None
    sc = trace.extract(b"aa:bb:0")
    assert sc.trace_id == "aa" and not sc.sampled


# -- live loopback RPC pair ------------------------------------------------

def test_span_propagation_through_live_rpc(tmp_path):
    log = str(tmp_path / "t.jsonl")
    trace.enable(log_path=log, proc="both", clock_interval=0.0)
    srv = VariableServer(fan_in=1)
    srv.start()
    cli = RPCClient("127.0.0.1:%d" % srv.port)
    try:
        cli.put_var("w", np.ones((4, 4), np.float32))
        with trace.span("round", step=0):
            cli.get_var("w")
    finally:
        cli.close()
        srv.stop()
    trace.disable()
    spans = _spans(log)
    server = next(s for s in spans if s["name"] == "pserver.GET")
    client = next(s for s in spans if s["name"] == "rpc.get")
    root = next(s for s in spans if s["name"] == "round")
    # the injected context linked server -> client verb -> root
    assert server["parent"] == client["span"]
    assert client["parent"] == root["span"]
    assert server["trace"] == client["trace"] == root["trace"]
    # clock probes landed (interval 0 = every opportunity) and map to
    # the registered server port
    rows = [json.loads(l) for l in open(log)]
    clocks = [r for r in rows if r["ev"] == "clock"]
    ports = {r["port"] for r in rows if r["ev"] == "server_port"}
    assert clocks and srv.port in ports
    assert all(abs(c["offset"]) <= max(c["rtt"], 0.5) for c in clocks)


def test_disarmed_client_against_armed_server(tmp_path):
    # "old client" direction: frames WITHOUT the header dispatch
    # correctly on a process whose tracing is armed
    trace.enable(log_path=str(tmp_path / "t.jsonl"), proc="server")
    srv = VariableServer(fan_in=1)
    srv.start()
    sock = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
    try:
        import struct
        payload = rpc.serialize_var(np.arange(6, dtype=np.float32))
        name = b"w"
        sock.sendall(struct.pack("<4sII", b"PUT ", len(name),
                                 len(payload)) + name + payload)
        head = rpc._recv_exact(sock, 12)
        assert bytes(head[:4]) == b"OK  "   # reply is headerless too
    finally:
        sock.close()
        srv.stop()


# -- clock offset ----------------------------------------------------------

def test_clock_midpoint_on_synthetic_skew():
    # server clock 5s AHEAD, symmetric 200ms round trip
    off, rtt = tclock.midpoint_offset(100.0, 105.1, 100.2)
    assert abs(off - 5.0) < 1e-9
    assert abs(rtt - 0.2) < 1e-9
    # behind works too
    off, _ = tclock.midpoint_offset(100.0, 96.9, 100.2)
    assert abs(off + 3.2) < 1e-9


def test_clock_probe_records_and_rate_limits(tmp_path):
    log = str(tmp_path / "t.jsonl")
    t = trace.enable(log_path=log, clock_interval=3600.0)
    off = tclock.probe(t, "peer:1", lambda: time.time() + 5.0)
    assert off is not None and abs(off - 5.0) < 0.5
    # rate-limited: the second probe within the interval is skipped
    assert tclock.probe(t, "peer:1", lambda: time.time()) is None
    trace.disable()
    rows = [json.loads(l) for l in open(log) if '"clock"' in l]
    assert len(rows) == 1 and abs(rows[0]["offset"] - 5.0) < 0.5


# -- merge golden fixture --------------------------------------------------

_T, _A, _B, _S = "t" * 16, "a" * 16, "b" * 16, "c" * 16


def _write_skew_fixture(tmp_path):
    client = tmp_path / "trainer.jsonl"
    server = tmp_path / "ps.jsonl"
    crows = [
        {"ts": 1.0, "ev": "proc_meta", "pid": 111, "proc": "trainer"},
        {"ts": 1.0, "ev": "span", "trace": _T, "span": _A,
         "parent": None, "name": "round", "t0": 1000.0, "dur": 0.1,
         "pid": 111, "proc": "trainer", "tid": 1},
        {"ts": 1.0, "ev": "span", "trace": _T, "span": _B,
         "parent": _A, "name": "rpc.get", "t0": 1000.01, "dur": 0.05,
         "pid": 111, "proc": "trainer", "tid": 1,
         "attrs": {"endpoint": "127.0.0.1:9999"}},
        {"ts": 1.0, "ev": "clock", "peer": "127.0.0.1:9999",
         "offset": 5.0, "rtt": 0.001, "pid": 111, "proc": "trainer"},
    ]
    # the server's clock runs 5s AHEAD: raw t0 lies OUTSIDE the client
    # span; only skew correction nests it
    srows = [
        {"ts": 1.0, "ev": "server_port", "port": 9999, "pid": 222,
         "proc": "pserver"},
        {"ts": 1.0, "ev": "span", "trace": _T, "span": _S,
         "parent": _B, "name": "pserver.GET", "t0": 1005.02,
         "dur": 0.02, "pid": 222, "proc": "pserver", "tid": 9},
    ]
    client.write_text("\n".join(json.dumps(r) for r in crows) + "\n")
    server.write_text("\n".join(json.dumps(r) for r in srows) + "\n"
                      + '{"ts": 2.0, "ev": "sp')   # torn tail
    return str(client), str(server)


def test_merge_golden_fixture_skew_corrected_nesting(tmp_path):
    client, server = _write_skew_fixture(tmp_path)
    out = str(tmp_path / "timeline.json")
    assert trace_cli(["merge", client, server, "-o", out]) == 0
    merged = json.load(open(out))
    info = merged["otherData"]["paddle_tpu.trace"]
    assert info["reference_pid"] == 111
    assert abs(info["clock_offsets"]["222"
               if "222" in info["clock_offsets"] else 222] - 5.0) < 1e-9
    assert info["skipped_lines"] == 1          # tolerated the torn tail
    events = merged["traceEvents"]
    lanes = {e["pid"]: e["args"]["name"] for e in events
             if e.get("name") == "process_name"}
    assert "trainer (pid 111)" in lanes.values()
    assert "pserver (pid 222)" in lanes.values()
    get = next(e for e in events if e.get("ph") == "X"
               and e["name"] == "rpc.get")
    ps = next(e for e in events if e.get("ph") == "X"
              and e["name"] == "pserver.GET")
    # CORRECTED nesting: server handling inside the client verb span
    assert get["ts"] <= ps["ts"]
    assert ps["ts"] + ps["dur"] <= get["ts"] + get["dur"]
    # without correction it would NOT nest (5s of skew >> 50ms span)
    raw_gap = (1005.02 - 1000.01) * 1e6
    assert raw_gap > get["dur"]
    # parent linkage survived into args
    assert ps["args"]["parent"] == get["args"]["span"] == _B
    # cross-process flow arrow present
    assert any(e.get("ph") == "s" for e in events)
    assert any(e.get("ph") == "f" for e in events)


def test_stats_cli_on_fixture(tmp_path, capsys):
    client, server = _write_skew_fixture(tmp_path)
    assert trace_cli(["stats", client, server, "--json"]) == 0
    s = json.loads(capsys.readouterr().out)
    verbs = {v["name"]: v for v in s["verbs"]}
    assert verbs["rpc.get"]["count"] == 1
    assert abs(verbs["rpc.get"]["p50_s"] - 0.05) < 1e-9
    assert s["rounds"]["count"] == 1
    assert abs(s["rounds"]["mean_by_verb_s"]["rpc.get"] - 0.05) < 1e-9
    # rpc.get dominated the only round
    assert s["stragglers"][0]["who"].startswith("rpc.get@")
    # text renderer too
    assert trace_cli(["stats", client, server]) == 0
    out = capsys.readouterr().out
    assert "rpc.get" in out and "straggler" in out


def test_merge_port_collision_resolved_by_endpoint_or_dropped(tmp_path):
    """Two hosts reusing port 7000: an exact endpoint match resolves
    the clock sample; a bare-port match against a COLLIDING port is
    dropped with a warning, never silently credited to the wrong
    process."""
    def w(name, rows):
        p = tmp_path / name
        p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        return str(p)

    span = {"ts": 1.0, "ev": "span", "trace": _T, "parent": None,
            "dur": 0.1, "tid": 1}
    a = w("ps0.jsonl", [
        {"ts": 1.0, "ev": "server_port", "port": 7000, "pid": 1,
         "proc": "ps0", "endpoint": "hostA:7000"},
        dict(span, span="e" * 16, name="x", t0=10.0, pid=1,
             proc="ps0")])
    b = w("ps1.jsonl", [
        {"ts": 1.0, "ev": "server_port", "port": 7000, "pid": 2,
         "proc": "ps1", "endpoint": "hostB:7000"},
        dict(span, span="f" * 16, name="x", t0=10.0, pid=2,
             proc="ps1")])
    c = w("tr.jsonl", [
        dict(span, span="g" * 16, name="round", t0=10.0, pid=3,
             proc="tr"),
        dict(span, span="h" * 16, name="round", t0=10.2, pid=3,
             proc="tr"),
        {"ts": 1.0, "ev": "clock", "peer": "hostB:7000",
         "offset": 2.0, "rtt": 0.001, "pid": 3, "proc": "tr"},
        {"ts": 1.0, "ev": "clock", "peer": "hostC:7000",
         "offset": 9.0, "rtt": 0.001, "pid": 3, "proc": "tr"}])
    offsets, ref, warnings = tmerge.clock_offsets(
        tmerge.load_logs([a, b, c]))
    assert ref == 3                     # the trainer drives the run
    assert offsets[2] == 2.0            # exact endpoint match
    assert offsets[1] == 0.0            # unreachable, left uncorrected
    assert any("port 7000" in w for w in warnings)      # collision
    assert any("pid 1" in w for w in warnings)          # no clock path


# -- retries as attempt children ------------------------------------------

def test_retry_attempts_are_children_of_one_client_span(tmp_path):
    log = str(tmp_path / "t.jsonl")
    trace.enable(log_path=log, proc="trainer", clock_interval=-1.0)
    srv = VariableServer(fan_in=1)
    srv.start()
    plan = faults.arm({"rpc": {"drop": 1.0, "max": 2, "ops": ["GET"],
                               "ports": [srv.port]}}, seed=7)
    pol = Policy(max_attempts=8, base_delay=0.01, max_delay=0.05,
                 deadline=10.0, seed=3)
    cli = RPCClient("127.0.0.1:%d" % srv.port, retry=pol)
    try:
        cli.put_var("w", np.ones((2,), np.float32))
        with trace.span("round"):
            cli.get_var("w")
    finally:
        faults.disarm()
        cli.close()
        srv.stop()
    trace.disable()
    assert [k for k, _ in plan.trips].count("drop") == 2
    spans = _spans(log)
    verb = next(s for s in spans if s["name"] == "rpc.get")
    attempts = [s for s in spans if s["name"] == "rpc.get.attempt"]
    # one LOGICAL client span; every try one attempt child under it
    assert len(attempts) == 3
    assert all(a["parent"] == verb["span"] for a in attempts)
    assert sorted(a["attrs"]["attempt"] for a in attempts) == [1, 2, 3]
    failed = [a for a in attempts if "error" in a["attrs"]]
    assert len(failed) == 2
    # reconnects annotated the attempts that re-dialed
    assert any(a["attrs"].get("reconnected") for a in attempts)
    assert verb["attrs"]["retries"] == 2
    # the server span nests under the SUCCESSFUL attempt
    server = next(s for s in spans if s["name"] == "pserver.GET")
    winner = next(a for a in attempts if a["attrs"]["attempt"] == 3)
    assert server["parent"] == winner["span"]


# -- executor + monitor integration ----------------------------------------

def test_executor_root_span_and_monitor_trace_id(tmp_path):
    tlog = str(tmp_path / "t.jsonl")
    mlog = str(tmp_path / "m.jsonl")
    trace.enable(log_path=tlog, proc="trainer")
    monitor.enable(log_path=mlog)
    x = fluid.layers.data("x", [4])
    y = fluid.layers.fc(x, 2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    exe.run(feed={"x": np.ones((2, 4), np.float32)}, fetch_list=[y])
    monitor.disable()
    trace.disable()
    steps = [s for s in _spans(tlog) if s["name"] == "exe.step"]
    assert len(steps) >= 2           # startup + main step, each a root
    assert all(s["parent"] is None for s in steps)
    # monitor flight-recorder step rows joined the fleet timeline
    mrows = monitor.read_jsonl(mlog)
    traced = [e for e in mrows if e["ev"] == "step" and e.get("trace")]
    assert traced
    assert {e["trace"] for e in traced} <= {s["trace"] for s in steps}
    # the new counters registered and ticked
    from paddle_tpu.monitor import runtime as mrt
    assert sum(mrt.TRACE_SPANS.snapshot().values()) >= len(steps)


def test_trace_disarmed_is_inert():
    assert not trace.enabled()
    # null span is reusable and annotate is a no-op
    with trace.span("nothing") as s:
        s.annotate(a=1)
        trace.annotate(b=2)
    assert trace.current_span() is None
    assert trace.active_trace_id() is None


def test_flag_rate_parsing():
    assert trt._parse_rate("") is None
    assert trt._parse_rate("0") is None
    assert trt._parse_rate("off") is None
    assert trt._parse_rate("1") == 1.0
    assert trt._parse_rate("true") == 1.0
    assert trt._parse_rate("0.25") == 0.25
    assert trt._parse_rate("7") == 1.0        # clipped
    assert trt._parse_rate("nonsense") is None


def test_maybe_enable_from_flags(tmp_path):
    from paddle_tpu import flags
    log = str(tmp_path / "flag-{pid}.jsonl")
    flags.set_flag("trace", "0.5")
    flags.set_flag("trace_log", log)
    flags.set_flag("trace_proc", "flagged")
    try:
        t = trt.maybe_enable_from_flags()
        assert t is not None and t.sample_rate == 0.5
        assert t.proc == "flagged"
        assert os.path.exists(log.replace("{pid}", str(os.getpid())))
    finally:
        flags.set_flag("trace", "")
        flags.set_flag("trace_log", "")
        flags.set_flag("trace_proc", "")
        trace.disable()


# -- satellite: monitor CLI torn-tail tolerance ----------------------------

def test_monitor_cli_tolerates_torn_trailing_line(tmp_path, capsys):
    p = str(tmp_path / "m.jsonl")
    rec = monitor.FlightRecorder(p)
    rec.record("run_meta", pid=1)
    rec.record("step", executor="exe", n=1, dt=0.01, synced=True)
    rec.close()
    with open(p, "a") as f:
        f.write('{"ts": 123.0, "ev": "st')     # writer killed mid-line
    from paddle_tpu.monitor.__main__ import main as mon_cli
    from paddle_tpu.monitor.__main__ import summarize_log
    s = summarize_log(p)
    assert s["steps"] == 1 and s["skipped_lines"] == 1
    assert mon_cli([p]) == 0
    assert "skipped" in capsys.readouterr().out
    # the strict reader's schema contract is unchanged
    with pytest.raises(ValueError):
        monitor.read_jsonl(p)


# -- satellite: profiler cap visibility ------------------------------------

def test_profiler_capped_trace_reports_dropped(tmp_path, monkeypatch):
    from paddle_tpu import profiler
    profiler.reset_profiler()
    monkeypatch.setattr(profiler, "_TRACE_CAP", 3)
    profiler.start_profiler()
    for i in range(7):
        with profiler.RecordEvent("ev%d" % i):
            pass
    profiler.stop_profiler(profile_path=str(tmp_path / "prof"))
    s = profiler.summary()
    assert s["trace_dropped"] == 4 and s["truncated"]
    assert s["spans"] == 3
    path = str(tmp_path / "c.json")
    profiler.export_chrome_trace(path)
    events = json.load(open(path))["traceEvents"]
    md = [e for e in events
          if e.get("ph") == "M" and e["name"] == "trace_dropped"]
    assert md and md[0]["args"]["trace_dropped"] == 4
    profiler.reset_profiler()
    assert profiler.summary()["trace_dropped"] == 0


# -- satellite: analysis gate covers trace ---------------------------------

def test_analysis_import_check_covers_trace():
    from paddle_tpu.analysis.__main__ import (IMPORT_CHECK_PACKAGES,
                                              import_check)
    trace_pkgs = [p for p in IMPORT_CHECK_PACKAGES
                  if p.startswith("paddle_tpu.trace")]
    assert "paddle_tpu.trace" in trace_pkgs
    assert import_check(tuple(trace_pkgs)) == []


# -- tier-1 e2e smoke: two real processes ----------------------------------

_SERVER_PROC = '''\
import os, sys, time
sys.path.insert(0, %(repo)r)
import numpy as np
import paddle_tpu  # PADDLE_TPU_TRACE env arms tracing at import
from paddle_tpu.distributed.master import MasterServer, TaskQueue
from paddle_tpu.distributed.rpc import VariableServer

LR = 0.15

def sgd(store, grads):
    for k, g in grads.items():
        p = k.replace("@GRAD", "")
        if p in store:
            store[p] = store[p] - LR * np.asarray(g)

srv = VariableServer(fan_in=1, optimize_fn=sgd, sync=True,
                     port_file=%(ps_port_file)r)
srv.start()
master = MasterServer(TaskQueue(payloads=list(range(%(n_tasks)d))),
                      port_file=%(master_port_file)r)
master.start()
deadline = time.time() + 120
while not os.path.exists(%(stop_file)r) and time.time() < deadline:
    time.sleep(0.05)
master.stop()
srv.stop()
import paddle_tpu.trace as trace
trace.disable()          # close the span log cleanly
'''


def _wait_for_file(path, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(path) and open(path).read().strip():
            return open(path).read().strip()
        time.sleep(0.05)
    raise TimeoutError("no %s after %ss" % (path, timeout))


def test_two_process_merged_timeline(tmp_path):
    """ISSUE-4 acceptance: trainer + pserver as two REAL processes over
    live sockets, each writing its own span log; the merged timeline
    nests the server-side GET dispatch span inside its client RPC span
    (same trace, parent linkage, skew-corrected timestamps)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    n_tasks = 5
    ps_port_file = str(tmp_path / "ps.port")
    master_port_file = str(tmp_path / "master.port")
    stop_file = str(tmp_path / "stop")
    server_log = str(tmp_path / "pserver.jsonl")
    client_log = str(tmp_path / "trainer.jsonl")
    script = tmp_path / "server_proc.py"
    script.write_text(_SERVER_PROC % {
        "repo": repo, "ps_port_file": ps_port_file,
        "master_port_file": master_port_file, "stop_file": stop_file,
        "n_tasks": n_tasks})
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PADDLE_TPU_TRACE": "1",
                "PADDLE_TPU_TRACE_LOG": server_log,
                "PADDLE_TPU_TRACE_PROC": "pserver",
                "PADDLE_TPU_TRACE_CLOCK_INTERVAL": "0"})
    env.pop("PADDLE_TPU_MONITOR", None)
    proc = subprocess.Popen([sys.executable, str(script)], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    try:
        ps_port = int(_wait_for_file(ps_port_file))
        master_port = int(_wait_for_file(master_port_file))
        trace.enable(log_path=client_log, proc="trainer",
                     clock_interval=0.0)

        rng = np.random.RandomState(0)
        proj = rng.randn(16, 4).astype(np.float32)
        main, startup = fluid.Program(), fluid.Program()
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope):
            img = fluid.layers.data("img", [16])
            label = fluid.layers.data("label", [1], dtype="int64")
            _, avg_cost, _ = mlp(img, label, hidden_sizes=(8,),
                                 num_classes=4)
            pgs = fluid.backward.append_backward(avg_cost)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            params = [p.name for p, _ in pgs]
            grads = [g.name for _, g in pgs]
            cli = RPCClient("127.0.0.1:%d" % ps_port,
                            retry=Policy(deadline=20.0, seed=2))
            mcli = MasterClient("127.0.0.1:%d" % master_port,
                                retry=Policy(deadline=20.0, seed=2))
            for p in params:
                cli.put_var(p, np.asarray(scope.find_var(p)))
            inc = "%016x" % time.time_ns() + "feedc0de"
            seq = itertools.count()
            done = 0
            while done < n_tasks:
                tid, payload = mcli.get_task()
                if tid is None:
                    if payload == "done":
                        break
                    time.sleep(0.02)
                    continue
                x = rng.rand(8, 16).astype(np.float32)
                y = np.argmax(x @ proj, axis=1).astype(
                    np.int64)[:, None]
                with trace.span("round", step=done):
                    outs = exe.run(main, feed={"img": x, "label": y},
                                   fetch_list=[avg_cost.name] + grads)
                    tag = "t0:i%s:s%d" % (inc, next(seq))
                    for g, gv in zip(grads, outs[1:]):
                        cli.send_var(g, np.asarray(gv), tag=tag)
                    cli.barrier(tag=tag)
                    for p in params:
                        scope.set(p, cli.get_var(p))
                mcli.task_done(tid)
                done += 1
            assert done == n_tasks
            cli.close()
            mcli.close()
        trace.disable()
    finally:
        open(stop_file, "w").write("stop")
        try:
            out, _ = proc.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
    assert proc.returncode == 0, out[-3000:]

    # merge the two logs -> one Perfetto timeline
    out_json = str(tmp_path / "timeline.json")
    assert trace_cli(["merge", client_log, server_log,
                      "-o", out_json]) == 0
    merged = json.load(open(out_json))
    info = merged["otherData"]["paddle_tpu.trace"]
    assert info["processes"] >= 2 and not info["warnings"]
    events = merged["traceEvents"]
    lanes = [e["args"]["name"] for e in events
             if e.get("name") == "process_name"]
    assert any("trainer" in n for n in lanes)
    assert any("pserver" in n for n in lanes)

    cspans = {s["span"]: s for s in _spans(client_log)}
    sspans = _spans(server_log)
    server_pid = sspans[0]["pid"]
    client_pid = next(iter(cspans.values()))["pid"]
    off = info["clock_offsets"]
    off = {int(k): v for k, v in off.items()} \
        if isinstance(off, dict) else off
    gets = [s for s in sspans if s["name"] == "pserver.GET"]
    assert gets, [s["name"] for s in sspans]
    nested = 0
    for g in gets:
        parent = cspans.get(g["parent"])
        if parent is None:
            continue
        assert parent["name"] in ("rpc.get", "rpc.get.attempt")
        assert parent["pid"] == client_pid
        assert g["trace"] == parent["trace"]
        # skew-corrected containment (epsilon for offset estimation
        # error, bounded by the probe RTT on loopback)
        eps = 0.005
        g0 = g["t0"] - off[server_pid]
        p0 = parent["t0"] - off[client_pid]
        if p0 - eps <= g0 and g0 + g["dur"] <= p0 + parent["dur"] + eps:
            nested += 1
    assert nested == len(gets), (nested, len(gets))
    # the trainer's rounds reached the fleet timeline as traces with
    # cross-process children
    rounds = [s for s in cspans.values() if s["name"] == "round"]
    assert len(rounds) == n_tasks
    # every round (send+barrier+get) reached the server under its trace
    server_traces = {s["trace"] for s in sspans}
    assert {r["trace"] for r in rounds} <= server_traces
