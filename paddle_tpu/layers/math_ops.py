"""Operator-overload support for Variable arithmetic (framework.py
monkey-patched methods in the reference)."""

import numpy as np

from ..core.program import Variable
from .layer_helper import LayerHelper


def _broadcast_shape(a, b):
    if a is None or b is None:
        return a or b
    try:
        return tuple(np.broadcast_shapes(tuple(a), tuple(b)))
    except ValueError:
        return a


_COMPARE_OPS = {"less_than", "less_equal", "greater_than", "greater_equal",
                "equal", "not_equal"}


def scale_var(x, scale=1.0, bias=0.0):
    helper = LayerHelper("scale")
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    helper.append_op(type="scale", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"scale": float(scale), "bias": float(bias)})
    return out


def _constant_like(x, value):
    helper = LayerHelper("fill")
    out = helper.create_variable_for_type_inference(
        x.dtype, shape=(1,), stop_gradient=True)
    helper.append_op(type="fill_constant", outputs={"Out": [out]},
                     attrs={"shape": [1], "dtype": x.dtype,
                            "value": float(value)})
    return out


def elementwise_binary(x, other, op_type, reverse=False):
    if np.isscalar(other):
        # fast paths that keep the graph small
        if op_type == "elementwise_add":
            return scale_var(x, 1.0, other)
        if op_type == "elementwise_sub":
            return scale_var(x, -1.0 if reverse else 1.0,
                             other if reverse else -other)
        if op_type == "elementwise_mul":
            return scale_var(x, other)
        if op_type == "elementwise_div" and not reverse:
            return scale_var(x, 1.0 / other)
        other = _constant_like(x, other)
    a, b = (other, x) if reverse else (x, other)
    helper = LayerHelper(op_type)
    dtype = "bool" if op_type in _COMPARE_OPS else a.dtype
    out = helper.create_variable_for_type_inference(
        dtype, shape=_broadcast_shape(a.shape, b.shape))
    helper.append_op(type=op_type, inputs={"X": [a], "Y": [b]},
                     outputs={"Out": [out]}, attrs={"axis": -1})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None,
          name=None):
    """fluid.layers.scale parity (scale_op.cc): out = x*scale + bias
    (or (x+bias)*scale when bias_after_scale=False)."""
    if not bias_after_scale:
        bias = bias * scale
    out = scale_var(x, scale, bias)
    if act is None:
        return out
    helper = LayerHelper(act, name=name)
    final = helper.create_variable_for_type_inference(out.dtype,
                                                      shape=out.shape)
    helper.append_op(type=act, inputs={"X": [out]}, outputs={"Out": [final]})
    return final
